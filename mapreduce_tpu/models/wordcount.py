"""WordCount: the flagship model of the framework.

End-to-end equivalent of the reference program (tokenize ``main.cu:187-202``
-> map ``main.cu:37-54`` -> reduce ``main.cu:69-108`` -> report
``main.cu:212-218``), rebuilt TPU-first: bytes go to the device as a padded
uint8 tensor, tokenization/hashing/counting happen in one jitted XLA program,
and only the small count table returns to the host, where exact strings are
recovered from first-occurrence positions.

This module is the simple single-buffer path used by the CLI and tests; the
streaming / multi-chip path lives in :mod:`mapreduce_tpu.runtime.executor` and
:mod:`mapreduce_tpu.parallel.mapreduce`.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu import constants
from mapreduce_tpu.config import Config, DEFAULT_CONFIG
from mapreduce_tpu.ops import datastats
from mapreduce_tpu.ops import sketch as sketch_ops
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.ops import tokenize as tok_ops


@dataclasses.dataclass(frozen=True)
class WordCountResult:
    """Host-side result with recovered strings, insertion-ordered."""

    words: list[bytes]  # reported words, by first occurrence
    counts: list[int]  # parallel to words
    total: int  # total tokens (includes any spilled/dropped ones; exact)
    distinct: int  # distinct words: exact when dropped_uniques == 0; under
    #   table spill, a KMV estimate read off the full table's largest kept
    #   key (~1/sqrt(capacity) relative error — 0.2% at the default 256K;
    #   see ops.table.kmv_distinct), far tighter than the summed per-chunk
    #   bound it replaces.  Top-k finalized runs keep the upper bound (the
    #   terminal reorder destroys the KMV property).
    dropped_uniques: int  # upper bound on distinct words spilled past table
    #   capacity or dropped as overlong; loose because cross-chunk merges sum
    #   per-chunk bounds and the pallas backend cannot hash (hence cannot
    #   dedupe) tokens longer than its lookback window
    dropped_count: int  # tokens belonging to spilled/dropped words (exact)
    distinct_estimate: float | None = None  # HLL estimate (~0.8% err @ p=14);
    #   populated by sketched runs — unlike ``distinct`` it stays accurate
    #   past table capacity
    cms: np.ndarray | None = dataclasses.field(default=None, compare=False)
    #   Count-Min sketch from a count_sketch run: estimate_count() answers
    #   frequency queries for ANY word, including ones spilled past capacity

    def as_dict(self) -> dict[bytes, int]:
        return dict(zip(self.words, self.counts))

    def estimate_count(self, word: bytes) -> int | None:
        """CMS frequency estimate for ``word`` (None without a sketch).

        Never under-estimates a word the run saw (within the batch-capacity
        envelope); over-estimates by at most ~total/width per row w.h.p.
        """
        if self.cms is None:
            return None
        return sketch_ops.cms_query(self.cms, word)


def apply_top_k(result: WordCountResult, k: int) -> WordCountResult:
    """Restrict a result to its k most frequent words (host-side, stable).

    The single owner of top-k reordering for host results; ``total`` keeps
    counting every token, matching CountTable.total_count() semantics.
    """
    order = sorted(range(len(result.words)), key=lambda i: -result.counts[i])[:k]
    return dataclasses.replace(
        result,
        words=[result.words[i] for i in order],
        counts=[result.counts[i] for i in order],
    )


def _seam_table_cap(w: int) -> int:
    """Seam-table capacity for the stable2 split aggregation: seam
    emissions are bounded by (W+1) tokens per (2W+2)-byte window * 129
    windows (4257 at W=32, 8256 at the W=63 maximum) — sized from W so a
    spill is IMPOSSIBLE at any legal config (a spill here would silently
    diverge from the concat-path oracle, which absorbs all seam rows in
    the big sort)."""
    return 129 * (w + 1)


# Seam-deferred overlong runs per chunk are bounded by ~2 per seam window
# (one left-truncated + one complete >W run fit in 2W+2 bytes) * 129 windows.
_SEAM_RESCUE_SLOTS = 384


def _combiner_table(cache, pos_hi) -> table_ops.CountTable:
    """One chunk's flushed hot-key cache -> an exact tiny CountTable
    (ISSUE 11).  Cache rows carry per-entry counts and the entry's first
    in-lane occurrence; the same hot key resident in several lanes
    coalesces through the generic build's segment reduce (counts sum, the
    smallest position wins), so the merge with the thinned stream's table
    reproduces the uncombined build bit-for-bit.  Capacity = the plane
    size: distinct cached keys can never exceed the slot count, so this
    build is spill-free by construction."""
    khi = cache.key_hi.reshape(-1)
    klo = cache.key_lo.reshape(-1)
    cnt = cache.count.reshape(-1)
    packed = cache.packed.reshape(-1)
    live = cnt > 0
    sent = jnp.uint32(constants.SENTINEL_KEY)
    inf = jnp.uint32(constants.POS_INF)
    stream = tok_ops.TokenStream(
        key_hi=jnp.where(live, khi, sent),
        key_lo=jnp.where(live, klo, sent),
        count=jnp.where(live, cnt, jnp.uint32(0)),
        pos=jnp.where(live, packed >> 6, inf),
        length=jnp.where(live, packed & jnp.uint32(63), jnp.uint32(0)))
    return table_ops.from_stream(stream, khi.shape[0], pos_hi=pos_hi)


class SeamedUpdate(NamedTuple):
    """A per-chunk map result whose seam table has NOT been folded yet.

    The streamed stable2 path defers the seam fold to the per-step running
    merge (a three-way :func:`...ops.table.merge` — runs of <= 3 rows fold
    in the same two sorts), saving the two dedicated (capacity + seam)-row
    sorts a pairwise seam merge costs per chunk.  ``batch`` carries the
    chunk's dropped_* accounting; ``seam`` is spill-free by construction
    (:func:`_seam_table_cap` covers the 129*(W+1) emission bound)."""

    batch: table_ops.CountTable
    seam: table_ops.CountTable


def _map_stream(chunk: jax.Array, config: Config, capacity: int,
                pos_hi: jax.Array | int = 0, split_seam: bool = False,
                with_stats: bool = False):
    """Tokenize one buffer with the configured backend and build its table.

    With ``split_seam`` (streamed stable2 only) the result is a
    :class:`SeamedUpdate` whose seam table the caller folds at its next
    merge; otherwise a single fully-folded :class:`CountTable`.

    With ``with_stats`` (ISSUE 8: the telemetered streamed path) the
    result is ``(update, ops.datastats.DataStats)`` — the chunk's
    data-plane counters (overlong/rescued/dropped, spill-fallback and
    rescue-escalation cond branches taken, spill rows) surfaced as tiny
    uint32 scalars the executor fetches at group retirement.  The update
    itself is BIT-IDENTICAL to the plain path: the counters read
    predicates the map already computes (``overlong``, ``spill``, the
    rescue pass's own clamped count) and the built table's ``dropped_*``
    scalars; with ``with_stats=False`` (the default, and every
    non-telemetered caller) the traced program is unchanged.
    """
    if split_seam and (config.sort_mode != "stable2"
                       or config.resolved_backend() != "pallas"
                       or not config.resolved_compact_slots):
        raise ValueError("split_seam requires the pallas stable2 compact "
                         "path (the only producer of a separate seam table)")
    # ``ret`` pairs every aggregation return with its per-chunk rescued
    # count when stats are on (threaded through the same lax.cond branches
    # the tables take, so both modes keep one control structure); the
    # plain mode returns tables alone, bit-for-bit as before.
    if with_stats:
        ret = lambda t, rescued: (t, rescued)
    else:
        ret = lambda t, rescued: t
    zero_u32 = jnp.zeros((), jnp.uint32)

    def assemble(res, overlong, spill, cache=None, spill_gate=None):
        """Pair the final update with its chunk DataStats (stats mode).

        ``cache``/``spill_gate`` (ISSUE 11): the fused combiner's flushed
        hot-key planes and the spill scalar that decided whether they
        were USED — on a spilled chunk the pair fallback ran combiner-
        free, so the counters gate to zero with it (the cache planes
        exist outside the cond; reading them here adds no branch)."""
        if not with_stats:
            return res
        update, rescued = res
        tbl = update.batch if isinstance(update, SeamedUpdate) else update
        rescue_on = bool(config.rescue_slots)
        tiered = config.rescue_slots_max > config.rescue_slots > 0
        c_hits = c_flushes = c_evicted = 0
        if cache is not None:
            used = (spill_gate == 0).astype(jnp.uint32)
            c_hits = used * jnp.sum(cache.count)
            c_flushes = used * jnp.sum((cache.count > 0).astype(jnp.uint32))
            c_evicted = used * jnp.sum((cache.count == 1).astype(jnp.uint32))
        stats = datastats.map_stats(
            overlong=overlong, rescued=rescued,
            spill=spill if spill is not None else 0,
            fallback=(spill != 0) if spill is not None else 0,
            invoked=(overlong > 0) if rescue_on else 0,
            escalated=(overlong > jnp.uint32(config.rescue_slots))
            if tiered else 0,
            dropped_tokens=tbl.dropped_count,
            dropped_uniques=tbl.dropped_uniques,
            combiner_hits=c_hits, combiner_flushes=c_flushes,
            combiner_evicted=c_evicted)
        return update, stats

    if config.resolved_backend() == "pallas":
        from mapreduce_tpu.ops import rescue as rescue_ops
        from mapreduce_tpu.ops.pallas import tokenize as pallas_tok

        def accounted(t, n_over):
            # ``n_over`` counts occurrences.  For dropped_count
            # (occurrences) that is exact; for dropped_uniques it is the
            # only available upper bound — unrescued overlong tokens
            # leave the device unhashed, so their distinct words cannot
            # be deduplicated.
            return t._replace(dropped_uniques=t.dropped_uniques + n_over,
                              dropped_count=t.dropped_count + n_over)

        def rescued_table(t, rescue_packed, overlong):
            """cond(overlong > 0): exact re-hash of the poison positions
            (ops/rescue.py) — rescued tokens join the batch table with
            true keys/lengths/first occurrences; only the residual stays
            in dropped accounting.  TIERED (VERDICT r4 weak #4): the
            common case re-hashes the first ``rescue_slots`` positions;
            when the chunk's overlong count exceeds that, a second cond
            escalates to the full ``rescue_slots_max`` extraction (URL-
            dense text: ~15K/chunk on the webby proxy) instead of
            silently leaving the residual dropped.  Overlong-free chunks
            (both bench corpora, all of test.txt) skip everything."""

            def pass_with(packed_r):
                rt, rescued = rescue_ops.rescue_table(
                    chunk, packed_r, config.pallas_max_token,
                    config.rescue_window, pos_hi)
                # rescued <= overlong holds by construction (one poison per
                # overlong run); the clamp bounds any future kernel drift
                # that double-emits a poison to an accounting error instead
                # of a silent uint32 wrap of dropped_count to ~2**32.
                ok = jnp.minimum(rescued, overlong)
                return ret(accounted(table_ops.merge(t, rt,
                                                     capacity=capacity),
                                     overlong - ok), ok)

            def with_rescue(_):
                r1 = config.rescue_slots
                if rescue_packed.shape[0] > r1:
                    return jax.lax.cond(
                        overlong > jnp.uint32(r1),
                        lambda _: pass_with(rescue_packed),
                        lambda _: pass_with(rescue_packed[:r1]), None)
                return pass_with(rescue_packed)

            return jax.lax.cond(overlong > 0, with_rescue,
                                lambda _: ret(accounted(t, overlong),
                                              zero_u32), None)

        # The spill-fallback / non-compact aggregation must not use stable2:
        # pair-layout streams are NOT position-ordered (rows interleave
        # lanes), so first-occurrence recovery needs the third sort key.
        concat_sort_mode = "sort3" if config.sort_mode == "stable2" \
            else config.sort_mode

        def aggregate_stream(stream, overlong, mode, cache=None):
            """ONE packed build over a single complete stream — the shared
            tail of the split concat path and the fused map path (whose
            kernel already holds every emission, cross-lane-seam tokens
            hashed in-kernel from the seam-carry plane): no seam table, no
            seam merge, and overlong poison rows ride the big sort's
            poison segment (contrast aggregate_stable2's seam-poison
            extraction dance).  With ``cache`` (the fused combiner's
            flushed hot-key planes, ISSUE 11) the occurrences the kernel
            absorbed fold back in as one tiny exact table merge — counts
            add, the merge keeps each key's smallest position, and the
            merged result equals the uncombined build's bit-for-bit
            (under batch-capacity spill both paths keep the same smallest
            ``capacity`` keys: the build and the merge share one
            largest-keys-drop rule; only the dropped_uniques upper bound
            can differ, as cross-table merges always could)."""
            built = table_ops.from_stream(
                stream, capacity, pos_hi=pos_hi,
                max_token_bytes=config.pallas_max_token,
                max_pos=int(chunk.shape[0]), sort_mode=mode,
                rescue_slots=config.rescue_slots_max,
                sort_impl=config.sort_impl,
                salt_bits=config.resolved_salt_bits,
                radix_geometry=config.resolved_radix_geometry)
            if not config.rescue_slots:
                res = ret(accounted(built, overlong), zero_u32)
            else:
                t, rescue_packed = built
                res = rescued_table(t, rescue_packed, overlong)
            if cache is None:
                return res
            t, resc = res if with_stats else (res, zero_u32)
            t = table_ops.merge(t, _combiner_table(cache, pos_hi),
                                capacity=capacity)
            return ret(t, resc)

        def aggregate(col, seam, overlong):
            # One aggregation over column + seam emissions together: the
            # seam rows are ~8.5K entries, absorbed by the big sort for
            # free, where a separate seam table + merge cost a second
            # (fixed-overhead-bound) reduce pass per chunk.
            return aggregate_stream(pallas_tok.concat_streams(col, seam),
                                    overlong, concat_sort_mode)

        def aggregate_stable2(col, seam, overlong):
            """Split aggregation for the lane-major layout: the column
            stream keeps its position order into a STABLE 2-key sort
            (first occurrence from tie order — the third comparator key,
            ~40% of the sort's compute, is gone), while the tiny seam
            stream builds its own table and folds in with one pairwise
            merge of (capacity + 8K) rows.  Kept keys/counts/positions
            and dropped_count are bit-identical to the concat path: the
            merge keeps each key's smallest (pos_hi, pos_lo), and the
            kept set of a capacity-merge of capacity-builds equals the
            kept set of one joint build (dropped keys are all larger than
            every kept one).  Only the dropped_uniques UPPER BOUND can
            differ under batch-capacity spill, as cross-table merges
            always could."""
            built = table_ops.from_stream(
                col, capacity, pos_hi=pos_hi,
                max_token_bytes=config.pallas_max_token,
                max_pos=int(chunk.shape[0]), sort_mode="stable2",
                rescue_slots=config.rescue_slots_max,
                sort_impl=config.sort_impl,
                salt_bits=config.resolved_salt_bits,
                radix_geometry=config.resolved_radix_geometry)
            seam_tbl = table_ops.from_stream(
                seam,
                min(capacity,
                    _seam_table_cap(config.pallas_max_token)),
                pos_hi=pos_hi)
            resc = zero_u32
            if not config.rescue_slots:
                t = accounted(built, overlong)
            else:
                t, col_rescue = built
                # Seam-deferred overlong runs are not in the column planes,
                # so their poisons cannot ride the big sort's poison
                # segment: extract them from the (tiny) seam stream
                # directly — count=0 rows with a real position are exactly
                # the seam poisons — and append their windows to the
                # rescue pass.  The combined array is re-sorted so the
                # tiered rescue's first-R1 slice keeps the globally
                # smallest positions (deterministic drop order), not a
                # per-source split.
                ones = jnp.uint32(0xFFFFFFFF)
                is_sp = (seam.count == 0) \
                    & (seam.pos != jnp.uint32(constants.POS_INF))
                sp = jnp.where(is_sp, seam.pos << 6, ones)
                sp = jax.lax.sort(sp)[:_SEAM_RESCUE_SLOTS]
                # Re-sort and slice back to the resolved budget: the tiered
                # rescue's slices then keep the globally smallest positions
                # (the same deterministic drop order as the concat path,
                # where seam poisons ride the big sort inside one budget).
                combined = jax.lax.sort(
                    jnp.concatenate([col_rescue, sp]))[:col_rescue.shape[0]]
                res = rescued_table(t, combined, overlong)
                t, resc = res if with_stats else (res, zero_u32)
            if split_seam:
                return ret(SeamedUpdate(batch=t, seam=seam_tbl), resc)
            return ret(table_ops.merge(t, seam_tbl, capacity=capacity), resc)

        def seamed(t):
            """Match the split-seam pytree for paths with no seam table to
            defer: an empty seam table rides along, inert in the caller's
            three-way merge."""
            if split_seam:
                return SeamedUpdate(
                    batch=t,
                    seam=table_ops.empty(min(
                        capacity,
                        _seam_table_cap(config.pallas_max_token))))
            return t

        def seamed_ret(res):
            """``seamed`` lifted over the (table, rescued) pairing."""
            if with_stats:
                t, resc = res
                return seamed(t), resc
            return seamed(res)

        def full_tok(_):
            """Full-resolution split path, also reporting its overlong
            scalar (the non-compact entry's stats need it; the cond
            branch below drops it)."""
            col, seam, overlong = pallas_tok.tokenize_split(
                chunk, max_token_bytes=config.pallas_max_token,
                block_rows=config.resolved_pair_block_rows)
            return seamed_ret(aggregate(col, seam, overlong)), overlong

        def full_path(_):
            return full_tok(_)[0]

        if config.map_impl == "fused":
            def fused_full_tok(_):
                # Spill fallback = the SAME fused kernel in pair mode
                # (full resolution, exact).  Pair-layout streams interleave
                # lanes, so first occurrence needs the third sort key.
                stream, overlong, _sp = pallas_tok.tokenize_fused(
                    chunk, max_token_bytes=config.pallas_max_token,
                    block_rows=config.resolved_pair_block_rows,
                    aux_rows=config.resolved_aux_rows)
                return seamed_ret(aggregate_stream(stream, overlong,
                                                   concat_sort_mode)), \
                    overlong

            def fused_full(_):
                return fused_full_tok(_)[0]

            if not config.resolved_compact_slots:
                res, overlong = fused_full_tok(None)
                return assemble(res, overlong, None)
            lane_major = config.sort_mode == "stable2"
            combiner_slots = config.resolved_combiner_slots
            if combiner_slots:
                # Hot-key combiner (ISSUE 11): the kernel counts cached
                # occurrences in VMEM and thins the stream; the flushed
                # cache folds back in inside the compact branch.  The
                # spill fallback stays the combiner-FREE pair path — on a
                # spilled chunk the aborted compact pass's cache is
                # discarded wholesale, so exactness never depends on it.
                stream, overlong, spill, cache = pallas_tok.tokenize_fused(
                    chunk, compact_slots=config.resolved_compact_slots,
                    max_token_bytes=config.pallas_max_token,
                    block_rows=config.resolved_block_rows,
                    lane_major=lane_major, combiner_slots=combiner_slots,
                    aux_rows=config.resolved_aux_rows)
            else:
                stream, overlong, spill = pallas_tok.tokenize_fused(
                    chunk, compact_slots=config.resolved_compact_slots,
                    max_token_bytes=config.pallas_max_token,
                    block_rows=config.resolved_block_rows,
                    lane_major=lane_major,
                    aux_rows=config.resolved_aux_rows)
                cache = None
            # Lane-major fused streams stay in global byte-position order
            # (cross-seam tokens land in their start-position slot), so the
            # stable2 tie-order contract holds over the single stream.
            mode = "stable2" if lane_major else concat_sort_mode
            return assemble(jax.lax.cond(
                spill == 0,
                lambda _: seamed_ret(aggregate_stream(stream, overlong,
                                                      mode, cache=cache)),
                fused_full, None), overlong, spill,
                cache=cache, spill_gate=spill)

        if not config.resolved_compact_slots:
            res, overlong = full_tok(None)
            return assemble(res, overlong, None)
        # Slot-compacted planes (config.compact_slots, default-on at 88:
        # +25% end-to-end on the chip, BENCHMARKS.md round 4): the sort
        # input shrinks ~1.45x.  A nonzero spill means some (block, lane)
        # window exceeded its slot budget and the compact planes are
        # incomplete — the cond then re-runs the chunk at full resolution,
        # so ANY input stays exact (the compact branch is bit-identical
        # when it runs; tools/density.py: the default budget never spills
        # on the bench corpora).
        lane_major = config.sort_mode == "stable2"
        col, seam, overlong, spill = pallas_tok.tokenize_split_compact(
            chunk, config.resolved_compact_slots,
            max_token_bytes=config.pallas_max_token,
            block_rows=config.resolved_block_rows, lane_major=lane_major)
        return assemble(jax.lax.cond(
            spill == 0,
            (lambda _: aggregate_stable2(col, seam, overlong)) if lane_major
            else (lambda _: aggregate(col, seam, overlong)),
            full_path,
            None), overlong, spill)
    stream = tok_ops.tokenize(chunk)
    built = table_ops.from_stream(stream, capacity, pos_hi=pos_hi)
    if not with_stats:
        return built
    # XLA backend: no kernel window, no spill/rescue machinery — the only
    # data-plane signals are the table's own dropped accounting (capacity
    # spill) and the state gauges ``state_stats`` fills.
    return built, datastats.map_stats(dropped_tokens=built.dropped_count,
                                      dropped_uniques=built.dropped_uniques)


@functools.partial(jax.jit, static_argnames=("capacity", "config"))
def _count_step(data: jax.Array, capacity: int, config: Config) -> table_ops.CountTable:
    return _map_stream(data, config, capacity)


def _pad_for_backend(data: bytes | np.ndarray, config: Config) -> np.ndarray:
    """Pad a buffer to the backend's minimum static size (the pallas kernel
    needs whole lane segments of >= 2W+2 bytes; XLA just needs a multiple of
    128).  Single owner of the rule for every single-buffer entry point."""
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
    min_len = config.pallas_min_chunk if config.resolved_backend() == "pallas" else 128
    return tok_ops.pad_to(buf, max(min_len, -(-buf.shape[0] // 128) * 128))


def count_table(data: bytes | np.ndarray, config: Config = DEFAULT_CONFIG) -> table_ops.CountTable:
    """Run the device pipeline over one in-memory buffer, return the table."""
    padded = _pad_for_backend(data, config)
    return _count_step(jax.device_put(padded), config.table_capacity, config)


def _reported_distinct(tbl: table_ops.CountTable, n_words: int,
                       dropped_uniques: int, estimate: bool) -> int:
    """``distinct`` for a recovered result: exact when nothing spilled;
    the table's free KMV estimate when it did (see WordCountResult)."""
    if estimate and dropped_uniques > 0:
        est = table_ops.kmv_distinct(tbl)
        if est is not None:
            return max(n_words, int(round(est)))
    return n_words + dropped_uniques


def recover_result(tbl: table_ops.CountTable, source: bytes,
                   estimate_distinct: bool = True,
                   ngram: int = 1) -> WordCountResult:
    """Host-side string recovery from a single-buffer table (pos_hi == 0).

    ``ngram`` is the gram order of the table: entries whose length is the
    ``SEAM_GRAM_LENGTH`` sentinel are >= 127-byte spans (the packed gram
    build stores lengths in 7 bits) and are recovered by scanning ``ngram``
    entries forward from the start, the cross-chunk seam idiom.
    """
    count = np.asarray(tbl.count).astype(np.int64)
    count_hi = np.asarray(tbl.count_hi).astype(np.int64)
    valid = (count > 0) | (count_hi > 0)
    pos = np.asarray(tbl.pos_lo)[valid]
    length = np.asarray(tbl.length)[valid].astype(np.int64)
    cnt = (count + (count_hi << np.int64(32)))[valid]
    seam = np.flatnonzero(length == int(constants.SEAM_GRAM_LENGTH))
    if len(seam):
        from mapreduce_tpu.data import reader as reader_mod

        length[seam] = reader_mod.scan_gram_lengths_bytes(
            source, pos[seam].astype(np.int64), ngram)
    order = np.argsort(pos, kind="stable")
    words = [bytes(source[int(p): int(p) + int(l)]) for p, l in zip(pos[order], length[order])]
    dropped_uniques, dropped_count = tbl.dropped_totals()
    return WordCountResult(
        words=words,
        counts=[int(c) for c in cnt[order]],
        total=int(np.asarray(tbl.total_count())),
        distinct=_reported_distinct(tbl, len(words), dropped_uniques,
                                    estimate_distinct),
        dropped_uniques=dropped_uniques,
        dropped_count=dropped_count,
    )


def count_words(data: bytes, config: Config = DEFAULT_CONFIG) -> WordCountResult:
    """The one-call API: exact word counts for an in-memory buffer."""
    return recover_result(count_table(data, config), data)


@functools.partial(jax.jit, static_argnames=("capacity", "n", "config"))
def _ngram_step(data: jax.Array, capacity: int, n: int,
                config: Config) -> table_ops.CountTable:
    from mapreduce_tpu.ops import ngram as ngram_ops

    if config.resolved_backend() == "pallas":
        return ngram_ops.ngram_table(data, n, capacity, 0, config)
    gs = ngram_ops.mark_long_spans(tok_ops.ngrams(tok_ops.tokenize(data), n))
    return ngram_ops.gram_table(gs, capacity, 0, max_pos=data.shape[0],
                                sort_mode=config.sort_mode,
                                sort_impl=config.sort_impl,
                                salt_bits=config.resolved_salt_bits,
                                radix_geometry=config.resolved_radix_geometry)


def count_ngrams(data: bytes, n: int, config: Config = DEFAULT_CONFIG) -> WordCountResult:
    """Exact n-gram counts for an in-memory buffer (see :class:`NGramCountJob`).

    Reported "words" are the exact source spans of the grams (separators
    between tokens included); ``total`` is the number of grams,
    ``max(tokens - n + 1, 0)``.
    """
    padded = _pad_for_backend(data, config)
    tbl = _ngram_step(jax.device_put(padded), config.table_capacity, n, config)
    return recover_result(tbl, data, ngram=n)


class BufferedTableState(NamedTuple):
    """Running table + a pending buffer of up to K staged batch tables
    (``Config.merge_every = K > 1``).  ``cursor`` counts batches staged
    since the last flush; flushed pending slots carry sentinel keys / zero
    counts, inert to the K-way reduce."""

    table: table_ops.CountTable
    pend_key_hi: jax.Array  # uint32[K * batch_capacity]
    pend_key_lo: jax.Array
    pend_count: jax.Array
    pend_pos_hi: jax.Array
    pend_pos_lo: jax.Array
    pend_length: jax.Array
    cursor: jax.Array  # uint32 scalar


class WordCountJob:
    """WordCount as a :class:`mapreduce_tpu.parallel.mapreduce.MapReduceJob`.

    The flagship job: per-device accumulation into a CountTable, associative
    table merge as the global reduction.  ``chunk_id`` (step * n_devices +
    device) becomes ``pos_hi`` so first-occurrence order is global file order
    and the executor can recover exact strings from (chunk_id, pos_lo, len).

    ``config.merge_every = K > 1`` amortizes the per-step pairwise merge:
    batch tables stage into a pending buffer and ONE K-way sort+reduce
    (:func:`...ops.table.merge_batched`) replaces K merges.
    """

    # graphcheck metadata: ``pend_count`` (merge_every > 1 staging buffer)
    # holds per-chunk BATCH counts, bounded by chunk bytes / 2 << 2**32 —
    # the name-based overflow lint would misread it as a corpus-scale
    # running counter.  The running table's own counts are lane-paired.
    analysis_overflow_exempt = frozenset({"pend_count"})

    def __init__(self, config: Config = DEFAULT_CONFIG):
        self.config = config
        self.capacity = config.table_capacity
        self.batch_capacity = config.batch_uniques
        self.merge_every = config.merge_every

    @staticmethod
    def _with_empty_pending(table: table_ops.CountTable,
                            n: int) -> BufferedTableState:
        """Single owner of the empty pending-buffer layout (init + flush)."""
        sent = jnp.full((n,), jnp.uint32(constants.SENTINEL_KEY))
        inf = jnp.full((n,), jnp.uint32(constants.POS_INF))
        zero = jnp.zeros((n,), jnp.uint32)
        return BufferedTableState(table, sent, jnp.array(sent), zero,
                                  inf, jnp.array(inf), jnp.array(zero),
                                  jnp.zeros((), jnp.uint32))

    def init_state(self):
        if self.merge_every == 1:
            return table_ops.empty(self.capacity)
        return self._with_empty_pending(table_ops.empty(self.capacity),
                                        self.merge_every * self.batch_capacity)

    def _split_seam(self) -> bool:
        """Streamed stable2 defers the per-chunk seam fold to the per-step
        THREE-WAY running merge (merge_every == 1 only: the pending-buffer
        staging path folds whole batch tables and has no third slot)."""
        return (self.merge_every == 1
                and self.config.sort_mode == "stable2"
                and self.config.resolved_backend() == "pallas"
                and bool(self.config.resolved_compact_slots))

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array):
        return _map_stream(chunk, self.config, self.batch_capacity,
                           pos_hi=chunk_id, split_seam=self._split_seam())

    # -- data-plane telemetry (ISSUE 8) ---------------------------------

    def map_chunk_stats_sharded(self, chunk, chunk_id, axis, device_index):
        """Stats-mode map: the same update plus the chunk's
        :class:`...ops.datastats.DataStats` counters.  The engine calls
        this instead of :meth:`map_chunk` only when data telemetry is on
        (``Engine(data_stats=True)``); results are bit-identical."""
        del axis, device_index  # the plain wordcount map is axis-free
        return _map_stream(chunk, self.config, self.batch_capacity,
                           pos_hi=chunk_id, split_seam=self._split_seam(),
                           with_stats=True)

    def _stats_table(self, state) -> table_ops.CountTable:
        """The running table the data-stats gauges read.  Deliberately
        NOT :meth:`_plain_table`: flushing a merge_every>1 pending buffer
        just to observe occupancy would add a K-way reduce per dispatch —
        the unflushed running table is at most K batches stale, which is
        telemetry-grade accurate at zero cost."""
        if isinstance(state, BufferedTableState):
            return state.table
        return state

    def state_stats(self, state, stats):
        """Fill the running-state gauges (occupancy, totals, top-bucket
        mass, cumulative dropped) after the group's last combine."""
        return datastats.with_table_gauges(stats, self._stats_table(state))

    def _flushed(self, st: BufferedTableState) -> BufferedTableState:
        """Fold all staged batches into the table (no-op when none staged)."""
        table = table_ops.merge_batched(
            st.table, st.pend_key_hi, st.pend_key_lo, st.pend_count,
            st.pend_pos_hi, st.pend_pos_lo, st.pend_length, self.capacity)
        return self._with_empty_pending(table, st.pend_key_hi.shape[0])

    def combine(self, state, update):
        if self.merge_every == 1:
            if isinstance(update, SeamedUpdate):
                # Three-way fold: batch + seam ride the running merge's two
                # sorts together (runs of <= 3 rows; see table_ops.merge).
                return table_ops.merge(state, update.batch,
                                       capacity=self.capacity,
                                       c=update.seam)
            return table_ops.merge(state, update, capacity=self.capacity)
        b = self.batch_capacity
        off = ((state.cursor % jnp.uint32(self.merge_every))
               * jnp.uint32(b)).astype(jnp.int32)
        put = lambda dst, src: jax.lax.dynamic_update_slice(dst, src, (off,))
        st = BufferedTableState(
            state.table,
            put(state.pend_key_hi, update.key_hi),
            put(state.pend_key_lo, update.key_lo),
            put(state.pend_count, update.count),
            put(state.pend_pos_hi, update.pos_hi),
            put(state.pend_pos_lo, update.pos_lo),
            put(state.pend_length, update.length),
            state.cursor + jnp.uint32(1))
        # Spilled batch accounting must not wait for the flush: the batch
        # table's own dropped_* scalars fold into the running table NOW
        # (merge_batched only carries the table's scalars).  Carry adds:
        # the running scalars are 64-bit lane pairs.
        du_lo, du_hi = table_ops.add64(
            st.table.dropped_uniques, st.table.dropped_uniques_hi,
            update.dropped_uniques, update.dropped_uniques_hi)
        dc_lo, dc_hi = table_ops.add64(
            st.table.dropped_count, st.table.dropped_count_hi,
            update.dropped_count, update.dropped_count_hi)
        st = st._replace(table=st.table._replace(
            dropped_uniques=du_lo, dropped_uniques_hi=du_hi,
            dropped_count=dc_lo, dropped_count_hi=dc_hi))
        return jax.lax.cond(st.cursor >= jnp.uint32(self.merge_every),
                            self._flushed, lambda s: s, st)

    def merge(self, a, b):
        if self.merge_every == 1:
            return table_ops.merge(a, b, capacity=self.capacity)
        fa, fb = self._flushed(a), self._flushed(b)
        return fa._replace(table=table_ops.merge(fa.table, fb.table,
                                                 capacity=self.capacity))

    def _plain_table(self, state) -> table_ops.CountTable:
        """The fully-folded CountTable behind any state shape."""
        if isinstance(state, BufferedTableState):
            return self._flushed(state).table
        return state

    def keyrange_merge(self, state, axis) -> table_ops.CountTable:
        """Collective global reduce via key-range all_to_all (the
        ``merge_strategy='keyrange'`` Engine hook): fold any pending
        batches locally, then one reduce-scatter + all_gather round
        (:func:`...parallel.collectives.key_range_merge`).  Returns the
        plain replicated CountTable; ``finalize`` accepts both shapes."""
        from mapreduce_tpu.parallel import collectives

        return collectives.key_range_merge(self._plain_table(state), axis)

    def keyrange_result_merge(self, a, b) -> table_ops.CountTable:
        """Merge two keyrange RESULTS (plain replicated CountTables) —
        the fold the hier-kr-tree outer tree legs and the overlap
        accumulator run on.  Batched-state cadence is irrelevant here:
        keyrange_merge already folded any pending rows."""
        return table_ops.merge(a, b, capacity=self.capacity)

    def finalize(self, state):
        return self._plain_table(state)

    def identity(self) -> str:
        # merge_every changes state SHAPE but not results; shapes are
        # validated against checkpoint leaves, so identity stays
        # cadence-independent.
        return "wordcount"


class TopKTable(NamedTuple):
    """A top-k finalized table plus the pre-reorder KMV snapshot.

    ``top_k`` is terminal: its count-descending reorder destroys the
    key-sorted KMV property, so the distinct estimate's inputs (occupancy
    and the largest kept key of the FULL table) are captured as scalars
    first — the Common-Crawl top-k config is exactly where table spill is
    likely, i.e. where the estimate matters (VERDICT r3 weak #6).  The
    executor reads the scalars host-side via
    :func:`mapreduce_tpu.ops.table.kmv_from_snapshot`.
    """

    table: table_ops.CountTable
    kmv_n_valid: jax.Array  # uint32: occupancy at snapshot
    kmv_kth_hi: jax.Array  # uint32: largest kept key, hi lane
    kmv_kth_lo: jax.Array  # uint32: largest kept key, lo lane


def topk_with_snapshot(tbl: table_ops.CountTable, k: int) -> TopKTable:
    """Snapshot KMV scalars, then apply the terminal top-k reorder."""
    n_valid, kth_hi, kth_lo = table_ops.kmv_snapshot(tbl)
    return TopKTable(table_ops.top_k(tbl, k), n_valid, kth_hi, kth_lo)


class TopKWordCountJob(WordCountJob):
    """WordCount whose device-side finalize keeps only the k most frequent
    words (the Common-Crawl top-k benchmark config, BASELINE.md), plus the
    pre-reorder KMV snapshot (:class:`TopKTable`)."""

    def __init__(self, k: int, config: Config = DEFAULT_CONFIG):
        super().__init__(config)
        self.k = k

    def finalize(self, state):
        return topk_with_snapshot(self._plain_table(state), self.k)

    def identity(self) -> str:
        # k only affects finalize, but including it keeps resume semantics
        # obvious: one checkpoint, one job description.
        return f"wordcount-top{self.k}"


class NGramState(NamedTuple):
    """Streamed n-gram accumulator: running table + the last n-1 stream
    entries seen (the seam carry; ``ops.ngram.GramCarry``)."""

    table: table_ops.CountTable
    carry: Any


class NGramUpdate(NamedTuple):
    """One streamed step's per-device contribution: the chunk's in-window
    gram table, the step's gathered chunk summaries ([D]-leading leaves,
    identical on every device), and this device's linear index."""

    batch: table_ops.CountTable
    summaries: Any
    device_index: jax.Array


class NGramCountJob(WordCountJob):
    """Count n-token grams (bigrams, trigrams, ...) instead of single words.

    A beyond-parity model family (the reference's map UDF emits only single
    words, ``mapper`` ``main.cu:37-54``) that reuses the whole stack: the
    gram stream rides the same CountTable / collective-merge / string-recovery
    machinery, and each reported "word" is the exact source span of the gram
    (inter-token separators included, e.g. ``b"Hello World"``).

    Streamed runs are EXACT across chunk seams: each chunk's map also emits
    its first/last n-1 stream entries, one small all_gather shares them
    across the step, and ``combine`` composes the running carry in global
    chunk order — forming every window that crosses a join exactly once, the
    way grep threads its exact line carry (the round-2 "streamed runs
    undercount by up to (n-1)*(chunks-1)" envelope is gone).  Cross-chunk
    entries carry ``SEAM_GRAM_LENGTH`` and the host recovers their spans by
    scanning forward from the absolute start offset.

    Backends: the XLA path pairs tokens with carry-forward scans over the
    flat per-byte stream and counts any token length exactly; the pallas
    backend sorts the fused kernel's packed stream by position (one sort
    key recovers global token order, seam emissions included, so grams
    straddle the kernel's 128-lane seams exactly) and pairs rows
    elementwise.  Grams containing a token longer than the kernel window W
    self-invalidate at in-stream poison rows and land in ``dropped_*``
    accounting — the same >W contract as the pallas wordcount path
    (:mod:`mapreduce_tpu.ops.ngram`).  On overlong-free data the backends
    produce bit-identical tables.
    """

    def __init__(self, n: int, config: Config = DEFAULT_CONFIG,
                 top_k: int | None = None):
        if n < 1:
            raise ValueError(f"ngram order must be >= 1, got {n}")
        if n > 1 and config.merge_every > 1:
            # Honest failure beats a knob silently ignored: the n-gram
            # combine stages seam tables and merges pairwise.
            raise ValueError("merge_every > 1 applies to the wordcount "
                             "family only (n-gram combine is pairwise)")
        super().__init__(config)
        self.n = n
        self.k = top_k

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array) -> table_ops.CountTable:
        """Per-chunk gram table (in-chunk windows only; the streamed seam
        machinery lives in :meth:`map_chunk_sharded` + :meth:`combine`)."""
        from mapreduce_tpu.ops import ngram as ngram_ops

        if self.config.resolved_backend() == "pallas":
            return ngram_ops.ngram_table(chunk, self.n, self.batch_capacity,
                                         chunk_id, self.config)
        gs = ngram_ops.mark_long_spans(
            tok_ops.ngrams(tok_ops.tokenize(chunk), self.n))
        return ngram_ops.gram_table(
            gs, self.batch_capacity, chunk_id, max_pos=chunk.shape[0],
            sort_mode=self.config.sort_mode,
            sort_impl=self.config.sort_impl,
            salt_bits=self.config.resolved_salt_bits,
            radix_geometry=self.config.resolved_radix_geometry)

    # -- exact cross-chunk grams (streamed runs) ----------------------------

    def init_state(self):
        from mapreduce_tpu.ops import ngram as ngram_ops

        if self.n == 1:
            return super().init_state()
        return NGramState(table=table_ops.empty(self.capacity),
                          carry=ngram_ops.empty_carry(self.n))

    def map_chunk_sharded(self, chunk, chunk_id, axis, device_index):
        """Streamed map: per-chunk table + this chunk's seam summary, with
        one small all_gather so every device sees the step's D summaries.
        The summaries are ~5*(n-1) words per chunk — noise next to the
        chunk itself."""
        if self.n == 1:
            return self.map_chunk(chunk, chunk_id)
        from mapreduce_tpu.ops import ngram as ngram_ops

        if self.config.resolved_backend() == "pallas":
            t, summ = ngram_ops.ngram_map_with_summary(
                chunk, self.n, self.batch_capacity, chunk_id, self.config)
        else:
            stream = tok_ops.tokenize(chunk)
            gs = ngram_ops.mark_long_spans(tok_ops.ngrams(stream, self.n))
            t = ngram_ops.gram_table(
                gs, self.batch_capacity, chunk_id, max_pos=chunk.shape[0],
                sort_mode=self.config.sort_mode,
                sort_impl=self.config.sort_impl,
                salt_bits=self.config.resolved_salt_bits,
                radix_geometry=self.config.resolved_radix_geometry)
            summ = ngram_ops.summary_from_stream(stream, chunk_id, self.n)
        gathered = jax.lax.all_gather(summ, axis_name=axis)  # leaves [D, n-1]
        return NGramUpdate(batch=t, summaries=gathered,
                           device_index=device_index)

    def map_chunk_stats_sharded(self, chunk, chunk_id, axis, device_index):
        """Stats-mode map for the gram family: the gram build computes no
        spill/rescue cond on its own (the fused pair-mode stream has no
        compact-window fallback), so the chunk counters carry only the
        batch table's dropped accounting — overlong-poisoned grams — and
        the gauges come off the running table as everywhere else."""
        upd = self.map_chunk_sharded(chunk, chunk_id, axis, device_index)
        tbl = upd.batch if isinstance(upd, NGramUpdate) else upd
        return upd, datastats.map_stats(dropped_tokens=tbl.dropped_count,
                                        dropped_uniques=tbl.dropped_uniques)

    def _stats_table(self, state) -> table_ops.CountTable:
        if isinstance(state, NGramState):
            return state.table
        return super()._stats_table(state)

    def combine(self, state, update):
        if self.n == 1:
            return super().combine(state, update)
        from mapreduce_tpu.ops import ngram as ngram_ops

        d_count = update.summaries.first.kind.shape[0]
        # Prefix carries in global chunk order: prefix[i] = everything before
        # this step's chunk i (state.carry composed with summaries 0..i-1).
        # A trace-time loop of D tiny elementwise folds; the final value is
        # the next step's carry, identical on every device.
        prefix = state.carry
        prefixes = [prefix]
        for i in range(d_count):
            s_i = jax.tree.map(lambda x, i=i: x[i], update.summaries)
            prefix = ngram_ops.compose_carry(prefix, s_i.last)
            prefixes.append(prefix)
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *prefixes[:d_count])
        d = update.device_index.astype(jnp.int32)
        my_prefix = jax.tree.map(lambda x: jnp.take(x, d, axis=0), stacked)
        my_first = jax.tree.map(lambda x: jnp.take(x, d, axis=0),
                                update.summaries.first)
        seam_tbl = ngram_ops.seam_gram_table(my_prefix, my_first, self.n)
        batch = table_ops.merge(update.batch, seam_tbl,
                                capacity=update.batch.capacity)
        table = table_ops.merge(state.table, batch, capacity=self.capacity)
        return NGramState(table=table, carry=prefixes[-1])

    def merge(self, a, b):
        if self.n == 1:
            return super().merge(a, b)
        # Cross-device table reduction; carries are identical on every
        # device after combine (computed from the same gathered summaries),
        # so either operand's is fine.
        return NGramState(
            table=table_ops.merge(a.table, b.table, capacity=self.capacity),
            carry=a.carry)

    def analysis_observables(self, state):
        """graphcheck metadata: compare only the gram table in the merge
        property check.  The seam carry is coordination state — identical
        across devices within a run (every combine sees the same gathered
        summaries), so merge keeping one operand's is correct, but states
        built from different chunks legitimately disagree on it."""
        if self.n == 1 or not isinstance(state, NGramState):
            return state
        return state.table

    def keyrange_merge(self, state, axis) -> table_ops.CountTable:
        """Key-range reduce of the gram table (the carry is spent once
        every chunk's combine has run; only the table crosses devices)."""
        if self.n == 1:
            return super().keyrange_merge(state, axis)
        from mapreduce_tpu.parallel import collectives

        return collectives.key_range_merge(state.table, axis)

    def partial_reset(self, local):
        """Post-partial-merge reset (ISSUE 20 leg 2): the gram table was
        shipped into the resident accumulator, so it returns to empty —
        but the seam carry is CROSS-STEP context (the tail bytes of the
        previous chunk row), which the next step's combine still needs.
        Called per device inside shard_map on the LOCAL state."""
        init = self.init_state()
        if self.n == 1 or not isinstance(local, NGramState):
            return init
        return NGramState(table=init.table, carry=local.carry)

    def on_input_boundary(self, state):
        """Files are independent corpora: grams must not span a file seam.

        Called on the engine's STACKED state (carry leaves [D, n-1]), so the
        reset must preserve shapes (zeros_like, the GrepJob idiom) — a fresh
        empty_carry would collapse the leading device axis and break the
        next step's sharding.
        """
        if self.n == 1:
            return state
        return NGramState(table=state.table,
                          carry=jax.tree.map(jnp.zeros_like, state.carry))

    def finalize(self, state):
        tbl = state.table if isinstance(state, NGramState) \
            else self._plain_table(state)
        return topk_with_snapshot(tbl, self.k) if self.k else tbl

    def identity(self) -> str:
        # Resuming a bigram run's snapshot as a trigram run (same shapes!)
        # would mix gram orders: n is part of the job identity.
        return f"ngram{self.n}" + (f"-top{self.k}" if self.k else "")


class SketchedState(NamedTuple):
    """Count table + HyperLogLog registers (a pytree; engine/collective
    machinery treats it like any other mergeable accumulator)."""

    table: table_ops.CountTable
    registers: jax.Array  # uint32[2**p]


class FreqSketchedState(NamedTuple):
    """Count table + Count-Min Sketch (a pytree)."""

    table: table_ops.CountTable
    cms: jax.Array  # uint32[depth, width]


class BatchedSketchState(NamedTuple):
    """Sketch state with a pending-update buffer (sketch_flush_every > 1).

    Per-chunk key batches are staged into ``pend_*`` with a cheap
    ``dynamic_update_slice`` and scattered into the sketch once every K
    steps — TPU scatters carry a large fixed cost regardless of size
    (BENCHMARKS.md), so one scatter of K batches beats K scatters.
    ``pend_cnt`` doubles as the validity mask: flushed slots are zeroed, so
    re-flushing (e.g. at every collective merge level) is a masked no-op
    for both the idempotent HLL max and the additive CMS.
    """

    table: table_ops.CountTable
    sketch: jax.Array
    pend_hi: jax.Array  # uint32[K * batch_capacity]
    pend_lo: jax.Array
    pend_cnt: jax.Array
    cursor: jax.Array  # uint32 scalar: batches staged since last flush


class _SketchComposedJob:
    """Compose any WordCount-family job with a mergeable sketch.

    Shared TPU shape of all sketch families: the sketch updates from the
    *deduplicated* per-chunk batch table (capacity-sized device ops, never
    stream-sized), and merges with an associative+commutative monoid that
    rides the same collectives as the table.  Envelope: tokens spilled past
    per-chunk batch extraction miss the sketch too (accounted in
    ``dropped_count``).

    With ``config.sketch_flush_every = K > 1`` the per-step scatter is
    batched through :class:`BatchedSketchState` (flushed at merges and in
    finalize, so results are bit-identical to K=1); ``finalize`` always
    returns the plain ``state_cls`` so downstream result handling never
    sees the buffer.

    Subclasses set ``state_cls`` (a ``(table, sketch)`` NamedTuple) and the
    three sketch ops.
    """

    state_cls: type

    def __init__(self, base: WordCountJob):
        self.base = base
        self.config = base.config
        self.flush_every = base.config.sketch_flush_every

    def _empty(self) -> jax.Array:
        raise NotImplementedError

    def _update_arrays(self, sk: jax.Array, key_hi, key_lo, counts) -> jax.Array:
        raise NotImplementedError

    def _update(self, sk: jax.Array, update: table_ops.CountTable) -> jax.Array:
        return self._update_arrays(sk, update.key_hi, update.key_lo, update.count)

    def _merge(self, a: jax.Array, b: jax.Array) -> jax.Array:
        raise NotImplementedError

    def init_state(self):
        if self.flush_every == 1:
            return self.state_cls(self.base.init_state(), self._empty())
        n = self.flush_every * self.base.batch_capacity
        z = jnp.zeros((n,), jnp.uint32)
        return BatchedSketchState(self.base.init_state(), self._empty(),
                                  z, jnp.array(z), jnp.array(z),
                                  jnp.zeros((), jnp.uint32))

    @staticmethod
    def _folded(update):
        """Fold a SeamedUpdate before sketching: the sketch updates from
        the per-chunk batch table, so a deferred seam table would silently
        drop seam-first words from the HLL/CMS envelope.  Sketched runs
        pay the pairwise seam merge the plain path optimized away."""
        if isinstance(update, SeamedUpdate):
            return table_ops.merge(update.batch, update.seam,
                                   capacity=update.batch.capacity)
        return update

    def map_chunk(self, chunk, chunk_id) -> table_ops.CountTable:
        return self._folded(self.base.map_chunk(chunk, chunk_id))

    def map_chunk_sharded(self, chunk, chunk_id, axis, device_index):
        """Forward the base job's axis-aware map (n-grams' exact seam
        machinery) so sketch composition doesn't silently disable it."""
        fn = getattr(self.base, "map_chunk_sharded", None)
        if fn is not None:
            return self._folded(fn(chunk, chunk_id, axis, device_index))
        return self._folded(self.base.map_chunk(chunk, chunk_id))

    def on_input_boundary(self, state):
        """Forward the base job's file-boundary hook (n-gram carry reset)."""
        hook = getattr(self.base, "on_input_boundary", None)
        if hook is None:
            return state
        return state._replace(table=hook(state.table))

    # -- data-plane telemetry (ISSUE 8): forward the base job's stats ----

    @property
    def data_stats_supported(self) -> bool:
        return datastats.supports(self.base)

    def map_chunk_stats_sharded(self, chunk, chunk_id, axis, device_index):
        upd, stats = self.base.map_chunk_stats_sharded(
            chunk, chunk_id, axis, device_index)
        return self._folded(upd), stats

    def state_stats(self, state, stats):
        base_state = state.table if isinstance(state, BatchedSketchState) \
            else state[0]
        return self.base.state_stats(base_state, stats)

    @staticmethod
    def _batch_of(update) -> table_ops.CountTable:
        """The plain CountTable inside an update (n-gram updates bundle it
        with seam summaries).  Sketch envelope: cross-chunk seam grams
        (< n per step) miss the sketch, like spilled batch rows do."""
        return update if isinstance(update, table_ops.CountTable) else update.batch

    def combine(self, state, update):
        batch = self._batch_of(update)
        if self.flush_every == 1:
            return self.state_cls(self.base.combine(state[0], update),
                                  self._update(state[1], batch))
        table = self.base.combine(state.table, update)
        b = batch.key_hi.shape[0]
        off = (state.cursor % jnp.uint32(self.flush_every)) * jnp.uint32(b)
        off = off.astype(jnp.int32)
        pend_hi = jax.lax.dynamic_update_slice(state.pend_hi, batch.key_hi, (off,))
        pend_lo = jax.lax.dynamic_update_slice(state.pend_lo, batch.key_lo, (off,))
        pend_cnt = jax.lax.dynamic_update_slice(state.pend_cnt, batch.count, (off,))
        cursor = state.cursor + jnp.uint32(1)

        def flush(_):
            sk = self._update_arrays(state.sketch, pend_hi, pend_lo, pend_cnt)
            return sk, jnp.zeros_like(pend_cnt), jnp.zeros((), jnp.uint32)

        def keep(_):
            return state.sketch, pend_cnt, cursor

        sk, pend_cnt, cursor = jax.lax.cond(
            cursor >= jnp.uint32(self.flush_every), flush, keep, operand=None)
        return BatchedSketchState(table, sk, pend_hi, pend_lo, pend_cnt, cursor)

    def _flushed(self, st: BatchedSketchState) -> BatchedSketchState:
        """Fold any staged rows into the sketch (masked no-op when empty)."""
        sk = self._update_arrays(st.sketch, st.pend_hi, st.pend_lo, st.pend_cnt)
        return BatchedSketchState(st.table, sk, st.pend_hi, st.pend_lo,
                                  jnp.zeros_like(st.pend_cnt),
                                  jnp.zeros((), jnp.uint32))

    def merge(self, a, b):
        if self.flush_every == 1:
            return self.state_cls(self.base.merge(a[0], b[0]),
                                  self._merge(a[1], b[1]))
        fa, fb = self._flushed(a), self._flushed(b)
        return BatchedSketchState(
            self.base.merge(fa.table, fb.table),
            self._merge(fa.sketch, fb.sketch),
            fa.pend_hi, fa.pend_lo, fa.pend_cnt, fa.cursor)

    def keyrange_merge(self, state, axis):
        """Compose the base job's key-range table reduce with the sketch's
        own monoid over the axis (tree-merge of the small sketch array —
        its cost is noise next to the table exchange)."""
        from mapreduce_tpu.parallel import collectives

        if self.flush_every == 1:
            table_state, sketch = state[0], state[1]
        else:
            st = self._flushed(state)
            table_state, sketch = st.table, st.sketch
        return self.state_cls(
            self.base.keyrange_merge(table_state, axis),
            collectives.tree_merge(sketch, self._merge, axis))

    def keyrange_result_merge(self, a, b):
        """Merge two keyrange results (``state_cls(plain_table, sketch)``
        pairs): the base job's result merge on the table, the sketch's
        own monoid on the sketch — the hier-kr-tree outer-leg / overlap-
        accumulator fold."""
        return self.state_cls(self.base.keyrange_result_merge(a[0], b[0]),
                              self._merge(a[1], b[1]))

    def finalize(self, state):
        if self.flush_every == 1:
            return self.state_cls(self.base.finalize(state[0]), state[1])
        if isinstance(state, BatchedSketchState):
            st = self._flushed(state)
            # Downstream (executor result unwrapping, checkpoint-of-results)
            # sees the same plain state shape as unbatched runs.
            return self.state_cls(self.base.finalize(st.table), st.sketch)
        # Already a plain state_cls (the keyrange hook returns one).
        return self.state_cls(self.base.finalize(state[0]), state[1])

    def identity(self) -> str:
        # flush_every changes state SHAPE but not results; shapes are
        # validated against the checkpoint leaves, so identity stays
        # cadence-independent.
        return f"{type(self).__name__.lower()}({self.base.identity()})"


class FreqSketchedWordCountJob(_SketchComposedJob):
    """Wrap any WordCount-family job with a Count-Min frequency sketch.

    Where :class:`SketchedWordCountJob` keeps the *distinct count* honest past
    table capacity, this keeps *per-word frequencies* queryable: the sketch's
    row-min upper-bounds any key's true count (error <= total/width per row
    w.h.p.), including words the exact table spilled.  Query host-side with
    :func:`mapreduce_tpu.ops.sketch.cms_query` — any word (or n-gram span),
    no device trip.
    """

    state_cls = FreqSketchedState

    def __init__(self, base: WordCountJob, depth: int = sketch_ops.CMS_DEPTH,
                 width_log2: int = sketch_ops.CMS_WIDTH_LOG2):
        super().__init__(base)
        self.depth = depth
        self.width_log2 = width_log2

    def _empty(self):
        return sketch_ops.cms_empty(self.depth, self.width_log2)

    def _update_arrays(self, sk, key_hi, key_lo, counts):
        return sketch_ops.cms_update(sk, key_hi, key_lo, counts)

    def _merge(self, a, b):
        return sketch_ops.cms_merge(a, b)


class SketchedWordCountJob(_SketchComposedJob):
    """Wrap any WordCount-family job with a distinct-count sketch.

    The table's ``distinct`` degrades to an upper bound once keys spill past
    capacity (see WordCountResult); the HyperLogLog keeps an accurate
    distinct estimate at any scale.  Register updates are a capacity-sized
    scatter-max (the TPU cost model: scatter cost scales with input length);
    the merge is elementwise ``maximum``, idempotent, so cross-chunk
    duplicate keys are harmless.
    """

    state_cls = SketchedState

    def __init__(self, base: WordCountJob, precision: int = sketch_ops.DEFAULT_PRECISION):
        super().__init__(base)
        self.precision = precision

    def _empty(self):
        return sketch_ops.empty(self.precision)

    def _update_arrays(self, sk, key_hi, key_lo, counts):
        return sketch_ops.update_from_keys(sk, key_hi, key_lo, counts > 0)

    def _merge(self, a, b):
        return sketch_ops.merge(a, b)
