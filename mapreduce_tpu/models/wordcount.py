"""WordCount: the flagship model of the framework.

End-to-end equivalent of the reference program (tokenize ``main.cu:187-202``
-> map ``main.cu:37-54`` -> reduce ``main.cu:69-108`` -> report
``main.cu:212-218``), rebuilt TPU-first: bytes go to the device as a padded
uint8 tensor, tokenization/hashing/counting happen in one jitted XLA program,
and only the small count table returns to the host, where exact strings are
recovered from first-occurrence positions.

This module is the simple single-buffer path used by the CLI and tests; the
streaming / multi-chip path lives in :mod:`mapreduce_tpu.runtime.executor` and
:mod:`mapreduce_tpu.parallel.mapreduce`.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import numpy as np

from mapreduce_tpu.config import Config, DEFAULT_CONFIG
from mapreduce_tpu.ops import sketch as sketch_ops
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.ops import tokenize as tok_ops


@dataclasses.dataclass(frozen=True)
class WordCountResult:
    """Host-side result with recovered strings, insertion-ordered."""

    words: list[bytes]  # reported words, by first occurrence
    counts: list[int]  # parallel to words
    total: int  # total tokens (includes any spilled/dropped ones; exact)
    distinct: int  # distinct words: exact when dropped_uniques == 0, else an
    #   upper bound (len(words) + dropped_uniques)
    dropped_uniques: int  # upper bound on distinct words spilled past table
    #   capacity or dropped as overlong; loose because cross-chunk merges sum
    #   per-chunk bounds and the pallas backend cannot hash (hence cannot
    #   dedupe) tokens longer than its lookback window
    dropped_count: int  # tokens belonging to spilled/dropped words (exact)
    distinct_estimate: float | None = None  # HLL estimate (~0.8% err @ p=14);
    #   populated by sketched runs — unlike ``distinct`` it stays accurate
    #   past table capacity

    def as_dict(self) -> dict[bytes, int]:
        return dict(zip(self.words, self.counts))


def apply_top_k(result: WordCountResult, k: int) -> WordCountResult:
    """Restrict a result to its k most frequent words (host-side, stable).

    The single owner of top-k reordering for host results; ``total`` keeps
    counting every token, matching CountTable.total_count() semantics.
    """
    order = sorted(range(len(result.words)), key=lambda i: -result.counts[i])[:k]
    return dataclasses.replace(
        result,
        words=[result.words[i] for i in order],
        counts=[result.counts[i] for i in order],
    )


def _map_stream(chunk: jax.Array, config: Config, capacity: int,
                pos_hi: jax.Array | int = 0) -> table_ops.CountTable:
    """Tokenize one buffer with the configured backend and build its table."""
    if config.resolved_backend() == "pallas":
        from mapreduce_tpu.ops.pallas import tokenize as pallas_tok

        # Consume the bulk and seam streams separately: building two tables
        # and merging the (tiny) seam one avoids concatenating a few KB onto
        # multi-hundred-MB column planes (full-copy per plane).
        col, seam, overlong = pallas_tok.tokenize_split(
            chunk, max_token_bytes=config.pallas_max_token)
        bounds = dict(max_token_bytes=config.pallas_max_token,
                      max_pos=int(chunk.shape[0]))
        t = table_ops.from_stream(col, capacity, pos_hi=pos_hi, **bounds)
        seam_cap = min(seam.key_hi.shape[0], capacity)
        t = table_ops.merge(
            t, table_ops.from_stream(seam, seam_cap, pos_hi=pos_hi, **bounds),
            capacity=capacity)
        # ``overlong`` counts occurrences.  For dropped_count (occurrences)
        # that is exact; for dropped_uniques it is the only available upper
        # bound — overlong tokens leave the kernel unhashed, so distinct
        # overlong words cannot be deduplicated on device.
        return t._replace(dropped_uniques=t.dropped_uniques + overlong,
                          dropped_count=t.dropped_count + overlong)
    stream = tok_ops.tokenize(chunk)
    return table_ops.from_stream(stream, capacity, pos_hi=pos_hi)


@functools.partial(jax.jit, static_argnames=("capacity", "config"))
def _count_step(data: jax.Array, capacity: int, config: Config) -> table_ops.CountTable:
    return _map_stream(data, config, capacity)


def count_table(data: bytes | np.ndarray, config: Config = DEFAULT_CONFIG) -> table_ops.CountTable:
    """Run the device pipeline over one in-memory buffer, return the table."""
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
    min_len = config.pallas_min_chunk if config.resolved_backend() == "pallas" else 128
    padded_len = max(min_len, -(-buf.shape[0] // 128) * 128)
    padded = tok_ops.pad_to(buf, padded_len)
    return _count_step(jax.device_put(padded), config.table_capacity, config)


def recover_result(tbl: table_ops.CountTable, source: bytes) -> WordCountResult:
    """Host-side string recovery from a single-buffer table (pos_hi == 0)."""
    count = np.asarray(tbl.count)
    valid = count > 0
    pos = np.asarray(tbl.pos_lo)[valid]
    length = np.asarray(tbl.length)[valid]
    cnt = count[valid]
    order = np.argsort(pos, kind="stable")
    words = [bytes(source[int(p): int(p) + int(l)]) for p, l in zip(pos[order], length[order])]
    dropped_uniques = int(np.asarray(tbl.dropped_uniques))
    return WordCountResult(
        words=words,
        counts=[int(c) for c in cnt[order]],
        total=int(np.asarray(tbl.total_count())),
        distinct=len(words) + dropped_uniques,
        dropped_uniques=dropped_uniques,
        dropped_count=int(np.asarray(tbl.dropped_count)),
    )


def count_words(data: bytes, config: Config = DEFAULT_CONFIG) -> WordCountResult:
    """The one-call API: exact word counts for an in-memory buffer."""
    return recover_result(count_table(data, config), data)


class WordCountJob:
    """WordCount as a :class:`mapreduce_tpu.parallel.mapreduce.MapReduceJob`.

    The flagship job: per-device accumulation into a CountTable, associative
    table merge as the global reduction.  ``chunk_id`` (step * n_devices +
    device) becomes ``pos_hi`` so first-occurrence order is global file order
    and the executor can recover exact strings from (chunk_id, pos_lo, len).
    """

    def __init__(self, config: Config = DEFAULT_CONFIG):
        self.config = config
        self.capacity = config.table_capacity
        self.batch_capacity = config.batch_uniques

    def init_state(self) -> table_ops.CountTable:
        return table_ops.empty(self.capacity)

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array) -> table_ops.CountTable:
        return _map_stream(chunk, self.config, self.batch_capacity, pos_hi=chunk_id)

    def combine(self, state, update):
        return table_ops.merge(state, update, capacity=self.capacity)

    def merge(self, a, b):
        return table_ops.merge(a, b, capacity=self.capacity)

    def finalize(self, state):
        return state


class TopKWordCountJob(WordCountJob):
    """WordCount whose device-side finalize keeps only the k most frequent
    words (the Common-Crawl top-k benchmark config, BASELINE.md)."""

    def __init__(self, k: int, config: Config = DEFAULT_CONFIG):
        super().__init__(config)
        self.k = k

    def finalize(self, state):
        return table_ops.top_k(state, self.k)


class SketchedState(NamedTuple):
    """Count table + HyperLogLog registers (a pytree; engine/collective
    machinery treats it like any other mergeable accumulator)."""

    table: table_ops.CountTable
    registers: jax.Array  # uint32[2**p]


class SketchedWordCountJob:
    """Wrap any WordCount-family job with a distinct-count sketch.

    The table's ``distinct`` degrades to an upper bound once keys spill past
    capacity (see WordCountResult); the sketch keeps an accurate distinct
    estimate at any scale.  Registers update from the *deduplicated* batch
    table each step — a capacity-sized scatter-max, never a stream-sized one
    (the TPU cost model: scatter cost scales with input length) — and merge
    with elementwise ``maximum``, an idempotent monoid that rides the same
    collectives as the table.

    Envelope: the sketch sees the keys that survive per-chunk batch
    extraction (``Config.batch_uniques`` distinct keys per chunk); a single
    chunk holding more uniques than that spills the excess from table and
    sketch alike.  Size batch capacity to per-chunk vocabulary as usual.
    """

    def __init__(self, base: WordCountJob, precision: int = sketch_ops.DEFAULT_PRECISION):
        self.base = base
        self.config = base.config
        self.precision = precision

    def init_state(self) -> SketchedState:
        return SketchedState(self.base.init_state(), sketch_ops.empty(self.precision))

    def map_chunk(self, chunk, chunk_id) -> table_ops.CountTable:
        return self.base.map_chunk(chunk, chunk_id)

    def combine(self, state: SketchedState, update: table_ops.CountTable) -> SketchedState:
        regs = sketch_ops.update_from_keys(
            state.registers, update.key_hi, update.key_lo, update.count > 0)
        return SketchedState(self.base.combine(state.table, update), regs)

    def merge(self, a: SketchedState, b: SketchedState) -> SketchedState:
        return SketchedState(self.base.merge(a.table, b.table),
                             sketch_ops.merge(a.registers, b.registers))

    def finalize(self, state: SketchedState) -> SketchedState:
        return SketchedState(self.base.finalize(state.table), state.registers)
