"""mapreduce_tpu: a TPU-native MapReduce framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the reference CUDA
MapReduce word counter (``zimisoho/cuda-mapreduce``, see SURVEY.md): device-side
tokenization via segmented associative scans, sort/segment-sum parallel
reduction into mergeable count tables, collective global aggregation over a
``jax.sharding.Mesh``, a streaming sharded ingest pipeline, and a generic
map/combine/merge MapReduce engine — replacing, respectively, the reference's
host tokenizer (``main.cu:187-202``), per-thread map kernel (``main.cu:109``),
single-thread serial reduce (``main.cu:119-123``), ``cudaMemcpy`` transport
(``main.cu:143-161``), and ``runMapReduce`` orchestrator (``main.cu:133``).
"""

from mapreduce_tpu.config import Config, DEFAULT_CONFIG, SMALL_CONFIG
from mapreduce_tpu.version import __version__

__all__ = ["Config", "DEFAULT_CONFIG", "SMALL_CONFIG", "__version__"]
