"""mapreduce_tpu: a TPU-native MapReduce framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the reference CUDA
MapReduce word counter (``zimisoho/cuda-mapreduce``, see SURVEY.md): device-side
tokenization via segmented associative scans, sort/segment-sum parallel
reduction into mergeable count tables, collective global aggregation over a
``jax.sharding.Mesh``, a streaming sharded ingest pipeline, and a generic
map/combine/merge MapReduce engine — replacing, respectively, the reference's
host tokenizer (``main.cu:187-202``), per-thread map kernel (``main.cu:109``),
single-thread serial reduce (``main.cu:119-123``), ``cudaMemcpy`` transport
(``main.cu:143-161``), and ``runMapReduce`` orchestrator (``main.cu:133``).
"""

from mapreduce_tpu.config import Config, DEFAULT_CONFIG, SMALL_CONFIG
from mapreduce_tpu.version import __version__


def count_words(data: bytes, config: Config = DEFAULT_CONFIG):
    """Top-level convenience: exact word counts for an in-memory buffer.
    See :func:`mapreduce_tpu.models.wordcount.count_words`."""
    from mapreduce_tpu.models import wordcount

    return wordcount.count_words(data, config)


def count_file(path, **kw):
    """Top-level convenience: streaming sharded word count over file(s).
    See :func:`mapreduce_tpu.runtime.executor.count_file`."""
    from mapreduce_tpu.runtime import executor

    return executor.count_file(path, **kw)


__all__ = ["Config", "DEFAULT_CONFIG", "SMALL_CONFIG", "__version__",
           "count_words", "count_file"]
