"""Tracing utilities: MapReduceJob hooks -> jaxprs, plus jaxpr walkers.

Everything graphcheck knows it learns from two sources built here:

* **per-hook jaxprs** under abstract inputs (``jax.make_jaxpr`` with
  ``ShapeDtypeStruct`` arguments) — no device work, no data;
* **engine programs**: the real jitted SPMD ``step``/``finish`` the
  :class:`~mapreduce_tpu.parallel.mapreduce.Engine` would dispatch, traced
  over the actual mesh — this is where ``shard_map`` bindings, collectives,
  and callbacks appear with their axis names attached.

A hook that cannot be traced (raises at trace time) is recorded as a
:class:`TraceFailure` value instead of propagating: passes decide whether
that is itself a finding (the sharding lint treats an unbound-axis-name
trace error as the mismatched-PartitionSpec finding it usually is).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator

import jax
import numpy as np

_ClosedJaxpr = jax.core.ClosedJaxpr
_Jaxpr = jax.core.Jaxpr


@dataclasses.dataclass(frozen=True)
class TraceFailure:
    """A hook that raised during tracing: the exception, preserved as data."""

    hook: str
    error_type: str
    error: str

    @classmethod
    def of(cls, hook: str, e: Exception) -> "TraceFailure":
        return cls(hook=hook, error_type=type(e).__name__, error=str(e))


def _chunk_bytes_for(job: Any, default: int = 1 << 10) -> int:
    """A chunk size the job's backend accepts (pallas needs seam windows)."""
    config = getattr(job, "config", None)
    if config is None:
        return default
    n = min(int(config.chunk_bytes), 1 << 16)
    if getattr(config, "backend", None) == "pallas":
        n = max(n, config.pallas_min_chunk)
    return max(128, (n // 128) * 128)


def state_shape(job: Any):
    """Abstract ``init_state`` pytree (ShapeDtypeStruct leaves)."""
    try:
        return jax.eval_shape(job.init_state)
    except Exception as e:
        return TraceFailure.of("init_state", e)


def abstract_chunk(job: Any):
    n = _chunk_bytes_for(job)
    return jax.ShapeDtypeStruct((n,), np.uint8)


def trace_hooks(job: Any, chunk_bytes: int | None = None) -> dict:
    """Trace each protocol hook to a ClosedJaxpr under abstract inputs.

    Returns ``{hook: ClosedJaxpr | TraceFailure}`` for ``init_state``,
    ``map_chunk``, ``combine``, ``merge``, ``finalize``.  ``combine`` is
    traced against the abstract update ``map_chunk`` produces; axis-aware
    maps (``map_chunk_sharded``) need a bound mesh axis and are traced as
    part of the engine step instead (:func:`trace_engine`).
    """
    n = chunk_bytes if chunk_bytes is not None else _chunk_bytes_for(job)
    n = max(128, (int(n) // 128) * 128)
    chunk = jax.ShapeDtypeStruct((n,), np.uint8)
    cid = jax.ShapeDtypeStruct((), np.uint32)
    out: dict[str, Any] = {}

    def attempt(hook, fn, *args):
        try:
            out[hook] = jax.make_jaxpr(fn)(*args)
        except Exception as e:
            out[hook] = TraceFailure.of(hook, e)

    attempt("init_state", lambda: job.init_state())
    st = state_shape(job)
    if isinstance(st, TraceFailure):
        for hook in ("map_chunk", "combine", "merge", "finalize"):
            out[hook] = TraceFailure.of(hook, RuntimeError(
                f"init_state untraceable: {st.error}"))
        return out
    attempt("map_chunk", lambda c, i: job.map_chunk(c, i), chunk, cid)
    try:
        upd = jax.eval_shape(lambda c, i: job.map_chunk(c, i), chunk, cid)
    except Exception as e:
        upd = TraceFailure.of("map_chunk", e)
    if isinstance(upd, TraceFailure):
        out["combine"] = TraceFailure.of("combine", RuntimeError(
            f"map_chunk untraceable: {upd.error}"))
    else:
        attempt("combine", lambda s, u: job.combine(s, u), st, upd)
    attempt("merge", lambda a, b: job.merge(a, b), st, st)
    attempt("finalize", lambda s: job.finalize(s), st)
    return out


def trace_engine(job: Any, mesh) -> dict:
    """Trace the Engine's jitted ``step`` and ``finish`` SPMD programs.

    These are the programs that actually hit the device: ``shard_map``
    bindings, collectives with axis names, and anything a hook smuggles in
    (callbacks, transfers) are all visible here.  Returns
    ``{'step'|'finish': ClosedJaxpr | TraceFailure}``.
    """
    from mapreduce_tpu.parallel.mapreduce import Engine

    out: dict[str, Any] = {}
    axes = tuple(mesh.axis_names)
    try:
        # ``analysis_data_stats`` (registry: the *_telemetry models): trace
        # the INSTRUMENTED step — data-plane counters returned next to the
        # state (ISSUE 8) — so the cost/host-sync passes certify exactly
        # the program telemetered runs dispatch.  ``analysis_merge_strategy``
        # (the *_fleet twins) likewise selects the Engine merge the traced
        # finish program builds — keyrange twins certify the all_to_all
        # program, not the default butterfly.
        eng = Engine(job, mesh, axis=axes if len(axes) > 1 else axes[0],
                     data_stats=getattr(job, "analysis_data_stats", False),
                     merge_strategy=getattr(job, "analysis_merge_strategy",
                                            "tree"))
    except Exception as e:
        f = TraceFailure.of("engine", e)
        return {"step": f, "finish": f}
    st = state_shape(job)
    if isinstance(st, TraceFailure):
        f = TraceFailure.of("engine", RuntimeError(
            f"init_state untraceable: {st.error}"))
        return {"step": f, "finish": f}
    n_dev = eng.n_devices
    stacked = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((n_dev,) + x.shape, x.dtype), st)
    chunks = jax.ShapeDtypeStruct((n_dev, _chunk_bytes_for(job)), np.uint8)
    step = jax.ShapeDtypeStruct((), np.uint32)
    try:
        out["step"] = jax.make_jaxpr(eng._build_step())(stacked, chunks, step)
    except Exception as e:
        out["step"] = TraceFailure.of("step", e)
    try:
        out["finish"] = jax.make_jaxpr(eng._build_finish())(stacked)
    except Exception as e:
        out["finish"] = TraceFailure.of("finish", e)
    return out


def sample_states(job: Any, n: int = 3, chunk_bytes: int = 1 << 10,
                  seed: int = 20260803) -> tuple[list, TraceFailure | None]:
    """Concrete, *reachable* states for randomized property checks.

    Each state is ``init_state`` folded with one random text chunk through
    a 1-device engine step (so axis-aware maps and their collectives run
    too, over an axis of size one).  Reachability matters: merge is only
    required to be associative+commutative on states the map/combine
    machinery can actually produce — random bit patterns would violate
    table invariants and prove nothing.  Returns ``(states, failure)``:
    host (numpy-leaf) pytrees and ``None``, or ``([], TraceFailure)`` when
    the job cannot execute on this host — the failure is preserved as data
    so the property-check-skipped finding can say WHY.
    """
    from mapreduce_tpu.parallel.mapreduce import Engine
    from mapreduce_tpu.parallel.mesh import data_mesh

    rng = np.random.default_rng(seed)
    cb = max(128, (int(chunk_bytes) // 128) * 128)
    try:
        mesh = data_mesh(1)
        eng = Engine(job, mesh, axis=mesh.axis_names[0])
        states = []
        for i in range(n):
            chunk = random_text(rng, cb)
            st = eng.step(eng.init_states(), chunk[None, :], i)
            states.append(jax.tree.map(lambda x: np.asarray(x)[0], st))
        return states, None
    except Exception as e:
        return [], TraceFailure.of("sample_states", e)


def random_text(rng: np.random.Generator, n_bytes: int) -> np.ndarray:
    """Random word-ish bytes (lowercase tokens, space/newline separated),
    with a random NUL-padded tail — chunks of a real stream end padded,
    and unequal payload sizes keep sampled states distinguishable (a
    property check on three identical states proves nothing)."""
    out = np.full((n_bytes,), 0x20, dtype=np.uint8)
    i = 0
    while i < n_bytes:
        length = int(rng.integers(1, 9))
        word = rng.integers(97, 123, size=length, dtype=np.uint8)
        end = min(i + length, n_bytes)
        out[i:end] = word[: end - i]
        i = end + 1
        if i - 1 < n_bytes and rng.random() < 0.2:
            out[i - 1] = 0x0A
    tail = int(rng.integers(0, max(n_bytes // 4, 2)))
    if tail:
        out[n_bytes - tail:] = 0
    return out


# -- jaxpr walking ----------------------------------------------------------


def eqn_subjaxprs(eqn) -> list:
    """Every ClosedJaxpr/Jaxpr nested in an equation's params (pjit bodies,
    cond branches, scan/while bodies, shard_map bodies, custom calls)."""
    out = []
    for v in eqn.params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for x in items:
            if isinstance(x, (_ClosedJaxpr, _Jaxpr)):
                out.append(x)
    return out


def iter_eqns(jaxpr, bound_axes: frozenset = frozenset()) -> Iterator:
    """Yield ``(eqn, bound_axes)`` over a jaxpr and every nested sub-jaxpr.

    ``bound_axes`` is the set of mesh axis names bound by enclosing
    ``shard_map`` scopes — what collectives inside may legally reduce over.
    """
    j = jaxpr.jaxpr if isinstance(jaxpr, _ClosedJaxpr) else jaxpr
    for eqn in j.eqns:
        yield eqn, bound_axes
        sub_axes = bound_axes
        if eqn.primitive.name == "shard_map":
            mesh = eqn.params.get("mesh")
            names = tuple(getattr(mesh, "axis_names", ()) or ())
            sub_axes = bound_axes | frozenset(names)
        for sub in eqn_subjaxprs(eqn):
            yield from iter_eqns(sub, sub_axes)


def collect_primitives(jaxpr) -> set[str]:
    """All primitive names appearing anywhere in a jaxpr (recursive)."""
    return {eqn.primitive.name for eqn, _ in iter_eqns(jaxpr)}


def eqn_axis_names(eqn) -> list[str]:
    """Mesh axis names a collective equation operates over (if any)."""
    names: list[str] = []
    for key in ("axis_name", "axes"):
        if key in eqn.params:
            v = eqn.params[key]
            items = v if isinstance(v, (tuple, list)) else (v,)
            names.extend(x for x in items if isinstance(x, str))
    return names


def eqn_location(eqn) -> str:
    """Human-oriented source location of an equation: the innermost frame
    OUTSIDE jax itself (jax internals would otherwise win every time)."""
    src = getattr(eqn, "source_info", None)
    try:
        frames = list(src.traceback.frames) if src and src.traceback else []
        import os

        jax_dir = os.sep + "jax" + os.sep
        user = [f for f in frames
                if jax_dir not in getattr(f, "file_name", "")]
        frame = (user or frames or [None])[0]
        if frame is not None:
            name = os.path.basename(getattr(frame, "file_name", "?"))
            line = getattr(frame, "start_line",
                           getattr(frame, "line_num", "?"))
            return f"{eqn.primitive.name} @ {name}:{line}"
    except Exception:
        pass
    return eqn.primitive.name


# -- state-leaf walking -----------------------------------------------------


def named_leaves(tree: Any, prefix: str = "state") -> list[tuple[str, Any]]:
    """Flatten a pytree to ``(dotted.path, leaf)`` pairs, preserving
    NamedTuple field names (jax's keypath API reduces namedtuples to
    positional indices, which the overflow lint's lane-pair matching
    needs names for)."""
    out: list[tuple[str, Any]] = []

    def rec(x, path):
        if isinstance(x, tuple) and hasattr(x, "_fields"):
            for name in x._fields:
                rec(getattr(x, name), f"{path}.{name}")
        elif isinstance(x, dict):
            for k in sorted(x):
                rec(x[k], f"{path}[{k!r}]")
        elif isinstance(x, (tuple, list)):
            for i, v in enumerate(x):
                rec(v, f"{path}[{i}]")
        else:
            out.append((path, x))

    rec(tree, prefix)
    return out
