"""Pallas kernel bindings extracted from traced jaxprs.

The vmem and kernel-race passes both need the same view of every
``pallas_call`` equation reachable from a traced program: which kernel it
is, its grid, every ref's block shape / dtype / memory space, which grid
iterations revisit the same block (the index map evaluated over the whole
grid), the scratch shapes, and the kernel body jaxpr itself.  This module
builds that view once (:func:`collect_pallas_calls`) so the passes stay
pure policy.

Everything here reads public-enough jax internals (``GridMapping`` /
``BlockMapping`` from ``jax._src.pallas.core``) *defensively*: a missing
attribute degrades to ``None``/unknown and the passes downgrade their
findings accordingly, rather than crashing the pipeline on a jax bump.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import numpy as np

from mapreduce_tpu.analysis import trace

# Revisit detection enumerates the grid; anything larger is reported as
# unverified rather than stalling analysis (production grids reach ~10^3,
# analysis-config grids are single digits).
MAX_GRID_ENUM = 4096


@dataclasses.dataclass(frozen=True)
class RefInfo:
    """One kernel operand ref: an input/output block or a scratch buffer."""

    role: str  # "in" | "out" | "scratch"
    index: int  # position within its role
    block_shape: tuple  # block shape (scratch: full shape)
    dtype: Any  # numpy dtype of the buffer
    memory_space: str  # "vmem" | "smem" | "any" | "?"
    array_shape: Optional[tuple]  # full HBM-side array shape (None: scratch)
    revisited: Optional[bool]  # same block touched by >1 grid iteration
    # (None = could not be determined: dynamic grid, enum bound exceeded)

    @property
    def block_bytes(self) -> int:
        return int(math.prod(self.block_shape) * self.dtype.itemsize)

    @property
    def array_bytes(self) -> int:
        if self.array_shape is None:
            return 0
        return int(math.prod(self.array_shape) * self.dtype.itemsize)


@dataclasses.dataclass(frozen=True)
class PallasCallInfo:
    """One pallas_call equation, digested for the analysis passes."""

    kernel_name: str  # e.g. "_tokenize_kernel"
    src: str  # "name at file:line" (from name_and_src_info)
    program: str  # which traced program it was found in (step/finish/...)
    grid: tuple
    refs: tuple  # RefInfo, kernel-argument order: ins, outs, scratch
    kernel_jaxpr: Any  # the kernel body Jaxpr (refs are its invars)
    vmem_limit_bytes: Optional[int]  # mosaic compiler-params override
    dimension_semantics: Any  # mosaic grid-parallelism declaration
    enclosing_has_cond: bool  # a cond primitive exists in the same program

    @property
    def ins(self) -> tuple:
        return tuple(r for r in self.refs if r.role == "in")

    @property
    def outs(self) -> tuple:
        return tuple(r for r in self.refs if r.role == "out")

    @property
    def scratch(self) -> tuple:
        return tuple(r for r in self.refs if r.role == "scratch")

    def signature(self) -> tuple:
        """Dedup key: the same kernel binding traced into several branches
        (spill-fallback conds) should be certified once."""
        return (self.kernel_name, self.grid,
                tuple((r.role, r.block_shape, str(r.dtype), r.memory_space)
                      for r in self.refs))


def _memory_space_of(aval) -> str:
    ms = getattr(aval, "memory_space", None)
    if ms is None:
        return "?"
    s = str(ms).lower()
    for known in ("vmem", "smem", "sem", "any"):
        if known in s:
            return known
    return s or "?"


def _eval_index_map(bm, idx: tuple) -> Optional[tuple]:
    imj = getattr(bm, "index_map_jaxpr", None)
    if imj is None:
        return None
    try:
        out = jax.core.eval_jaxpr(imj.jaxpr, imj.consts, *idx)
        return tuple(int(x) for x in out)
    except Exception:
        return None


def _revisited(bm, grid: tuple) -> Optional[bool]:
    """Does any block index recur across grid iterations?  None: unknown."""
    try:
        points = int(math.prod(grid)) if grid else 1
    except TypeError:  # dynamic grid bound
        return None
    if points > MAX_GRID_ENUM:
        return None
    seen = set()
    # Row-major enumeration of the grid index space.
    dims = [int(g) for g in grid] or [1]
    idx = [0] * len(dims)
    for _ in range(points):
        block = _eval_index_map(bm, tuple(idx))
        if block is None:
            return None
        if block in seen:
            return True
        seen.add(block)
        for d in reversed(range(len(dims))):
            idx[d] += 1
            if idx[d] < dims[d]:
                break
            idx[d] = 0
    return False


def _kernel_invars(kernel_jaxpr) -> list:
    j = getattr(kernel_jaxpr, "jaxpr", kernel_jaxpr)
    return list(j.invars)


def digest_eqn(eqn, program: str, enclosing_has_cond: bool
               ) -> Optional[PallasCallInfo]:
    """Build a PallasCallInfo from one pallas_call equation (None when the
    params cannot be read — the caller reports that as an INFO finding)."""
    params = eqn.params
    gm = params.get("grid_mapping")
    kj = params.get("jaxpr")
    if gm is None or kj is None:
        return None
    name_info = str(params.get("name_and_src_info", "") or "")
    kernel_name = name_info.split(" at ")[0].strip() or "<pallas-kernel>"
    grid = tuple(getattr(gm, "grid", ()) or ())

    n_in = int(getattr(gm, "num_inputs", 0))
    mappings = list(getattr(gm, "block_mappings", ()) or ())
    refs: list[RefInfo] = []
    for i, bm in enumerate(mappings):
        role = "in" if i < n_in else "out"
        aval = getattr(bm, "block_aval", None)
        inner = getattr(aval, "inner_aval", aval)
        shape = tuple(getattr(bm, "block_shape", ()) or
                      getattr(inner, "shape", ()))
        dtype = np.dtype(getattr(inner, "dtype", np.uint8))
        full = getattr(bm, "array_shape_dtype", None)
        refs.append(RefInfo(
            role=role, index=i if role == "in" else i - n_in,
            block_shape=shape, dtype=dtype,
            memory_space=_memory_space_of(aval),
            array_shape=tuple(full.shape) if full is not None else None,
            revisited=_revisited(bm, grid)))
    invars = _kernel_invars(kj)
    # Kernel invars trail with the scratch operands.
    n_scratch = int(getattr(gm, "num_scratch_operands", 0))
    for s, v in enumerate(invars[len(invars) - n_scratch:] if n_scratch
                          else []):
        aval = v.aval
        inner = getattr(aval, "inner_aval", aval)
        refs.append(RefInfo(
            role="scratch", index=s,
            block_shape=tuple(getattr(inner, "shape", ())),
            dtype=np.dtype(getattr(inner, "dtype", np.uint8)),
            memory_space=_memory_space_of(aval),
            array_shape=None,
            revisited=True))  # scratch persists across grid iterations

    cp = params.get("compiler_params") or {}
    mosaic = cp.get("mosaic", {}) if isinstance(cp, dict) else {}
    if not isinstance(mosaic, dict):  # newer jax: a params dataclass
        mosaic = {k: getattr(mosaic, k, None)
                  for k in ("vmem_limit_bytes", "dimension_semantics")}
    return PallasCallInfo(
        kernel_name=kernel_name, src=name_info, program=program,
        grid=grid, refs=tuple(refs), kernel_jaxpr=kj,
        vmem_limit_bytes=mosaic.get("vmem_limit_bytes"),
        dimension_semantics=mosaic.get("dimension_semantics"),
        enclosing_has_cond=enclosing_has_cond)


def _has_cond_outside_kernels(jaxpr) -> bool:
    """A ``cond`` primitive reachable WITHOUT descending into pallas_call
    kernel bodies: the spill-fallback reachability signal (a ``pl.when``
    inside the kernel itself guards nothing about the spill result)."""
    j = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in j.eqns:
        if eqn.primitive.name == "cond":
            return True
        if eqn.primitive.name == "pallas_call":
            continue
        for sub in trace.eqn_subjaxprs(eqn):
            if _has_cond_outside_kernels(sub):
                return True
    return False


def collect_pallas_calls(traces: dict) -> tuple[list, list]:
    """Digest every pallas_call reachable from ``{program: ClosedJaxpr |
    TraceFailure}``.  Returns ``(infos, undigestable)`` where undigestable
    is ``[(program, src_string)]`` for equations whose params could not be
    read (jax drift) — the passes surface those instead of silently
    certifying nothing."""
    infos: list[PallasCallInfo] = []
    bad: list[tuple[str, str]] = []
    seen: set = set()
    for program, traced in traces.items():
        if isinstance(traced, trace.TraceFailure):
            continue
        has_cond = _has_cond_outside_kernels(traced)
        for eqn, _ in trace.iter_eqns(traced):
            if eqn.primitive.name != "pallas_call":
                continue
            info = digest_eqn(eqn, program, has_cond)
            if info is None:
                bad.append((program,
                            str(eqn.params.get("name_and_src_info", "?"))))
                continue
            sig = info.signature()
            if sig in seen:
                continue
            seen.add(sig)
            infos.append(info)
    return infos, bad


# -- kernel-body ref event analysis (for the race lint) ----------------------

# Ref-access primitives in pallas kernel jaxprs: `ref[...]` reads lower to
# `get`, `ref[...] = x` to `swap` (result unused), accumulation to
# `addupdate` (an atomic read-modify-write).
_READS = {"get", "masked_load"}
_WRITES = {"swap", "masked_swap"}
_RMW = {"addupdate"}


@dataclasses.dataclass(frozen=True)
class RefEvent:
    kind: str  # "read" | "write"
    guarded: bool  # inside a cond branch (pl.when / lax.cond)
    order: int  # program-order index within the kernel body


def ref_events(kernel_jaxpr) -> dict[int, list[RefEvent]]:
    """Per-ref read/write events of a kernel body, in program order.

    Returns ``{kernel_invar_position: [RefEvent, ...]}``.  Conditional
    scopes (``pl.when`` lowers to ``cond``) mark their events guarded;
    refs closed over into branch/body jaxprs are followed through the
    equation's invars (branch invars map 1:1 onto ``eqn.invars[1:]`` for
    cond, onto ``eqn.invars`` for pjit-style calls).
    """
    j = getattr(kernel_jaxpr, "jaxpr", kernel_jaxpr)
    root_refs = {v: i for i, v in enumerate(j.invars)}
    events: dict[int, list[RefEvent]] = {}
    counter = [0]

    def record(pos: int, kind: str, guarded: bool) -> None:
        events.setdefault(pos, []).append(
            RefEvent(kind=kind, guarded=guarded, order=counter[0]))

    def lookup(refmap: dict, v) -> Optional[int]:
        # Equation operands may be unhashable Literals, never refs.
        try:
            return refmap.get(v)
        except TypeError:
            return None

    def walk(jaxpr, refmap: dict, guarded: bool) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            counter[0] += 1
            if name in _READS or name in _WRITES or name in _RMW:
                pos = lookup(refmap, eqn.invars[0])
                if pos is not None:
                    if name in _READS or name in _RMW:
                        record(pos, "read", guarded)
                    if name in _WRITES or name in _RMW:
                        record(pos, "write", guarded)
                continue
            subs = trace.eqn_subjaxprs(eqn)
            if not subs:
                continue
            sub_guarded = guarded or name == "cond"
            # Map refs that flow into the sub-jaxpr: cond passes operands
            # [pred, *args] with branch invars = args; call-like primitives
            # (pjit, scan, while) pass operands 1:1 (scan/while carry
            # prefixes don't matter here — only ref-typed vars can match).
            operands = eqn.invars[1:] if name == "cond" else eqn.invars
            for sub in subs:
                sj = getattr(sub, "jaxpr", sub)
                submap: dict = {}
                for outer, inner in zip(operands, sj.invars):
                    pos = lookup(refmap, outer)
                    if pos is not None:
                        submap[inner] = pos
                if len(sj.invars) != len(operands) and not submap:
                    # Arity mismatch (consts prefix, carry layout): retry
                    # aligning from the tail, where pallas puts refs.
                    for outer, inner in zip(reversed(operands),
                                            reversed(sj.invars)):
                        pos = lookup(refmap, outer)
                        if pos is not None:
                            submap[inner] = pos
                if submap:
                    walk(sj, submap, sub_guarded)

    walk(j, dict(root_refs), False)
    return events
