"""Hierarchical link model: pricing collective schedules over a mesh.

The ``collective`` byte family the costmodel tallies but deliberately never
prices (:mod:`mapreduce_tpu.analysis.costmodel`: "they price interconnect,
not local HBM") finally gets a cost.  The model is the classical
alpha-beta decomposition over a THREE-level link hierarchy — intra-chip
HBM, the ICI ring within a slice/host, DCN across hosts — with per-level
bandwidth+latency read from a checked-in measured-rates fixture
(``analysis/baselines/measured_link_rates.json``, next to the HBM/sort
fixture the hbm-cost pass already cross-checks against).

Like the byte model it completes, this is a stable, auditable BOUND, not
a simulator: every schedule is priced as ``rounds * alpha + bytes/beta``
per link level, congestion-free.  The schedules priced are exactly the
ones the runtime builds (:mod:`mapreduce_tpu.parallel.collectives` — the
``STRATEGIES`` descriptors there must stay in bijection with
:data:`STRATEGIES` here; a test asserts it):

* **ring all-reduce** — ``2(D-1) alpha + 2 (D-1)/D * M/beta`` (XLA's
  native ``psum`` lowering: reduce-scatter + all-gather rings);
* **butterfly tree** — ``log2(D) * (alpha + M/beta)``: the
  ``tree_merge`` ppermute butterfly, full payload every round;
* **all-gather + fold** — ``alpha + (D-1) M/beta``: ``gather_merge``;
* **reduce-scatter** — ``alpha + (D-1)/D * M/beta``;
* **keyrange all-to-all** — ``2 alpha + 2 s M/beta``: one budgeted
  ``all_to_all`` (s*M with slack s) + one all-gather of the reduced
  blocks (``key_range_merge``'s traffic table);
* **2-D hierarchical** — inner (ICI) level first, then the outer (DCN)
  level with the already-merged payload (``hierarchical_merge``).

The ring-vs-tree crossover — tree wins small payloads (fewer
latency-bound rounds at the front), ring wins large ones (moves
``2(D-1)/D`` of the bytes instead of ``log2 D`` times the bytes) — is
closed-form here (:func:`ring_tree_crossover_bytes`; at D=4 it reduces
to ``M* = 8 alpha beta``), the hand-checkable arithmetic
``tools/redplan.py --selftest`` gates in tier-1.

Deliberately jax-free and stdlib-only: the planner loads this module by
file path (the ``analysis/geometry.py`` precedent) so the tier-1
selftest runs without importing jax; the collective-cost pass imports it
normally.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Optional, Sequence

_BASELINES_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "baselines")
LINK_RATES_PATH = os.path.join(_BASELINES_DIR, "measured_link_rates.json")

#: CountTable wire footprint: 7 uint32 planes (key_hi/key_lo/count/
#: count_hi/pos_hi/pos_lo/length) per slot; the dropped_* scalars are
#: noise.  The payload unit every strategy moves.
TABLE_PLANES = 7

#: Top single-key mass past which keyrange's hot-owner derating applies
#: (obs/datahealth.TOP_MASS_HOT — kept literal so this module stays
#: loadable by file path with no package import).
TOP_MASS_HOT = 0.05


@dataclasses.dataclass(frozen=True)
class Link:
    """One link level: per-hop latency (seconds) + bandwidth (bytes/s)."""

    name: str
    alpha_s: float
    beta_bps: float

    def time(self, payload_bytes: float, rounds: int = 1) -> float:
        """``rounds * alpha + payload/beta`` — the alpha-beta unit."""
        return rounds * self.alpha_s + payload_bytes / self.beta_bps


def load_link_rates(path: Optional[str] = None) -> dict:
    """The measured link fixture -> ``{"levels": {name: Link},
    "keyrange_slack": float}``."""
    with open(path or LINK_RATES_PATH) as f:
        raw = json.load(f)
    levels = {name: Link(name=name, alpha_s=float(spec["alpha_s"]),
                         beta_bps=float(spec["beta_gbps"]) * 1e9)
              for name, spec in raw["levels"].items()}
    return {"levels": levels,
            "keyrange_slack": float(raw.get("keyrange_slack", 2.0))}


@dataclasses.dataclass(frozen=True)
class MeshAxis:
    """One mesh axis with the link level its collectives ride."""

    name: str
    size: int
    level: str  # 'ici' | 'dcn' (hbm is the intra-chip degenerate case)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A mesh shape with link-level attribution, outermost axis first.

    The runtime contract (``parallel/mesh.two_level_mesh``): devices are
    process-major, so the OUTER axis crosses the process (host/slice)
    boundary and rides DCN, inner axes ride ICI.  A single-host mesh is
    all-ICI.
    """

    axes: tuple  # tuple[MeshAxis, ...]

    @classmethod
    def single_host(cls, n_devices: int, axis: str = "data") -> "MeshSpec":
        return cls(axes=(MeshAxis(axis, int(n_devices), "ici"),))

    @classmethod
    def fleet(cls, processes: int, local_devices: int,
              axes: Sequence[str] = ("replica", "data")) -> "MeshSpec":
        return cls(axes=(MeshAxis(axes[0], int(processes), "dcn"),
                         MeshAxis(axes[1], int(local_devices), "ici")))

    @classmethod
    def from_mesh(cls, axis_names: Sequence[str], axis_sizes: Sequence[int],
                  processes: int = 1) -> "MeshSpec":
        """Attribute a traced mesh's axes: with >1 process the outermost
        axis crosses the host boundary (process-major device order)."""
        axes = []
        for i, (name, size) in enumerate(zip(axis_names, axis_sizes)):
            level = "dcn" if processes > 1 and i == 0 else "ici"
            axes.append(MeshAxis(str(name), int(size), level))
        return cls(axes=tuple(axes))

    @property
    def n_devices(self) -> int:
        return math.prod(a.size for a in self.axes)

    def axis(self, name: str) -> Optional[MeshAxis]:
        for a in self.axes:
            if a.name == name:
                return a
        return None

    def slowest_level(self) -> str:
        return "dcn" if any(a.level == "dcn" for a in self.axes) else "ici"

    def label(self) -> str:
        return "x".join(f"{a.size}{'d' if a.level == 'dcn' else 'i'}"
                        for a in self.axes)


def table_bytes(capacity: int) -> int:
    """CountTable wire bytes at a capacity: 7 uint32 planes."""
    return TABLE_PLANES * 4 * int(capacity)


# -- per-schedule alpha-beta pricing (one level, D participants) -------------


def allreduce_ring(m: float, d: int, link: Link) -> float:
    """Ring all-reduce (reduce-scatter + all-gather rings): 2(D-1) hops,
    each moving M/D — XLA's native ``psum`` schedule."""
    if d <= 1:
        return 0.0
    return link.time(2 * (d - 1) / d * m, rounds=2 * (d - 1))


def allreduce_tree(m: float, d: int, link: Link) -> float:
    """Butterfly (recursive-doubling) all-reduce: log2(D) rounds, FULL
    payload every round — ``collectives.tree_merge``."""
    if d <= 1:
        return 0.0
    rounds = max(1, math.ceil(math.log2(d)))
    return link.time(rounds * m, rounds=rounds)


def allgather(m: float, d: int, link: Link) -> float:
    """One all-gather of every participant's full M: receive (D-1)*M —
    ``collectives.gather_merge``'s wire cost (the fold is local)."""
    if d <= 1:
        return 0.0
    return link.time((d - 1) * m, rounds=1)


def reduce_scatter(m: float, d: int, link: Link) -> float:
    """Ring reduce-scatter: (D-1) hops of M/D."""
    if d <= 1:
        return 0.0
    return link.time((d - 1) / d * m, rounds=d - 1)


def all_to_all(m: float, d: int, link: Link) -> float:
    """One all-to-all: each participant ships (D-1)/D of its M."""
    if d <= 1:
        return 0.0
    return link.time((d - 1) / d * m, rounds=1)


def keyrange(m: float, d: int, link: Link, slack: float = 2.0) -> float:
    """``key_range_merge``: one budgeted all-to-all (s*M with slack s) +
    one all-gather of the already-reduced blocks (s*M) — the traffic
    table in its docstring, priced at the slowest link the flattened
    axis crosses."""
    if d <= 1:
        return 0.0
    return link.time(slack * m, rounds=1) + link.time(slack * m, rounds=1)


def ring_tree_crossover_bytes(d: int, link: Link) -> float:
    """Payload M* where ring and butterfly all-reduce cost the same:
    ``M* = alpha*beta * (2(D-1) - log2 D) / (log2 D - 2(D-1)/D)``.
    Below M* the butterfly's fewer latency rounds win; above it the
    ring's 2(D-1)/D byte factor wins.  At D=4 this is ``8*alpha*beta``
    — the hand arithmetic the redplan selftest asserts."""
    if d < 4:  # at D=2 both schedules move M in 1-2 rounds; no crossover
        return math.inf
    log_d = math.ceil(math.log2(d))
    num = 2 * (d - 1) - log_d
    den = log_d - 2 * (d - 1) / d
    if den <= 0:
        return math.inf
    return link.alpha_s * link.beta_bps * num / den


#: Collective primitive -> (schedule fn, human schedule name).  What the
#: collective-cost pass prices each traced eqn with.  ``psum``-family
#: prims ride XLA's native ring; ``all_gather``/``reduce_scatter``/
#: ``all_to_all`` price as themselves; ``ppermute`` is one round of M.
_PRIM_SCHEDULES = {
    "psum": (allreduce_ring, "ring-allreduce"),
    "pmax": (allreduce_ring, "ring-allreduce"),
    "pmin": (allreduce_ring, "ring-allreduce"),
    "pbroadcast": (allreduce_tree, "broadcast-tree"),
    "all_gather": (allgather, "all-gather"),
    "reduce_scatter": (reduce_scatter, "reduce-scatter"),
    "psum_scatter": (reduce_scatter, "reduce-scatter"),
    "all_to_all": (all_to_all, "all-to-all"),
    "ppermute": (lambda m, d, link: link.time(m, rounds=1) if d > 1 else 0.0,
                 "ppermute-round"),
}

COLLECTIVE_PRIMS = frozenset(_PRIM_SCHEDULES) | {"axis_index"}


def price_eqn(prim: str, payload_bytes: int, axis_names: Sequence[str],
              mesh: MeshSpec, levels: dict) -> Optional[dict]:
    """Model one traced collective equation: per-axis alpha-beta seconds
    at the axis's link level.  Multi-axis collectives price each level
    sequentially with the full payload (conservative).  Returns None for
    communication-free prims (``axis_index``) or unknown axes."""
    if prim not in _PRIM_SCHEDULES:
        return None
    fn, schedule = _PRIM_SCHEDULES[prim]
    per_axis = []
    total = 0.0
    for name in axis_names:
        ax = mesh.axis(name)
        if ax is None:
            return None
        link = levels[ax.level]
        s = fn(float(payload_bytes), ax.size, link)
        per_axis.append({"axis": name, "d": ax.size, "level": ax.level,
                         "seconds": s})
        total += s
    if not per_axis:
        return None
    return {"schedule": schedule, "seconds": total, "per_axis": per_axis}


# -- reduction-strategy descriptors + pricing --------------------------------


@dataclasses.dataclass(frozen=True)
class Strategy:
    """One reduction strategy the planner enumerates — named EXACTLY
    after the runtime builder in ``parallel/collectives.py`` (Engine
    ``merge_strategy`` values; a test asserts the bijection)."""

    name: str
    builder: str  # dotted runtime location, for the artifact/doc trail
    power_of_two_only: bool = False
    needs_keyrange_hook: bool = False
    description: str = ""


STRATEGIES = {
    "tree": Strategy(
        name="tree",
        builder="mapreduce_tpu.parallel.collectives.tree_merge",
        power_of_two_only=True,
        description="butterfly ppermute all-reduce, log2(D) full-payload "
                    "rounds per axis (innermost level first on 2-D "
                    "meshes); non-power-of-two axes fall back to gather"),
    "gather": Strategy(
        name="gather",
        builder="mapreduce_tpu.parallel.collectives.gather_merge",
        description="all_gather every state + local fold; any axis size, "
                    "O(D) memory, (D-1)*M wire bytes per axis"),
    "keyrange": Strategy(
        name="keyrange",
        builder="mapreduce_tpu.parallel.collectives.key_range_merge",
        needs_keyrange_hook=True,
        description="key-range reduce-scatter: one budgeted all_to_all + "
                    "owner reduce + all_gather of reduced blocks, over "
                    "the FLATTENED axis (trades the ICI/DCN hierarchy "
                    "for a single scheduled collective)"),
    # The 2-D placed compositions (ISSUE 20): one strategy per link
    # level, priced exactly as the runtime composes them (inner axis
    # first; only feasible on multi-axis meshes — plan() skips them on a
    # single-host shape with a reason instead of pricing a degenerate).
    "hier-kr-tree": Strategy(
        name="hier-kr-tree",
        builder="mapreduce_tpu.parallel.collectives.hier_kr_tree_merge",
        power_of_two_only=True,
        needs_keyrange_hook=True,
        description="placed 2-D reduction: keyrange on the inner (ICI) "
                    "axis — budgeted all_to_all + owner reduce over the "
                    "cheap link — then butterfly tree over the outer "
                    "(DCN) axes with the already-reduced payload"),
    "hier-tree-tree": Strategy(
        name="hier-tree-tree",
        builder="mapreduce_tpu.parallel.collectives.hier_tree_tree_merge",
        power_of_two_only=True,
        description="the named 2-D tree composition: butterfly per "
                    "level, innermost first (same schedule 'tree' runs "
                    "on a multi-axis mesh, as an explicit placement)"),
}


def keyrange_budget_rows(capacity: int, d: int, slack: float) -> int:
    """``key_range_merge``'s per-destination row budget B (its docstring
    formula, reproduced so the planner's spill-risk arithmetic can never
    drift silently from the runtime — a test pins them equal)."""
    if d <= 1:
        return int(capacity)
    return min(int(capacity),
               -(-int(slack * capacity) // d) + 8 + 4 * (d - 1).bit_length())


def _price_tree_leg(ax: MeshAxis, m: float, levels: dict,
                    notes: list) -> dict:
    """One butterfly leg over one axis (with tree_merge's documented
    non-power-of-two gather fallback) — shared by 'tree' and the
    hierarchical compositions so the legs can never price differently."""
    link = levels[ax.level]
    if ax.size & (ax.size - 1):
        s = allgather(m, ax.size, link)
        sched = "all-gather (non-power-of-two fallback)"
        notes.append(f"axis {ax.name!r} (D={ax.size}) is not a "
                     "power of two: tree_merge falls back to "
                     "gather there")
    else:
        s = allreduce_tree(m, ax.size, link)
        sched = "butterfly-tree"
    return {"axis": ax.name, "d": ax.size, "level": ax.level,
            "schedule": sched, "seconds": s}


def price_strategy(name: str, payload_bytes: int, mesh: MeshSpec,
                   levels: dict, slack: float = 2.0) -> dict:
    """Model one strategy end to end over a mesh: per-level schedule
    seconds, innermost-first for the hierarchical strategies (the
    ``hierarchical_merge`` order), flattened-axis for keyrange, and
    per-level placement for the hier-* compositions (keyrange priced at
    the INNER axis's link, tree legs over the outer axes)."""
    strat = STRATEGIES[name]
    per_level = []
    total = 0.0
    notes = []
    m = float(payload_bytes)
    if name == "keyrange":
        d = mesh.n_devices
        level = mesh.slowest_level()
        link = levels[level]
        s = keyrange(m, d, link, slack=slack)
        per_level.append({"axis": "<flattened>", "d": d, "level": level,
                          "schedule": "keyrange-a2a", "seconds": s})
        total = s
    elif name == "hier-kr-tree":
        # hier_kr_tree_merge's placement: the budgeted all_to_all round
        # runs over the innermost (fast-link) axis only, then the
        # already-reduced payload crosses the outer levels as tree legs.
        inner = mesh.axes[-1]
        link = levels[inner.level]
        s = keyrange(m, inner.size, link, slack=slack)
        per_level.append({"axis": inner.name, "d": inner.size,
                          "level": inner.level, "schedule": "keyrange-a2a",
                          "seconds": s})
        total = s
        for ax in reversed(mesh.axes[:-1]):
            leg = _price_tree_leg(ax, m, levels, notes)
            per_level.append(leg)
            total += leg["seconds"]
    elif name in ("tree", "hier-tree-tree"):
        # hierarchical_merge order: innermost (fast) axis first, so the
        # outer (slow) level moves one already-merged payload per group.
        for ax in reversed(mesh.axes):
            leg = _price_tree_leg(ax, m, levels, notes)
            per_level.append(leg)
            total += leg["seconds"]
    else:
        for ax in reversed(mesh.axes):
            link = levels[ax.level]
            s = allgather(m, ax.size, link)
            per_level.append({"axis": ax.name, "d": ax.size,
                              "level": ax.level,
                              "schedule": "all-gather+fold", "seconds": s})
            total += s
    return {"strategy": name, "builder": strat.builder,
            "modeled_s": total, "per_level": per_level, "notes": notes}


def plan(processes: int, local_devices: int, capacity: int, *,
         rates: Optional[dict] = None, top_mass: Optional[float] = None,
         table_occupancy: Optional[float] = None,
         has_keyrange_hook: bool = True,
         incumbent: Optional[str] = None) -> dict:
    """Enumerate + price + rank every feasible reduction strategy for a
    fleet shape — the planner core ``tools/redplan.py`` drives.

    ``top_mass``/``table_occupancy`` (a prior run's measured key
    distribution, via ``obs/history.resolve_prior``) derate keyrange:
    past ``TOP_MASS_HOT`` the hot key's owner partition is the reduce's
    critical path (modeled_s scaled by ``1 + top_mass``), and a
    partition load near the budget B flags spill risk (exactness holds
    — spilled keys are fully evicted per the runtime contract — but a
    spilling merge is a different result surface than tree/gather's).
    """
    rates = rates or load_link_rates()
    levels, slack = rates["levels"], rates["keyrange_slack"]
    mesh = MeshSpec.fleet(processes, local_devices) if processes > 1 \
        else MeshSpec.single_host(local_devices)
    payload = table_bytes(capacity)
    ranked = []
    skipped = []
    decl_order = {name: i for i, name in enumerate(STRATEGIES)}
    for name, strat in STRATEGIES.items():
        if name.startswith("hier-") and len(mesh.axes) < 2:
            skipped.append({"strategy": name,
                            "why": "needs a multi-axis mesh (a single-"
                                   "host shape has one link level to "
                                   "place over)"})
            continue
        if strat.needs_keyrange_hook and not has_keyrange_hook:
            skipped.append({"strategy": name,
                            "why": "job has no keyrange_merge hook"})
            continue
        priced = price_strategy(name, payload, mesh, levels, slack=slack)
        if name in ("keyrange", "hier-kr-tree"):
            # hier-kr-tree's keyrange leg runs over the INNER axis only,
            # so its budget/derating arithmetic uses that axis's size.
            d = mesh.n_devices if name == "keyrange" else mesh.axes[-1].size
            budget = keyrange_budget_rows(capacity, d, slack)
            priced["keyrange_budget_rows"] = budget
            if top_mass is not None and top_mass > TOP_MASS_HOT:
                if name == "keyrange":
                    priced["modeled_s"] *= 1.0 + float(top_mass)
                else:
                    inner = priced["per_level"][0]
                    delta = inner["seconds"] * float(top_mass)
                    inner["seconds"] += delta
                    priced["modeled_s"] += delta
                leg = "" if name == "keyrange" \
                    else " (on the inner keyrange leg)"
                priced["notes"].append(
                    f"skew derating x{1 + top_mass:.2f}{leg}: measured "
                    f"top_mass {top_mass:.2f} > {TOP_MASS_HOT} puts the "
                    "hot key's owner partition on the critical path")
            if table_occupancy is not None and d > 1 \
                    and table_occupancy * capacity / d > 0.8 * budget:
                priced["spill_risk"] = True
                priced["notes"].append(
                    f"partition load ~{table_occupancy * capacity / d:.0f} "
                    f"rows nears the budget B={budget}: budget spill "
                    "(exact, but a different result surface) is likely")
        priced["modeled_s"] = round(priced["modeled_s"], 9)
        for lv in priced["per_level"]:
            lv["seconds"] = round(lv["seconds"], 9)
        ranked.append(priced)
    # Ties go to the earlier-declared, simpler strategy (hier-tree-tree
    # prices identically to tree on every 2-D mesh by construction — the
    # incumbent must not be displaced by its own composition's alias).
    ranked.sort(key=lambda p: (p["modeled_s"], decl_order[p["strategy"]]))
    return {
        "mesh": {"processes": int(processes),
                 "local_devices": int(local_devices),
                 "devices": mesh.n_devices, "label": mesh.label()},
        "capacity": int(capacity),
        "payload_bytes": payload,
        "keyrange_slack": slack,
        "ranked": ranked,
        "skipped": skipped,
        "top": ranked[0]["strategy"] if ranked else None,
        "incumbent": incumbent,
        "incumbent_is_top": (incumbent == ranked[0]["strategy"]
                             if ranked and incumbent else None),
    }
