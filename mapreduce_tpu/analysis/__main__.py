"""``python -m mapreduce_tpu.analysis`` -> the graphcheck CLI."""

import sys

from mapreduce_tpu.analysis.cli import main

sys.exit(main())
