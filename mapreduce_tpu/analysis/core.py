"""graphcheck core: findings, the pass registry, and the pipeline runner.

The analyzer certifies a :class:`~mapreduce_tpu.parallel.mapreduce.MapReduceJob`
*before* it is dispatched: every hook is traced to a jaxpr under abstract
inputs (:mod:`mapreduce_tpu.analysis.trace`), and a pipeline of pluggable
passes walks those jaxprs (plus the engine's full SPMD step/finish programs)
for correctness and performance hazards the type system cannot see — a
non-commutative merge fed to the collective tree-reduce, a 32-bit counter on
a corpus that overflows it, a host callback buried in a jitted body, a
collective over an axis the mesh does not carry.

Findings are structured (severity, pass id, hook, location, remediation
hint) so CI can gate on them: :meth:`Report.exit_code` is non-zero exactly
when an error-severity finding exists.

Registering a custom pass::

    from mapreduce_tpu.analysis import core

    @core.register_pass
    class MyPass:
        pass_id = "my-pass"
        description = "what it checks"

        def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
            ...

Passes run in registration order; each receives the shared
:class:`AnalysisContext` and returns findings (never raises — a pass that
cannot run reports that as a finding).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Iterable, Optional, Protocol, runtime_checkable

# Severity levels, most severe first.  Ordering is by list position.
ERROR = "error"
WARNING = "warning"
INFO = "info"
_SEVERITIES = (ERROR, WARNING, INFO)


def severity_rank(severity: str) -> int:
    """Lower rank = more severe (for sorting reports)."""
    return _SEVERITIES.index(severity)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured analyzer finding.

    ``location`` is human-oriented (a jaxpr equation's primitive and source
    line, or a state-leaf path like ``state.count``); ``hint`` says how to
    fix it.  ``model`` is the registry name (or repr) of the analyzed job.
    """

    severity: str  # one of ERROR/WARNING/INFO
    pass_id: str  # which pass emitted it
    model: str  # which job/model was being analyzed
    hook: str  # which hook/program: init_state/map_chunk/combine/merge/...
    message: str  # what is wrong
    location: str = ""  # where (jaxpr eqn, leaf path, ...)
    hint: str = ""  # suggested remediation

    def __post_init__(self) -> None:
        if self.severity not in _SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        loc = f" [{self.location}]" if self.location else ""
        hint = f"\n    hint: {self.hint}" if self.hint else ""
        return (f"{self.severity.upper():7s} {self.pass_id} "
                f"{self.model}.{self.hook}{loc}: {self.message}{hint}")


@dataclasses.dataclass
class Report:
    """All findings of one pipeline run (possibly over several models).

    ``artifacts`` carries the machine-readable non-finding outputs passes
    compute along the way (per-model cost reports, kernel VMEM footprints)
    keyed ``{model: {artifact_name: jsonable}}`` — surfaced by
    :meth:`as_json` so CI can consume the numbers, not just the verdicts.
    """

    findings: list[Finding] = dataclasses.field(default_factory=list)
    models: list[str] = dataclasses.field(default_factory=list)
    artifacts: dict = dataclasses.field(default_factory=dict)

    def extend(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    def by_severity(self, severity: str) -> list[Finding]:
        return [f for f in self.findings if f.severity == severity]

    @property
    def errors(self) -> list[Finding]:
        return self.by_severity(ERROR)

    @property
    def exit_code(self) -> int:
        """CI gate: non-zero exactly when an error-severity finding exists."""
        return 1 if self.errors else 0

    def sorted_findings(self) -> list[Finding]:
        return sorted(self.findings,
                      key=lambda f: (severity_rank(f.severity), f.pass_id,
                                     f.model, f.hook))

    def format_text(self, min_severity: str | None = None) -> str:
        """Human report.  ``min_severity`` hides lower-severity findings
        from the listing but the trailer always counts the FULL report —
        a CI log must never claim zero warnings because they were merely
        hidden."""
        cutoff = severity_rank(min_severity) if min_severity else \
            len(_SEVERITIES) - 1
        lines = [f"graphcheck: analyzed {', '.join(self.models) or 'nothing'}"]
        hidden = 0
        for f in self.sorted_findings():
            if severity_rank(f.severity) <= cutoff:
                lines.append(f.format())
            else:
                hidden += 1
        counts = {s: len(self.by_severity(s)) for s in _SEVERITIES}
        trailer = "graphcheck: " + ", ".join(
            f"{n} {s}(s)" for s, n in counts.items())
        if hidden:
            trailer += f" ({hidden} hidden by --min-severity)"
        lines.append(trailer)
        return "\n".join(lines)

    def as_json(self) -> str:
        return json.dumps({
            "models": self.models,
            "findings": [f.as_dict() for f in self.sorted_findings()],
            "artifacts": self.artifacts,
            "exit_code": self.exit_code,
        }, indent=2)


@runtime_checkable
class AnalysisPass(Protocol):
    """A pipeline pass: stateless object with an id and a ``run`` method."""

    pass_id: str
    description: str

    def run(self, ctx: "AnalysisContext") -> list[Finding]: ...


_REGISTRY: dict[str, type] = {}


def register_pass(cls):
    """Class decorator: add a pass to the default pipeline (import order =
    run order).  Re-registering an id replaces the old pass (test idiom)."""
    pid = getattr(cls, "pass_id", None)
    if not pid:
        raise ValueError(f"{cls!r} needs a non-empty pass_id")
    _REGISTRY[pid] = cls
    return cls


def default_pipeline() -> list[AnalysisPass]:
    """Fresh instances of every registered pass, in registration order."""
    return [cls() for cls in _REGISTRY.values()]


def pass_ids() -> list[str]:
    return list(_REGISTRY)


class AnalysisContext:
    """Everything a pass may inspect for ONE job: the job itself, its
    per-hook jaxprs, the engine step/finish programs, the mesh, and the
    corpus-scale bound the overflow lint checks dtypes against.

    Tracing is lazy and memoized; traces that fail are recorded as
    :class:`~mapreduce_tpu.analysis.trace.TraceFailure` values rather than
    raising, so one opaque hook cannot take down the whole pipeline.
    """

    def __init__(self, job: Any, model: str, mesh=None, *,
                 corpus_bytes: int = 1 << 40,
                 property_chunk_bytes: int = 1 << 10,
                 property_samples: int = 3,
                 baselines_dir: Optional[str] = None,
                 write_baselines: bool = False):
        from mapreduce_tpu.parallel.mesh import data_mesh, two_level_mesh

        self.job = job
        self.model = model
        # ``analysis_fleet`` (the *_fleet registry twins): the job declares
        # the SIMULATED fleet topology it must be certified over —
        # {"processes": P, "local_devices": L}.  It wins over the caller's
        # mesh (the CLI builds one shared single-host mesh for every
        # model): a 2-D process-major mesh when L > 1 (outer axis rides
        # DCN, parallel/mesh.two_level_mesh contract), a flat mesh of P
        # otherwise.  The collective-cost pass reads ``self.fleet`` to
        # attribute link levels.
        self.fleet = dict(getattr(job, "analysis_fleet", None) or {})
        if self.fleet:
            p = int(self.fleet.get("processes", 1))
            ld = int(self.fleet.get("local_devices", 1))
            self.mesh = two_level_mesh(p, ld) if ld > 1 else data_mesh(p)
        else:
            self.mesh = mesh if mesh is not None else data_mesh()
        self.corpus_bytes = int(corpus_bytes)
        self.property_chunk_bytes = int(property_chunk_bytes)
        self.property_samples = int(property_samples)
        self.baselines_dir = baselines_dir  # None -> the checked-in dir
        self.write_baselines = bool(write_baselines)
        self.artifacts: dict = {}  # pass outputs, copied into the Report
        self._hook_traces = None
        self._engine_traces = None
        self._pallas_calls = None
        self._property_states = None
        self.property_failure = None  # TraceFailure when sampling failed

    # -- corpus-scale arithmetic (shared by the overflow lint) ---------------

    @property
    def corpus_token_bound(self) -> int:
        """Upper bound on total tokens at the configured corpus scale: at
        most one token per two bytes (token + separator)."""
        return self.corpus_bytes // 2 + 1

    # -- lazy traces ---------------------------------------------------------

    @property
    def hook_traces(self) -> dict:
        """hook name -> ClosedJaxpr | TraceFailure (see trace.trace_hooks)."""
        if self._hook_traces is None:
            from mapreduce_tpu.analysis import trace

            self._hook_traces = trace.trace_hooks(self.job)
        return self._hook_traces

    @property
    def engine_traces(self) -> dict:
        """'step'/'finish' -> ClosedJaxpr | TraceFailure over the real mesh."""
        if self._engine_traces is None:
            from mapreduce_tpu.analysis import trace

            self._engine_traces = trace.trace_engine(self.job, self.mesh)
        return self._engine_traces

    @property
    def pallas_calls(self):
        """``(infos, undigestable)`` — every pallas_call binding reachable
        from the engine step/finish programs, digested once for the
        vmem/kernel-race passes (:mod:`..pallas_info`)."""
        if self._pallas_calls is None:
            from mapreduce_tpu.analysis import pallas_info

            self._pallas_calls = pallas_info.collect_pallas_calls(
                self.engine_traces)
        return self._pallas_calls

    @property
    def state_shape(self):
        """Abstract init_state pytree (ShapeDtypeStruct leaves), or a
        TraceFailure when init_state itself does not trace."""
        from mapreduce_tpu.analysis import trace

        return trace.state_shape(self.job)

    def property_states(self) -> list:
        """Concrete, reachable job states for randomized property checks:
        each is init_state folded with one random chunk's map via a
        1-device engine (so axis-aware maps work too).  Memoized; returns
        [] when the job cannot execute on this host (e.g. an explicit
        pallas backend with no TPU) — ``property_failure`` then carries
        the underlying exception as data."""
        if self._property_states is None:
            from mapreduce_tpu.analysis import trace

            self._property_states, self.property_failure = \
                trace.sample_states(self.job, n=self.property_samples,
                                    chunk_bytes=self.property_chunk_bytes)
        return self._property_states


def run_pipeline(ctx: AnalysisContext,
                 passes: Optional[list[AnalysisPass]] = None) -> Report:
    """Run every pass over one context; a crashing pass becomes an ERROR
    finding (the analyzer must never die less gracefully than the program
    it is vetting)."""
    report = Report(models=[ctx.model])
    for p in passes if passes is not None else default_pipeline():
        try:
            report.extend(p.run(ctx))
        except Exception as e:  # pragma: no cover - defensive
            report.findings.append(Finding(
                severity=ERROR, pass_id=p.pass_id, model=ctx.model,
                hook="<pipeline>",
                message=f"pass crashed: {type(e).__name__}: {e}",
                hint="fix the pass (or report a graphcheck bug)"))
    if ctx.artifacts:
        report.artifacts[ctx.model] = ctx.artifacts
    return report


def analyze_job(job: Any, model: str = "", mesh=None,
                passes: Optional[list[AnalysisPass]] = None,
                **ctx_kw) -> Report:
    """One-call API: build a context for ``job`` and run the pipeline."""
    ctx = AnalysisContext(job, model or type(job).__name__, mesh=mesh,
                          **ctx_kw)
    return run_pipeline(ctx, passes)
