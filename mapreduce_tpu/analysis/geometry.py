"""Certifier-gated kernel-geometry search (ISSUE 12 tentpole).

PR 10 closed the loop over *runtime* knobs and PR 11 proved geometry is
where the wins live (block_rows 384 -> 512 under the combiner bought −25%
sort rows).  This module makes the kernel geometries themselves
searchable, in the spirit of CUDA-LLM (PAPERS.md: search over kernel
variants with a correctness gate as fitness):

1. :func:`enumerate_candidates` walks the candidate lattice — window
   heights, slot budgets, combiner cache depths, seam-aux heights, radix
   digit widths / slab slacks, each axis stepped on the (8, 128)/(32, 128)
   tile grids the :class:`~mapreduce_tpu.config.Geometry` validation
   encodes;
2. every candidate is **certified statically** (:func:`certify`): its
   full kernel-plan set (``ops/pallas/meta.geometry_plans`` — the SAME
   constructor that derives the shipped ``production_plans``) must fit
   the VMEM/SMEM budgets the vmem-budget pass enforces.  Candidates that
   fail are never emitted;
3. every certified candidate is **priced** (:func:`price`) with the
   hbm-cost model's own arithmetic: :func:`stable2_sort_rows` (the
   canonical formula — ``analysis/costmodel.py`` imports it from here)
   re-derived from the CANDIDATE geometry instead of the shipped
   constant, the sort's one-pass bytes, the radix slab write
   amplification, and the measured-density spill headroom;
4. :func:`shortlist` ranks the certified set by modeled sort traffic —
   the measured dominant cost of the chunk budget — and hands the top-K
   to the probe-pass machinery (``tools/geomsearch.py`` reusing the
   PR-10 loop in ``tools/autotune.py``) for measured on-device ranking.

The kernel-race and spill-reachability certifications are *structural*
program properties: every candidate compiles the SAME kernel bodies at
different static shapes, so the guarded-init/read-modify-write discipline
and the spill-fallback cond are geometry-independent — ``tools/
geomsearch.py --gate`` (and tests/test_geometry.py) still runs the full
graphcheck pipeline over shortlisted candidates to prove it, no device
needed.

Deliberately jax-free (imports only ``config`` and ``ops/pallas/meta``):
``tools/geomsearch.py --selftest`` drives the whole enumerate → certify →
price → rank path without jax in the process, the ``autotune --selftest``
contract.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from mapreduce_tpu.config import (DEFAULT_GEOMETRY, GEOMETRY_PRESETS,
                                  Geometry)
from mapreduce_tpu.ops.pallas import meta

#: Bumped when the candidate/shortlist artifact schema changes shape.
GEOMETRY_SEARCH_VERSION = 1

#: The pricing chunk: the production default (32 MB), where the round-6
#: sort pricing and the PR-11 row arithmetic live.
PRICING_CHUNK_BYTES = 1 << 25

#: Measured worst-case window density (tools/density.py, BENCHMARKS.md
#: round 4/11): 114 token ends in one 384-byte window on the Zipf bench
#: corpus (75 natural).  ceil(density * block_rows) > slots flags a
#: candidate spill-RISKY — never rejected (the fallback is exact; the
#: probe pass measures what the risk costs), but ranked with its eyes
#: open and smoked first by tools/kernel_smoke.py --geometry.
MEASURED_MAX_ENDS = 114
MEASURED_MAX_ENDS_WINDOW = 384

LANES = 128


def stable2_sort_rows(chunk_bytes: int, block_rows: int, slots: int,
                      lanes: int = LANES) -> int:
    """Rows of the stable2 aggregation sort for a pallas chunk, from the
    kernel geometry alone: the lane-major column pass emits ``slots``
    output rows per ``block_rows``-byte window per lane, over the padded
    column view (one extra pad block; the seam stream aggregates
    separately on this path).  The canonical formula — the hbm-cost
    pass's static leg (``analysis/costmodel.py`` re-exports it) and the
    search's pricing both read exactly this."""
    seg_len = chunk_bytes // lanes
    pad_rows = (-seg_len) % block_rows + block_rows
    grid = (seg_len + pad_rows) // block_rows
    return grid * slots * lanes


def radix_slab_write_amplification(geom: Geometry) -> float:
    """Slab bytes written per one-pass bytes for one partition level —
    the round-6 pricing note's slack-factor write amplification, derived
    from the CANDIDATE geometry instead of quoted: every block writes
    ``3 * B * cap`` slab rows per ``3 * block_rows`` input rows."""
    B = 1 << geom.radix_bits
    return (B * geom.radix_cap) / geom.radix_block_rows


def window_spill_risk(block_rows: int, slots: int) -> bool:
    """Does the measured worst-case density overflow this window's slot
    budget?  The PR-11 512-row dead-end branch, as arithmetic: 114 ends
    per 384 bytes -> ceil(0.297 * block_rows) vs slots."""
    worst = -(-MEASURED_MAX_ENDS * block_rows // MEASURED_MAX_ENDS_WINDOW)
    return worst > slots


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One certified, priced geometry candidate."""

    geometry: Geometry
    label: str  # preset name when one matches, else a compact spec
    axis: str  # which lattice axis produced it ('default' for the base)
    #: stable2 aggregation sort rows at the pricing chunk — the primary
    #: ranking key (the sort is the measured chunk-budget floor).
    sort_rows: int
    #: One full reorder pass over the 3 uint32 sort planes (read+write).
    sort_pass_bytes: int
    #: Peak single-kernel VMEM footprint over the candidate's plan set.
    vmem_peak_bytes: int
    #: Radix slab write amplification (one partition level).
    radix_amplification: float
    #: Measured-density spill risk of the candidate's compact window.
    spill_risk: bool

    def as_dict(self) -> dict:
        return {"label": self.label, "axis": self.axis,
                "sort_rows": self.sort_rows,
                "sort_pass_bytes": self.sort_pass_bytes,
                "vmem_peak_bytes": self.vmem_peak_bytes,
                "radix_amplification": round(self.radix_amplification, 3),
                "spill_risk": self.spill_risk,
                "geometry": self.geometry.as_dict()}


def certify(geom: Geometry) -> list[str]:
    """Static certifier: every kernel plan the geometry implies must fit
    the budgets the vmem-budget pass enforces (the same ``meta`` limits,
    through the same :func:`...meta.geometry_plans` constructor that
    derives the shipped list).  Returns the rejection reasons — empty
    means certified.  Construction-invalid geometries report their
    ValueError the same way, so callers probe the lattice uniformly."""
    errors: list[str] = []
    for plan in meta.geometry_plans(geom):
        label = f"{plan.kernel} [{plan.geometry}]"
        budget = plan.budget
        if budget > meta.VMEM_PHYSICAL:
            errors.append(
                f"{label}: declared vmem_limit_bytes {budget >> 20} MiB "
                f"exceeds the {meta.VMEM_PHYSICAL >> 20} MiB physical VMEM")
        if plan.vmem_bytes > budget:
            errors.append(
                f"{label}: static VMEM footprint {plan.vmem_bytes >> 10} "
                f"KiB exceeds the {budget >> 20} MiB budget "
                "(double-buffered blocks + scratch)")
        if plan.smem_bytes > meta.SMEM_BUDGET:
            errors.append(
                f"{label}: SMEM footprint {plan.smem_bytes} B exceeds the "
                f"{meta.SMEM_BUDGET >> 10} KiB budget")
    return errors


def label_for(geom: Geometry) -> str:
    """A preset name when one matches, else a compact spec string (for
    humans and row labels; the machine-readable form is the dict)."""
    for name, preset in GEOMETRY_PRESETS.items():
        if geom == preset:
            return name
    parts = []
    for f in dataclasses.fields(Geometry):
        v = getattr(geom, f.name)
        if v != getattr(DEFAULT_GEOMETRY, f.name):
            parts.append(f"{f.name}={v}")
    return ",".join(parts) or "default"


def price(geom: Geometry,
          chunk_bytes: int = PRICING_CHUNK_BYTES) -> dict:
    """The hbm-cost-model pricing of one candidate at ``chunk_bytes``:
    sort rows/pass bytes from the CANDIDATE's stable2 window, VMEM peak
    over its plan set, radix amplification, spill headroom."""
    rows = stable2_sort_rows(chunk_bytes, geom.block_rows,
                             geom.compact_slots)
    plans = meta.geometry_plans(geom)
    return {
        "chunk_bytes": chunk_bytes,
        "sort_rows": rows,
        "sort_pass_bytes": 2 * rows * 3 * 4,
        "vmem_peak_bytes": max(p.vmem_bytes for p in plans),
        "radix_amplification": radix_slab_write_amplification(geom),
        "spill_risk": window_spill_risk(geom.block_rows,
                                        geom.compact_slots),
    }


def _candidate(geom: Geometry, axis: str, chunk_bytes: int) -> Candidate:
    p = price(geom, chunk_bytes)
    return Candidate(geometry=geom, label=label_for(geom), axis=axis,
                     sort_rows=p["sort_rows"],
                     sort_pass_bytes=p["sort_pass_bytes"],
                     vmem_peak_bytes=p["vmem_peak_bytes"],
                     radix_amplification=p["radix_amplification"],
                     spill_risk=p["spill_risk"])


#: The candidate lattice: per-axis values stepped on the tile grids.  One
#: axis family varies at a time off the default (a full cross product
#: explodes combinatorially AND makes probe attribution useless — a
#: one-axis delta is a readable A/B, the PR-11 discipline).
LATTICE_AXES: dict = {
    "block_rows": (256, 384, 512, 640, 768),
    "aux_rows": (96, 128),
    "combiner_slots": (8, 16, 24, 32),
    "combiner_block_rows": (384, 512, 640),
    "pair_block_rows": (128, 256, 384),
    "sort3": tuple((br, s) for br in (256, 384, 512)
                   for s in (72, 80, 88, 96, 104, 112, 120, 128)
                   if s <= br // 2),
    "radix": tuple((b, sl) for b in (2, 3, 4, 5) for sl in (2, 4)),
}


def enumerate_candidates(chunk_bytes: int = PRICING_CHUNK_BYTES
                         ) -> list[Candidate]:
    """Walk the lattice, certify, price.  Every RETURNED candidate passed
    the static certifier by construction (off-lattice or over-budget
    points are dropped); the default geometry is always candidate zero."""
    out: list[Candidate] = []
    seen: set = set()

    def add(axis: str, **fields) -> None:
        try:
            geom = Geometry(**fields)
        except ValueError:
            return  # off the tile lattice: not a candidate
        if geom in seen:
            return
        seen.add(geom)
        if certify(geom):
            return  # over budget: the certifier is the gate
        out.append(_candidate(geom, axis, chunk_bytes))

    add("default")
    for br in LATTICE_AXES["block_rows"]:
        add("block_rows", block_rows=br)
    for ar in LATTICE_AXES["aux_rows"]:
        add("aux_rows", aux_rows=ar)
    for cs in LATTICE_AXES["combiner_slots"]:
        add("combiner_slots", combiner_slots=cs)
    for cbr in LATTICE_AXES["combiner_block_rows"]:
        add("combiner_block_rows", combiner_block_rows=cbr)
    for pbr in LATTICE_AXES["pair_block_rows"]:
        add("pair_block_rows", pair_block_rows=pbr)
    for sbr, ss in LATTICE_AXES["sort3"]:
        add("sort3", sort3_block_rows=sbr, sort3_slots=ss)
    for bits, slack in LATTICE_AXES["radix"]:
        add("radix", radix_bits=bits, radix_slab_slack=slack)
    return out


def shortlist(candidates: Iterable[Candidate], k: int = 5,
              axis: Optional[str] = None) -> list[Candidate]:
    """Top-K by modeled sort traffic (rows ascending, VMEM peak as the
    tie-break).  ``axis`` narrows to one lattice family plus the default
    (the readable A/B a probe run wants).  Spill-risky candidates rank by
    the same cost — the model says what they'd save, the flag says what
    the probe must watch — mirroring how the cost pass prices worst-case
    cond branches rather than hiding them."""
    pool = [c for c in candidates
            if axis is None or c.axis in (axis, "default")]
    ranked = sorted(pool, key=lambda c: (c.sort_rows, c.vmem_peak_bytes,
                                         c.label))
    return ranked[:k]


def search_artifact(candidates: list[Candidate], k: int = 5) -> dict:
    """The machine-readable search artifact (docs/analysis.md schema):
    what tools/geomsearch.py emits and the probe driver consumes."""
    return {
        "geometry_search_version": GEOMETRY_SEARCH_VERSION,
        "pricing_chunk_bytes": PRICING_CHUNK_BYTES,
        "candidates": len(candidates),
        "default": next((c.as_dict() for c in candidates
                         if c.axis == "default"), None),
        "shortlist": [c.as_dict() for c in shortlist(candidates, k)],
    }


def resolve_auto(profile_path: str, family: str = "wordcount"):
    """Resolve ``Config.geometry='auto'`` against a searched profile
    (ISSUE 12): the freshest ``tuned.json`` profile for ``family`` whose
    config carries a non-default geometry decides — its label (preset
    round-trip) or spec dict (Config accepts both).  No profile, no
    geometry entry, or an unreadable file resolves to 'default' — the
    combiner='auto' degrade-to-off contract.

    The read itself lives in the run-history warehouse now (ISSUE 14:
    ``obs/history.resolve_prior`` is the one place prior-run questions
    are answered); this wrapper supplies the Config-side validation the
    jax-free warehouse cannot import."""
    from mapreduce_tpu.obs import history

    def _valid_spec(spec: dict) -> bool:
        try:
            Geometry(**spec)
        except (TypeError, ValueError):
            return False  # future-shaped profile: skip, never crash
        return True

    return history.resolve_prior(
        profile_path=profile_path, family=family,
        presets=set(GEOMETRY_PRESETS), geometry_ok=_valid_spec)["geometry"]
