"""graphcheck: jaxpr-level static analysis for map/reduce programs.

Certifies a :class:`~mapreduce_tpu.parallel.mapreduce.MapReduceJob` before
it hits the TPU: hooks are traced to jaxprs under abstract inputs and a
pluggable pass pipeline checks reducer algebra, accumulator dtypes vs
corpus scale, host-sync/recompile hazards, and sharding/collective axis
consistency.  See ``docs/analysis.md`` and the CLI
(``python -m mapreduce_tpu.analysis`` / ``tools/graphcheck.py``).
"""

from mapreduce_tpu.analysis.core import (AnalysisContext, Finding, Report,
                                         ERROR, WARNING, INFO,
                                         analyze_job, default_pipeline,
                                         pass_ids, register_pass,
                                         run_pipeline)
# Importing the package registers the built-in pipeline.
from mapreduce_tpu.analysis import passes as _passes  # noqa: F401

__all__ = ["AnalysisContext", "Finding", "Report", "ERROR", "WARNING",
           "INFO", "analyze_job", "default_pipeline", "pass_ids",
           "register_pass", "run_pipeline"]
