"""Pass: overflow / dtype lint.

JAX-on-TPU runs with x64 disabled, so every device integer is 32 bits —
and a 32-bit count accumulator silently wraps at corpus scale (the exact
failure mode the reference hits past ``MAX_OUTPUT_COUNT``,
``main.cu:103-104``, and the one this framework exists to never have).
The framework-wide convention is the uint32 ``lo``/``hi`` lane pair with
explicit carry (``ops.table.add64``); this lint walks the accumulator
state's dtypes against a configurable corpus-scale bound and flags
counter-shaped leaves that are NOT lane-paired:

* a leaf whose name says it counts (``count``/``total``/``matches``/
  ``lines``/``sum``/``num``...) with an integer dtype of <= 32 bits and no
  ``*_hi`` sibling lane is an ERROR when the corpus bound exceeds the
  dtype's range, a WARNING when it is within one doubling;
* integer downcasts (``convert_element_type`` to a narrower int) inside
  ``combine``/``merge`` are WARNINGs — silent truncation on the
  accumulator path;
* the padding-sentinel envelope of the count-table plane is checked
  statically: ``SENTINEL_KEY``/``POS_INF`` must be the maximum uint32 so
  dead rows sort last (``ops/table.py`` invariant) — a changed constant
  would silently corrupt every merge.

The lane-pair convention recognized: ``X`` + ``X_hi``, or ``X_lo`` +
``X_hi``, as NamedTuple siblings.
"""

from __future__ import annotations

import re

import numpy as np

from mapreduce_tpu.analysis import core, trace

_COUNTERISH = re.compile(
    r"(count|total|matches|lines|occurrence|freq|sum|n_|num)", re.IGNORECASE)


def _leaf_field(path: str) -> str:
    """Final field name of a dotted leaf path."""
    return path.rsplit(".", 1)[-1]


def _sibling_fields(path: str, leaves: list[tuple[str, object]]) -> set[str]:
    """Field names sharing the leaf's parent container."""
    parent = path.rsplit(".", 1)[0] if "." in path else ""
    out = set()
    for p, _ in leaves:
        if "." in p and p.rsplit(".", 1)[0] == parent:
            out.add(_leaf_field(p))
    return out


def _lane_paired(field: str, siblings: set[str]) -> bool:
    """True when the field participates in a lo/hi lane pair."""
    if field.endswith("_hi"):
        return True  # it IS a high lane
    if field.endswith("_lo"):
        return (field[:-3] + "_hi") in siblings
    return (field + "_hi") in siblings


def _int_capacity(dtype) -> int | None:
    """Max representable count of an integer dtype (None for non-ints)."""
    if not np.issubdtype(dtype, np.integer):
        return None
    info = np.iinfo(dtype)
    return int(info.max)


@core.register_pass
class OverflowPass:
    pass_id = "overflow-dtype"
    description = ("accumulator dtypes vs corpus scale: un-paired 32-bit "
                   "counters, integer downcasts, sentinel envelope")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        out.extend(self._sentinel_findings(ctx))

        st = ctx.state_shape
        if isinstance(st, trace.TraceFailure):
            out.append(core.Finding(
                severity=core.WARNING, pass_id=self.pass_id,
                model=ctx.model, hook="init_state",
                message=f"state shape unavailable ({st.error_type}: "
                        f"{st.error}); dtype lint skipped",
                hint="make init_state traceable under jax.eval_shape"))
            return out
        leaves = trace.named_leaves(st)
        bound = ctx.corpus_token_bound
        # Jobs may exempt specific leaves (by field name or full path) that
        # a name-based lint would misread — e.g. staging buffers of
        # per-chunk counts whose values are bounded by chunk size, not
        # corpus size.  The declaration site carries the justification.
        exempt = set(getattr(ctx.job, "analysis_overflow_exempt", ()))
        for path, leaf in leaves:
            cap = _int_capacity(leaf.dtype)
            if cap is None or cap >= (1 << 63) - 1:
                continue
            field = _leaf_field(path)
            if path in exempt or field in exempt:
                continue
            if not _COUNTERISH.search(field):
                continue
            if _lane_paired(field, _sibling_fields(path, leaves)):
                continue
            if bound > cap:
                out.append(core.Finding(
                    severity=core.ERROR, pass_id=self.pass_id,
                    model=ctx.model, hook="init_state",
                    message=(f"counter leaf '{path}' is {leaf.dtype} "
                             f"(max {cap:,}) but the corpus bound is "
                             f"{bound:,} tokens: silent wrap at scale"),
                    location=path,
                    hint="carry the count as a uint32 lo/hi lane pair with "
                         "explicit carry (ops.table.add64 — the grep "
                         "accumulator idiom); device uint64 is unavailable "
                         "with x64 off"))
            elif bound > cap // 2:
                out.append(core.Finding(
                    severity=core.WARNING, pass_id=self.pass_id,
                    model=ctx.model, hook="init_state",
                    message=(f"counter leaf '{path}' is {leaf.dtype} "
                             f"(max {cap:,}); the corpus bound {bound:,} is "
                             "within one doubling of overflow"),
                    location=path,
                    hint="promote to a lo/hi lane pair before the next "
                         "corpus scale-up"))

        out.extend(self._downcast_findings(ctx))
        return out

    def _downcast_findings(self, ctx) -> list[core.Finding]:
        out = []
        for hook in ("combine", "merge"):
            traced = ctx.hook_traces.get(hook)
            if traced is None or isinstance(traced, trace.TraceFailure):
                continue
            seen = set()
            for eqn, _ in trace.iter_eqns(traced):
                if eqn.primitive.name != "convert_element_type":
                    continue
                new = np.dtype(eqn.params.get("new_dtype"))
                old = eqn.invars[0].aval.dtype if eqn.invars else None
                if old is None:
                    continue
                old = np.dtype(old)
                if (np.issubdtype(old, np.integer)
                        and np.issubdtype(new, np.integer)
                        and new.itemsize < old.itemsize
                        and (old, new) not in seen):
                    seen.add((old, new))
                    out.append(core.Finding(
                        severity=core.WARNING, pass_id=self.pass_id,
                        model=ctx.model, hook=hook,
                        message=(f"integer downcast {old}->{new} on the "
                                 f"{hook} path: high bits are silently "
                                 "dropped"),
                        location=trace.eqn_location(eqn),
                        hint="keep accumulator arithmetic at full width "
                             "(weak-type promotion can introduce this "
                             "invisibly — pin dtypes with jnp.uint32(...))"))
        return out

    def _sentinel_findings(self, ctx) -> list[core.Finding]:
        from mapreduce_tpu import constants

        out = []
        maxu = (1 << 32) - 1
        if int(constants.SENTINEL_KEY) != maxu:
            out.append(core.Finding(
                severity=core.ERROR, pass_id=self.pass_id,
                model=ctx.model, hook="constants",
                message=(f"SENTINEL_KEY is {int(constants.SENTINEL_KEY):#x}, "
                         "not the maximum uint32: dead table rows would stop "
                         "sorting last and every merge would corrupt"),
                location="mapreduce_tpu/constants.py",
                hint="keep SENTINEL_KEY = 0xFFFFFFFF"))
        if int(constants.POS_INF) != maxu:
            out.append(core.Finding(
                severity=core.ERROR, pass_id=self.pass_id,
                model=ctx.model, hook="constants",
                message=(f"POS_INF is {int(constants.POS_INF):#x}, not the "
                         "maximum uint32: empty-slot positions would win "
                         "first-occurrence minima"),
                location="mapreduce_tpu/constants.py",
                hint="keep POS_INF = 0xFFFFFFFF"))
        return out
