"""Pass: sharding / collective-axis lint.

The engine's SPMD programs bind mesh axes through ``shard_map``; a job's
axis-aware map (or a hand-written collective) that names an axis the mesh
does not carry fails — at best loudly at trace time, at worst (axis name
collides with a DIFFERENT axis on a multi-axis mesh) by silently reducing
over the wrong device group.  This pass checks, statically:

* the engine ``step``/``finish`` programs trace at all — an unbound axis
  name (the mismatched-PartitionSpec case) surfaces here and is converted
  into the structured ERROR finding it is;
* every ``shard_map`` binding inside the programs names only axes of the
  analysis mesh;
* every collective (``psum``/``all_gather``/``ppermute``/``all_to_all``/
  ``axis_index``/``reduce_scatter``) reduces over axes bound by its
  enclosing ``shard_map`` scope AND present on the mesh
  (:mod:`mapreduce_tpu.parallel.collectives` contract: collectives must
  be called inside ``shard_map``).

NOT covered (open item, do not rely on it): a collective reducing over a
strict SUBSET of a multi-axis mesh's declared data axes — the
partial-merge hazard — passes this lint today; only unknown and unbound
axis names are flagged.
"""

from __future__ import annotations

from mapreduce_tpu.analysis import core, trace

_COLLECTIVES = {"psum", "pmax", "pmin", "all_gather", "ppermute",
                "all_to_all", "axis_index", "reduce_scatter",
                "psum_scatter"}


def _shard_map_axes(eqn) -> set[str]:
    """Axis names a shard_map equation binds (from its in/out names and
    its mesh param)."""
    names: set[str] = set()
    mesh = eqn.params.get("mesh")
    names.update(getattr(mesh, "axis_names", ()) or ())
    for key in ("in_names", "out_names"):
        for entry in eqn.params.get(key, ()) or ():
            if isinstance(entry, dict):
                for v in entry.values():
                    names.update(v if isinstance(v, (tuple, list)) else (v,))
    return {n for n in names if isinstance(n, str)}


@core.register_pass
class ShardingPass:
    pass_id = "sharding-lint"
    description = ("shard_map/PartitionSpec axis names vs the mesh; "
                   "collectives reduce over declared, bound axes")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        mesh_axes = set(ctx.mesh.axis_names)
        for hook, traced in ctx.engine_traces.items():
            if isinstance(traced, trace.TraceFailure):
                out.append(core.Finding(
                    severity=core.ERROR, pass_id=self.pass_id,
                    model=ctx.model, hook=hook,
                    message=(f"engine {hook} program failed to trace "
                             f"({traced.error_type}: {traced.error}) — "
                             "typically a collective or PartitionSpec "
                             "naming an axis the mesh does not carry"),
                    hint=f"mesh axes are {sorted(mesh_axes)}; use the axis "
                         "name the engine passes to map_chunk_sharded "
                         "instead of hardcoding one"))
                continue
            out.extend(self._jaxpr_findings(ctx, hook, traced, mesh_axes))
        return out

    def _jaxpr_findings(self, ctx, hook, traced, mesh_axes):
        out = []
        seen: set[tuple] = set()
        for eqn, bound in trace.iter_eqns(traced):
            name = eqn.primitive.name
            if name == "shard_map":
                unknown = _shard_map_axes(eqn) - mesh_axes
                if unknown and ("sm", tuple(sorted(unknown))) not in seen:
                    seen.add(("sm", tuple(sorted(unknown))))
                    out.append(core.Finding(
                        severity=core.ERROR, pass_id=self.pass_id,
                        model=ctx.model, hook=hook,
                        message=(f"shard_map binds axis(es) "
                                 f"{sorted(unknown)} absent from the mesh "
                                 f"{sorted(mesh_axes)}"),
                        location=trace.eqn_location(eqn),
                        hint="build the mesh with matching axis names "
                             "(parallel/mesh.py) or fix the PartitionSpec"))
                continue
            if name not in _COLLECTIVES:
                continue
            axes = trace.eqn_axis_names(eqn)
            for ax in axes:
                key = (name, ax)
                if key in seen:
                    continue
                if ax not in mesh_axes:
                    seen.add(key)
                    out.append(core.Finding(
                        severity=core.ERROR, pass_id=self.pass_id,
                        model=ctx.model, hook=hook,
                        message=(f"collective '{name}' reduces over axis "
                                 f"{ax!r}, absent from the mesh "
                                 f"{sorted(mesh_axes)}"),
                        location=trace.eqn_location(eqn),
                        hint="use the axis name the engine passes into "
                             "map_chunk_sharded"))
                elif ax not in bound:
                    seen.add(key)
                    out.append(core.Finding(
                        severity=core.ERROR, pass_id=self.pass_id,
                        model=ctx.model, hook=hook,
                        message=(f"collective '{name}' over axis {ax!r} "
                                 "outside any shard_map binding it"),
                        location=trace.eqn_location(eqn),
                        hint="collectives must run inside shard_map "
                             "(parallel/collectives.py contract)"))
        return out
