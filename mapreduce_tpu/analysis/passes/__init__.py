"""Built-in graphcheck passes.  Import order = pipeline run order."""

from mapreduce_tpu.analysis.passes import (algebra, overflow, hostsync,
                                           sharding, cost, vmem, kernelrace,
                                           fusion, collective)

__all__ = ["algebra", "overflow", "hostsync", "sharding", "cost", "vmem",
           "kernelrace", "fusion", "collective"]
