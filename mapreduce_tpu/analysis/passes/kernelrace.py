"""Pass: Pallas ref-hazard lint across grid iterations.

TPU grids execute sequentially, and the shipped kernels lean on that hard:
the tokenize carry scratch hands the lookback window from block to block,
the radix partition accumulates SMEM histograms and a running spill
scalar.  Those patterns are correct exactly when they keep a narrow
discipline, and this lint checks the discipline statically on every
traced kernel body:

* a ref REVISITED across grid iterations (scratch, or an output whose
  index map sends two iterations to the same block) must only be written
  via **read-modify-write** (a read of the same ref earlier in the body)
  or under a **guard** (``pl.when``/``cond``) — an unguarded blind write
  is a cross-iteration write/write hazard: iteration *i+1* clobbers what
  iteration *i* produced (ERROR);
* a revisited ref whose first access is an unguarded READ with no guarded
  write anywhere reads uninitialized memory on iteration 0 (WARNING —
  Mosaic zero-fills some scratch, but relying on it is exactly the class
  of latent bug the SMEM-histogram pattern hides);
* a write to an INPUT block ref is always an ERROR;
* ``dimension_semantics`` declaring a ``parallel`` grid dimension while
  the kernel carries cross-iteration state (scratch or revisited refs)
  breaks the sequential-grid assumption outright (ERROR).

The event extraction (``get``/``swap``/``addupdate`` walking, cond-guard
tracking) lives in :mod:`..pallas_info` so the vmem pass shares the
digested view.
"""

from __future__ import annotations

from mapreduce_tpu.analysis import core, pallas_info


def _ref_label(info, pos: int) -> str:
    """Human label of kernel invar position ``pos``."""
    n_in = len(info.ins)
    n_out = len(info.outs)
    if pos < n_in:
        r = info.ins[pos]
    elif pos < n_in + n_out:
        r = info.outs[pos - n_in]
    else:
        r = info.scratch[pos - n_in - n_out]
    return (f"{r.role}[{r.index}] {r.memory_space} "
            f"{tuple(r.block_shape)}")


def _ref_at(info, pos: int):
    n_in, n_out = len(info.ins), len(info.outs)
    if pos < n_in:
        return info.ins[pos]
    if pos < n_in + n_out:
        return info.outs[pos - n_in]
    if pos < n_in + n_out + len(info.scratch):
        return info.scratch[pos - n_in - n_out]
    return None


@core.register_pass
class KernelRacePass:
    pass_id = "kernel-race"
    description = ("cross-grid-iteration write/write and uninitialized-"
                   "read hazards on Pallas refs (SMEM accumulators, "
                   "carry scratch, revisited output blocks)")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        infos, _ = ctx.pallas_calls  # undigested reported by vmem pass
        for info in infos:
            out.extend(self._kernel_findings(ctx, info))
        return out

    def _kernel_findings(self, ctx, info) -> list[core.Finding]:
        out: list[core.Finding] = []
        events = pallas_info.ref_events(info.kernel_jaxpr)
        carries_state = bool(info.scratch) or any(
            r.revisited for r in info.outs)

        sem = info.dimension_semantics
        if sem and any("parallel" in str(s).lower() for s in sem) \
                and carries_state:
            out.append(core.Finding(
                severity=core.ERROR, pass_id=self.pass_id,
                model=ctx.model, hook=info.program,
                message=(f"{info.kernel_name}: 'parallel' grid dimension "
                         "declared but the kernel carries cross-iteration "
                         "state (scratch / revisited blocks)"),
                location=info.src,
                hint="drop the parallel dimension_semantics or make every "
                     "iteration's blocks disjoint"))

        for pos, evts in sorted(events.items()):
            ref = _ref_at(info, pos)
            if ref is None:
                continue
            label = _ref_label(info, pos)
            if ref.role == "in" and any(e.kind == "write" for e in evts):
                out.append(core.Finding(
                    severity=core.ERROR, pass_id=self.pass_id,
                    model=ctx.model, hook=info.program,
                    message=f"{info.kernel_name}: write to input ref "
                            f"{label}",
                    location=info.src,
                    hint="inputs are read-only views of the HBM operand; "
                         "stage through scratch or an output"))
                continue
            if ref.role == "in":
                # Revisited INPUT blocks (an index map pinning every
                # iteration to the same operand block — the fused seam-aux
                # plane) are re-fetched from HBM, never uninitialized, and
                # unwritable per the check above: no cross-iteration hazard.
                continue
            if not ref.revisited:
                # Disjoint blocks per iteration: blind writes are the
                # normal output pattern; nothing cross-iteration to race.
                continue
            ordered = sorted(evts, key=lambda e: e.order)
            # Rule A: every unguarded write must be RMW — preceded by a
            # read of the same ref in body order.
            for e in ordered:
                if e.kind != "write" or e.guarded:
                    continue
                has_prior_read = any(r.kind == "read" and r.order <= e.order
                                     for r in ordered)
                if not has_prior_read:
                    out.append(core.Finding(
                        severity=core.ERROR, pass_id=self.pass_id,
                        model=ctx.model, hook=info.program,
                        message=(f"{info.kernel_name}: unguarded blind "
                                 f"write to revisited ref {label} — grid "
                                 "iterations overwrite each other "
                                 "(write/write hazard)"),
                        location=info.src,
                        hint="accumulate (read-modify-write), or guard the "
                             "write with pl.when on the revisit phase, or "
                             "make the index map injective over the grid"))
                    break
            # Rule B: first access an unguarded read + no guarded init.
            first = ordered[0] if ordered else None
            has_guarded_write = any(e.kind == "write" and e.guarded
                                    for e in ordered)
            if first is not None and first.kind == "read" \
                    and not first.guarded and not has_guarded_write:
                out.append(core.Finding(
                    severity=core.WARNING, pass_id=self.pass_id,
                    model=ctx.model, hook=info.program,
                    message=(f"{info.kernel_name}: revisited ref {label} "
                             "is read before any guarded initialization — "
                             "iteration 0 sees uninitialized memory"),
                    location=info.src,
                    hint="zero it under pl.when(first-iteration) like the "
                         "tokenize carry / radix histogram idiom"))
        if not out and (carries_state or info.scratch):
            out.append(core.Finding(
                severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
                hook=info.program,
                message=(f"{info.kernel_name}: cross-iteration refs follow "
                         "the guarded-init + read-modify-write discipline"),
                location=info.src))
        return out
