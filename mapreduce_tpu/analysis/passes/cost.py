"""Pass: static HBM/FLOP cost certifier with a measured-rate cross-check.

Three jobs, all CI-gateable:

1. **Cost report** (artifact ``cost``): per-program HBM bytes read/written,
   FLOPs, and family shares from the traced step/finish jaxprs
   (:mod:`..costmodel`), plus ``effective input passes`` — how many times
   the step program streams its own chunk through HBM.  The unit the
   BENCHMARKS dead-end ledger prices in, now computed by machine.

2. **Sort-pricing cross-check**: the round-6 ledger's central measured
   claim — the XLA aggregation sort runs at **2.6-3.4 effective HBM
   passes** — becomes an asserted artifact.  The pass re-derives the
   stable2 sort's row count from kernel geometry
   (:func:`..costmodel.stable2_sort_rows`), requires the traced sort
   equation to match it EXACTLY at the model's own config (the static
   leg), extrapolates to the production chunk, and recomputes the pass
   range from the measured fixture (sort ms / one-pass ms at the measured
   HBM rate).  Outside the declared tolerance of the claimed range →
   ERROR: either the kernel geometry drifted (row count changed) or the
   fixture is stale — both must be resolved deliberately, not in prose.

3. **Baseline regression gate**: each shipped model's predicted effective
   passes is checked into ``analysis/baselines/<model>.json``.  Growth
   beyond ``REGRESSION_TOLERANCE`` (20%) fails the pipeline unless the
   baselines are intentionally regenerated (``--write-baselines``); a
   SHRINK past the same margin is only a warning nudging a re-baseline.

4. **Fused-vs-split gate** (ISSUE 6): a model whose config runs the fused
   map path (``Config.map_impl='fused'``) must price STRICTLY below its
   split-path counterpart's checked-in baseline — the machine-checked
   before/after that certifies the fusion actually deleted HBM traffic
   instead of moving it.  Counterpart pairs are declared in
   ``_SPLIT_COUNTERPART``; a fused model without one is an ERROR too (an
   ungated fusion is exactly the unmeasured claim this pass exists to
   forbid).
"""

from __future__ import annotations

import json
import os

from mapreduce_tpu.analysis import core, costmodel, trace

REGRESSION_TOLERANCE = 0.20

# Fused-map registry models gated against their split-path twin's baseline
# (same chunk geometry, Config.map_impl the only delta — see
# models.FUSED_ANALYSIS_CONFIG).
_SPLIT_COUNTERPART = {"wordcount_fused": "wordcount_pallas",
                      "wordcount_fused_telemetry": "wordcount_telemetry"}

# Combiner registry models gated against their combiner-OFF twin's baseline
# (same chunk geometry, Config.combiner the only delta — ISSUE 11): the
# hot-key cache must price STRICTLY below the uncombined fused path, the
# machine-checked proof that the taller windows it pays for actually
# delete sort traffic.  Models in this dict (and their counterparts) are
# exempt from the fused-vs-split gate: their fused-ness is already
# certified by wordcount_fused at ITS geometry, and this pair exists at a
# different chunk so the combiner's window arithmetic is exact.
_UNCOMBINED_COUNTERPART = {"wordcount_combiner": "wordcount_nocombiner"}
_FUSED_GATE_EXEMPT = set(_UNCOMBINED_COUNTERPART) \
    | set(_UNCOMBINED_COUNTERPART.values())

# Data-stats-instrumented registry models gated against their
# UNINSTRUMENTED twin's baseline (same config, Engine data_stats the only
# delta — ISSUE 8): observability must never silently regress the cost
# certificates, so the instrumented step's effective_input_passes may move
# at most TELEMETRY_TOLERANCE from the plain program's.
_PLAIN_COUNTERPART = {"wordcount_telemetry": "wordcount_pallas",
                      "wordcount_fused_telemetry": "wordcount_fused"}
TELEMETRY_TOLERANCE = 0.01

_BASELINES_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "baselines")
_RATES_PATH = os.path.join(_BASELINES_DIR, "measured_rates.json")


def measured_rates() -> dict:
    with open(_RATES_PATH) as f:
        return json.load(f)


def baseline_path(model: str, baselines_dir: str | None = None) -> str:
    return os.path.join(baselines_dir or _BASELINES_DIR, f"{model}.json")


def load_baseline(model: str, baselines_dir: str | None = None):
    path = baseline_path(model, baselines_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


@core.register_pass
class CostPass:
    pass_id = "hbm-cost"
    description = ("static per-eqn HBM/FLOP cost report; sort pricing "
                   "cross-checked against measured rates; baseline "
                   "regression gate")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        chunk_bytes = trace._chunk_bytes_for(ctx.job)
        report: dict = {"traced_chunk_bytes": chunk_bytes, "programs": {}}
        config = getattr(ctx.job, "config", None)
        if config is not None and hasattr(config, "geometry_label"):
            # Which kernel-geometry set priced this report (ISSUE 12):
            # candidate geometries are first-class here — every derived
            # figure below re-reads the CANDIDATE's resolved values, not
            # the shipped constants.
            report["geometry"] = config.geometry_label

        step_cost = None
        collective_bytes: dict = {}
        for hook, traced in ctx.engine_traces.items():
            if isinstance(traced, trace.TraceFailure):
                continue  # the sharding pass owns trace-failure reporting
            cost = costmodel.program_cost(traced)
            report["programs"][hook] = cost.as_dict()
            collective_bytes[hook] = cost.collective_bytes
            if hook == "step":
                step_cost = cost
        if step_cost is None:
            return out  # nothing traced; nothing to certify

        # The collective family, surfaced instead of silently excluded
        # (ISSUE 16): these bytes price interconnect, not local HBM, so
        # they stay out of effective_input_passes — but a report that
        # omits them under-states the program's traffic.  ``priced`` stays
        # False here; the collective-cost pass flips it (and attaches the
        # modeled seconds) when it has mesh/link context.
        total_coll = sum(collective_bytes.values())
        report["collective"] = {
            "per_program_bytes": collective_bytes,
            "total_bytes": total_coll,
            "priced": False,
            "note": "interconnect bytes, excluded from the HBM total; "
                    "priced by the collective-cost pass (meshcost link "
                    "model) when mesh context is available"}
        if total_coll:
            out.append(core.Finding(
                severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
                hook="finish" if collective_bytes.get("finish") else "step",
                message=(f"collective family: {total_coll >> 10} KiB "
                         "interconnect traffic "
                         f"({', '.join(f'{h}={b}' for h, b in sorted(collective_bytes.items()))} bytes), "
                         "excluded from the HBM total"),
                hint="the collective-cost pass prices these bytes per "
                     "link level (ICI/DCN) via analysis/meshcost.py"))

        passes = step_cost.hbm_bytes / max(chunk_bytes, 1)
        report["effective_input_passes"] = round(passes, 3)
        out.append(core.Finding(
            severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
            hook="step",
            message=(f"step streams {step_cost.hbm_bytes >> 10} KiB HBM for "
                     f"a {chunk_bytes >> 10} KiB chunk = "
                     f"{passes:.2f} effective input passes "
                     f"({step_cost.flops / 1e6:.1f} MFLOP est.)"),
            hint="worst-case bound: cond charges its costlier branch "
                 "(spill fallbacks); fusible eqns charge zero HBM"))

        out.extend(self._sort_findings(ctx, report))
        out.extend(self._baseline_findings(ctx, report))
        out.extend(self._fused_gate_findings(ctx, report))
        out.extend(self._combiner_gate_findings(ctx, report))
        out.extend(self._telemetry_gate_findings(ctx, report))
        ctx.artifacts["cost"] = report
        return out

    # -- telemetry-overhead gate (ISSUE 8) -------------------------------

    def _telemetry_gate_findings(self, ctx, report) -> list[core.Finding]:
        """An instrumented (data-stats) model must price within
        ``TELEMETRY_TOLERANCE`` of its uninstrumented twin's checked-in
        baseline — observability that silently grows the HBM bill would
        invalidate every cost certificate downstream of it."""
        plain_model = _PLAIN_COUNTERPART.get(ctx.model)
        passes = report.get("effective_input_passes")
        if plain_model is None or passes is None:
            return []
        plain = load_baseline(plain_model, ctx.baselines_dir)
        if plain is None:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"uninstrumented counterpart {plain_model!r} has "
                         "no cost baseline: the telemetry overhead cannot "
                         "be gated"),
                hint=f"regenerate with `python -m mapreduce_tpu.analysis "
                     f"{plain_model} --write-baselines` and commit the JSON")]
        plain_raw = plain.get("effective_input_passes")
        if not isinstance(plain_raw, (int, float)) or plain_raw <= 0 \
                or plain.get("traced_chunk_bytes") \
                != report["traced_chunk_bytes"]:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"counterpart {plain_model!r} baseline is not "
                         f"comparable (passes={plain_raw!r}, chunk="
                         f"{plain.get('traced_chunk_bytes')!r} vs "
                         f"{report['traced_chunk_bytes']}): the telemetry "
                         "overhead cannot be gated"),
                hint="keep the twin configs on the same chunk geometry and "
                     "regenerate the baseline")]
        plain_ref = float(plain_raw)
        overhead = (passes - plain_ref) / plain_ref
        report["telemetry_overhead"] = {
            "plain_model": plain_model,
            "plain_effective_input_passes": plain_ref,
            "instrumented_effective_input_passes": passes,
            "overhead_frac": round(overhead, 5),
            "tolerance": TELEMETRY_TOLERANCE}
        if abs(overhead) > TELEMETRY_TOLERANCE:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"data-stats instrumentation moves "
                         f"effective_input_passes {overhead:+.2%} "
                         f"({passes:.2f} vs {plain_ref:.2f} "
                         f"{plain_model}), past the "
                         f"{TELEMETRY_TOLERANCE:.0%} gate: observability "
                         "is regressing the cost certificates"),
                hint="the stats path grew real HBM traffic — keep the "
                     "counters to predicates the map already computes and "
                     "capacity-sized gauge reductions")]
        return [core.Finding(
            severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
            hook="step",
            message=(f"telemetry overhead certified: {passes:.2f} vs "
                     f"{plain_ref:.2f} uninstrumented "
                     f"({overhead:+.3%}, gate {TELEMETRY_TOLERANCE:.0%})"))]

    # -- the 2.6-3.4-passes artifact ------------------------------------

    def _sort_findings(self, ctx, report) -> list[core.Finding]:
        config = getattr(ctx.job, "config", None)
        step = ctx.engine_traces.get("step")
        if config is None or step is None or \
                isinstance(step, trace.TraceFailure):
            return []
        # The measured claim is about the shipped packed fast path: pallas
        # backend, stable2 comparator, XLA sort implementation, at the
        # DEFAULT 384-row window.  The combiner's 512-row geometry sorts a
        # deliberately different row count — extrapolating the measured
        # 384-geometry sort milliseconds over it would manufacture a
        # phantom pricing drift; its own strictly-below gate
        # (_combiner_gate_findings) owns that geometry instead.
        if config.resolved_backend() != "pallas" or \
                config.sort_mode != "stable2" or config.sort_impl != "xla" \
                or config.resolved_combiner_slots:
            return []
        sort = costmodel.find_aggregation_sort(step, num_keys=2)
        if sort is None:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message="pallas/stable2/xla config but no 3-plane "
                        "aggregation sort in the traced step program",
                hint="the packed fast path changed shape; update "
                     "costmodel.find_aggregation_sort with it")]
        expected = costmodel.stable2_sort_rows(
            config.chunk_bytes, config.resolved_block_rows or 256,
            config.resolved_compact_slots)
        rates = measured_rates()
        art = {"traced_rows": sort.rows, "expected_rows": expected,
               "num_keys": sort.num_keys, "location": sort.location}
        report["aggregation_sort"] = art
        if sort.rows != expected:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"aggregation sort carries {sort.rows} rows but "
                         f"kernel geometry predicts {expected} "
                         f"(chunk={config.chunk_bytes}, "
                         f"block_rows={config.resolved_block_rows or 256}, "
                         f"slots={config.resolved_compact_slots})"),
                location=sort.location,
                hint="the sort pricing formula no longer matches the "
                     "program; fix costmodel.stable2_sort_rows or the "
                     "kernel, then re-measure")]
        # A non-default Config.geometry (ISSUE 12): the STATIC leg above
        # already certified the candidate's row arithmetic against the
        # traced program (expected was derived from the candidate's own
        # resolved_block_rows/slots), but the measured sort-milliseconds
        # fixture describes the SHIPPED 384-row geometry — extrapolating
        # it over a different window would manufacture a phantom pricing
        # drift, the combiner-512 lesson.  The candidate's modeled delta
        # lives in the geometry search artifact; the probe pass measures.
        from mapreduce_tpu.config import DEFAULT_GEOMETRY

        if config.resolved_geometry != DEFAULT_GEOMETRY:
            art["measured_leg"] = "skipped: non-default geometry " \
                f"({config.geometry_label}); rates fixture describes the " \
                "shipped default"
            return [core.Finding(
                severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"candidate geometry {config.geometry_label!r}: "
                         f"sort rows {sort.rows} certified against the "
                         "candidate's own window arithmetic; measured-rate "
                         "cross-check pinned to the shipped default "
                         "geometry (probe passes own the measurement)"),
                location=sort.location)]
        # Static extrapolation to the measured production geometry, then
        # the measured-rate leg: passes = sort_ms / one-pass ms.
        prod_rows = costmodel.stable2_sort_rows(
            rates["production_chunk_bytes"],
            config.resolved_block_rows or 256,
            config.resolved_compact_slots)
        pass_ms = (2 * prod_rows * 3 * 4) / (rates["hbm_gbps"] * 1e6)
        lo = rates["sort_ms_range"][0] / pass_ms
        hi = rates["sort_ms_range"][1] / pass_ms
        claimed_lo, claimed_hi = rates["claimed_sort_passes"]
        tol = rates["tolerance"]
        art.update({"production_rows": prod_rows,
                    "one_pass_ms": round(pass_ms, 3),
                    "derived_passes": [round(lo, 3), round(hi, 3)],
                    "claimed_passes": [claimed_lo, claimed_hi],
                    "tolerance": tol})
        ok = (abs(lo - claimed_lo) <= tol * claimed_lo
              and abs(hi - claimed_hi) <= tol * claimed_hi)
        if not ok:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"derived sort cost {lo:.2f}-{hi:.2f} effective "
                         f"HBM passes vs claimed {claimed_lo}-{claimed_hi} "
                         f"(tolerance {tol:.0%}): the round-6 pricing no "
                         "longer holds"),
                location=sort.location,
                hint="re-measure on chip (opshare + BENCHMARKS round 6 "
                     "discipline) and update "
                     "analysis/baselines/measured_rates.json deliberately")]
        return [core.Finding(
            severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
            hook="step",
            message=(f"sort pricing certified: {prod_rows} rows at "
                     f"{rates['production_chunk_bytes'] >> 20} MB chunk -> "
                     f"{lo:.2f}-{hi:.2f} effective HBM passes "
                     f"(claimed {claimed_lo}-{claimed_hi})"),
            location=sort.location)]

    # -- fused-vs-split gate (ISSUE 6) ----------------------------------

    def _fused_gate_findings(self, ctx, report) -> list[core.Finding]:
        config = getattr(ctx.job, "config", None)
        passes = report.get("effective_input_passes")
        if config is None or passes is None or config.map_impl != "fused" \
                or config.resolved_backend() != "pallas" \
                or ctx.model in _FUSED_GATE_EXEMPT:
            return []
        split_model = _SPLIT_COUNTERPART.get(ctx.model)
        if split_model is None:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message="fused map path with no declared split counterpart: "
                        "the fusion's win is unmeasured",
                hint="add the pair to cost._SPLIT_COUNTERPART so the gate "
                     "prices the fusion against its split baseline")]
        split = load_baseline(split_model, ctx.baselines_dir)
        if split is None:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"split counterpart {split_model!r} has no cost "
                         "baseline: the fused-vs-split gap cannot be gated"),
                hint=f"regenerate with `python -m mapreduce_tpu.analysis "
                     f"{split_model} --write-baselines` and commit the JSON")]
        split_raw = split.get("effective_input_passes")
        if not isinstance(split_raw, (int, float)) or split_raw <= 0:
            # A broken baseline must name itself: falling through would
            # publish a nonsense gap and misdiagnose as "the fusion
            # stopped deleting traffic".
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"split counterpart {split_model!r} baseline has "
                         f"no usable effective_input_passes "
                         f"({split_raw!r}): the fused-vs-split gap cannot "
                         "be gated"),
                hint=f"regenerate with `python -m mapreduce_tpu.analysis "
                     f"{split_model} --write-baselines` and commit the JSON")]
        split_ref = float(split_raw)
        if split.get("traced_chunk_bytes") != report["traced_chunk_bytes"]:
            # Do NOT publish a gap: bench._cost_record copies the artifact
            # verbatim, and a passes_saved the gate just declared
            # incomparable must not reach BENCH JSON / benchwatch rows.
            # A baseline MISSING the field is incomparable too — a wildcard
            # match would wave through a different-geometry pricing.
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"fused model traces a "
                         f"{report['traced_chunk_bytes']}-byte chunk but the "
                         f"split counterpart's baseline priced "
                         f"{split.get('traced_chunk_bytes')!r}: the passes "
                         "are not comparable"),
                hint="keep FUSED_ANALYSIS_CONFIG and the split model's "
                     "config on the same chunk geometry (regenerate the "
                     "baseline if it predates geometry recording)")]
        # Geometry certified comparable: publish the gap (bench copies it
        # into BENCH JSON; a LOSING gap still publishes — it is comparable
        # evidence, and the ERROR below gates it).
        report["fused_vs_split"] = {
            "split_model": split_model,
            "split_effective_input_passes": split_ref,
            "fused_effective_input_passes": passes,
            "passes_saved": round(split_ref - passes, 3)}
        if passes >= split_ref:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"fused map path prices {passes:.2f} effective HBM "
                         f"passes, NOT strictly below the split baseline "
                         f"{split_ref:.2f} ({split_model}): the fusion "
                         "stopped deleting traffic"),
                hint="the token-plane round-trip crept back in (or the "
                     "split baseline is stale); fix the kernel path or "
                     "re-measure deliberately, BENCHMARKS.md discipline")]
        return [core.Finding(
            severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
            hook="step",
            message=(f"fusion certified: {passes:.2f} effective HBM passes "
                     f"vs split baseline {split_ref:.2f} ({split_model}) — "
                     f"{split_ref - passes:.2f} passes of token-plane "
                     "round-trip deleted"))]

    # -- combiner-vs-off gate (ISSUE 11) --------------------------------

    def _combiner_gate_findings(self, ctx, report) -> list[core.Finding]:
        """A hot-key-combiner model must price STRICTLY below its
        combiner-off twin's checked-in baseline at the same chunk
        geometry — the fused-vs-split discipline applied to the taller
        combiner windows: the cache only exists to delete sort rows, so
        the moment it stops doing that statically, CI says so."""
        config = getattr(ctx.job, "config", None)
        passes = report.get("effective_input_passes")
        off_model = _UNCOMBINED_COUNTERPART.get(ctx.model)
        if config is None or passes is None or off_model is None:
            return []
        if not config.resolved_combiner_slots:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message="combiner-gated model resolves to NO hot-key cache "
                        "(combiner/map_impl/compact config drifted): the "
                        "gate would compare two identical programs",
                hint="keep COMBINER_ANALYSIS_CONFIG on the fused compact "
                     "path with combiner='hot-cache'")]
        off = load_baseline(off_model, ctx.baselines_dir)
        if off is None:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"combiner-off counterpart {off_model!r} has no "
                         "cost baseline: the combiner's win is unmeasured"),
                hint=f"regenerate with `python -m mapreduce_tpu.analysis "
                     f"{off_model} --write-baselines` and commit the JSON")]
        off_raw = off.get("effective_input_passes")
        if not isinstance(off_raw, (int, float)) or off_raw <= 0:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"counterpart {off_model!r} baseline has no usable "
                         f"effective_input_passes ({off_raw!r}): the "
                         "combiner gap cannot be gated"),
                hint=f"regenerate with `python -m mapreduce_tpu.analysis "
                     f"{off_model} --write-baselines` and commit the JSON")]
        if off.get("traced_chunk_bytes") != report["traced_chunk_bytes"]:
            # Same no-publish rule as the fused gate: an incomparable gap
            # must never reach BENCH JSON via the copied artifact.
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"combiner model traces a "
                         f"{report['traced_chunk_bytes']}-byte chunk but "
                         f"{off_model!r} priced "
                         f"{off.get('traced_chunk_bytes')!r}: the passes "
                         "are not comparable"),
                hint="keep COMBINER_ANALYSIS_CONFIG and its twin on the "
                     "same chunk geometry")]
        off_ref = float(off_raw)
        report["combiner_vs_off"] = {
            "off_model": off_model,
            "off_effective_input_passes": off_ref,
            "combiner_effective_input_passes": passes,
            "passes_saved": round(off_ref - passes, 3)}
        if passes >= off_ref:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"hot-key combiner prices {passes:.2f} effective "
                         f"HBM passes, NOT strictly below the combiner-off "
                         f"baseline {off_ref:.2f} ({off_model}): the cache "
                         "stopped deleting sort traffic"),
                hint="the taller-window arithmetic broke (geometry drift?) "
                     "or the off baseline is stale; fix or re-measure "
                     "deliberately, BENCHMARKS.md discipline")]
        return [core.Finding(
            severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
            hook="step",
            message=(f"combiner certified: {passes:.2f} effective HBM "
                     f"passes vs combiner-off baseline {off_ref:.2f} "
                     f"({off_model}) — {off_ref - passes:.2f} passes of "
                     "sort traffic deleted"))]

    # -- baseline regression gate ---------------------------------------

    def _baseline_findings(self, ctx, report) -> list[core.Finding]:
        passes = report.get("effective_input_passes")
        if passes is None:
            return []
        if ctx.write_baselines:
            path = baseline_path(ctx.model, ctx.baselines_dir)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump({
                    "model": ctx.model,
                    "effective_input_passes": passes,
                    "step_hbm_bytes":
                        report["programs"]["step"]["hbm_bytes"],
                    "step_flops": report["programs"]["step"]["flops"],
                    "traced_chunk_bytes": report["traced_chunk_bytes"],
                    "_regenerate":
                        "python -m mapreduce_tpu.analysis --write-baselines",
                }, f, indent=2)
                f.write("\n")
            return [core.Finding(
                severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
                hook="step", message=f"baseline written: {path}")]
        base = load_baseline(ctx.model, ctx.baselines_dir)
        if base is None:
            return [core.Finding(
                severity=core.WARNING, pass_id=self.pass_id,
                model=ctx.model, hook="step",
                message="no cost baseline checked in for this model",
                hint="regenerate with `python -m mapreduce_tpu.analysis "
                     f"{ctx.model} --write-baselines` and commit the JSON")]
        ref = float(base.get("effective_input_passes", 0.0))
        report["baseline_effective_input_passes"] = ref
        if ref <= 0:
            return []
        growth = (passes - ref) / ref
        if growth > REGRESSION_TOLERANCE:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"predicted HBM passes regressed {growth:+.0%}: "
                         f"{passes:.2f} vs baseline {ref:.2f} "
                         f"(gate: {REGRESSION_TOLERANCE:.0%})"),
                hint="either fix the regression or regenerate baselines "
                     "deliberately (--write-baselines) with the pricing "
                     "note in BENCHMARKS.md")]
        if growth < -REGRESSION_TOLERANCE:
            return [core.Finding(
                severity=core.WARNING, pass_id=self.pass_id,
                model=ctx.model, hook="step",
                message=(f"predicted HBM passes improved {growth:+.0%} vs "
                         f"baseline {ref:.2f}"),
                hint="nice — re-baseline (--write-baselines) so the gate "
                     "protects the win")]
        return []
