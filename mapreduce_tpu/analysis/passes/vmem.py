"""Pass: static VMEM/SMEM budget certifier for Pallas kernels.

Two legs, both from BlockSpec/grid/scratch shapes alone:

* **traced bindings** — every ``pallas_call`` reachable from the model's
  step/finish programs is footprinted (pipelined in/out blocks twice —
  Pallas double-buffers grid blocks so the next DMA overlaps compute —
  plus scratch once) and checked against the kernel's own declared
  ``vmem_limit_bytes`` (else Mosaic's 16 MB default), the physical
  ceiling, and the SMEM budget;
* **shipped geometries** — the production kernel plans the modules declare
  via their metadata hooks (``ops/pallas/meta.production_plans()``) are
  certified the same way, so the stable2/sort3/radix production shapes
  stay covered even though analysis configs trace toy grids
  (:func:`certify_production_kernels`, run once per pipeline by the CLI).

The pass also checks **spill-fallback reachability**: a kernel whose
metadata declares spill semantics (compact tokenize, radix partition)
emits a counter that callers MUST gate an exactness fallback on — a
traced program containing such a kernel but no ``cond`` primitive at all
has statically unreachable fallback, which is how "always exact" silently
becomes "usually exact".
"""

from __future__ import annotations

from mapreduce_tpu.analysis import core
from mapreduce_tpu.ops.pallas import meta


def _footprint(info) -> tuple[int, int]:
    """(vmem_bytes, smem_bytes) of one traced binding: in/out blocks are
    double-buffered, scratch is resident once."""
    vmem = smem = 0
    for r in info.refs:
        mult = 1 if r.role == "scratch" else 2
        if r.memory_space == "smem":
            smem += r.block_bytes * mult
        elif r.memory_space in ("vmem", "any", "?"):
            # Unknown spaces are charged as VMEM: over-counting toward the
            # budget is the safe direction for a certifier.
            vmem += r.block_bytes * mult
    return vmem, smem


def _budget_findings(pass_id, model, hook, label, vmem, smem, limit,
                     location="") -> list[core.Finding]:
    out = []
    budget = limit or meta.VMEM_DEFAULT_LIMIT
    if budget > meta.VMEM_PHYSICAL:
        out.append(core.Finding(
            severity=core.ERROR, pass_id=pass_id, model=model, hook=hook,
            message=(f"{label}: declared vmem_limit_bytes "
                     f"{budget >> 20} MiB exceeds the {meta.VMEM_PHYSICAL >> 20}"
                     f" MiB physical VMEM"),
            location=location,
            hint="lower the compiler-params override; the physical core "
                 "cannot back it"))
    if vmem > budget:
        out.append(core.Finding(
            severity=core.ERROR, pass_id=pass_id, model=model, hook=hook,
            message=(f"{label}: static VMEM footprint {vmem >> 10} KiB "
                     f"exceeds the {budget >> 20} MiB budget "
                     "(double-buffered blocks + scratch)"),
            location=location,
            hint="shrink block shapes or raise vmem_limit_bytes (<= "
                 f"{meta.VMEM_PHYSICAL >> 20} MiB physical) deliberately"))
    if smem > meta.SMEM_BUDGET:
        out.append(core.Finding(
            severity=core.ERROR, pass_id=pass_id, model=model, hook=hook,
            message=(f"{label}: SMEM footprint {smem} B exceeds the "
                     f"{meta.SMEM_BUDGET >> 10} KiB budget"),
            location=location,
            hint="SMEM holds scalars/control only; move bulk state to VMEM"))
    return out


@core.register_pass
class VmemPass:
    pass_id = "vmem-budget"
    description = ("static VMEM/SMEM footprint of every traced Pallas "
                   "kernel vs per-core budgets; spill-fallback "
                   "reachability")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        infos, undigested = ctx.pallas_calls
        for program, src in undigested:
            out.append(core.Finding(
                severity=core.WARNING, pass_id=self.pass_id,
                model=ctx.model, hook=program,
                message=f"pallas_call params unreadable for {src!r} "
                        "(jax internals drift?) — kernel NOT certified",
                hint="update analysis/pallas_info.py for this jax version"))
        kernels = []
        for info in infos:
            vmem, smem = _footprint(info)
            out.extend(_budget_findings(
                self.pass_id, ctx.model, info.program, info.kernel_name,
                vmem, smem, info.vmem_limit_bytes, location=info.src))
            kernels.append({"kernel": info.kernel_name,
                            "program": info.program,
                            "grid": list(info.grid),
                            "vmem_bytes": vmem, "smem_bytes": smem,
                            "vmem_limit_bytes": info.vmem_limit_bytes})
            out.extend(self._spill_findings(ctx, info))
        if kernels:
            ctx.artifacts["vmem"] = kernels
            out.append(core.Finding(
                severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=f"{len(kernels)} pallas kernel binding(s) "
                        "certified under the VMEM/SMEM budgets"))
        return out

    def _spill_findings(self, ctx, info) -> list[core.Finding]:
        km = meta.lookup(info.kernel_name)
        if km is None or not km.spills(len(info.outs)):
            return []
        if info.enclosing_has_cond:
            return []
        return [core.Finding(
            severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
            hook=info.program,
            message=(f"{info.kernel_name} emits a spill counter but the "
                     f"traced {info.program} program contains no cond: "
                     "the exactness fallback is statically unreachable"),
            location=info.src,
            hint="gate a fallback on the spill scalar with lax.cond (the "
                 "compact-path idiom, models/wordcount._map_stream)")]


def certify_production_kernels() -> list[core.Finding]:
    """Certify every SHIPPED kernel geometry's declared plan (the
    metadata hooks in ops/pallas/*) against the budgets — run once per
    pipeline invocation (CLI/tests), not per model."""
    out: list[core.Finding] = []
    for plan in meta.production_plans():
        found = _budget_findings(
            VmemPass.pass_id, "<kernels>", "production",
            f"{plan.kernel} [{plan.geometry}]",
            plan.vmem_bytes, plan.smem_bytes, plan.vmem_limit_bytes)
        out.extend(found)
        if not found:
            out.append(core.Finding(
                severity=core.INFO, pass_id=VmemPass.pass_id,
                model="<kernels>", hook="production",
                message=(f"{plan.kernel} [{plan.geometry}]: "
                         f"{plan.vmem_bytes >> 10} KiB VMEM + "
                         f"{plan.smem_bytes} B SMEM within the "
                         f"{plan.budget >> 20} MiB budget")))
    return out
