"""Pass: fusion-opportunity finder over the traced step/finish jaxprs.

The costcheck byte model (:mod:`..costmodel`) charges **materializing**
primitives their full operand+result HBM traffic; every value flowing
between two adjacent materializing equations is a round-trip the program
pays that a fused kernel would not — exactly the token-plane round-trip
ISSUE 6's fused map path deleted (tokenize -> hash -> window compaction in
one ``pallas_call``).  This pass finds the NEXT such seams mechanically:

* walk each traced program scope by scope (control bodies are their own
  scopes — a cond branch cannot fuse with its sibling), INLINING
  transparent call boundaries: ``pjit``/``closed_call``/``remat``/
  ``shard_map`` wrappers are function-call plumbing XLA inlines (every
  ``jnp.sort``/``jnp.cumsum`` arrives wrapped in its own one-eqn ``pjit``),
  so their bodies continue the enclosing scope with invar/outvar identity
  threaded through — without this, no cross-library-call adjacency is
  visible at all;
* within a scope, track the most recent materializing equation and the
  set of values derived from its outputs through *fusible* (elementwise)
  equations — XLA fuses those chains into their consumers, so they do not
  break adjacency;
* when a later materializing equation consumes one of those values, the
  pair is a **candidate fusion**: the producer's MATERIALIZED output bytes
  (not the consumer-side operand a dtype-changing chain derives from it)
  are HBM traffic a fused implementation saves — the consumer's read
  always, the producer's write only when nothing in the chain escapes to
  another consumer or the program output (an escaping intermediate must
  stay in HBM, so only the read is recovered) — provided the pair's
  combined operand+result footprint fits the
  vmem-budget pass's envelope (:data:`..ops.pallas.meta.VMEM_DEFAULT_LIMIT`,
  Mosaic's default per-core stack budget): a fusion whose working set
  cannot be resident on-chip is not a kernel, it is a different algorithm,
  and flagging it would send someone chasing an impossible win.

Findings are INFO (candidates are leads, not defects — the error-severity
tier-1 gate stays clean by construction); the machine-readable candidate
list lands in the ``fusion`` artifact so tooling can rank programs by
recoverable HBM bytes.  Methodology per CUDA-LLM (PAPERS.md): this pass
proposes variants, the hbm-cost baselines are the fitness gate that
certifies each one actually landed.
"""

from __future__ import annotations

from mapreduce_tpu.analysis import core, costmodel, trace
from mapreduce_tpu.ops.pallas import meta

# At most this many per-program candidates become findings (ranked by
# saved bytes); the artifact always carries the full list.
MAX_FINDINGS_PER_PROGRAM = 4


def _family(eqn) -> str:
    return costmodel._classify(eqn.primitive.name)


def _invar_vars(eqn) -> list:
    """The eqn's Var operands (Literals are unhashable constants — they
    carry no producer, so they can never witness adjacency)."""
    return [v for v in eqn.invars
            if hasattr(v, "aval") and hasattr(v, "count")]


def _is_control(eqn) -> bool:
    name = eqn.primitive.name
    return name in costmodel._CONTROL or (
        bool(trace.eqn_subjaxprs(eqn)) and name != "pallas_call")


# Call-shaped wrappers whose body is semantically inline in the enclosing
# scope (XLA inlines them; crucially every jax.numpy library call — sort,
# cumsum, ... — arrives as its own one-eqn pjit).  cond/while/scan stay
# fresh scopes: their bodies run zero/N times or per-branch.
_TRANSPARENT = {"pjit", "closed_call", "core_call", "xla_call", "remat",
                "checkpoint", "custom_jvp_call", "custom_vjp_call",
                "custom_vjp_call_jaxpr", "custom_partitioning", "shard_map"}


class _Values:
    """Value-identity tracking across inlined call boundaries.

    Canonical ids are fresh INTEGERS assigned each time a defining
    equation is *visited* — never the jaxpr ``Var`` objects themselves:
    JAX caches library-call jaxprs, so two same-shaped ``jnp.sort`` calls
    share one inner jaxpr (and its Vars), and keying on the shared Var
    would alias the two calls' results into one value (a phantom
    adjacency between unrelated equations).  Re-visiting the shared body
    re-assigns new ids, so each invocation's values stay distinct.
    """

    def __init__(self):
        self._env: dict = {}   # Var -> int id (resolved at insert)
        self._next = 0

    def _fresh(self) -> int:
        self._next += 1
        return self._next

    def of(self, v) -> int:
        """The var's current value id (fresh on first sight — top-level
        invars/constvars define themselves)."""
        if v not in self._env:
            self._env[v] = self._fresh()
        return self._env[v]

    def define(self, outvars) -> None:
        """A visited equation defines its outputs as NEW values."""
        for v in outvars:
            self._env[v] = self._fresh()

    def alias(self, dst, src) -> None:
        """Call-boundary plumbing: ``dst`` names the same value as
        ``src`` (inner invar = caller operand; caller outvar = body
        result)."""
        self._env[dst] = self.of(src)


class _Scan:
    """Per-program accumulators shared across every scope of one walk.

    ``raw`` collects candidate tuples; ``root_bytes`` prices each
    materializing producer OUTPUT (the value actually written to HBM —
    pricing the consumer-side derived aval would mis-size dtype-changing
    fusible chains); ``uses``/``chain_uses`` count, per value id, total
    consuming equations vs consumptions by the candidate's own fusible
    chain + consumer, so the finalizer can tell whether the
    intermediate's WRITE is deletable (no other consumer needs it) or
    only the consumer's read is saved."""

    def __init__(self):
        self.raw: list = []
        self.root_bytes: dict = {}   # root id -> producer outvar bytes
        self.uses: dict = {}         # id -> consuming-eqn count
        self.chain_uses: dict = {}   # id -> consumptions inside its chain

    def use(self, ids) -> None:
        for i in ids:
            self.uses[i] = self.uses.get(i, 0) + 1

    def chain_use(self, ids) -> None:
        for i in ids:
            self.chain_uses[i] = self.chain_uses.get(i, 0) + 1


def _scan_scope(eqns, acc: _Scan, values: _Values, state: list) -> None:
    """One linear scope: emit (producer, consumer, roots, chain,
    combined_bytes) candidate tuples into ``acc.raw`` (``roots`` = the
    producer-output ids reaching the consumer, ``chain`` = the chain's
    frozen carried dict for the post-walk fanout check); inline
    transparent call bodies into the CURRENT scope (``values`` threads
    value identity across the call boundary, ``state = [prev, carried]``
    is shared so adjacency survives the return); recurse into control
    bodies as fresh scopes."""
    for eqn in eqns:
        subs = trace.eqn_subjaxprs(eqn)
        if subs and eqn.primitive.name in _TRANSPARENT and len(subs) == 1:
            j = getattr(subs[0], "jaxpr", subs[0])
            if len(j.invars) == len(eqn.invars) \
                    and len(j.outvars) == len(eqn.outvars):
                for inner, outer in zip(j.invars, eqn.invars):
                    if hasattr(outer, "count"):  # Var (Literals carry none)
                        values.alias(inner, outer)
                _scan_scope(j.eqns, acc, values, state)
                for outer, inner in zip(eqn.outvars, j.outvars):
                    if hasattr(inner, "count"):
                        values.alias(outer, inner)
                continue
        ids = {values.of(v) for v in _invar_vars(eqn)}
        acc.use(ids)
        if _is_control(eqn):
            for sub in subs:
                j = getattr(sub, "jaxpr", sub)
                _scan_scope(j.eqns, acc, _Values(), [None, {}])
            state[0], state[1] = None, {}
            continue
        fam = _family(eqn)
        prev, carried = state
        if fam == "fusible":
            # Elementwise chains fuse into their consumers: they extend
            # the producer's reach instead of breaking adjacency.
            hit = [i for i in ids if i in carried]
            values.define(eqn.outvars)
            if prev is not None and hit:
                acc.chain_use(hit)
                roots = frozenset().union(*(carried[i] for i in hit))
                for v in eqn.outvars:
                    carried[values.of(v)] = roots
            continue
        if fam == "collective":
            values.define(eqn.outvars)
            state[0], state[1] = None, {}
            continue
        # A materializing equation.  Does it consume the previous one?
        if prev is not None:
            hit = [i for i in ids if i in carried]
            if hit:
                acc.chain_use(hit)
                roots = sorted(frozenset().union(*(carried[i]
                                                   for i in hit)))
                combined = sum(
                    costmodel._aval_bytes(v.aval)
                    for e in (prev, eqn)
                    for v in list(e.invars) + list(e.outvars)
                    if hasattr(v, "aval"))
                # carried is frozen from here: the consumer becomes the
                # new prev and state[1] is rebound below, so the dict
                # reference is a safe post-walk snapshot of the chain.
                acc.raw.append((prev, eqn, roots, carried, combined))
        values.define(eqn.outvars)
        ids_out = [values.of(v) for v in eqn.outvars]
        for i, v in zip(ids_out, eqn.outvars):
            acc.root_bytes[i] = costmodel._aval_bytes(v.aval)
        state[0] = eqn
        state[1] = {i: frozenset((i,)) for i in ids_out}


@core.register_pass
class FusionPass:
    pass_id = "fusion-opportunity"
    description = ("adjacent materializing eqns whose combined footprint "
                   "fits the VMEM envelope: candidate kernel fusions and "
                   "the HBM bytes each would save")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        artifact: dict = {"programs": {}, "envelope_bytes":
                          meta.VMEM_DEFAULT_LIMIT}
        total_saved = n_candidates = 0
        for hook, traced in ctx.engine_traces.items():
            if isinstance(traced, trace.TraceFailure):
                continue  # the sharding pass owns trace-failure reporting
            acc = _Scan()
            values = _Values()
            _scan_scope(traced.jaxpr.eqns, acc, values, [None, {}])
            # A value the program RETURNS must stay materialized no
            # matter what fuses: count the top-level outputs as uses so
            # the write-deletable check below sees them.
            acc.use({values.of(v) for v in traced.jaxpr.outvars
                     if hasattr(v, "count")})
            cands = []
            for prev, eqn, roots, chain, combined in acc.raw:
                # The envelope gate: pairs whose working set cannot sit in
                # VMEM are NOT candidates (see module docstring).
                if combined > meta.VMEM_DEFAULT_LIMIT:
                    continue
                inter = sum(acc.root_bytes[r] for r in roots)
                if inter <= 0:
                    continue
                saved = 0
                for r in roots:
                    # The consumer's READ of the root always fuses away;
                    # the root's WRITE is deletable only if every use of
                    # the root — and of every chain value derived from it
                    # (an escaping derived value re-reads the root in its
                    # own fusion cluster) — sits inside this chain.
                    chain_ids = [i for i, rs in chain.items() if r in rs]
                    escapes = any(
                        acc.uses.get(i, 0) != acc.chain_uses.get(i, 0)
                        for i in chain_ids)
                    saved += acc.root_bytes[r] * (1 if escapes else 2)
                cands.append({
                    "producer": prev.primitive.name,
                    "consumer": eqn.primitive.name,
                    "location": trace.eqn_location(eqn),
                    "intermediate_bytes": inter,
                    "hbm_bytes_saved": saved,
                    "combined_vmem_bytes": combined,
                })
            cands.sort(key=lambda c: -c["hbm_bytes_saved"])
            artifact["programs"][hook] = cands
            n_candidates += len(cands)
            total_saved += sum(c["hbm_bytes_saved"] for c in cands)
            for c in cands[:MAX_FINDINGS_PER_PROGRAM]:
                out.append(core.Finding(
                    severity=core.INFO, pass_id=self.pass_id,
                    model=ctx.model, hook=hook,
                    message=(f"candidate fusion {c['producer']} -> "
                             f"{c['consumer']}: the "
                             f"{c['intermediate_bytes'] >> 10} KiB "
                             f"intermediate round-trips HBM "
                             f"({c['hbm_bytes_saved'] >> 10} KiB saved "
                             f"fused; combined working set "
                             f"{c['combined_vmem_bytes'] >> 10} KiB fits "
                             "the VMEM envelope)"),
                    location=c["location"],
                    hint="a lead, not a defect: prototype the fused "
                         "kernel, then certify the win with the hbm-cost "
                         "baseline (the ISSUE 6 map-fusion workflow)"))
        artifact["candidates"] = n_candidates
        artifact["total_hbm_bytes_saved"] = total_saved
        ctx.artifacts["fusion"] = artifact
        if n_candidates:
            out.append(core.Finding(
                severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"{n_candidates} candidate fusion(s), "
                         f"{total_saved >> 10} KiB of recoverable HBM "
                         "traffic (see the 'fusion' artifact)")))
        return out
