"""Pass: reducer-algebra checker.

The collective global reduce (tree ``ppermute`` butterfly, ``all_gather``
fold, key-range ``all_to_all``) is only correct when ``merge`` is
associative AND commutative — the engine reorders and re-associates merge
applications freely across devices.  Nothing in the type system enforces
that, and the reference program silently assumed it (``reducer``,
``main.cu:69-108``).

Two complementary checks:

* **structural**: walk the ``merge``/``combine`` jaxprs for primitives that
  are intrinsically non-commutative/non-associative when they land on the
  accumulator path — ``sub``/``div``/``rem``/``pow``, and ``scatter``
  (overwrite semantics: last write wins, so merge order changes results;
  ``scatter-add`` is the order-independent form).  Index arithmetic uses
  these legitimately (sort ranks, prefix-sum differences), so structural
  hits alone are advisory (INFO/WARNING);
* **randomized property check**: the decider, and the fallback for opaque
  subtrees the structural walk cannot see through.  Reachable states are
  generated through the job's own map/combine machinery (random bit
  patterns would violate state invariants and prove nothing) and
  ``merge(a, b) == merge(b, a)`` / ``merge(merge(a, b), c) ==
  merge(a, merge(b, c))`` are checked on them.  A mismatch is an ERROR:
  the collective reduce WILL give device-count-dependent answers.

Jobs whose states carry redundant coordination leaves that are only equal
in real collective context (grep's ``line_carry``, the n-gram seam carry)
declare an ``analysis_observables(state)`` hook returning the result-
bearing sub-pytree the property check should compare.
"""

from __future__ import annotations

import jax
import numpy as np

from mapreduce_tpu.analysis import core, trace

# Primitives that break commutativity/associativity when applied to the
# accumulated values themselves.
_NONCOMMUTATIVE = {"sub", "div", "rem", "pow", "atan2"}
# Scatter variants: plain scatter = overwrite (last write wins).
_SCATTER_OVERWRITE = {"scatter"}


def _structural_findings(ctx: core.AnalysisContext, hook: str,
                         jaxpr) -> list[core.Finding]:
    out = []
    seen: set[str] = set()
    for eqn, _ in trace.iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in _NONCOMMUTATIVE and name not in seen:
            seen.add(name)
            out.append(core.Finding(
                severity=core.INFO, pass_id=AlgebraPass.pass_id,
                model=ctx.model, hook=hook,
                message=(f"non-commutative primitive '{name}' reachable in "
                         f"{hook} (advisory: legitimate for index math; the "
                         "randomized property check decides)"),
                location=trace.eqn_location(eqn),
                hint="ensure the accumulator fold itself is order-independent"))
        elif name in _SCATTER_OVERWRITE and name not in seen:
            seen.add(name)
            out.append(core.Finding(
                severity=core.WARNING, pass_id=AlgebraPass.pass_id,
                model=ctx.model, hook=hook,
                message=("scatter-OVERWRITE reachable in "
                         f"{hook}: last write wins, so merge order changes "
                         "results on colliding keys"),
                location=trace.eqn_location(eqn),
                hint="use scatter-add (.at[idx].add) or scatter-max for "
                     "order-independent accumulation"))
    return out


def _observables(job, state):
    fn = getattr(job, "analysis_observables", None)
    return fn(state) if fn is not None else state


def _diff_leaves(job, x, y) -> list[str]:
    """Paths of observable leaves where two states disagree."""
    xs = trace.named_leaves(_observables(job, x))
    ys = trace.named_leaves(_observables(job, y))
    bad = []
    for (px, lx), (_, ly) in zip(xs, ys):
        ax, ay = np.asarray(lx), np.asarray(ly)
        if np.issubdtype(ax.dtype, np.floating):
            ok = np.allclose(ax, ay, rtol=1e-5, atol=1e-6, equal_nan=True)
        else:
            ok = np.array_equal(ax, ay)
        if not ok:
            bad.append(px)
    return bad


@core.register_pass
class AlgebraPass:
    pass_id = "reducer-algebra"
    description = ("merge must be associative+commutative for the "
                   "collective reduce (structural walk + randomized "
                   "property check on reachable states)")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        for hook in ("merge", "combine"):
            traced = ctx.hook_traces.get(hook)
            if isinstance(traced, trace.TraceFailure):
                out.append(core.Finding(
                    severity=core.INFO, pass_id=self.pass_id,
                    model=ctx.model, hook=hook,
                    message=(f"{hook} is opaque to structural analysis "
                             f"({traced.error_type}: {traced.error}); "
                             "relying on the property-check fallback"),
                    hint="make the hook traceable under abstract inputs"))
            elif traced is not None:
                out.extend(_structural_findings(ctx, hook, traced))

        states = ctx.property_states()
        if len(states) < 3:
            why = ctx.property_failure
            detail = f" ({why.error_type}: {why.error})" if why else ""
            out.append(core.Finding(
                severity=core.WARNING, pass_id=self.pass_id,
                model=ctx.model, hook="merge",
                message="property check skipped: could not generate "
                        f"reachable states on this host{detail}",
                hint="run graphcheck where the job's backend can execute "
                     "(the structural findings above are all it verified)"))
            return out
        a, b, c = states[:3]
        job = ctx.job
        try:
            merge = jax.jit(job.merge)
            ab, ba = merge(a, b), merge(b, a)
            comm_bad = _diff_leaves(job, ab, ba)
            ab_c = merge(merge(a, b), c)
            a_bc = merge(a, merge(b, c))
            assoc_bad = _diff_leaves(job, ab_c, a_bc)
        except Exception as e:
            out.append(core.Finding(
                severity=core.WARNING, pass_id=self.pass_id,
                model=ctx.model, hook="merge",
                message=f"property check failed to run ({type(e).__name__}: "
                        f"{e})",
                hint="merge must accept two states of init_state's shape"))
            return out
        if comm_bad:
            out.append(core.Finding(
                severity=core.ERROR, pass_id=self.pass_id,
                model=ctx.model, hook="merge",
                message=("merge is NOT commutative on reachable states: "
                         f"merge(a,b) != merge(b,a) at {comm_bad[:4]}"),
                location=", ".join(comm_bad[:4]),
                hint="the collective tree/gather reduce reorders operands "
                     "freely; rewrite merge as an order-independent fold "
                     "(sum/min/max/union), or declare coordination-only "
                     "leaves via analysis_observables"))
        if assoc_bad:
            out.append(core.Finding(
                severity=core.ERROR, pass_id=self.pass_id,
                model=ctx.model, hook="merge",
                message=("merge is NOT associative on reachable states: "
                         f"merge(merge(a,b),c) != merge(a,merge(b,c)) at "
                         f"{assoc_bad[:4]}"),
                location=", ".join(assoc_bad[:4]),
                hint="tree-merge re-associates across devices; make the "
                     "fold associative or use the gather strategy with a "
                     "documented fold order"))
        return out
