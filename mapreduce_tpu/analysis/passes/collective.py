"""Pass: mesh-aware collective pricing + SPMD divergence lint (ISSUE 16).

Two jobs, both over the traced engine ``step``/``finish`` programs:

1. **Collective cost** (artifact ``collective_cost``): every collective
   equation is attributed to its mesh axis, the axis to a link level
   (ICI within a host/slice, DCN across — the process-major contract of
   ``parallel/mesh.two_level_mesh``; ``AnalysisContext.fleet`` carries
   the simulated topology of the ``*_fleet`` registry twins), and the
   payload priced through the alpha-beta schedules of
   :mod:`..meshcost` — bytes x link level x schedule -> modeled
   seconds.  The ``collective`` byte family the hbm-cost pass tallies
   but cannot price finally gets seconds, and the hbm-cost artifact's
   ``collective.priced`` marker is flipped true with the modeled total
   attached.  Modeled seconds are baseline-gated like effective input
   passes (``analysis/baselines/<model>.collective.json``, same 20%
   tolerance, same ``--write-baselines`` regeneration).

2. **SPMD divergence lint**: a collective reachable under
   *device-varying* control flow is the static form of the distributed
   hang the chaos harness can only catch dynamically — some
   participants enter the collective, others take the branch without
   it, and the fleet deadlocks.  Inside ``shard_map`` scopes the pass
   runs a varying-taint dataflow (shard-body inputs vary per device;
   ``psum``-family outputs are uniform once they cover every bound
   axis; ``axis_index`` is varying by construction) and ERRORs any
   ``cond``/``switch`` whose predicate is varying while its branches
   disagree on the collectives they execute — a collective in one
   branch only, the same collective over mismatched axis names, or any
   other signature divergence.  Branches that agree (or conds under
   uniform predicates — every participant takes the same branch) stay
   quiet, so the spill-fallback conds of the shipped models pass.
"""

from __future__ import annotations

import json
import os

from mapreduce_tpu.analysis import core, costmodel, meshcost, trace
from mapreduce_tpu.analysis.passes.cost import (REGRESSION_TOLERANCE,
                                                _BASELINES_DIR)

# Communicating collectives (axis_index is per-device arithmetic: it
# varies, but it moves no bytes and cannot hang a peer).
_COMM = frozenset(costmodel._COLLECTIVES) - {"axis_index"} | {"psum_scatter"}

# Collectives whose outputs are identical on every participant after the
# reduction — taint stops here IF the eqn covers every bound mesh axis
# (a psum over only the inner axis of a 2-D mesh still varies across the
# outer one).
_UNIFORMING = frozenset({"psum", "pmax", "pmin", "all_gather", "pbroadcast"})

_LINT_CAP = 8  # findings per program before the pass summarizes
_ENTRY_CAP = 32  # per-program priced-eqn entries kept in the artifact


def collective_baseline_path(model: str,
                             baselines_dir: str | None = None) -> str:
    return os.path.join(baselines_dir or _BASELINES_DIR,
                        f"{model}.collective.json")


def load_collective_baseline(model: str, baselines_dir: str | None = None):
    path = collective_baseline_path(model, baselines_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _is_literal(v) -> bool:
    return hasattr(v, "val")  # jax.core.Literal carries .val; Var does not


def _unwrap(jaxpr):
    return getattr(jaxpr, "jaxpr", jaxpr)


@core.register_pass
class CollectivePass:
    pass_id = "collective-cost"
    description = ("price collective bytes per mesh axis / link level "
                   "(ICI vs DCN, meshcost schedules) with a baseline "
                   "gate; ERROR on collectives under device-varying "
                   "control flow (SPMD divergence)")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        names = tuple(ctx.mesh.axis_names)
        sizes = tuple(int(ctx.mesh.shape[n]) for n in names)
        fleet = dict(getattr(ctx, "fleet", None) or {})
        processes = int(fleet.get("processes", 1))
        mesh_spec = meshcost.MeshSpec.from_mesh(names, sizes, processes)
        rates = meshcost.load_link_rates()
        levels = rates["levels"]

        art: dict = {
            "mesh": {"axes": [{"name": a.name, "size": a.size,
                               "level": a.level} for a in mesh_spec.axes],
                     "devices": mesh_spec.n_devices,
                     "processes": processes,
                     "label": mesh_spec.label()},
            "link_rates": {lv.name: {"alpha_s": lv.alpha_s,
                                     "beta_gbps": lv.beta_bps / 1e9}
                           for lv in levels.values()},
            "programs": {},
        }
        total_s = 0.0
        total_bytes = 0
        for hook, traced in ctx.engine_traces.items():
            if isinstance(traced, trace.TraceFailure):
                continue  # the sharding pass owns trace-failure reporting
            entries: list = []
            unpriced: list = []
            s, b = self._price_walk(traced, 1, mesh_spec, levels,
                                    entries, unpriced)
            art["programs"][hook] = {
                "modeled_s": round(s, 9), "bytes": b,
                "collectives": entries[:_ENTRY_CAP],
                "truncated": max(0, len(entries) - _ENTRY_CAP),
                "unpriced": unpriced[:_ENTRY_CAP]}
            total_s += s
            total_bytes += b
            if unpriced:
                out.append(core.Finding(
                    severity=core.WARNING, pass_id=self.pass_id,
                    model=ctx.model, hook=hook,
                    message=(f"{len(unpriced)} collective eqn(s) over axes "
                             "the mesh spec cannot attribute to a link "
                             f"level (e.g. {unpriced[0]['prim']} over "
                             f"{unpriced[0]['axes']}); their bytes are "
                             "tallied but not priced"),
                    hint="axis names must match the analysis mesh "
                         "(sharding-lint owns unknown-axis errors)"))
            out.extend(self._lint_program(ctx, hook, traced))
        art["modeled_total_s"] = round(total_s, 9)
        art["total_bytes"] = total_bytes

        if total_bytes or any(p["collectives"]
                              for p in art["programs"].values()):
            ctx.artifacts["collective_cost"] = art
            self._mark_priced(ctx, total_s)
            per_level: dict = {}
            for prog in art["programs"].values():
                for e in prog["collectives"]:
                    for pa in e["per_axis"]:
                        per_level[pa["level"]] = \
                            per_level.get(pa["level"], 0.0) + pa["seconds"]
            levels_txt = ", ".join(f"{k}={v * 1e6:.1f}us"
                                   for k, v in sorted(per_level.items()))
            out.append(core.Finding(
                severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"collectives modeled at {total_s * 1e6:.1f}us "
                         f"over mesh {art['mesh']['label']} "
                         f"({total_bytes} bytes; {levels_txt})"),
                hint="alpha-beta bound from "
                     "analysis/baselines/measured_link_rates.json; "
                     "congestion-free, per-device"))
            out.extend(self._baseline_findings(ctx, art))
        return out

    # -- pricing walk (mirrors costmodel.program_cost's control rules) ----

    def _price_walk(self, jaxpr, times, mesh_spec, levels, entries,
                    unpriced):
        """Accumulate (modeled seconds, collective bytes) for one program
        region, multiplied by ``times`` (scan bodies run length times;
        cond charges its costlier branch; while bodies are a one-trip
        lower bound) — the exact control rules of
        :func:`..costmodel.program_cost`, so the bytes priced here equal
        the ``collective_bytes`` the hbm-cost artifact reports."""
        j = _unwrap(jaxpr)
        total_s = 0.0
        total_b = 0
        for eqn in j.eqns:
            prim = eqn.primitive.name
            if prim in _COMM:
                payload = sum(costmodel._aval_bytes(v.aval)
                              for v in eqn.invars)
                axes = trace.eqn_axis_names(eqn)
                priced = meshcost.price_eqn(prim, payload, axes, mesh_spec,
                                            levels)
                loc = trace.eqn_location(eqn)
                if priced is None:
                    unpriced.append({"prim": prim, "bytes": payload,
                                     "axes": axes, "location": loc})
                    total_b += payload * times
                    continue
                entries.append({
                    "prim": prim, "bytes": payload, "times": times,
                    "axes": axes, "schedule": priced["schedule"],
                    "seconds": round(priced["seconds"] * times, 9),
                    "per_axis": [dict(pa, seconds=round(
                        pa["seconds"] * times, 9))
                        for pa in priced["per_axis"]],
                    "location": loc})
                total_s += priced["seconds"] * times
                total_b += payload * times
                continue
            subs = trace.eqn_subjaxprs(eqn)
            if not subs or prim == "pallas_call":
                continue
            if prim == "cond":
                costs = [costmodel.program_cost(s) for s in subs]
                pick = max(range(len(subs)),
                           key=lambda i: costs[i].hbm_bytes + costs[i].flops)
                s, b = self._price_walk(subs[pick], times, mesh_spec,
                                        levels, entries, unpriced)
            elif prim == "scan":
                length = int(eqn.params.get("length", 1) or 1)
                s = b = 0
                for sub in subs:
                    ss, sb = self._price_walk(sub, times * length, mesh_spec,
                                              levels, entries, unpriced)
                    s, b = s + ss, b + sb
            else:  # pjit / while / shard_map / custom calls: once through
                s = b = 0
                for sub in subs:
                    ss, sb = self._price_walk(sub, times, mesh_spec, levels,
                                              entries, unpriced)
                    s, b = s + ss, b + sb
            total_s += s
            total_b += b
        return total_s, total_b

    def _mark_priced(self, ctx, total_s) -> None:
        cost_art = ctx.artifacts.get("cost")
        coll = cost_art.get("collective") if isinstance(cost_art, dict) \
            else None
        if isinstance(coll, dict):
            coll["priced"] = True
            coll["modeled_s"] = round(total_s, 9)
            coll["priced_by"] = self.pass_id

    # -- baseline regression gate (hbm-cost discipline) -------------------

    def _baseline_findings(self, ctx, art) -> list[core.Finding]:
        modeled = art["modeled_total_s"]
        if ctx.write_baselines:
            path = collective_baseline_path(ctx.model, ctx.baselines_dir)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump({
                    "model": ctx.model,
                    "modeled_total_s": modeled,
                    "total_bytes": art["total_bytes"],
                    "mesh": art["mesh"]["label"],
                    "_regenerate":
                        "python -m mapreduce_tpu.analysis --write-baselines",
                }, f, indent=2)
                f.write("\n")
            return [core.Finding(
                severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
                hook="step", message=f"collective baseline written: {path}")]
        base = load_collective_baseline(ctx.model, ctx.baselines_dir)
        if base is None:
            return [core.Finding(
                severity=core.WARNING, pass_id=self.pass_id,
                model=ctx.model, hook="step",
                message="no collective-cost baseline checked in for this "
                        "model",
                hint="regenerate with `python -m mapreduce_tpu.analysis "
                     f"{ctx.model} --write-baselines` and commit the JSON")]
        if base.get("mesh") != art["mesh"]["label"]:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"collective baseline priced mesh "
                         f"{base.get('mesh')!r} but this run traced "
                         f"{art['mesh']['label']!r}: modeled seconds are "
                         "not comparable"),
                hint="re-baseline deliberately (--write-baselines) after "
                     "a topology change")]
        ref = float(base.get("modeled_total_s", 0.0))
        art["baseline_modeled_total_s"] = ref
        if ref <= 0:
            return []
        growth = (modeled - ref) / ref
        if growth > REGRESSION_TOLERANCE:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook="step",
                message=(f"modeled collective seconds regressed "
                         f"{growth:+.0%}: {modeled * 1e6:.1f}us vs baseline "
                         f"{ref * 1e6:.1f}us (gate: "
                         f"{REGRESSION_TOLERANCE:.0%})"),
                hint="either fix the regression or regenerate baselines "
                     "deliberately (--write-baselines)")]
        if growth < -REGRESSION_TOLERANCE:
            return [core.Finding(
                severity=core.WARNING, pass_id=self.pass_id,
                model=ctx.model, hook="step",
                message=(f"modeled collective seconds improved {growth:+.0%}"
                         f" vs baseline {ref * 1e6:.1f}us"),
                hint="nice — re-baseline (--write-baselines) so the gate "
                     "protects the win")]
        return []

    # -- SPMD divergence lint ---------------------------------------------

    def _lint_program(self, ctx, hook, traced) -> list[core.Finding]:
        findings: list[core.Finding] = []
        self._lint_walk(ctx, hook, traced, varying=set(),
                        bound_axes=frozenset(), in_shard=False,
                        findings=findings, seen=set())
        if len(findings) > _LINT_CAP:
            kept, dropped = findings[:_LINT_CAP], len(findings) - _LINT_CAP
            kept.append(core.Finding(
                severity=core.ERROR, pass_id=self.pass_id, model=ctx.model,
                hook=hook,
                message=f"... and {dropped} further divergent-collective "
                        "finding(s) suppressed"))
            return kept
        return findings

    def _collective_signature(self, jaxpr) -> tuple:
        """Canonical multiset of (collective, sorted axis names) a region
        executes — branches of a device-varying cond must agree on it."""
        sig = []
        for eqn, _ in trace.iter_eqns(jaxpr):
            if eqn.primitive.name in _COMM:
                sig.append((eqn.primitive.name,
                            tuple(sorted(trace.eqn_axis_names(eqn)))))
        return tuple(sorted(sig))

    def _divergence_finding(self, ctx, hook, eqn, sigs) -> core.Finding:
        loc = trace.eqn_location(eqn)
        prims = [tuple(p for p, _ in s) for s in sigs]
        n_empty = sum(1 for s in sigs if not s)
        if 0 < n_empty < len(sigs):
            msg = ("collective(s) "
                   f"{sorted({p for s in sigs for p, _ in s})} run in "
                   f"{len(sigs) - n_empty} of {len(sigs)} branches of a "
                   "cond whose predicate varies per device: participants "
                   "taking the empty branch never enter the collective — "
                   "a distributed hang")
            hint = ("hoist the collective out of the cond, or make the "
                    "predicate uniform (reduce it with psum/pmax first)")
        elif len(set(prims)) == 1:
            axes = sorted({a for s in sigs for _, ax in s for a in ax})
            msg = (f"branches of a device-varying cond run the same "
                   f"collective(s) over MISMATCHED axis names {axes}: "
                   "device groups disagree on who participates — a "
                   "distributed hang (or a silent wrong-group reduction)")
            hint = ("use one axis name on every path (the axis the engine "
                    "passes to map_chunk_sharded)")
        else:
            msg = (f"branches of a device-varying cond execute different "
                   f"collective programs {sorted(set(prims))}: "
                   "participants diverge at the first mismatched "
                   "collective — a distributed hang")
            hint = ("make every branch execute the same collective "
                    "sequence, or branch on a uniform predicate")
        return core.Finding(severity=core.ERROR, pass_id=self.pass_id,
                            model=ctx.model, hook=hook, message=msg,
                            location=loc, hint=hint)

    def _lint_walk(self, ctx, hook, jaxpr, varying, bound_axes, in_shard,
                   findings, seen) -> None:
        """Varying-taint dataflow over one jaxpr scope.  ``varying`` is
        the set of this scope's Vars known to differ across devices of
        the bound axes; sub-jaxpr scopes are seeded conservatively (any
        tainted operand taints every body input)."""
        j = _unwrap(jaxpr)
        for eqn in j.eqns:
            prim = eqn.primitive.name
            operands = [v for v in eqn.invars if not _is_literal(v)]
            tainted_in = any(v in varying for v in operands)
            subs = trace.eqn_subjaxprs(eqn)

            if prim == "shard_map":
                mesh = eqn.params.get("mesh")
                axes = frozenset(a for a in
                                 (getattr(mesh, "axis_names", ()) or ())
                                 if isinstance(a, str))
                for sub in subs:
                    sj = _unwrap(sub)
                    self._lint_walk(ctx, hook, sub,
                                    varying=set(sj.invars),
                                    bound_axes=bound_axes | axes,
                                    in_shard=True, findings=findings,
                                    seen=seen)
                # Outputs at this scope are the stacked global arrays —
                # not per-device values of an enclosing shard scope.
                continue

            if prim == "cond" and subs:
                pred = eqn.invars[0]
                pred_varying = in_shard and not _is_literal(pred) \
                    and pred in varying
                if pred_varying:
                    sigs = [self._collective_signature(s) for s in subs]
                    if len(set(sigs)) > 1:
                        key = ("cond", trace.eqn_location(eqn),
                               tuple(sigs))
                        if key not in seen:
                            seen.add(key)
                            findings.append(self._divergence_finding(
                                ctx, hook, eqn, sigs))
                for sub in subs:
                    sj = _unwrap(sub)
                    self._lint_walk(
                        ctx, hook, sub,
                        varying=set(sj.invars) if (in_shard and tainted_in)
                        else set(),
                        bound_axes=bound_axes, in_shard=in_shard,
                        findings=findings, seen=seen)
                if tainted_in:
                    varying.update(eqn.outvars)
                continue

            if subs and prim != "pallas_call":
                for sub in subs:
                    sj = _unwrap(sub)
                    self._lint_walk(
                        ctx, hook, sub,
                        varying=set(sj.invars) if (in_shard and tainted_in)
                        else set(),
                        bound_axes=bound_axes, in_shard=in_shard,
                        findings=findings, seen=seen)

            if prim == "axis_index" and in_shard:
                varying.update(eqn.outvars)
                continue
            if prim in _UNIFORMING and in_shard:
                # Uniform across every axis the eqn reduces/gathers over;
                # still varying if some bound axis is uncovered.
                if set(trace.eqn_axis_names(eqn)) >= bound_axes:
                    continue
                varying.update(eqn.outvars)
                continue
            if tainted_in:
                varying.update(eqn.outvars)
