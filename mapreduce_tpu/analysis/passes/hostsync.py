"""Pass: host-sync & recompile-hazard detector.

The streamed-ingest bench spent 66 s of a 97 s run inside ``dispatch``
(BENCH_r05) — the classic smell of a hot loop that re-enters Python, blocks
on the host, or recompiles.  The worst offenders are *visible statically*
in the step program's jaxpr:

* **callbacks** (``pure_callback``/``io_callback``/``debug_callback``/
  ``debug_print``) inside a jitted body force a device->host->device round
  trip per step — ERROR on the step/finish hot paths;
* **infeed/outfeed** likewise couple every step to the host — ERROR;
* **large baked-in constants**: a big array captured as a jaxpr constant
  (instead of passed as an argument) is re-uploaded per executable and —
  when the Python value varies per call — forces a fresh compile each
  step, the direct recompile hazard of unhashable/varying "static" args.
  WARNING above 1 MiB;
* **program size**: per-dispatch overhead scales with program size; the
  pass reports eqn counts (INFO) so a dispatch-bound phase report can be
  attributed without profiling.

The executor hot path keeps ``step_index`` a *traced* uint32 argument
(``Engine.step`` converts before dispatch) — the pass asserts the traced
step program indeed has the step scalar as an input rather than a constant.
"""

from __future__ import annotations

import numpy as np

from mapreduce_tpu.analysis import core, trace

_CALLBACKS = {"pure_callback", "io_callback", "debug_callback",
              "debug_print", "python_callback"}
_HOST_COUPLING = {"infeed", "outfeed"}
_CONST_WARN_BYTES = 1 << 20


def _const_bytes(jaxpr) -> list[tuple[int, str]]:
    """(nbytes, dtype/shape repr) of every jaxpr constant, recursive."""
    out = []

    def one(closed):
        consts = getattr(closed, "consts", None) or ()
        for c in consts:
            arr = np.asarray(c) if hasattr(c, "shape") else None
            if arr is not None:
                out.append((int(arr.size) * arr.dtype.itemsize,
                            f"{arr.dtype}[{','.join(map(str, arr.shape))}]"))

    one(jaxpr)
    for eqn, _ in trace.iter_eqns(jaxpr):
        for sub in trace.eqn_subjaxprs(eqn):
            one(sub)
    return out


@core.register_pass
class HostSyncPass:
    pass_id = "host-sync"
    description = ("callbacks / host coupling / baked constants / program "
                   "size in the jitted step+finish hot paths")

    def run(self, ctx: core.AnalysisContext) -> list[core.Finding]:
        out: list[core.Finding] = []
        for hook, traced in ctx.engine_traces.items():
            if isinstance(traced, trace.TraceFailure):
                # The sharding pass owns trace-failure reporting (axis
                # errors are its findings); stay quiet here.
                continue
            out.extend(self._program_findings(ctx, hook, traced))
        step = ctx.engine_traces.get("step")
        if step is not None and not isinstance(step, trace.TraceFailure):
            out.extend(self._step_arg_findings(ctx, step))
        out.extend(self._probe_findings(ctx))
        return out

    def _probe_findings(self, ctx) -> list[core.Finding]:
        """ISSUE 5: the executor's in-flight window launches one extra
        program per dispatched group — the completion probe
        (:func:`...runtime.executor._probe_body` over the smallest state
        leaf).  Certify it stays a pure device-side copy: a callback or
        infeed here would put a host round trip back into the no-retry hot
        loop the window exists to pipeline."""
        import jax
        import numpy as _np

        from mapreduce_tpu.runtime import executor as executor_mod

        st = ctx.state_shape
        if isinstance(st, trace.TraceFailure):
            return []  # init_state failures are reported elsewhere
        leaves = jax.tree.leaves(st)
        if not leaves:
            return []
        leaf = min(leaves, key=lambda x: int(
            _np.prod(x.shape, dtype=_np.int64)) * x.dtype.itemsize)
        try:
            traced = jax.make_jaxpr(executor_mod._probe_body)(
                jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
        except Exception as e:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id,
                model=ctx.model, hook="probe",
                message=f"window completion probe does not trace: {e!r}",
                hint="executor._probe_body must stay a trivial jittable "
                     "copy of one state leaf")]
        bad = []
        n_eqns = 0
        for eqn, _ in trace.iter_eqns(traced):
            n_eqns += 1
            name = eqn.primitive.name
            if name in _CALLBACKS or name in _HOST_COUPLING:
                bad.append(core.Finding(
                    severity=core.ERROR, pass_id=self.pass_id,
                    model=ctx.model, hook="probe",
                    message=(f"'{name}' inside the window completion "
                             "probe: every dispatched group would pay a "
                             "host round trip, serializing the pipeline "
                             "the in-flight window exists to build"),
                    location=trace.eqn_location(eqn),
                    hint="keep executor._probe_body a pure device-side "
                         "copy; do telemetry host-side at retirement"))
        if bad:
            return bad
        return [core.Finding(
            severity=core.INFO, pass_id=self.pass_id, model=ctx.model,
            hook="probe",
            message=(f"window completion probe traces to {n_eqns} "
                     f"equation(s) over {leaf.dtype}"
                     f"[{','.join(map(str, leaf.shape))}]: no host "
                     "coupling — the async window adds no hidden sync"))]

    def _program_findings(self, ctx, hook, traced) -> list[core.Finding]:
        out = []
        n_eqns = 0
        seen: set[str] = set()
        for eqn, _ in trace.iter_eqns(traced):
            n_eqns += 1
            name = eqn.primitive.name
            if name in _CALLBACKS and name not in seen:
                seen.add(name)
                out.append(core.Finding(
                    severity=core.ERROR, pass_id=self.pass_id,
                    model=ctx.model, hook=hook,
                    message=(f"host callback '{name}' inside the jitted "
                             f"{hook} program: every dispatch round-trips "
                             "to the host (the 66 s dispatch-phase smell)"),
                    location=trace.eqn_location(eqn),
                    hint="move host work outside the step (log from the "
                         "executor loop; fetch metrics at finish)"))
            elif name in _HOST_COUPLING and name not in seen:
                seen.add(name)
                out.append(core.Finding(
                    severity=core.ERROR, pass_id=self.pass_id,
                    model=ctx.model, hook=hook,
                    message=f"'{name}' couples the {hook} program to the "
                            "host per dispatch",
                    location=trace.eqn_location(eqn),
                    hint="stream data via the executor's staged batches "
                         "instead"))
        for nbytes, desc in _const_bytes(traced):
            if nbytes >= _CONST_WARN_BYTES:
                out.append(core.Finding(
                    severity=core.WARNING, pass_id=self.pass_id,
                    model=ctx.model, hook=hook,
                    message=(f"large constant {desc} ({nbytes >> 20} MiB) "
                             f"baked into the {hook} program: re-shipped "
                             "per executable, and a per-call-varying value "
                             "here means a fresh compile per step"),
                    hint="pass varying arrays as traced arguments (or hash-"
                         "stable statics); keep big tables out of closures"))
        out.append(core.Finding(
            severity=core.INFO, pass_id=self.pass_id,
            model=ctx.model, hook=hook,
            message=f"{hook} program traces to {n_eqns} equations",
            hint="per-dispatch overhead scales with program size; fold "
                 "steps with superstep (lax.scan) when dispatch-bound"))
        return out

    def _step_arg_findings(self, ctx, step) -> list[core.Finding]:
        # The step program's flat inputs are (state leaves..., chunk, step
        # scalar).  A rank-0 invar must exist; if the builder had closed
        # over a Python int instead, each step index would be a distinct
        # baked constant -> one compile per step.
        jaxpr = step.jaxpr
        has_scalar_invar = any(
            getattr(v.aval, "shape", None) == () for v in jaxpr.invars)
        if not has_scalar_invar:
            return [core.Finding(
                severity=core.ERROR, pass_id=self.pass_id,
                model=ctx.model, hook="step",
                message="step program has no scalar (step-index) input: "
                        "the index is baked per trace, forcing one compile "
                        "per step",
                hint="pass step_index as a traced uint32 argument "
                     "(Engine.step does this; custom drivers must too)")]
        return []
