"""Static per-eqn HBM/FLOP accounting over jaxprs (the costcheck model).

The jaxpr-cost-model tradition (XLA's HLO cost analysis; Roofline-style
byte/FLOP accounting) applied to the traced step/finish programs: every
equation is classified into a family and charged HBM bytes and FLOPs from
its operand/result shapes and dtypes alone — no device, no profiler.

The byte model is deliberately simple and DOCUMENTED, because its job is
to be a stable, auditable bound, not a simulator:

* **materializing** primitives (sort, gather/scatter, concatenate, slices,
  transposes, pallas_call, ...) charge input + output bytes — they move
  their operands through HBM;
* **fusible** primitives (elementwise, compares, converts, broadcasts,
  reductions) charge ZERO HBM bytes but do charge FLOPs — XLA fuses
  elementwise chains into their consumers, and charging them as traffic
  made the round-1 hand pricing overshoot 3-5x (the same lesson as
  opshare's wrapper-span double-counting);
* **control** primitives recurse: ``cond`` charges the costlier branch
  (the certified bound is worst-case over the spill-fallback conds),
  ``scan`` charges body x length, ``while`` charges one trip and flags
  itself a lower bound;
* **collectives** are tallied in their own family and excluded from the
  HBM total — they price interconnect, not local HBM.

``effective passes`` = HBM bytes / bytes-of-one-input-pass: how many times
the program streams its own input, the unit the BENCHMARKS dead-end ledger
prices in (the XLA sort measured at 2.6-3.4 such passes, round 6).
"""

from __future__ import annotations

import dataclasses
import math

from mapreduce_tpu.analysis import trace

_COLLECTIVES = {"psum", "pmax", "pmin", "all_gather", "all_to_all",
                "reduce_scatter", "ppermute", "pbroadcast", "axis_index"}
_MATERIALIZING = {"sort", "gather", "scatter", "scatter-add", "scatter_add",
                  "concatenate", "dynamic_slice", "dynamic_update_slice",
                  "slice", "pad", "transpose", "rev", "copy",
                  "pallas_call", "cumsum", "cumlogsumexp", "cummax",
                  "cummin", "cumprod", "associative_scan"}
_CONTROL = {"pjit", "cond", "while", "scan", "shard_map", "custom_jvp_call",
            "custom_vjp_call", "custom_vjp_call_jaxpr", "closed_call",
            "core_call", "xla_call", "remat", "checkpoint", "custom_partitioning"}
# Sort comparators run log2(n) network stages over the comparator keys; the
# FLOP charge is n*log2(n) per operand plane (coarse, but shape-derived).


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(math.prod(shape)) * dtype.itemsize


def _aval_elems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    return int(math.prod(shape))


@dataclasses.dataclass
class Cost:
    """Additive cost of a program region."""

    hbm_read: int = 0
    hbm_written: int = 0
    flops: int = 0
    collective_bytes: int = 0
    eqns: int = 0
    lower_bound: bool = False  # a while-loop body was charged once
    families: dict = dataclasses.field(default_factory=dict)

    @property
    def hbm_bytes(self) -> int:
        return self.hbm_read + self.hbm_written

    def add(self, other: "Cost", times: int = 1) -> None:
        self.hbm_read += other.hbm_read * times
        self.hbm_written += other.hbm_written * times
        self.flops += other.flops * times
        self.collective_bytes += other.collective_bytes * times
        self.eqns += other.eqns * times
        self.lower_bound |= other.lower_bound
        for k, v in other.families.items():
            self.families[k] = self.families.get(k, 0) + v * times

    def charge(self, family: str, read: int, written: int, flops: int) -> None:
        self.hbm_read += read
        self.hbm_written += written
        self.flops += flops
        self.families[family] = self.families.get(family, 0) + read + written

    def as_dict(self) -> dict:
        return {"hbm_read_bytes": self.hbm_read,
                "hbm_written_bytes": self.hbm_written,
                "hbm_bytes": self.hbm_bytes,
                "flops": self.flops,
                "collective_bytes": self.collective_bytes,
                "eqns": self.eqns,
                "lower_bound": self.lower_bound,
                "family_bytes": dict(sorted(self.families.items()))}


def _classify(name: str) -> str:
    if name in _COLLECTIVES:
        return "collective"
    if name == "sort":
        return "sort"
    if name == "pallas_call":
        return "pallas"
    if "gather" in name:
        return "gather"
    if "scatter" in name:
        return "scatter"
    if name in _MATERIALIZING:
        return "layout/copy"
    return "fusible"


def program_cost(jaxpr) -> Cost:
    """Walk one (Closed)Jaxpr, charging each equation per the module
    model.  Shapes inside ``shard_map`` bodies are per-shard, so the
    returned cost is per-device — divide by the per-device input bytes for
    effective passes."""
    j = getattr(jaxpr, "jaxpr", jaxpr)
    cost = Cost()
    for eqn in j.eqns:
        name = eqn.primitive.name
        cost.eqns += 1
        if name in _CONTROL or (trace.eqn_subjaxprs(eqn) and
                                name not in ("pallas_call",)):
            subs = [program_cost(s) for s in trace.eqn_subjaxprs(eqn)]
            if not subs:
                continue
            if name == "cond":
                cost.add(max(subs, key=lambda c: c.hbm_bytes + c.flops))
            elif name == "scan":
                times = int(eqn.params.get("length", 1) or 1)
                for s in subs:
                    cost.add(s, times)
            elif name == "while":
                for s in subs:
                    cost.add(s)
                cost.lower_bound = True
            else:
                for s in subs:
                    cost.add(s)
            continue
        in_bytes = sum(_aval_bytes(v.aval) for v in eqn.invars)
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        out_elems = sum(_aval_elems(v.aval) for v in eqn.outvars)
        family = _classify(name)
        if family == "collective":
            cost.collective_bytes += in_bytes
            cost.families["collective"] = \
                cost.families.get("collective", 0) + in_bytes
        elif family == "sort":
            rows = max((_aval_elems(v.aval) for v in eqn.invars), default=0)
            stages = max(1, int(math.log2(rows)) if rows > 1 else 1)
            cost.charge("sort", in_bytes, out_bytes,
                        rows * stages * max(1, len(eqn.invars)))
        elif name == "dot_general":
            # 2*max(M*K, K*N): coarse contraction FLOPs; operands stream
            # HBM (must precede the fusible branch, which would absorb it).
            m = _aval_elems(eqn.invars[0].aval)
            n = _aval_elems(eqn.invars[1].aval)
            cost.charge("dot", in_bytes, out_bytes, 2 * max(m, n))
        elif family == "fusible":
            cost.charge("fusible", 0, 0, out_elems)
        else:
            cost.charge(family, in_bytes, out_bytes, out_elems)
    return cost


# -- the aggregation-sort artifact ------------------------------------------


@dataclasses.dataclass(frozen=True)
class SortEqnInfo:
    rows: int  # elements per plane
    planes: int  # operands carried through the sort
    num_keys: int
    is_stable: bool
    location: str

    @property
    def pass_bytes(self) -> int:
        """Bytes of one full-stream reorder pass over this sort's operands:
        read + write every plane (the round-6 pricing unit)."""
        return 2 * self.rows * self.planes * 4  # uint32 planes


def find_aggregation_sort(jaxpr, num_keys: int | None = None
                          ) -> SortEqnInfo | None:
    """The packed fast path's aggregation sort: the LARGEST sort equation
    carrying exactly the three uint32 planes (key_hi, key_lo, packed).
    ``num_keys`` narrows to one comparator strategy — stable2 is the
    3-plane ``num_keys=2`` stable sort, sort3 the ``num_keys=3`` one (a
    stable2 step still CONTAINS a sort3 eqn in its spill-fallback branch,
    so the filter matters); the 7-array generic table builds never match."""
    best: SortEqnInfo | None = None
    for eqn, _ in trace.iter_eqns(jaxpr):
        if eqn.primitive.name != "sort":
            continue
        avals = [v.aval for v in eqn.invars]
        if len(avals) != 3:
            continue
        if any(str(getattr(a, "dtype", "")) != "uint32" for a in avals):
            continue
        rows = _aval_elems(avals[0])
        if any(_aval_elems(a) != rows for a in avals):
            continue
        if num_keys is not None and \
                int(eqn.params.get("num_keys", 1)) != num_keys:
            continue
        info = SortEqnInfo(
            rows=rows, planes=3,
            num_keys=int(eqn.params.get("num_keys", 1)),
            is_stable=bool(eqn.params.get("is_stable", False)),
            location=trace.eqn_location(eqn))
        if best is None or info.rows > best.rows:
            best = info
    return best


# The canonical sort-row formula moved to analysis/geometry.py (ISSUE 12:
# the jax-free geometry search prices CANDIDATE geometries with the same
# arithmetic the cost pass asserts against the traced sort equation —
# one formula, re-exported here for the pass's historical import path).
from mapreduce_tpu.analysis.geometry import stable2_sort_rows  # noqa: E402,F401
