"""graphcheck CLI: run the pass pipeline over built-in models.

``python -m mapreduce_tpu.analysis --all-models`` (or
``python tools/graphcheck.py``) analyzes the shipped model zoo and exits
non-zero when any error-severity finding fires — the CI gate.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graphcheck",
        description="jaxpr-level static analyzer for mapreduce_tpu jobs "
                    "(reducer algebra, overflow/dtype, host-sync, "
                    "sharding lints; costcheck: HBM cost, VMEM budget, "
                    "kernel-race certification).")
    p.add_argument("models", nargs="*",
                   help="built-in model names to analyze "
                        "(default: all; see --list)")
    p.add_argument("--all-models", action="store_true",
                   help="analyze every built-in model")
    p.add_argument("--list", action="store_true",
                   help="list built-in models and registered passes")
    p.add_argument("--corpus-bytes", type=int, default=1 << 40,
                   help="corpus-scale bound for the overflow lint "
                        "(default 1 TiB)")
    p.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON")
    p.add_argument("--min-severity", choices=("error", "warning", "info"),
                   default="info",
                   help="hide findings below this severity in text output")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual device count for the analysis mesh "
                        "(forced-CPU; default 8)")
    p.add_argument("--write-baselines", action="store_true",
                   help="regenerate the per-model cost baselines "
                        "(analysis/baselines/*.json) instead of gating "
                        "against them — commit the result deliberately")
    p.add_argument("--baselines-dir", default=None, metavar="DIR",
                   help="read/write cost baselines here instead of the "
                        "checked-in analysis/baselines/ (CI/test override)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    # Static analysis needs devices only to build a mesh; force the CPU
    # platform with a virtual mesh so graphcheck runs anywhere (the
    # tests/driver idiom — runtime/platform.py owns the mechanics).  A
    # process that already initialized a backend keeps it.
    from mapreduce_tpu.runtime.platform import force_cpu

    jax = force_cpu(min_devices=args.devices)

    from mapreduce_tpu import analysis
    from mapreduce_tpu import models as models_mod
    from mapreduce_tpu.parallel.mesh import data_mesh

    if args.list:
        print("models:", ", ".join(models_mod.model_names()))
        print("passes:", ", ".join(analysis.pass_ids()))
        return 0

    names = list(args.models)
    if args.all_models or not names:
        names = models_mod.model_names()

    mesh = data_mesh(min(args.devices, len(jax.devices())))
    report = analysis.Report()
    for name in names:
        try:
            job = models_mod.build_model(name)
        except ValueError as e:
            print(f"graphcheck: {e}", file=sys.stderr)
            return 2
        one = analysis.analyze_job(job, model=name, mesh=mesh,
                                   corpus_bytes=args.corpus_bytes,
                                   baselines_dir=args.baselines_dir,
                                   write_baselines=args.write_baselines)
        report.models.extend(one.models)
        report.extend(one.findings)
        report.artifacts.update(one.artifacts)

    # Shipped kernel geometries are certified once per run, not per model:
    # the metadata hooks (ops/pallas/meta.py) cover production shapes the
    # toy analysis configs never trace.
    from mapreduce_tpu.analysis.passes.vmem import certify_production_kernels

    report.models.append("<kernels>")
    report.extend(certify_production_kernels())

    if args.json:
        print(report.as_json())
    else:
        print(report.format_text(min_severity=args.min_severity))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
