"""Command-line entry point with exact reference-output parity.

The reference CLI ignores ``argv`` and hardcodes ``test.txt``
(``main.cu:164,167``); its stdout contract is::

    Input Data:
    <echo of the input lines>
    --------------------------
    <word>\t<count>        (one line per distinct word, insertion order)
    --------------------------
    Total Count:<N>

SURVEY §7 fixes the contract as: positional file argument, defaulting to
``test.txt`` when absent.  This module preserves that stdout shape byte-for-
byte on the golden fixture while adding real flags (top-k, sizing, JSON
output, device/mesh selection) the reference lacks.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from mapreduce_tpu.config import Config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mapreduce-tpu",
        description="TPU-native MapReduce word count (reference-parity CLI).",
    )
    p.add_argument("input", nargs="?", default="test.txt",
                   help="input text file (default: test.txt, matching the reference)")
    p.add_argument("--top-k", type=int, default=0,
                   help="report only the k most frequent words (0 = all)")
    p.add_argument("--chunk-bytes", type=int, default=1 << 20)
    p.add_argument("--table-capacity", type=int, default=1 << 18)
    p.add_argument("--format", choices=("reference", "json", "tsv"), default="reference",
                   help="'reference' replicates the CUDA program's stdout shape")
    p.add_argument("--no-echo", action="store_true",
                   help="suppress the 'Input Data:' echo (for large corpora)")
    p.add_argument("--stats", action="store_true", help="print timing/throughput to stderr")
    return p


def _decode(words: list[bytes]) -> list[str]:
    """Lossless-enough display decoding: distinct byte words stay distinct."""
    return [w.decode("utf-8", errors="backslashreplace") for w in words]


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    try:
        with open(args.input, "rb") as f:
            data = f.read()
    except OSError as e:
        print(f"error: cannot read {args.input}: {e}", file=sys.stderr)
        return 2

    try:
        config = Config(chunk_bytes=args.chunk_bytes, table_capacity=args.table_capacity)
    except ValueError as e:
        parser.error(str(e))

    t0 = time.perf_counter()
    from mapreduce_tpu.models import wordcount

    result = wordcount.count_words(data, config)
    elapsed = time.perf_counter() - t0

    words, counts = result.words, result.counts
    if args.top_k:
        order = sorted(range(len(words)), key=lambda i: -counts[i])[: args.top_k]
        words = [words[i] for i in order]
        counts = [counts[i] for i in order]

    out = sys.stdout
    display = _decode(words)
    if args.format == "reference":
        if not args.no_echo:
            out.write("Input Data:\n")
            text = data.decode("utf-8", errors="replace")
            out.write(text if text.endswith("\n") or not text else text + "\n")
        out.write("--------------------------\n")
        for w, c in zip(display, counts):
            out.write(f"{w}\t{c}\n")
        out.write("--------------------------\n")
        out.write(f"Total Count:{result.total}\n")
    elif args.format == "tsv":
        for w, c in zip(display, counts):
            out.write(f"{w}\t{c}\n")
    else:
        # "counts" is a list of pairs, not an object: distinct byte words must
        # stay distinct entries even if their display decodings collide.
        out.write(json.dumps({
            "counts": [[w, c] for w, c in zip(display, counts)],
            "total": result.total,
            "distinct": len(result.words),
            "dropped_uniques": result.dropped_uniques,
            "dropped_count": result.dropped_count,
        }) + "\n")

    if args.stats:
        gb = len(data) / 1e9
        print(f"[stats] {len(data)} bytes, {result.total} words, "
              f"{elapsed:.3f}s, {gb / elapsed:.3f} GB/s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
