"""Command-line entry point with exact reference-output parity.

The reference CLI ignores ``argv`` and hardcodes ``test.txt``
(``main.cu:164,167``); its stdout contract is::

    Input Data:
    <echo of the input lines>
    --------------------------
    <word>\t<count>        (one line per distinct word, insertion order)
    --------------------------
    Total Count:<N>

SURVEY §7 fixes the contract as: positional file argument, defaulting to
``test.txt`` when absent.  This module preserves that stdout shape byte-for-
byte on the golden fixture while adding real flags (top-k, sizing, JSON
output, device/mesh selection) the reference lacks.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from mapreduce_tpu.config import (MERGE_STRATEGIES, Config,
                                  PlatformRefusedError)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mapreduce-tpu",
        description="TPU-native MapReduce word count (reference-parity CLI).",
    )
    from mapreduce_tpu.version import __version__

    p.add_argument("--version", action="version",
                   version=f"%(prog)s {__version__}")
    p.add_argument("input", nargs="*", default=["test.txt"],
                   help="input text file(s) (default: test.txt, matching the "
                        "reference; multiple files stream as one corpus)")
    p.add_argument("--top-k", type=int, default=0,
                   help="report only the k most frequent words (0 = all)")
    p.add_argument("--ngram", type=int, default=1, metavar="N",
                   help="count n-token grams instead of single words "
                        "(reported entries are the exact source spans, e.g. "
                        "'Hello World'; --stream counts grams exactly, "
                        "including ones spanning chunk seams)")
    p.add_argument("--chunk-bytes", type=int, default=1 << 25,
                   help="bytes per device step (default 32 MB, the measured "
                        "v5e sweet spot; small inputs are never padded up "
                        "to this)")
    p.add_argument("--table-capacity", type=int, default=1 << 18)
    p.add_argument("--format", choices=("reference", "json", "tsv"), default="reference",
                   help="'reference' replicates the CUDA program's stdout shape")
    p.add_argument("--no-echo", action="store_true",
                   help="suppress the 'Input Data:' echo (for large corpora)")
    p.add_argument("--stream", action="store_true",
                   help="use the sharded streaming executor (for large files)")
    p.add_argument("--checkpoint", default=None, metavar="PATH",
                   help="with --stream: checkpoint state to PATH and resume from it")
    p.add_argument("--checkpoint-every", type=int, default=25, metavar="STEPS")
    p.add_argument("--superstep", type=int, default=1, metavar="K",
                   help="with --stream: fold K chunks into one dispatch "
                        "(lax.scan) to amortize per-dispatch overhead")
    p.add_argument("--inflight", type=int, default=Config.inflight_groups,
                   metavar="W",
                   help="with --stream: keep up to W superstep groups "
                        "dispatched-but-unretired, so reader/staging/H2D "
                        "and device compute of different groups overlap "
                        "(1 = serialized dispatch, the safe fallback and "
                        "A/B control; default %(default)s)")
    p.add_argument("--prefetch-depth", type=int, default=None, metavar="N",
                   help="with --stream: batches the background reader may "
                        "run ahead (default auto: superstep * inflight, "
                        "clamped to [2, 16] — co-tuned with the window)")
    p.add_argument("--autotune", action="store_true",
                   help="with --stream: feed the run's own telemetry "
                        "(timeline bottleneck, data health, window stats) "
                        "through the config autotuner and fold the "
                        "recommended next inflight/prefetch/superstep/"
                        "chunk-bytes into a `tune` ledger record and the "
                        "run summary — the live run is unchanged; "
                        "tools/autotune.py walks the loop offline")
    p.add_argument("--stats", action="store_true", help="print timing/throughput to stderr")
    p.add_argument("--retry", type=int, default=0, metavar="N",
                   help="with --stream: retry a failed device step N times "
                        "from an in-memory known-good snapshot before "
                        "surfacing the failure")
    p.add_argument("--fault-plan", default=None, metavar="SPEC",
                   help="with --stream: deterministic fault injection at "
                        "the executor's named seams (runtime/faults.py "
                        "grammar, e.g. 'seed=42,rate=0.02' or "
                        "'at=dispatch:3:resource'); every fired fault "
                        "lands as a `fault` ledger record so the run can "
                        "be replayed from its own ledger (tools/chaos.py "
                        "replay). Default from MAPREDUCE_FAULT_PLAN; "
                        "results stay bit-identical to the fault-free "
                        "run when the retry budget absorbs the chaos")
    p.add_argument("--distinct-sketch", action="store_true",
                   help="with --stream: carry a HyperLogLog so the distinct "
                        "count stays accurate past table capacity "
                        "(distinct_estimate in json output)")
    p.add_argument("--count-sketch", action="store_true",
                   help="with --stream: carry a Count-Min sketch so any "
                        "word's frequency stays queryable past table "
                        "capacity (see --estimate)")
    p.add_argument("--estimate", action="append", default=[], metavar="WORD",
                   help="report the sketch-estimated count of WORD "
                        "(repeatable; implies --count-sketch)")
    p.add_argument("--sketch-flush-every", type=int, default=1, metavar="K",
                   help="sketched runs: stage per-chunk sketch updates and "
                        "scatter once every K steps (amortizes the fixed "
                        "TPU scatter cost; results are identical)")
    p.add_argument("--grep", action="append", default=None, metavar="PATTERN",
                   help="count occurrences of PATTERN instead of words "
                        "(overlapping matches + exact matching lines; "
                        "composes with --stream for sharded corpora; "
                        "repeatable — P patterns share ONE pass over the "
                        "corpus)")
    p.add_argument("--grep-syntax", choices=("literal", "class"),
                   default="literal",
                   help="pattern syntax for --grep: 'class' enables "
                        "regex-lite byte classes — '.' (any byte but "
                        "newline), '[a-z0-9]', '[^...]', '\\\\x' escapes; "
                        "fixed length, no repetition/alternation")
    p.add_argument("--sample", type=int, default=None, metavar="K",
                   help="report a uniform random sample of K token "
                        "occurrences instead of counts (mergeable bottom-k "
                        "sketch; composes with --stream; deterministic for "
                        "a given corpus + chunking)")
    p.add_argument("--backend", choices=("auto", "xla", "pallas"), default="auto",
                   help="map-phase implementation (auto = pallas fused kernel "
                        "on TPU, xla scan elsewhere)")
    p.add_argument("--merge-every", type=int, default=1, metavar="K",
                   help="fold per-chunk batch tables into the running table "
                        "once every K steps (one K-way reduce replaces K "
                        "pairwise merges; word-count family only; kept "
                        "counts identical)")
    p.add_argument("--merge-strategy",
                   choices=MERGE_STRATEGIES + ("auto",),
                   default="tree",
                   help="collective global-reduce strategy for streamed "
                        "word-count runs: butterfly tree (log2(D) rounds), "
                        "all_gather + fold, key-range all_to_all "
                        "reduce-scatter (one round; the pod-scale choice), "
                        "or a hierarchical 2-D program (hier-kr-tree / "
                        "hier-tree-tree — fleet meshes only; the CLI's 1-D "
                        "mesh rejects them). 'auto' warm-starts from the "
                        "static reduction planner's freshest tuned profile "
                        "(tools/redplan.py --out, read from the "
                        "--geometry-profile file; no matching profile "
                        "falls back loudly to tree)")
    p.add_argument("--merge-overlap", action="store_true",
                   help="with --stream: drain the local tables into a "
                        "device-resident merged accumulator at window "
                        "boundaries (one async partial collective per "
                        "--inflight retired groups), overlapping "
                        "interconnect time with map compute; results stay "
                        "bit-identical and each partial lands as an "
                        "op='partial' collective ledger record (v10); "
                        "requires --retry 0")
    p.add_argument("--compact-slots", type=int, default=None, metavar="S",
                   help="slot-compact the pallas kernel's output to S rows "
                        "per 256-byte window (multiple of 8; 0 = off; "
                        "default auto = 88, +25%% measured on-chip). Cuts "
                        "the aggregation sort's input ~1.45x at S=88; "
                        "windows denser than S fall back to the full path "
                        "for that chunk (always exact)")
    p.add_argument("--sort-mode", choices=("sort3", "stable2", "segmin"),
                   default="stable2",
                   help="aggregation sort strategy on the pallas fast path "
                        "(bit-identical results): 'stable2' drops the third "
                        "sort key via a lane-major kernel layout + stable "
                        "2-key sort; 'segmin' trades it for a segmented min "
                        "scan (CPU only — wedges the TPU). See "
                        "tools/sortbench.py")
    p.add_argument("--sort-impl", choices=("xla", "radix", "radix_partition"),
                   default="xla",
                   help="aggregation sort implementation on the packed fast "
                        "path (bit-identical results): 'xla' = lax.sort, "
                        "the measured floor; 'radix_partition' / 'radix' = "
                        "the Pallas MSD digit partition with per-bucket "
                        "finishing sorts (1 / 2 digit levels; priced "
                        "LOSING from measured rates, shipped for on-chip "
                        "falsification — BENCHMARKS.md round 6). Like "
                        "--sort-mode, applies to the packed fast path only "
                        "(pallas wordcount family + gram builds); the xla "
                        "wordcount path runs the generic build either way")
    p.add_argument("--map-impl", choices=("split", "fused"), default="split",
                   help="pallas map-phase implementation (bit-identical "
                        "results): 'split' = compact kernel + XLA seam "
                        "fix-up over 129 seam windows (the shipped path); "
                        "'fused' = tokenize -> hash -> window compaction in "
                        "ONE kernel pass over raw chunk bytes, lane seams "
                        "resolved in-VMEM from a seam-carry plane — no "
                        "token-plane round-trip to HBM before the "
                        "aggregation sort (costcheck prices the gap; "
                        "'split' stays default until the on-chip window "
                        "confirms the predicted win, BENCHMARKS.md round 9)")
    p.add_argument("--combiner", choices=("off", "hot-cache", "salt", "auto"),
                   default="off",
                   help="skew-adaptive map-side combiner (bit-identical "
                        "results): 'hot-cache' = a per-lane VMEM hot-key "
                        "cache in the fused pallas kernel pre-aggregates "
                        "the top-mass keys per chunk, deleting the "
                        "dominant duplicate rows before the aggregation "
                        "sort (pairs with --map-impl fused; taller kernel "
                        "windows cut sort rows ~25%%, priced by costcheck); "
                        "'salt' = spread a pathological single hot key "
                        "over salted sort segments with an exact de-salt "
                        "at the reduce; 'auto' = resolve from the previous "
                        "run's data-health verdict in --ledger (skew-hot "
                        "-> hot-cache, else off)")
    p.add_argument("--combiner-slots", type=int, default=None, metavar="C",
                   help="per-lane hot-key cache entries for --combiner "
                        "hot-cache (multiple of 8 in [8, 32]; default 8)")
    p.add_argument("--geometry", default=None, metavar="G",
                   help="kernel-geometry set (ISSUE 12): a preset name "
                        "('tall512', 'combiner16'), 'auto' to resolve "
                        "from the geometry search's tuned profile "
                        "(--geometry-profile), or omit for the shipped "
                        "default constants.  Results are bit-identical "
                        "across certified geometries; only the cost "
                        "moves")
    p.add_argument("--geometry-profile", default="tuned.json",
                   metavar="PATH",
                   help="tuned.json searched profiles for --geometry "
                        "auto (default ./tuned.json; missing file "
                        "resolves to the default geometry)")
    p.add_argument("--max-token-bytes", type=int, default=32, metavar="W",
                   help="pallas backend: tokens longer than W bytes are "
                        "dropped into dropped_* accounting (xla counts any "
                        "length)")
    p.add_argument("--rescue-overlong", type=int, default=None, metavar="R",
                   help="pallas backend: re-hash up to R >W-byte tokens per "
                        "chunk exactly via bounded XLA windows (URLs/markup "
                        "on natural text; default auto: 1024 under sort3, "
                        "off under segmin; 0 disables)")
    p.add_argument("--rescue-overlong-max", type=int, default=None,
                   metavar="R2",
                   help="second-tier rescue budget: chunks whose overlong "
                        "count exceeds --rescue-overlong escalate to R2 "
                        "slots under a cond (default auto: chunk_bytes/1024 "
                        "clamped to [R, 65536] — covers URL-dense text with "
                        "no hand-sizing)")
    p.add_argument("--rescue-window", type=int, default=192, metavar="B",
                   help="rescue lookback bound: tokens up to B-1 bytes are "
                        "recovered exactly; longer ones stay accounted")
    p.add_argument("--verify-sample", type=int, default=0, metavar="K",
                   help="after a word-count run, exactly recount K reported "
                        "words host-side (byte-string keyed, no hashing) "
                        "and fail loudly on any mismatch — the detection "
                        "path for the ~n^2/2^65 64-bit key-collision "
                        "envelope (see utils/verify.py); costs one host "
                        "pass over the corpus")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace (XProf/Perfetto) to DIR")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="append a JSONL run ledger to PATH. Streamed runs "
                        "record one step + one group record per dispatch "
                        "group (phase timings, bytes, device memory, "
                        "compile events, lifecycle stamps, data-plane "
                        "counters) plus a per-run data summary; a failed "
                        "run also dumps flight-recorder forensics to "
                        "PATH.flight.json. Batch (non---stream) runs emit "
                        "run_start / data / run_end. Summarize with "
                        "tools/obs_report.py")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write the end-of-run metrics-registry snapshot "
                        "(executor/reader/checkpoint/collective/data "
                        "counters, gauges, histograms) as JSON to PATH")
    p.add_argument("--platform", choices=("auto", "cpu"), default="auto",
                   help="'cpu' forces the run onto the host CPU even when the "
                        "environment pins JAX to an accelerator (equivalent "
                        "to JAX_PLATFORMS=cpu; the escape hatch when the "
                        "device is unreachable)")
    return p


def _apply_platform(requested: str = "auto") -> str:
    """Force the JAX platform when the user asked for one; return the
    EFFECTIVE platform string (lowercase) the run will use.

    The environment may pin ``jax.config.jax_platforms`` at interpreter
    startup (sitecustomize registering a remote PJRT plugin), making the
    ``JAX_PLATFORMS`` env var alone too late — so a user request for cpu
    (``--platform cpu`` or ``JAX_PLATFORMS=cpu``) must land via
    ``jax.config.update`` before any device use
    (:func:`...runtime.platform.force_cpu`, which also verifies the force
    landed).  The return value is read from the CONFIG, not the env var:
    the config is what JAX will actually dial, so the pre-flight probe
    gate must agree with it.
    """
    import os

    from mapreduce_tpu.runtime import platform as platform_mod

    want = "" if requested in (None, "auto") else requested.lower()
    if not want and os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        want = "cpu"
    if want == "cpu":
        platform_mod.force_cpu()
    return platform_mod.effective_platforms()


_CTRL_ESCAPES = str.maketrans({"\t": "\\t", "\n": "\\n", "\r": "\\r",
                               "\x00": "\\x00", "\x0b": "\\x0b", "\x0c": "\\x0c"})


def _decode(words: list[bytes]) -> list[str]:
    """Lossless-enough display decoding: distinct byte words stay distinct.

    Control separators are escaped so n-gram spans (which carry their real
    inter-token separator bytes) keep report lines one-per-entry; single
    words never contain separators, so reference byte-parity is unaffected.
    """
    return [w.decode("utf-8", errors="backslashreplace").translate(_CTRL_ESCAPES)
            for w in words]


def _echo_file(paths: list[str]) -> None:
    """Stream the input bytes to stdout (the reference's line echo,
    main.cu:180) without materializing the files in memory."""
    sys.stdout.write("Input Data:\n")
    sys.stdout.flush()
    for path in paths:
        last = b"\n"
        with open(path, "rb") as f:
            while True:
                block = f.read(1 << 20)
                if not block:
                    break
                sys.stdout.buffer.write(block)
                last = block[-1:]
        if last != b"\n":
            sys.stdout.buffer.write(b"\n")
    sys.stdout.buffer.flush()


def _print_stats(input_bytes: int, count: int, unit: str, elapsed: float) -> None:
    print(f"[stats] {input_bytes} bytes, {count} {unit}, "
          f"{elapsed:.3f}s, {input_bytes / 1e9 / elapsed:.3f} GB/s",
          file=sys.stderr)


def _grep_main(args, paths, data, config, input_bytes: int,
               telemetry=None) -> int:
    """--grep mode: pattern counts instead of word counts.  Multiple --grep
    flags run as ONE fused pass (one ingest, P match masks)."""
    from mapreduce_tpu.models import grep

    from mapreduce_tpu.runtime import profiling

    patterns = [g.encode() for g in args.grep]
    syntax = args.grep_syntax
    kw = dict(config=config, syntax=syntax, checkpoint_path=args.checkpoint,
              checkpoint_every=args.checkpoint_every if args.checkpoint else 0,
              retry=args.retry, telemetry=telemetry)
    batch_tel = telemetry if not args.stream else None
    if batch_tel is not None:
        _batch_run_start(batch_tel, "grep", paths, config, input_bytes)
    t0 = time.perf_counter()
    try:
        with profiling.trace(args.profile):
            if args.stream and len(patterns) == 1:
                results = [grep.grep_file(paths, patterns[0], **kw)]
            elif args.stream:
                results = grep.grep_file_multi(paths, patterns, **kw)
            else:
                # Each file is grepped separately and summed: a newline-
                # bearing pattern (only NUL is rejected) must not fabricate a
                # match across the artificial seam a joined buffer would add.
                per_file = [grep.grep_bytes_multi(c, patterns, syntax)
                            for c in data]
                results = [grep.GrepResult(
                    p, sum(f[i].matches for f in per_file),
                    sum(f[i].lines for f in per_file))
                    for i, p in enumerate(patterns)]
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - t0
    if batch_tel is not None:
        batch_tel.ledger_write("run_end", bytes=input_bytes,
                               words=sum(r.matches for r in results),
                               elapsed_s=round(elapsed, 6))

    out = sys.stdout
    multi = len(results) > 1
    if args.format == "json":
        if multi:
            out.write(json.dumps({"patterns": [
                {"pattern": g, "matches": r.matches, "lines": r.lines}
                for g, r in zip(args.grep, results)]}) + "\n")
        else:
            out.write(json.dumps({"pattern": args.grep[0],
                                  "matches": results[0].matches,
                                  "lines": results[0].lines}) + "\n")
    elif args.format == "tsv":
        if multi:
            for g, r in zip(args.grep, results):
                out.write(f"{g}\t{r.matches}\t{r.lines}\n")
        else:
            out.write(f"matches\t{results[0].matches}\n"
                      f"lines\t{results[0].lines}\n")
    else:
        for g, r in zip(args.grep, results):
            if multi:
                out.write(f"Pattern:{g}\n")
            out.write(f"Matches:{r.matches}\n")
            out.write(f"Matching Lines:{r.lines}\n")
    if args.stats:
        _print_stats(input_bytes, sum(r.matches for r in results),
                     "matches", elapsed)
    return 0


def _sample_main(args, paths, data, config, input_bytes: int,
                 telemetry=None) -> int:
    """--sample mode: uniform token sample instead of counts."""
    from mapreduce_tpu.models import sample as sample_mod
    from mapreduce_tpu.runtime import profiling

    batch_tel = telemetry if not args.stream else None
    if batch_tel is not None:
        _batch_run_start(batch_tel, "sample", paths, config, input_bytes)
    t0 = time.perf_counter()
    try:
        with profiling.trace(args.profile):
            if args.stream:
                result = sample_mod.sample_file(
                    paths, args.sample, config=config,
                    checkpoint_path=args.checkpoint,
                    checkpoint_every=args.checkpoint_every if args.checkpoint else 0,
                    retry=args.retry, telemetry=telemetry)
            else:
                result = sample_mod.sample_bytes(data, args.sample, config)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - t0
    if batch_tel is not None:
        batch_tel.ledger_write("run_end", bytes=input_bytes,
                               words=result.total,
                               elapsed_s=round(elapsed, 6))

    out = sys.stdout
    display = _decode(result.tokens)
    if args.format == "json":
        out.write(json.dumps({"sample": display, "k": args.sample,
                              "total": result.total}) + "\n")
    elif args.format == "tsv":
        for w in display:
            out.write(w + "\n")
    else:
        out.write("--------------------------\n")
        for w in display:
            out.write(w + "\n")
        out.write("--------------------------\n")
        out.write(f"Sampled:{len(display)} of {result.total}\n")
    if args.stats:
        _print_stats(input_bytes, result.total, "tokens", elapsed)
    return 0


def main(argv: list[str] | None = None) -> int:
    import os

    parser = build_parser()
    args = parser.parse_args(argv)
    if args.ngram < 1:
        parser.error(f"--ngram must be >= 1, got {args.ngram}")
    if (args.count_sketch or args.estimate) and not args.stream:
        parser.error("--count-sketch/--estimate require --stream")
    if args.distinct_sketch and not args.stream:
        # Honest failure beats a flag silently ignored: the non-stream path
        # never consults the sketch.
        parser.error("--distinct-sketch requires --stream")
    if args.sketch_flush_every != 1 and not (args.distinct_sketch
                                             or args.count_sketch
                                             or args.estimate):
        parser.error("--sketch-flush-every requires a sketch flag "
                     "(--distinct-sketch / --count-sketch / --estimate)")
    if args.checkpoint and not args.stream:
        parser.error("--checkpoint requires --stream")
    if args.autotune and not args.stream:
        parser.error("--autotune requires --stream (the single-buffer path "
                     "has no pipeline knobs to tune)")
    if args.autotune and (args.grep is not None or args.sample is not None):
        # The hint path rides run_job's word-count-family summary; grep/
        # sample streams have no tuner integration yet — honest refusal.
        parser.error("--autotune applies to word-count runs only")
    if args.retry and not args.stream:
        parser.error("--retry requires --stream (the non-stream path has no "
                     "step dispatch to retry)")
    if args.retry < 0:
        parser.error(f"--retry must be >= 0, got {args.retry}")
    if args.fault_plan is None:
        # The env default binds only to streamed runs: exporting
        # MAPREDUCE_FAULT_PLAN to chaos-test a service must not turn
        # every unrelated batch-mode invocation into a hard error.
        import os as _os

        env_plan = _os.environ.get("MAPREDUCE_FAULT_PLAN") or None
        if env_plan:
            if args.stream:
                args.fault_plan = env_plan
            else:
                print("warning: MAPREDUCE_FAULT_PLAN is set but this is "
                      "not a --stream run; fault injection skipped",
                      file=sys.stderr)
    elif not args.stream:
        parser.error("--fault-plan requires --stream (the injection seams "
                     "exist only on the streamed path)")
    if args.grep_syntax != "literal" and args.grep is None:
        parser.error("--grep-syntax requires --grep")
    if (args.count_sketch or args.estimate) and args.distinct_sketch:
        parser.error("--count-sketch/--estimate and --distinct-sketch are "
                     "mutually exclusive per run")
    if args.sample is not None and args.sample < 1:
        # A distinct None default so an explicit --sample 0 errors instead
        # of silently falling through to word-count mode.
        parser.error(f"--sample must be >= 1, got {args.sample}")
    if args.grep is not None or args.sample is not None:
        # Honest failure beats a flag silently ignored: grep/sample modes
        # do not count words, so word-count-only flags are errors.
        mode = "--grep" if args.grep is not None else "--sample"
        for flag, present in (("--ngram", args.ngram != 1),
                              ("--top-k", bool(args.top_k)),
                              ("--distinct-sketch", args.distinct_sketch),
                              ("--count-sketch", args.count_sketch),
                              ("--estimate", bool(args.estimate)),
                              ("--merge-every", args.merge_every != 1)):
            if present:
                parser.error(f"{flag} is not supported with {mode}")
    if args.grep is not None and args.sample is not None:
        parser.error("--grep and --sample are mutually exclusive")
    if args.verify_sample:
        if args.verify_sample < 0:
            parser.error(f"--verify-sample must be >= 0, got {args.verify_sample}")
        if args.ngram > 1 or args.grep is not None or args.sample is not None:
            # Recounting is word-keyed; gram spans contain separators and
            # grep/sample report no counts to check.
            parser.error("--verify-sample applies to word-count runs only")
    if args.ngram > 1 and args.merge_every > 1:
        # Mirror NGramCountJob's refusal as a clean usage error instead of a
        # mid-run traceback (the n-gram combine is pairwise by design).
        parser.error("--merge-every applies to word-count runs only "
                     "(not --ngram)")
    if args.merge_every != 1 and not args.stream:
        # Honest failure beats a knob silently ignored: the single-buffer
        # path has no per-step merges to batch.
        parser.error("--merge-every requires --stream")
    if args.merge_strategy != "tree":
        # Same honesty rule: the collective strategy only exists on the
        # streamed word-count path (grep/sample states ride psum-like
        # merges; the single-buffer path has no collective at all).
        if not args.stream:
            parser.error("--merge-strategy requires --stream")
        if args.grep is not None or args.sample is not None:
            parser.error("--merge-strategy applies to word-count runs only")
        if args.merge_strategy.startswith("hier-"):
            # The hierarchical 2-D programs place legs on named mesh axes;
            # the CLI drives a 1-D data mesh, so refuse here instead of
            # surfacing the Engine's multi-axis ValueError mid-run.
            parser.error(f"--merge-strategy {args.merge_strategy} needs a "
                         "multi-axis device mesh; the CLI drives a 1-D "
                         "mesh (2-D programs run via the fleet registry "
                         "twins / run_job_global)")
    if args.merge_overlap:
        if not args.stream:
            parser.error("--merge-overlap requires --stream")
        if args.retry:
            parser.error("--merge-overlap requires --retry 0 (the replay "
                         "anchor snapshots local state only; an overlapped "
                         "window has shipped counts the anchor cannot "
                         "restore)")
    paths = args.input
    try:
        # Probe readability up front (the reference silently succeeds on
        # fopen failure, main.cu:174); stream mode never loads the files.
        chunks = []
        input_bytes = 0
        for path in paths:  # one pass so a failure blames the right file
            input_bytes += os.path.getsize(path)
            with open(path, "rb") as f:
                if not args.stream:
                    chunks.append(f.read())
        # Non-stream, multi-file: files are independent token streams; join
        # with a separator so no token merges across a file boundary.  Grep
        # keeps the per-file list instead — its patterns may contain the
        # separator, so any join byte could fabricate cross-file matches.
        if args.stream:
            data = None
        elif args.grep is not None:
            data = chunks
        else:
            data = b"\n".join(chunks)
        del chunks  # don't hold a second copy of the corpus for the run
    except OSError as e:
        print(f"error: cannot read {path}: {e}", file=sys.stderr)
        return 2

    try:
        config = Config(chunk_bytes=args.chunk_bytes, table_capacity=args.table_capacity,
                        backend=args.backend, superstep=args.superstep,
                        inflight_groups=args.inflight,
                        prefetch_depth=args.prefetch_depth,
                        pallas_max_token=args.max_token_bytes,
                        sketch_flush_every=args.sketch_flush_every,
                        sort_mode=args.sort_mode,
                        sort_impl=args.sort_impl,
                        map_impl=args.map_impl,
                        merge_every=args.merge_every,
                        compact_slots=args.compact_slots,
                        combiner=args.combiner,
                        combiner_slots=args.combiner_slots,
                        geometry=args.geometry,
                        rescue_overlong=args.rescue_overlong,
                        rescue_overlong_max=args.rescue_overlong_max,
                        rescue_window=args.rescue_window,
                        fault_plan=args.fault_plan,
                        merge_overlap=args.merge_overlap,
                        autotune="hint" if args.autotune else "off")
    except ValueError as e:
        parser.error(str(e))

    if args.geometry == "auto":
        # Resolve 'auto' BEFORE any trace, against the geometry search's
        # tuned profiles (the combiner='auto' discipline: resolution is
        # the driver's job; the resolved set is stamped into this run's
        # records via the run_start geometry label).
        import dataclasses as _dc

        from mapreduce_tpu.analysis.geometry import resolve_auto

        resolved_geom = resolve_auto(args.geometry_profile)
        config = _dc.replace(
            config, geometry=None if resolved_geom == "default"
            else resolved_geom)
        print(f"geometry: auto -> {config.geometry_label}",
              file=sys.stderr)

    if args.combiner == "auto":
        # Resolve 'auto' BEFORE any trace, against the prior run's records
        # in the --ledger file (append-mode ledgers hold run history — the
        # most recent data-health verdict decides; no ledger history
        # resolves to 'off').  The resolved mode is stamped into this
        # run's own run_start/data records, so a chain of 'auto' runs is
        # a self-documenting feedback loop.  The read goes through the
        # run-history warehouse's resolve_prior (ISSUE 14: the one place
        # "what did runs like this one do before" is answered) — same
        # outcome as the old datahealth.resolve_combiner read.
        import dataclasses as _dc

        records = []
        if args.ledger and os.path.exists(args.ledger):
            from mapreduce_tpu.obs import read_ledger

            records = read_ledger(args.ledger)
        from mapreduce_tpu.obs import history

        resolved = history.resolve_prior(records=records)["combiner"]
        # An 'off' resolution also drops any explicit cache sizing: the
        # slots knob only exists with the cache (Config validates that).
        config = _dc.replace(
            config, combiner=resolved,
            combiner_slots=config.combiner_slots
            if resolved == "hot-cache" else None)
        print(f"combiner: auto -> {resolved}"
              + ("" if records else " (no ledger history)"), file=sys.stderr)

    if args.merge_strategy == "auto":
        # Resolve 'auto' BEFORE any trace, against the static reduction
        # planner's tuned profiles (tools/redplan.py --out writes the
        # modeled winner next to the geometry/autotune profiles) — the
        # geometry/combiner 'auto' discipline: resolution is the driver's
        # job, and the RESOLVED strategy is stamped into this run's
        # run_start, never the literal 'auto'.  The CLI drives a 1-D
        # mesh, so only single-axis strategies are eligible — a hier-*
        # winner planned over a 2-D fleet mesh is skipped, and no
        # matching profile falls back loudly to 'tree'.
        from mapreduce_tpu.obs import history

        single_axis = tuple(s for s in MERGE_STRATEGIES
                            if not s.startswith("hier-"))
        prior = history.resolve_prior(profile_path=args.geometry_profile,
                                      merge_allowed=single_axis)
        args.merge_strategy = prior["merge_strategy"]
        print(f"merge-strategy: auto -> {args.merge_strategy}"
              + ("" if prior["merge_strategy_profile"]
                 else " (no redplan profile; tree)"), file=sys.stderr)

    from mapreduce_tpu.runtime import profiling

    # Persistent XLA compile cache (multi-minute first compiles otherwise;
    # MAPREDUCE_COMPILE_CACHE overrides the location, empty disables).
    profiling.enable_compile_cache()

    # Honor a cpu request (--platform cpu / JAX_PLATFORMS=cpu) BEFORE any
    # device use, then gate the watchdog on the EFFECTIVE platform: the
    # environment may pin jax.config.jax_platforms to a remote accelerator
    # at interpreter startup, in which case the env var alone neither
    # redirects the run nor predicts what JAX will dial.
    try:
        effective = _apply_platform(args.platform)
    except RuntimeError as e:  # cpu force could not land (backend already up)
        print(f"error: {e}", file=sys.stderr)
        return 2

    # Pre-flight device deadline: a wedged TPU relay hangs every device op
    # uninterruptibly, and the reference program at least runs unattended —
    # so when a non-CPU platform is effectively configured, probe
    # reachability ONCE in a bounded subprocess and fail fast with a message
    # instead of producing zero bytes of output forever.  With no platform
    # configured (local dev: jax resolves a local backend, nothing remote to
    # wedge) or cpu forced, no probe runs and no subprocess cost is paid.
    # MAPREDUCE_WATCHDOG_S overrides the deadline (0 disables).
    watchdog_s = float(os.environ.get("MAPREDUCE_WATCHDOG_S", "120"))
    if watchdog_s > 0 and effective not in ("", "cpu"):
        from mapreduce_tpu.runtime.probe import probe_once

        platform, err = probe_once(watchdog_s, platforms=effective)
        if platform is None:
            print(f"error: device unreachable within {watchdog_s:.0f}s "
                  f"({err}). Retry later, or run on the host CPU with "
                  "--platform cpu (or JAX_PLATFORMS=cpu); "
                  "MAPREDUCE_WATCHDOG_S adjusts this deadline (0 disables).",
                  file=sys.stderr)
            return 3

    if args.sort_mode == "segmin":
        from mapreduce_tpu.config import SEGMIN_TPU_ERROR, segmin_allowed

        # Fail with a clean message before any device work when a non-CPU
        # platform is configured.  With NO platform configured (effective
        # ''), jax may still resolve a local TPU — that case is caught by
        # the deep trace-time guard in ops.table.from_packed_rows, whose
        # ValueError the compute paths below surface as a clean exit 2.
        if effective not in ("", "cpu") and not segmin_allowed():
            print(f"error: {SEGMIN_TPU_ERROR}", file=sys.stderr)
            return 2

    # One telemetry handle across every mode: the run ledger + flight
    # recorder (--ledger) and the registry snapshot (--metrics-out).  The
    # finally guarantees the snapshot and ledger flush land even when the
    # run itself failed — a crashed telemetered run must leave evidence.
    # --autotune also forces a handle (ledgerless when --ledger is
    # absent): the hint is derived from telemetry, and the CLI reports it
    # from the handle (count_file never returns the RunResult that
    # carries it).
    tel = None
    if args.ledger or args.metrics_out or args.autotune:
        from mapreduce_tpu import obs

        try:
            tel = obs.Telemetry.create(ledger_path=args.ledger)
        except OSError as e:
            print(f"error: cannot open ledger {args.ledger}: {e}",
                  file=sys.stderr)
            return 2
    try:
        if args.grep is not None:
            return _grep_main(args, paths, data, config, input_bytes,
                              telemetry=tel)
        if args.sample is not None:
            return _sample_main(args, paths, data, config, input_bytes,
                                telemetry=tel)
        return _wordcount_main(args, paths, data, config, input_bytes,
                               telemetry=tel)
    except Exception as e:
        # Orderly preemption shutdown (ISSUE 15): the stream drained and
        # (when configured) checkpointed before raising — a clean
        # one-line exit with the resume cursor, not a crash traceback.
        # Exit 75 (EX_TEMPFAIL): relaunch the same command to resume.
        from mapreduce_tpu.runtime import faults as faults_mod

        if not isinstance(e, faults_mod.Preempted):
            raise
        print(f"preempted: {e}", file=sys.stderr)
        return 75
    finally:
        if tel is not None:
            if args.metrics_out:
                try:
                    with open(args.metrics_out, "w") as f:
                        json.dump(tel.registry.snapshot(), f, indent=1)
                        f.write("\n")
                except OSError as e:
                    print(f"error: cannot write {args.metrics_out}: {e}",
                          file=sys.stderr)
            tel.close()


def _print_tune(telemetry) -> None:
    """Report the run's autotune recommendation (ISSUE 10) to stderr —
    the CLI's "run summary" surface for --autotune.  The full record
    (signals + decision trail) lands in the ledger; stdout stays the
    reference-parity result."""
    t = getattr(telemetry, "last_tune", None)
    if not t:
        print("autotune: no recommendation (hint path unavailable "
              "for this run)", file=sys.stderr)
        return
    changed = t.get("changed") or {}
    moves = ", ".join(f"{k} {v[0]} -> {v[1]}" for k, v in changed.items())
    verdict = "converged" if t.get("converged") else (moves or "no move")
    print(f"autotune: {t.get('rule')} — {verdict}", file=sys.stderr)
    if t.get("reason"):
        print(f"autotune: {t['reason']}", file=sys.stderr)


def _resolved_backend_name(config) -> str:
    """The backend a run will actually use, for ledger records: 'auto'
    must never reach the ledger (consumers key data records on the real
    map path), but backend resolution needs jax — degrade to the raw
    string rather than fail a telemetry write."""
    try:
        return config.resolved_backend()
    except Exception:
        return config.backend


def _batch_run_start(tel, job: str, paths, config, input_bytes: int) -> None:
    """Telemetered BATCH (non---stream) runs emit a run_start up front
    (ISSUE 8 satellite: --ledger no longer requires --stream): the
    single-buffer path has no step dispatches, so the ledger carries
    run_start, a result-derived `data` record, and run_end — enough for
    obs_report/--compare, and a crash leaves the honest run_start-only
    trail."""
    from mapreduce_tpu.runtime.executor import _geometry_stamp

    tel.ledger_write("run_start", driver="single_buffer", job=job,
                     devices=1, chunk_bytes=input_bytes,
                     superstep=1, backend=_resolved_backend_name(config),
                     map_impl=config.map_impl,
                     combiner=config.resolved_combiner,
                     **_geometry_stamp(config),
                     merge_strategy="none", input=list(paths),
                     resume_step=0, resume_offset=0, retry=0)


def _wordcount_main(args, paths, data, config, input_bytes: int,
                    telemetry=None) -> int:
    """Default mode: word counts (the reference's contract)."""
    from mapreduce_tpu.runtime import profiling

    batch_tel = telemetry if not args.stream else None
    if batch_tel is not None:
        job = f"ngram{args.ngram}" if args.ngram > 1 else "wordcount"
        _batch_run_start(batch_tel, job, paths, config, input_bytes)
    t0 = time.perf_counter()
    try:
        with profiling.trace(args.profile):
            if args.stream:
                from mapreduce_tpu.runtime.executor import count_file

                result = count_file(paths, config=config, top_k=args.top_k or None,
                                    distinct_sketch=args.distinct_sketch,
                                    count_sketch=args.count_sketch or bool(args.estimate),
                                    ngram=args.ngram,
                                    merge_strategy=args.merge_strategy,
                                    checkpoint_path=args.checkpoint,
                                    checkpoint_every=args.checkpoint_every if args.checkpoint else 0,
                                    retry=args.retry, telemetry=telemetry)
            else:
                from mapreduce_tpu.models import wordcount

                result = wordcount.count_ngrams(data, args.ngram, config) \
                    if args.ngram > 1 else wordcount.count_words(data, config)
    except PlatformRefusedError as e:
        # Config-vs-platform refusals raised at trace time (the segmin TPU
        # wedge guard) exit cleanly; any OTHER ValueError is a real bug and
        # keeps its traceback.
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - t0
    if batch_tel is not None:
        # Result-derived data record: the batch path runs one jitted
        # program over the whole buffer, so the data-plane story IS the
        # result's accounting (tokens, dropped, distinct, top count).
        batch_tel.ledger_write(
            "data", groups=1, chunks=1,
            backend=_resolved_backend_name(config),
            map_impl=config.map_impl,
            combiner=config.resolved_combiner,
            capacity=config.table_capacity, tokens=result.total,
            dropped_tokens=result.dropped_count,
            dropped_uniques=result.dropped_uniques,
            table_valid=len(result.words),
            top_count=max(result.counts, default=0),
            table_occupancy=round(
                len(result.words) / max(config.table_capacity, 1), 4))
        batch_tel.ledger_write("run_end", bytes=input_bytes,
                               words=result.total,
                               elapsed_s=round(elapsed, 6))

    if args.top_k and not args.stream:  # stream mode already applied top-k
        from mapreduce_tpu.models.wordcount import apply_top_k

        result = apply_top_k(result, args.top_k)
    words, counts = result.words, result.counts

    estimates = {w: result.estimate_count(w.encode()) for w in args.estimate} \
        if result.cms is not None else {}

    out = sys.stdout
    display = _decode(words)
    if args.format == "reference":
        if not args.no_echo:
            _echo_file(paths)
        out.write("--------------------------\n")
        for w, c in zip(display, counts):
            out.write(f"{w}\t{c}\n")
        out.write("--------------------------\n")
        out.write(f"Total Count:{result.total}\n")
        for w, e in estimates.items():
            out.write(f"estimate:{w}\t{e}\n")
    elif args.format == "tsv":
        for w, c in zip(display, counts):
            out.write(f"{w}\t{c}\n")
        for w, e in estimates.items():
            out.write(f"estimate:{w}\t{e}\n")
    else:
        # "counts" is a list of pairs, not an object: distinct byte words must
        # stay distinct entries even if their display decodings collide.
        payload = {
            "counts": [[w, c] for w, c in zip(display, counts)],
            "total": result.total,
            "distinct": result.distinct,
            "dropped_uniques": result.dropped_uniques,
            "dropped_count": result.dropped_count,
        }
        if result.distinct_estimate is not None:
            payload["distinct_estimate"] = round(result.distinct_estimate, 1)
        if estimates:
            payload["estimates"] = estimates
        out.write(json.dumps(payload) + "\n")

    if args.stats:
        _print_stats(input_bytes, result.total, "words", elapsed)

    if args.autotune:
        _print_tune(telemetry)

    if args.verify_sample:
        from mapreduce_tpu.utils.verify import verify_result

        mismatches = verify_result(words, counts, paths,
                                   sample=args.verify_sample)
        if mismatches:
            for w, rep, true in mismatches:
                print(f"verify: MISMATCH {w!r}: reported {rep}, exact "
                      f"recount {true} (possible 64-bit key collision — "
                      "see mapreduce_tpu/utils/verify.py)", file=sys.stderr)
            return 4
        print(f"verify: ok ({min(args.verify_sample, len(words))} words "
              "recounted exactly)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
