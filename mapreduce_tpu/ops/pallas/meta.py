"""Kernel metadata for the static analyzer (costcheck's vmem/race passes).

Each Pallas kernel module declares, next to the kernel it describes:

* a :class:`KernelMeta` entry — spill semantics (does the kernel emit a
  spill counter whose nonzero value REQUIRES an exactness fallback in the
  caller?) keyed by the kernel function's name, which is how a traced
  ``pallas_call`` equation identifies itself (``name_and_src_info``);
* a ``vmem_plan`` hook returning :class:`VmemPlan` — the kernel's
  VMEM/SMEM footprint at a given geometry, computed from the same
  BlockSpec/scratch arithmetic the wrapper uses, so the analyzer can
  certify PRODUCTION geometries without tracing a production-sized
  program (analysis-config traces certify the same kernels at toy grids).

The per-core budgets live here too, single-owner: Mosaic's default VMEM
stack budget is 16 MB (measured: the compact tokenize kernel exceeds it
and ships a 64 MB override — ops/pallas/tokenize.py); v5e carries ~128 MB
physical VMEM, the hard ceiling no override may cross.  SMEM holds only
scalars/control (pallas guide); the shipped kernels use tens of bytes —
the 64 KiB budget is generous headroom, not a measured limit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

VMEM_DEFAULT_LIMIT = 16 * 1024 * 1024  # Mosaic default stack budget
VMEM_PHYSICAL = 128 * 1024 * 1024  # v5e per-core physical VMEM
SMEM_BUDGET = 64 * 1024


@dataclasses.dataclass(frozen=True)
class KernelMeta:
    """Analyzer-facing contract of one Pallas kernel function."""

    name: str  # kernel function __name__ (pallas_call's own id)
    # Does this binding emit a spill counter requiring a caller-side
    # exactness fallback?  Receives (num_outputs,) — the tokenize kernel
    # only spills in compact mode (6 outputs vs the pair path's 5).
    spills: Callable[[int], bool]
    description: str = ""


@dataclasses.dataclass(frozen=True)
class Buffer:
    """One VMEM/SMEM allocation of a kernel binding."""

    label: str
    space: str  # "vmem" | "smem"
    bytes: int
    double_buffered: bool  # pipelined in/out blocks get 2x


@dataclasses.dataclass(frozen=True)
class VmemPlan:
    """Static footprint of one kernel geometry.

    ``vmem_bytes`` counts pipelined blocks twice (Pallas double-buffers
    grid in/out blocks so the next block's DMA overlaps compute) plus
    scratch once.  It is a LOWER bound: Mosaic may spill intermediate
    vectors to VMEM beyond declared blocks — which is exactly why the
    compact kernels ship an explicit ``vmem_limit_bytes`` override and the
    analyzer checks the plan against that declared limit, not against the
    physical ceiling alone.
    """

    kernel: str
    geometry: str  # human description of the knob setting
    buffers: tuple  # Buffer
    vmem_limit_bytes: Optional[int] = None  # kernel's own compiler override

    @property
    def vmem_bytes(self) -> int:
        return sum(b.bytes * (2 if b.double_buffered else 1)
                   for b in self.buffers if b.space == "vmem")

    @property
    def smem_bytes(self) -> int:
        return sum(b.bytes * (2 if b.double_buffered else 1)
                   for b in self.buffers if b.space == "smem")

    @property
    def budget(self) -> int:
        return self.vmem_limit_bytes or VMEM_DEFAULT_LIMIT

    def as_dict(self) -> dict:
        return {"kernel": self.kernel, "geometry": self.geometry,
                "vmem_bytes": self.vmem_bytes,
                "smem_bytes": self.smem_bytes,
                "vmem_limit_bytes": self.vmem_limit_bytes,
                "budget_bytes": self.budget,
                "buffers": [dataclasses.asdict(b) for b in self.buffers]}


_KERNEL_META: dict[str, KernelMeta] = {}


def register(meta: KernelMeta) -> KernelMeta:
    """Add (or replace — test idiom) a kernel's analyzer metadata."""
    _KERNEL_META[meta.name] = meta
    return meta


def lookup(kernel_name: str) -> Optional[KernelMeta]:
    return _KERNEL_META.get(kernel_name)


# -- geometry -> footprint constructors (ISSUE 12) ---------------------------
#
# The SAME BlockSpec/scratch arithmetic the kernel wrappers bind, as pure
# jax-free functions of the geometry knobs: the wrappers' ``vmem_plan``
# hooks delegate here, ``production_plans`` below derives the shipped list
# from ``config.DEFAULT_GEOMETRY`` through the same constructors, and the
# kernel-geometry search (``analysis/geometry.py``) prices CANDIDATE
# geometries with them — one source of truth, so the certified list can
# never silently drift from what the call sites bind.

_LANES = 128  # TPU vector lanes; mirrors ops/pallas/tokenize.LANES


def tokenize_plan(block_rows: int = 256, compact_slots: int = 0,
                  w: int = 32, lane_major: bool = False, fused: bool = False,
                  combiner_slots: int = 0, aux_rows: int = 96) -> VmemPlan:
    """Static VMEM/SMEM footprint of one tokenize-kernel geometry — the
    arithmetic behind ``ops/pallas/tokenize.vmem_plan`` (which delegates
    here).  ``fused`` adds the seam-carry aux plane (``aux_rows`` tall)
    and the in-VMEM transposed byte block of the fused map path;
    ``combiner_slots`` the hot-key cache's four ``(C, LANES)`` planes
    (cache state lives in revisited output blocks, the spill-scalar
    idiom, so it is pipelined like any other output)."""
    out_rows = compact_slots if compact_slots else block_rows // 2
    n_scalars = 3 if compact_slots else 2
    bufs = [Buffer("bytes-in", "vmem", block_rows * _LANES, True)]
    if fused:
        bufs.append(Buffer("seam-aux", "vmem", aux_rows * _LANES, True))
        # The raw lane-view block is transposed (widened) in VMEM before
        # the lookback loop; charge the int32 copy as resident scratch.
        bufs.append(Buffer("transpose-scratch", "vmem",
                           block_rows * _LANES * 4, False))
    bufs += [Buffer(f"plane-out[{i}]", "vmem", out_rows * _LANES * 4, True)
             for i in range(3)]
    bufs += [Buffer(f"scalar[{i}]", "smem", 4, False)
             for i in range(n_scalars)]
    if combiner_slots:
        bufs += [Buffer(f"combiner-cache[{name}]", "vmem",
                        combiner_slots * _LANES * 4, True)
                 for name in ("key_hi", "key_lo", "count", "packed")]
    bufs.append(Buffer("carry-scratch", "vmem", (w + 1) * _LANES * 4, False))
    geom = (f"block_rows={block_rows} w={w} slots={compact_slots or 'pair'}"
            + (" lane-major" if lane_major else "")
            + (" fused" if fused else "")
            + (f" combiner={combiner_slots}" if combiner_slots else ""))
    return VmemPlan(
        kernel="_tokenize_kernel", geometry=geom, buffers=tuple(bufs),
        vmem_limit_bytes=64 * 1024 * 1024 if compact_slots else None)


def radix_plan(bits: int = 3, block_rows: int = 256,
               slab_slack: int = 4) -> VmemPlan:
    """Static VMEM/SMEM footprint of one radix-partition geometry — the
    arithmetic behind ``ops/pallas/radix.vmem_plan`` (which delegates
    here)."""
    from mapreduce_tpu.config import radix_slab_cap

    B = 1 << bits
    cap = radix_slab_cap(bits, block_rows, slab_slack)
    bufs = [Buffer(f"plane-in[{i}]", "vmem", block_rows * _LANES * 4, True)
            for i in range(3)]
    bufs += [Buffer(f"slab-out[{b}]", "vmem", cap * _LANES * 4, True)
             for b in range(3 * B)]
    bufs.append(Buffer("histogram", "smem", B * 4, False))
    bufs.append(Buffer("spill", "smem", 4, False))
    return VmemPlan(
        kernel="_partition_kernel",
        geometry=f"bits={bits} block_rows={block_rows} "
                 f"slab_slack={slab_slack} (cap={cap})",
        buffers=tuple(bufs))


def geometry_plans(geom) -> list[VmemPlan]:
    """Every kernel footprint one :class:`~mapreduce_tpu.config.Geometry`
    implies — the stable2 compact window, the sort3 compact and pair
    variants, the fused map path, the hot-key combiner window, the fused
    spill fallback, and both radix digit widths (the candidate's own and
    the widest legal B, the register-pressure extreme).  The geometry
    search certifies candidates through exactly this list."""
    return [
        tokenize_plan(block_rows=geom.block_rows,
                      compact_slots=geom.compact_slots, lane_major=True),
        tokenize_plan(block_rows=geom.sort3_block_rows,
                      compact_slots=geom.sort3_slots),
        tokenize_plan(block_rows=geom.pair_block_rows),
        tokenize_plan(block_rows=geom.block_rows,
                      compact_slots=geom.compact_slots, lane_major=True,
                      fused=True, aux_rows=geom.aux_rows),
        tokenize_plan(block_rows=geom.combiner_block_rows,
                      compact_slots=geom.compact_slots, lane_major=True,
                      fused=True, aux_rows=geom.aux_rows,
                      combiner_slots=geom.combiner_slots),
        tokenize_plan(block_rows=geom.pair_block_rows, fused=True,
                      aux_rows=geom.aux_rows),
        radix_plan(bits=geom.radix_bits, block_rows=geom.radix_block_rows,
                   slab_slack=geom.radix_slab_slack),
        radix_plan(bits=5, block_rows=geom.radix_block_rows,
                   slab_slack=geom.radix_slab_slack),
    ]


def production_plans() -> list[VmemPlan]:
    """Every SHIPPED kernel geometry's static footprint — derived from
    ``config.DEFAULT_GEOMETRY`` through the same constructor the geometry
    search uses (ISSUE 12: one source of truth; the hand-maintained list
    this replaces could silently drift from the kernel call sites).  The
    set the vmem pass certifies regardless of which analysis-config
    models happened to trace them."""
    from mapreduce_tpu.config import DEFAULT_GEOMETRY

    return geometry_plans(DEFAULT_GEOMETRY)
