"""Kernel metadata for the static analyzer (costcheck's vmem/race passes).

Each Pallas kernel module declares, next to the kernel it describes:

* a :class:`KernelMeta` entry — spill semantics (does the kernel emit a
  spill counter whose nonzero value REQUIRES an exactness fallback in the
  caller?) keyed by the kernel function's name, which is how a traced
  ``pallas_call`` equation identifies itself (``name_and_src_info``);
* a ``vmem_plan`` hook returning :class:`VmemPlan` — the kernel's
  VMEM/SMEM footprint at a given geometry, computed from the same
  BlockSpec/scratch arithmetic the wrapper uses, so the analyzer can
  certify PRODUCTION geometries without tracing a production-sized
  program (analysis-config traces certify the same kernels at toy grids).

The per-core budgets live here too, single-owner: Mosaic's default VMEM
stack budget is 16 MB (measured: the compact tokenize kernel exceeds it
and ships a 64 MB override — ops/pallas/tokenize.py); v5e carries ~128 MB
physical VMEM, the hard ceiling no override may cross.  SMEM holds only
scalars/control (pallas guide); the shipped kernels use tens of bytes —
the 64 KiB budget is generous headroom, not a measured limit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

VMEM_DEFAULT_LIMIT = 16 * 1024 * 1024  # Mosaic default stack budget
VMEM_PHYSICAL = 128 * 1024 * 1024  # v5e per-core physical VMEM
SMEM_BUDGET = 64 * 1024


@dataclasses.dataclass(frozen=True)
class KernelMeta:
    """Analyzer-facing contract of one Pallas kernel function."""

    name: str  # kernel function __name__ (pallas_call's own id)
    # Does this binding emit a spill counter requiring a caller-side
    # exactness fallback?  Receives (num_outputs,) — the tokenize kernel
    # only spills in compact mode (6 outputs vs the pair path's 5).
    spills: Callable[[int], bool]
    description: str = ""


@dataclasses.dataclass(frozen=True)
class Buffer:
    """One VMEM/SMEM allocation of a kernel binding."""

    label: str
    space: str  # "vmem" | "smem"
    bytes: int
    double_buffered: bool  # pipelined in/out blocks get 2x


@dataclasses.dataclass(frozen=True)
class VmemPlan:
    """Static footprint of one kernel geometry.

    ``vmem_bytes`` counts pipelined blocks twice (Pallas double-buffers
    grid in/out blocks so the next block's DMA overlaps compute) plus
    scratch once.  It is a LOWER bound: Mosaic may spill intermediate
    vectors to VMEM beyond declared blocks — which is exactly why the
    compact kernels ship an explicit ``vmem_limit_bytes`` override and the
    analyzer checks the plan against that declared limit, not against the
    physical ceiling alone.
    """

    kernel: str
    geometry: str  # human description of the knob setting
    buffers: tuple  # Buffer
    vmem_limit_bytes: Optional[int] = None  # kernel's own compiler override

    @property
    def vmem_bytes(self) -> int:
        return sum(b.bytes * (2 if b.double_buffered else 1)
                   for b in self.buffers if b.space == "vmem")

    @property
    def smem_bytes(self) -> int:
        return sum(b.bytes * (2 if b.double_buffered else 1)
                   for b in self.buffers if b.space == "smem")

    @property
    def budget(self) -> int:
        return self.vmem_limit_bytes or VMEM_DEFAULT_LIMIT

    def as_dict(self) -> dict:
        return {"kernel": self.kernel, "geometry": self.geometry,
                "vmem_bytes": self.vmem_bytes,
                "smem_bytes": self.smem_bytes,
                "vmem_limit_bytes": self.vmem_limit_bytes,
                "budget_bytes": self.budget,
                "buffers": [dataclasses.asdict(b) for b in self.buffers]}


_KERNEL_META: dict[str, KernelMeta] = {}


def register(meta: KernelMeta) -> KernelMeta:
    """Add (or replace — test idiom) a kernel's analyzer metadata."""
    _KERNEL_META[meta.name] = meta
    return meta


def lookup(kernel_name: str) -> Optional[KernelMeta]:
    return _KERNEL_META.get(kernel_name)


def production_plans() -> list[VmemPlan]:
    """Every SHIPPED kernel geometry's static footprint: the stable2
    default, the sort3 compact and pair variants, and both radix levels'
    partition kernel — the set the vmem pass certifies regardless of which
    analysis-config models happened to trace them."""
    from mapreduce_tpu.ops.pallas import radix, tokenize

    return [
        tokenize.vmem_plan(block_rows=384, compact_slots=128,
                           lane_major=True),   # stable2 default
        tokenize.vmem_plan(block_rows=256, compact_slots=88),  # sort3 compact
        tokenize.vmem_plan(block_rows=256, compact_slots=0),   # pair path
        tokenize.vmem_plan(block_rows=384, compact_slots=128,
                           lane_major=True, fused=True),  # fused map path
        tokenize.vmem_plan(block_rows=512, compact_slots=128,
                           lane_major=True, fused=True,
                           combiner_slots=8),  # hot-key combiner (ISSUE 11)
        tokenize.vmem_plan(block_rows=256, compact_slots=0,
                           fused=True),        # fused spill fallback (pair)
        radix.vmem_plan(),                                     # default B=8
        radix.vmem_plan(bits=5),                               # widest legal B
    ]
