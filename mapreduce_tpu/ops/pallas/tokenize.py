"""Pallas TPU kernel: fused tokenize + rolling-hash in one HBM pass.

TPU-native replacement for the map-phase device work of the reference (the
per-thread char-copy loops of ``mapper``, ``main.cu:37-54``, plus the host
tokenizer, ``main.cu:187-202``).  The pure-XLA formulation in
:mod:`mapreduce_tpu.ops.tokenize` runs a segmented ``associative_scan`` —
log-depth but several full-array materializations.  This kernel computes the
identical per-position (key_hi, key_lo, length) outputs in a *single* pass:
bytes stream HBM -> VMEM once, all W-byte lookback happens on-chip, and only
the three token-end output planes go back to HBM.

Layout
------
A flat uint8 chunk of N bytes is viewed column-major as ``(L, 128)``:
lane j holds the contiguous byte segment ``[j*L, (j+1)*L)``, rows are byte
positions within the segment.  A shift by one *byte* is then a shift by one
*row* — a cheap sublane move — and the W-step lookback loop is W static row
slices, fully vectorized over 128 lanes x block_rows sublanes.

The grid walks row-blocks top to bottom.  TPU grids execute sequentially, so
a ``(W+1, 128)`` VMEM scratch carries the previous block's tail rows: the
lookback window never re-reads HBM.

Token length is bounded by W (default 32).  Three cases leave the kernel for
the two tiny fix-up passes the wrapper runs in XLA:

* tokens touching a 128-lane *seam* (the boundary between consecutive byte
  segments, where "previous byte" lives in another lane) are suppressed
  in-kernel and re-tokenized from 129 seam windows of ``2W+2`` bytes each
  (<= 9 KB total) — the chunk-seam strategy of SURVEY §7 applied at lane
  granularity;
* tokens longer than W bytes are dropped and *counted* (exactly once, at
  their true end) into an overlong counter the caller folds into the count
  table's ``dropped_*`` accounting — never silent corruption (contrast the
  reference's unchecked buffer overflows past MAX_WORD_COUNT, ``main.cu:184``);
* the hash recurrence, fmix32 finalization, and sentinel clamping replicate
  :func:`mapreduce_tpu.ops.tokenize.tokenize` bit-for-bit, so tables built
  from either backend merge interchangeably.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mapreduce_tpu import constants
from mapreduce_tpu.ops import tokenize as tok_ops
from mapreduce_tpu.ops.pallas import meta
from mapreduce_tpu.ops.tokenize import TokenStream

LANES = 128
DEFAULT_MAX_TOKEN = 32  # W: max token bytes handled fully on the fast path
DEFAULT_BLOCK_ROWS = 256

# Fused-path seam-carry plane (one small second kernel input): rows
# [0, w+1) hold each lane's PREVIOUS lane's last w+1 bytes (PAD for lane
# 0), rows [AUX_HEAD_ROW, AUX_ROWS) hold the NEXT lane's first byte
# replicated (PAD for lane 127).  96 rows keep the uint8 block on the
# (32, 128) tile grid; AUX_HEAD_ROW = 64 leaves room for the W <= 63
# maximum tail.  With this plane resident the kernel resolves 128-lane
# seams entirely in VMEM — the XLA seam fix-up pass (and its per-chunk
# re-read of seam bytes from HBM) disappears from the fused map path.
AUX_ROWS = 96
AUX_HEAD_ROW = 64

# Analyzer contract (costcheck vmem/race passes): compact mode emits a
# spill counter (output #6) whose nonzero value means the planes are
# INCOMPLETE — the caller MUST wrap a full-resolution fallback in lax.cond
# (models/wordcount._map_stream does).  The pair path (5 outputs) is exact.
meta.register(meta.KernelMeta(
    name="_tokenize_kernel",
    spills=lambda num_outputs: num_outputs >= 6,
    description="fused tokenize+hash; compact mode spills past the "
                "per-window slot budget"))


def vmem_plan(block_rows: int = DEFAULT_BLOCK_ROWS,
              compact_slots: int = 0, w: int = DEFAULT_MAX_TOKEN,
              lane_major: bool = False, fused: bool = False,
              combiner_slots: int = 0,
              aux_rows: int = AUX_ROWS) -> meta.VmemPlan:
    """Static VMEM/SMEM footprint of one tokenize-kernel geometry — the
    analyzer's metadata hook (ops/pallas/meta.py).  Delegates to the
    jax-free :func:`...meta.tokenize_plan` constructor (ISSUE 12: the
    SAME arithmetic prices search candidates and derives the shipped
    ``production_plans`` list, so footprints cannot drift from what
    :func:`_column_pass` binds)."""
    return meta.tokenize_plan(block_rows=block_rows,
                              compact_slots=compact_slots, w=w,
                              lane_major=lane_major, fused=fused,
                              combiner_slots=combiner_slots,
                              aux_rows=aux_rows)


class CombinerCache(NamedTuple):
    """Flushed hot-key cache planes of one chunk (ISSUE 11): per lane, up
    to C resident entries — the first C distinct keys the lane saw, every
    occurrence of which was counted here instead of emitted.  All planes
    are ``(C, LANES)`` uint32; ``count == 0`` marks a never-filled slot
    (sentinel keys).  ``packed`` is the entry's FIRST in-lane occurrence
    (``start << 6 | len``), so a table built from these rows merges with
    the thinned stream's table bit-identically to the uncombined build
    (counts add exactly; the merge keeps each key's smallest position).

    Host-derivable telemetry (no extra kernel counters needed):
    ``hits = count.sum()`` occurrences absorbed, ``flushes = (count >
    0).sum()`` rows re-emitted at the flush, ``evicted = (count ==
    1).sum()`` cold entries whose slot bought nothing (the flush is where
    every entry is evicted; count-1 entries are the wasted ones).
    """

    key_hi: jax.Array
    key_lo: jax.Array
    count: jax.Array
    packed: jax.Array


class PackedTokenStream(NamedTuple):
    """A TokenStream (first five fields, same order — duck-compatible) plus
    the kernel's raw ``start << 6 | len`` plane and exact token count.

    Aggregation consumes ``packed`` directly as its sort payload and
    ``total`` for drop accounting, skipping two stream-sized HBM passes that
    reconstructing them from pos/length/count would cost.  ``packed`` is
    None when a nonzero base_offset made the raw plane unusable as-is.
    """

    key_hi: jax.Array
    key_lo: jax.Array
    count: jax.Array
    pos: jax.Array
    length: jax.Array
    packed: jax.Array | None
    total: jax.Array


def _pow_mod32(base: np.uint32, k: int) -> np.uint32:
    return np.uint32(pow(int(base), k, 1 << 32))


# Bit-for-bit parity with the XLA backend is the contract; share its hashing
# definition rather than copying it.
_fmix32 = tok_ops._fmix32


def _sep_mask_i32(x: jax.Array) -> jax.Array:
    """Separator test on int32-widened bytes.

    Mosaic (v5e) cannot lower 8-bit vector comparisons ("Target does not
    support this comparison"), so the kernel widens bytes to int32 at load
    and classifies there.  Derived from ``constants.SEPARATOR_BYTES`` — the
    same source of truth as :func:`...ops.tokenize.separator_mask` — so the
    backends can never drift apart.
    """
    sep = x == constants.SEPARATOR_BYTES[0]
    for b in constants.SEPARATOR_BYTES[1:]:
        sep = sep | (x == b)
    return sep


def _compact_planes(khi, klo, packed, has, slots: int):
    """In-VMEM slot compaction of pair-resolution planes (VERDICT r4 #2).

    ``has`` marks live pair rows (emission or poison).  Per lane, live rows
    keep their order and pack into the first ``rank`` output slots; the
    rest fill with the all-ones sentinel.

    Algorithm: log-shift compaction.  Each live row must move UP (toward
    row 0) by ``d = #dead rows above it`` — d is non-decreasing down a
    lane, so applying its binary decomposition one bit at a time (shift by
    2^b where bit b of the remaining distance is set) can never collide:
    if the element at row j still has to travel >= 2^b, every row between
    its destination and j holds either a hole or an element also moving.
    Monotonicity survives each pass (clearing low bits preserves order),
    so log2(p) passes of three (p, L) selects replace the previous
    per-slot one-hot selection — O(p log p) VPU work instead of
    O(p * slots), measured ~20 ms/chunk of kernel time at S=88
    (BENCHMARKS.md round 4), and a scoped-VMEM footprint back near the
    pair path's.

    Returns (khi[slots,L], klo[slots,L], packed[slots,L], n_spilled) where
    n_spilled counts live rows beyond the per-lane budget — the caller's
    exactness fallback trigger.
    """
    p, lanes = has.shape
    rank = has.astype(jnp.int32)
    k = 1
    while k < p:  # inclusive cumsum along sublanes: log-shift adds
        top = jnp.zeros((k, lanes), jnp.int32)
        rank = rank + jnp.concatenate([top, rank[:-k]], axis=0)
        k *= 2
    lane_live = rank[p - 1:p, :]  # (1, L) live rows per lane
    spilled = jnp.maximum(lane_live - slots, 0)
    n_spilled = jnp.sum(spilled).astype(jnp.uint32)

    row = jax.lax.broadcasted_iota(jnp.int32, (p, lanes), 0)
    dist = jnp.where(has, row - (rank - 1), 0)  # dead rows above each live row
    vals = [khi.astype(jnp.int32), klo.astype(jnp.int32),
            packed.astype(jnp.int32)]
    # Masks ride as int32 0/1 planes: Mosaic cannot shift/concatenate i1
    # vector registers ("Invalid vector register cast" on the chip), the
    # same class of constraint as the int32-widened separator test above.
    live = has.astype(jnp.int32)
    s = 1
    while s < p:
        def up(x):  # x[i] <- x[i+s] (shift toward row 0); int32 planes only
            pad = jnp.zeros((s, lanes), jnp.int32)
            return jnp.concatenate([x[s:], pad], axis=0)

        src_live = up(live)
        src_dist = up(dist)
        move_in = (src_live != 0) & ((src_dist & s) != 0)
        stay = (live != 0) & ((dist & s) == 0)
        vals = [jnp.where(move_in, up(v), jnp.where(stay, v, -1))
                for v in vals]
        dist = jnp.where(move_in, src_dist - s, dist)
        live = (move_in | stay).astype(jnp.int32)
        s *= 2
    sent = jnp.uint32(0xFFFFFFFF)
    out = [jnp.where(live[:slots] != 0, v[:slots].astype(jnp.uint32), sent)
           for v in vals]
    return out[0], out[1], out[2], n_spilled


def _tokenize_kernel(x_ref, *refs, w: int, block_rows: int, data_rows: int,
                     compact_slots: int = 0, lane_major: bool = False,
                     fused: bool = False, combiner_slots: int = 0):
    """One grid step: emit pair-compacted (key_hi, key_lo, packed) planes.

    Logical output row t of block i describes byte-row ``m = i*block_rows +
    t - 1`` of each lane (one-row offset so the next-byte separator test only
    ever looks at rows already resident).  A token end at byte row m requires
    byte m+1 to be a separator, so two consecutive rows can never both emit —
    the kernel folds each (2r, 2r+1) row pair to one output row *in VMEM*,
    writing half-resolution planes: at ~10 GB/s effective HBM bandwidth on
    the bench chip, the full-resolution planes plus the XLA-side re-read/
    re-write for pairing and (pos,len) packing were ~700 MB of traffic per
    32 MB chunk — most of the map phase's cost.

    ``packed`` = ``start_pos << 6 | length`` (the downstream sort payload;
    requires data length < 2**26 and w <= 63, validated by the wrapper);
    non-emitting pairs carry the sentinel key and all-ones packed.  ``ntok``
    accumulates the total emission count so callers get exact totals without
    another stream-sized pass.

    ``fused`` is the fully-fused map path (ISSUE 6): the byte input is the
    RAW ``(LANES, block_rows)`` lane view (transposed to the column layout
    in VMEM — the sublane-shift lookback structure is kept, the XLA-side
    transpose+pad materialization is not), and a second ``(AUX_ROWS,
    LANES)`` seam-carry input resolves 128-lane seams in-kernel: the i==0
    carry holds the PREVIOUS lane's tail instead of artificial separators,
    and the last data row's next-byte test reads the NEXT lane's first
    byte.  No token is deferred — the XLA seam fix-up pass (and its HBM
    round-trip over seam windows) does not exist on this path.
    """
    # Positional refs: the optional seam-carry aux input (fused mode),
    # the three planes + two scalars, then the optional spill scalar
    # (compact mode only), the optional combiner cache planes, and the
    # carry scratch.
    if fused:
        aux_ref, refs = refs[0], refs[1:]
    else:
        aux_ref = None
    khi_ref, klo_ref, packed_ref, over_ref, ntok_ref = refs[:5]
    refs = refs[5:]
    if compact_slots:
        spill_ref, refs = refs[0], refs[1:]
    else:
        spill_ref = None
    if combiner_slots:
        (ckhi_ref, cklo_ref, ccnt_ref, cpk_ref), refs = refs[:4], refs[4:]
    (carry_ref,) = refs
    i = pl.program_id(0)
    tb = block_rows
    aux = aux_ref[:].astype(jnp.int32) if fused else None

    @pl.when(i == 0)
    def _():
        if fused:
            # The carry above each lane's first block is the PREVIOUS
            # lane's last w+1 bytes (PAD for lane 0): the lookback crosses
            # lane seams over real bytes, in VMEM.
            carry_ref[:] = aux[: w + 1, :]
        else:
            # Rows "above" the first block are artificial separators: every
            # lane top is a segment start (real continuation is the previous
            # lane's tail, which the seam pass owns).
            carry_ref[:] = jnp.full_like(carry_ref, constants.PAD_BYTE)
        over_ref[0, 0] = jnp.uint32(0)
        ntok_ref[0, 0] = jnp.uint32(0)
        if spill_ref is not None:
            spill_ref[0, 0] = jnp.uint32(0)
        if combiner_slots:
            # Hot-key cache state rides REVISITED output blocks (index map
            # pinned to (0, 0)) under the guarded-init + read-modify-write
            # discipline the kernel-race pass certifies — the spill-scalar
            # idiom widened to planes.  After the last grid step the refs
            # hold the flushed cache verbatim: no separate flush pass.
            ckhi_ref[:] = jnp.full_like(ckhi_ref,
                                        jnp.uint32(constants.SENTINEL_KEY))
            cklo_ref[:] = jnp.full_like(cklo_ref,
                                        jnp.uint32(constants.SENTINEL_KEY))
            ccnt_ref[:] = jnp.zeros_like(ccnt_ref)
            cpk_ref[:] = jnp.full_like(cpk_ref, jnp.uint32(0xFFFFFFFF))

    # Widen bytes to int32 immediately: v5e Mosaic has no 8-bit vector
    # compares, and 32-bit lanes are the VPU-native layout anyway.  The
    # fused path's raw lane-view block transposes to the same column
    # layout here (a VMEM-local move) so the whole lookback below is
    # shared verbatim between the paths.
    x = x_ref[:].astype(jnp.int32).T if fused else x_ref[:].astype(jnp.int32)
    ext = jnp.concatenate([carry_ref[:], x], axis=0)  # (w+1+tb, LANES) int32
    carry_ref[:] = x[tb - (w + 1):, :]

    sep = _sep_mask_i32(ext)
    c = (ext + 1).astype(jnp.uint32)

    row_in_block = jax.lax.broadcasted_iota(jnp.int32, (tb, LANES), 0)
    m = i * tb + row_in_block - 1  # byte row within the lane's segment

    # Positions handled this step: ext rows [w, w+tb) = byte rows m below.
    cur_sep = sep[w:w + tb]
    nxt_sep = sep[w + 1:w + tb + 1]
    if fused:
        # The lane's LAST data byte's successor is the next lane's first
        # byte (aux head row), not the pad row the column view shows.
        nh_sep = _sep_mask_i32(aux[AUX_HEAD_ROW:AUX_HEAD_ROW + 1, :])
        nxt_sep = jnp.where(m == data_rows - 1, nh_sep, nxt_sep)
    is_end = (~cur_sep) & nxt_sep

    intok = ~cur_sep
    h1 = jnp.where(intok, c[w:w + tb], jnp.uint32(0))
    h2 = h1
    ln = intok.astype(jnp.uint32)
    for k in range(1, w):
        intok = intok & ~sep[w - k:w - k + tb]
        ck = c[w - k:w - k + tb]
        h1 = h1 + jnp.where(intok, ck * _pow_mod32(constants.HASH_BASE_1, k), jnp.uint32(0))
        h2 = h2 + jnp.where(intok, ck * _pow_mod32(constants.HASH_BASE_2, k), jnp.uint32(0))
        ln = ln + intok.astype(jnp.uint32)

    # True length may exceed w: the byte w back is still inside the run.
    run_exceeds_w = intok & ~sep[0:tb]

    if fused:
        # No deferral: the seam-carry aux made every lookback and every
        # next-byte test exact across lane seams.  Only the phantom m=-1
        # row (block 0's one-row output trail — the previous lane's last
        # byte, owned by THAT lane's last data row) is masked.
        alive = m >= 0
        emit = is_end & ~run_exceeds_w & alive
        overlong_here = is_end & run_exceeds_w & alive
    else:
        # Defer to the seam pass: tokens starting at lane row 0 (previous
        # byte is another lane's data) and tokens ending at the lane's last
        # data row (next byte is another lane's data, so is_end itself is
        # unreliable there).
        starts_at_lane_top = ln.astype(jnp.int32) == m + 1
        ends_at_lane_bottom = m == data_rows - 1
        emit = is_end & ~run_exceeds_w & ~starts_at_lane_top \
            & ~ends_at_lane_bottom

        # Overlong runs are counted exactly once, at their true end.  Runs
        # whose lookback crosses the lane top are counted by the seam pass
        # instead (their suppression here shows up as starts_at_lane_top=
        # False only when the lookback window is fully in-lane, which
        # run_exceeds_w guarantees).
        overlong_here = is_end & run_exceeds_w & ~ends_at_lane_bottom
    # Mosaic cannot lower reductions over unsigned ints; sum in int32.
    n_overlong = jnp.sum(overlong_here.astype(jnp.int32)).astype(jnp.uint32)
    over_ref[0, 0] = over_ref[0, 0] + n_overlong

    khi = _fmix32(h1 ^ ln)
    klo = _fmix32(h2 + jnp.uint32(0x9E3779B9) * ln)
    sent = jnp.uint32(constants.SENTINEL_KEY)
    # Clamp real keys off BOTH reserved values — (sent, sent) dead filler,
    # (sent, sent-1) poison — to (sent, sent-2); the same rule as the XLA
    # backend's tokenize (bit-identity contract).
    at_sent = (khi == sent) & (klo >= sent - jnp.uint32(1))
    klo = jnp.where(at_sent, sent - jnp.uint32(2), klo)

    if combiner_slots:
        # Map-side hot-key combiner (ISSUE 11): per lane, emissions whose
        # key is resident in the cache are COUNTED here and suppressed
        # from the stream; empty slots greedily adopt the first-seen
        # distinct keys (on Zipf streams the top-mass keys appear within
        # the first windows with overwhelming probability — PR 8's
        # top_mass proxy is exactly the collapsible mass).  Every update
        # is a static C-slot loop of lane-wise compares + sublane
        # reductions: no scatter, no data-dependent control flow.  Exact
        # by construction — a missed key flows to the sort unchanged, a
        # cached key's count and first in-lane occurrence flush at chunk
        # end — so results are bit-identical on every distribution.
        row = jax.lax.broadcasted_iota(jnp.int32, (tb, LANES), 0)
        lane_c = jax.lax.broadcasted_iota(jnp.int32, (tb, LANES), 1)
        start_raw = lane_c * data_rows + m + 1 - ln.astype(jnp.int32)
        packed_raw = (start_raw.astype(jnp.uint32) << 6) | ln
        ck = ckhi_ref[:]
        cl = cklo_ref[:]
        cc = ccnt_ref[:]
        cp = cpk_ref[:]
        ck_rows = [ck[c:c + 1, :] for c in range(combiner_slots)]
        cl_rows = [cl[c:c + 1, :] for c in range(combiner_slots)]
        cc_rows = [cc[c:c + 1, :] for c in range(combiner_slots)]
        cp_rows = [cp[c:c + 1, :] for c in range(combiner_slots)]
        # Hit pass: resident keys absorb their occurrences.  Sentinel
        # slots can never match — emissions carry clamped keys, so an
        # emitting row's (khi, klo) is never (sent, sent).
        for c in range(combiner_slots):
            m_hit = emit & (khi == ck_rows[c]) & (klo == cl_rows[c])
            n_hit = jnp.sum(m_hit.astype(jnp.int32), axis=0, keepdims=True)
            cc_rows[c] = cc_rows[c] + n_hit.astype(jnp.uint32)
            emit = emit & ~m_hit
        # Fill pass: each empty slot adopts the lane's first remaining
        # live emission (per-lane one-hot select via a masked int32 sum —
        # bit-exact, the sum has at most one nonzero term), records its
        # first occurrence, and absorbs its other occurrences in this
        # block.  Slots only ever fill, so an adopted entry's ``packed``
        # is provably the key's first in-lane occurrence: were the key
        # seen earlier with this slot empty, it would have been adopted
        # then.
        big = jnp.int32(tb + 1)
        for c in range(combiner_slots):
            empty = cc_rows[c] == 0
            cand = jnp.where(emit, row, big)
            idx = jnp.min(cand, axis=0, keepdims=True)
            take = empty & (idx < big)
            pick = emit & (row == idx)

            def sel(v):
                return jnp.sum(jnp.where(pick, v.astype(jnp.int32), 0),
                               axis=0, keepdims=True).astype(jnp.uint32)

            nk_hi, nk_lo, npk = sel(khi), sel(klo), sel(packed_raw)
            m_new = emit & take & (khi == nk_hi) & (klo == nk_lo)
            n_new = jnp.sum(m_new.astype(jnp.int32), axis=0, keepdims=True)
            ck_rows[c] = jnp.where(take, nk_hi, ck_rows[c])
            cl_rows[c] = jnp.where(take, nk_lo, cl_rows[c])
            cp_rows[c] = jnp.where(take, npk, cp_rows[c])
            cc_rows[c] = jnp.where(take, n_new.astype(jnp.uint32),
                                   cc_rows[c])
            emit = emit & ~m_new
        ckhi_ref[:] = jnp.concatenate(ck_rows, axis=0)
        cklo_ref[:] = jnp.concatenate(cl_rows, axis=0)
        ccnt_ref[:] = jnp.concatenate(cc_rows, axis=0)
        cpk_ref[:] = jnp.concatenate(cp_rows, axis=0)

    khi = jnp.where(emit, khi, sent)
    # Poison rows carry the reserved key (sent, sent-1): they sort into
    # their OWN segment immediately before the dead-filler segment, so the
    # rescue extraction can find them with a binary search even when the
    # aggregation sort carries no third key to order the filler behind them
    # (sort_mode='stable2').
    klo = jnp.where(emit, klo,
                    jnp.where(overlong_here, sent - jnp.uint32(1), sent))
    ln_e = jnp.where(emit, ln, jnp.uint32(0))
    ntok_ref[0, 0] = ntok_ref[0, 0] + jnp.sum(emit.astype(jnp.int32)).astype(jnp.uint32)

    # packed = start << 6 | length: the sort payload, built where the data
    # already is.  start = global byte offset of the token's first byte.
    # Overlong ends emit a POISON row instead: position of the run's last
    # byte with zero length bits (impossible for a real token).  Consumers
    # needing global token order (n-grams, sampling) keep poison rows in
    # their position sort, where they break row-adjacency across the
    # suppressed token — so grams spanning it self-invalidate instead of
    # pairing phantom neighbors (this replaces a whole-chunk lax.cond
    # fallback to the XLA scan, which embedded a pathologically-slow-to-
    # compile program in every n-gram step; VERDICT r2 #4).
    lane = jax.lax.broadcasted_iota(jnp.int32, (tb, LANES), 1)
    start = lane * data_rows + m + 1 - ln_e.astype(jnp.int32)
    last_byte = (lane * data_rows + m).astype(jnp.uint32)
    packed = jnp.where(emit, (start.astype(jnp.uint32) << 6) | ln_e,
                       jnp.where(overlong_here, last_byte << 6,
                                 jnp.uint32(0xFFFFFFFF)))

    # Pairwise fold: adjacent rows are never both token ends (a real or
    # overlong end at m needs byte m+1 to be a separator), so each
    # (2r, 2r+1) pair holds at most one emission or poison — select it via
    # a sublane-group reshape.
    def fold(a, take_even):
        g = a.reshape(tb // 2, 2, LANES)
        return jnp.where(take_even, g[:, 0, :], g[:, 1, :])

    live = (emit | overlong_here).reshape(tb // 2, 2, LANES)
    even_has = live[:, 0, :]
    khi_h = fold(khi, even_has)
    klo_h = fold(klo, even_has)
    packed_h = fold(packed, even_has)
    if compact_slots:
        has_h = live[:, 0, :] | live[:, 1, :]
        khi_c, klo_c, pck_c, n_spill = _compact_planes(
            khi_h, klo_h, packed_h, has_h, compact_slots)
        if lane_major:
            # Transposed (LANES, S) output blocks laid side by side give a
            # flattened stream in GLOBAL BYTE-POSITION order (lane j owns
            # the contiguous segment [j*L, (j+1)*L); within a lane, windows
            # and slots ascend with position) — the precondition for
            # sort_mode='stable2' recovering first occurrence from sort
            # stability alone.  At S=128 the transposed block is a fully
            # tile-aligned (128, 128) store.
            khi_ref[:] = khi_c.T
            klo_ref[:] = klo_c.T
            packed_ref[:] = pck_c.T
        else:
            khi_ref[:] = khi_c
            klo_ref[:] = klo_c
            packed_ref[:] = pck_c
        spill_ref[0, 0] = spill_ref[0, 0] + n_spill
    else:
        khi_ref[:] = khi_h
        klo_ref[:] = klo_h
        packed_ref[:] = packed_h


def _column_pass(cols_padded: jax.Array, w: int, block_rows: int,
                 data_rows: int, interpret: bool, compact_slots: int = 0,
                 lane_major: bool = False, fused_aux: jax.Array | None = None,
                 combiner_slots: int = 0):
    """Run the kernel over the (rows, 128) column view (one trailing pad block).

    Returns pair-compacted planes of rows//2 output rows — or, with
    ``compact_slots`` = S > 0, slot-compacted planes of rows/block_rows*S
    output rows plus a spill count (live rows beyond any lane's budget) —
    as (key_hi, key_lo, packed), plus the (overlong, token_count, spill)
    scalars (spill is 0 on the pair path).  With ``lane_major`` (compact
    mode only) the planes are (LANES, grid*S) transposed blocks whose
    row-major flattening is global byte-position order.

    With ``fused_aux`` (the :func:`_seam_aux` seam-carry plane) the input
    is instead the RAW ``(LANES, rows)`` lane view — no XLA-side transpose
    — and the kernel runs the fused map path (in-kernel seams, no token
    deferred; see ``_tokenize_kernel``).
    """
    fused = fused_aux is not None
    rows = cols_padded.shape[1] if fused else cols_padded.shape[0]
    grid = rows // block_rows
    kern = functools.partial(_tokenize_kernel, w=w, block_rows=block_rows,
                             data_rows=data_rows, compact_slots=compact_slots,
                             lane_major=lane_major, fused=fused,
                             combiner_slots=combiner_slots)
    out_rows = grid * compact_slots if compact_slots else rows // 2
    block_out = compact_slots if compact_slots else block_rows // 2
    if lane_major:
        out32 = jax.ShapeDtypeStruct((LANES, out_rows), jnp.uint32)
        plane_spec = pl.BlockSpec((LANES, block_out), lambda i: (0, i),
                                  memory_space=pltpu.VMEM)
    else:
        out32 = jax.ShapeDtypeStruct((out_rows, LANES), jnp.uint32)
        plane_spec = pl.BlockSpec((block_out, LANES), lambda i: (i, 0),
                                  memory_space=pltpu.VMEM)
    scalar = jax.ShapeDtypeStruct((1, 1), jnp.uint32)
    n_scalars = 3 if compact_slots else 2
    # Compact mode needs scoped VMEM above Mosaic's 16 MB default stack
    # budget (measured on-chip: the default limit rejects it with a
    # vmem-stack OOM at compile time; 64 MB compiles).  The limit predates
    # the log-shift rewrite — whether the smaller-footprint kernel now fits
    # the default is an open on-chip re-measurement (ADVICE r4); v5e has
    # ~128 MB physical VMEM, so the override is safe headroom either way.
    # The pair path stays well under the default; one shared limit keeps
    # the call site single-owner.
    # Older jax spells the params class TPUCompilerParams; same fields.
    _params_cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    params = _params_cls(vmem_limit_bytes=64 * 1024 * 1024) \
        if compact_slots else None
    if fused:
        # The aux plane's height is a geometry knob (ISSUE 12): the spec
        # reads it off the plane itself, so _seam_aux stays the single
        # owner of the plane layout.
        in_specs = [pl.BlockSpec((LANES, block_rows), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((fused_aux.shape[0], LANES),
                                 lambda i: (0, 0),
                                 memory_space=pltpu.VMEM)]
        args = (cols_padded, fused_aux)
    else:
        in_specs = [pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)]
        args = (cols_padded,)
    cache_shapes: list = []
    cache_specs: list = []
    if combiner_slots:
        # Cache state lives in revisited VMEM output blocks (index map
        # pinned to (0, 0)): the refs carry the cache across the
        # sequential grid, and their post-kernel value IS the flush.
        cache_shapes = [jax.ShapeDtypeStruct((combiner_slots, LANES),
                                             jnp.uint32)] * 4
        cache_specs = [pl.BlockSpec((combiner_slots, LANES),
                                    lambda i: (0, 0),
                                    memory_space=pltpu.VMEM)] * 4
    outs = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=in_specs,
        out_shape=[out32, out32, out32] + [scalar] * n_scalars + cache_shapes,
        out_specs=[plane_spec] * 3
        + [pl.BlockSpec((1, 1), lambda i: (0, 0),
                        memory_space=pltpu.SMEM)] * n_scalars + cache_specs,
        scratch_shapes=[pltpu.VMEM((w + 1, LANES), jnp.int32)],
        compiler_params=params,
        interpret=interpret,
    )(*args)
    khi, klo, packed, over, ntok = outs[:5]
    spill = outs[5][0, 0] if compact_slots else jnp.uint32(0)
    cache = CombinerCache(*outs[3 + n_scalars:3 + n_scalars + 4]) \
        if combiner_slots else None
    return khi, klo, packed, over[0, 0], ntok[0, 0], spill, cache


def _seam_pass(data: jax.Array, seg_len: int, w: int,
               base_offset: jax.Array) -> tuple[TokenStream, jax.Array]:
    """Re-tokenize the 129 lane-seam windows with the XLA scan path.

    Window j covers bytes ``[j*seg_len - w - 1, j*seg_len + w + 1)`` (out of
    range = PAD).  It emits exactly the tokens the kernel deferred: those whose
    span touches a seam byte (``j*seg_len - 1`` or ``j*seg_len``), provided the
    whole token is visible in the window.  A run truncated by the window edge
    is an overlong token; it is counted, not emitted.
    """
    n = data.shape[0]
    wlen = 2 * w + 2
    # Window j covers [j*L - w - 1, j*L + w + 1): the last w+1 bytes of lane
    # segment j-1 plus the first w+1 bytes of segment j.  Build all 129
    # windows from static slices of the (LANES, L) view — a fancy-index
    # gather here costs ~13 us/element on TPU (measured: ~100 ms for these
    # ~8.5K bytes, 4x the entire rest of the pipeline).
    view = data.reshape(LANES, seg_len)
    pad_row = jnp.full((1, w + 1), constants.PAD_BYTE, dtype=jnp.uint8)
    tails = jnp.concatenate([pad_row, view[:, seg_len - (w + 1):]], axis=0)
    heads = jnp.concatenate([view[:, : w + 1], pad_row], axis=0)
    windows = jnp.concatenate([tails, heads], axis=1)  # (LANES+1, 2w+2)
    starts = jnp.arange(0, n + seg_len, seg_len)  # 129 window origins j*seg_len

    streams = jax.vmap(tok_ops.tokenize)(windows)  # fields: (129, wlen)
    wpos_end = jnp.arange(wlen)[None, :].astype(jnp.int32)
    length = streams.length.astype(jnp.int32)
    wstart = wpos_end - length + 1
    is_tok = streams.count > 0

    # Seam bytes sit at window positions w and w+1.
    touches = (wstart <= w) & (wpos_end >= w) | (wstart <= w + 1) & (wpos_end >= w + 1)
    complete = (wstart >= 1) & (wpos_end <= 2 * w)
    # Enforce the same <=W contract as the in-lane kernel so whether a token
    # is counted never depends on where the chunk layout happened to cut it.
    emit = is_tok & touches & complete & (length <= w)

    # Overlong tokens counted here, exactly once each: truncated-at-left
    # fragments whose true end is visible (their lookback crossed the seam, so
    # the kernel deferred them), and complete-but-longer-than-W seam tokens.
    is_overlong = is_tok & touches & ((wstart == 0) & (wpos_end <= 2 * w)
                                      | complete & (length > w))
    overlong = jnp.sum(is_overlong.astype(jnp.uint32))

    sent = jnp.uint32(constants.SENTINEL_KEY)
    global_start = (starts[:, None] - (w + 1) + wstart).astype(jnp.int32)
    # Poison rows mirror the kernel's: the overlong run's LAST byte position,
    # zero length, the reserved poison key (sent, sent-1), count 0.  They
    # ride the `pos` plane (count=0 rows are inert everywhere else) so
    # concat_streams can pack them for position-ordered consumers.
    global_end = (starts[:, None] - (w + 1) + wpos_end).astype(jnp.int32)
    pos = jnp.where(emit, global_start, jnp.where(is_overlong, global_end,
                                                  jnp.int32(-1)))
    stream = TokenStream(
        key_hi=jnp.where(emit, streams.key_hi, sent).reshape(-1),
        key_lo=jnp.where(emit, streams.key_lo,
                         jnp.where(is_overlong, sent - jnp.uint32(1),
                                   sent)).reshape(-1),
        count=jnp.where(emit, jnp.uint32(1), jnp.uint32(0)).reshape(-1),
        pos=jnp.where(pos >= 0, pos.astype(jnp.uint32)
                      + jnp.asarray(base_offset, jnp.uint32),
                      jnp.uint32(constants.POS_INF)).reshape(-1),
        length=jnp.where(emit, streams.length, jnp.uint32(0)).reshape(-1),
    )
    return stream, overlong


def tokenize_split(data: jax.Array, base_offset: jax.Array | int = 0,
                   max_token_bytes: int = DEFAULT_MAX_TOKEN,
                   block_rows: int | None = None,
                   interpret: bool | None = None
                   ) -> tuple[PackedTokenStream, TokenStream, jax.Array]:
    """Pallas-backed tokenize returning ``(col_stream, seam_stream, overlong)``
    — the bulk column-pass emissions and the tiny (~129*(2W+2) entries) seam
    fix-up emissions as *separate* streams.

    Aggregation-aware callers should consume the two streams separately
    (build a table from each and merge): concatenating them forces a full
    copy of every multi-hundred-MB column plane just to append a few KB.
    :func:`tokenize` below does exactly that concatenation for callers that
    want the single-stream view.

    Emits the same (key, count, pos, length) tuples per token as
    :func:`mapreduce_tpu.ops.tokenize.tokenize` for every token of at most
    ``max_token_bytes`` bytes; longer tokens are dropped and tallied in the
    returned ``overlong`` (uint32 scalar) for the caller to fold into
    ``CountTable.dropped_*``.  Stream entries are NOT in byte order (the
    column view interleaves lanes); downstream aggregation sorts by key, so
    order is irrelevant there.

    Requirements: ``len(data) % 128 == 0`` and at least one full block.
    """
    col, seam, overlong, _ = _tokenize_split_impl(
        data, base_offset, max_token_bytes, block_rows, interpret, 0)
    return col, seam, overlong


def tokenize_split_compact(data: jax.Array, compact_slots: int,
                           base_offset: jax.Array | int = 0,
                           max_token_bytes: int = DEFAULT_MAX_TOKEN,
                           block_rows: int | None = None,
                           interpret: bool | None = None,
                           lane_major: bool = False
                           ) -> tuple[PackedTokenStream, TokenStream,
                                      jax.Array, jax.Array]:
    """:func:`tokenize_split` with slot-compacted column planes: returns
    ``(col_stream, seam_stream, overlong, spill)``.

    The column planes hold ``compact_slots`` output rows per ``block_rows``
    byte rows (vs the pair path's ``block_rows/2``) — the downstream sort's
    input shrinks by the same ratio, which is where the chunk budget goes
    (BENCHMARKS.md op profile).  ``spill`` (uint32) counts live rows beyond
    any (block, lane) window's budget: when it is nonzero the compact
    planes are INCOMPLETE and the caller must discard them and re-run the
    full-resolution path (``models/wordcount._map_stream`` wraps exactly
    that in a ``lax.cond``).  Measured window densities (tools/density.py):
    the default 88 slots per 256-byte window never spills on either bench
    corpus (observed max 77, Zipf) — the fallback is for adversarial text
    (e.g. runs of single-letter tokens at density > 0.34), which stays
    exact at ~2x the chunk cost.

    ``lane_major`` writes the column planes transposed so the flattened
    col_stream is in GLOBAL BYTE-POSITION order — the input contract of
    ``sort_mode='stable2'`` aggregation (first occurrence recovered from
    sort stability instead of a third comparator key).  The row SET is
    identical either way; only the order changes.
    """
    if compact_slots <= 0:
        raise ValueError(f"compact_slots must be > 0, got {compact_slots}")
    return _tokenize_split_impl(data, base_offset, max_token_bytes,
                                block_rows, interpret, compact_slots,
                                lane_major)


def _resolve_args(data, max_token_bytes, block_rows, interpret,
                  compact_slots: int):
    """Shared argument validation/resolution for the split and fused entry
    points: returns ``(w, seg_len, block_rows, interpret)``."""
    if interpret is None:
        # Mosaic only targets TPU; elsewhere (CPU tests, debugging) the
        # interpreter executes the same kernel semantics.
        interpret = jax.default_backend() != "tpu"
    if data.dtype != jnp.uint8:
        raise TypeError(f"pallas tokenize expects uint8, got {data.dtype}")
    n = data.shape[0]
    if n % LANES:
        raise ValueError(f"input length {n} must be a multiple of {LANES}")
    if n > (1 << 26):
        raise ValueError(
            f"input of {n} bytes exceeds the pallas backend's 2**26 (64 MB) "
            "chunk bound (positions are packed into 26 bits for the sort "
            "payload); lower chunk_bytes or use the xla backend")
    w = max_token_bytes
    if w < 1:
        raise ValueError(f"max_token_bytes must be >= 1, got {w}")
    if w > 63:
        raise ValueError(f"max_token_bytes must be <= 63 (length is packed "
                         f"into 6 bits), got {w}")
    seg_len = n // LANES
    if block_rows is None:
        # Blocks must cover the W-row lookback plus one row, and stay even
        # (pairwise compaction halves the output rows, which are a multiple
        # of block_rows).
        block_rows = max(DEFAULT_BLOCK_ROWS, w + 2 + (w % 2))
    if block_rows < w + 2:
        raise ValueError(f"block_rows {block_rows} must be >= max_token_bytes+2")
    if block_rows % 2:
        raise ValueError(f"block_rows must be even, got {block_rows}")
    if compact_slots and not 8 <= compact_slots <= block_rows // 2:
        raise ValueError(f"compact_slots {compact_slots} must be in "
                         f"[8, block_rows/2={block_rows // 2}]")
    if compact_slots % 8:
        raise ValueError(f"compact_slots must be a multiple of 8 (sublane "
                         f"alignment), got {compact_slots}")
    if seg_len < 2 * w + 2:
        raise ValueError(
            f"input of {n} bytes gives lane segments of {seg_len} < 2W+2="
            f"{2 * w + 2} bytes; seam windows would overlap (grow the chunk "
            f"or shrink max_token_bytes)")
    return w, seg_len, block_rows, interpret


def _packed_stream(khi, klo, packed, total, base_offset) -> PackedTokenStream:
    """Flatten kernel planes into the :class:`PackedTokenStream` view.

    The kernel already compacted and packed (start << 6 | len) in VMEM;
    pos/length/count are elementwise functions of ``packed``, which XLA
    fuses into whatever consumes them (aggregation feeds ``packed``
    straight into its sort, so the reconstructed planes never hit HBM
    there).
    """
    khi = khi.reshape(-1)
    klo = klo.reshape(-1)
    packed = packed.reshape(-1)
    # Zero length bits mark overlong-end POISON rows (position-ordering
    # markers, not tokens): excluded from the token view here, kept in the
    # packed plane for position-ordered consumers.
    has_tok = (packed != jnp.uint32(0xFFFFFFFF)) \
        & ((packed & jnp.uint32(63)) != 0)
    ln = jnp.where(has_tok, packed & jnp.uint32(63), jnp.uint32(0))
    start = jnp.where(has_tok,
                      (packed >> 6) + jnp.asarray(base_offset, jnp.uint32),
                      jnp.uint32(constants.POS_INF))
    base_is_zero = isinstance(base_offset, int) and base_offset == 0
    return PackedTokenStream(
        key_hi=khi, key_lo=klo,
        count=has_tok.astype(jnp.uint32),
        pos=start, length=ln,
        packed=packed if base_is_zero else None,
        total=total)


def _tokenize_split_impl(data, base_offset, max_token_bytes, block_rows,
                         interpret, compact_slots: int,
                         lane_major: bool = False):
    w, seg_len, block_rows, interpret = _resolve_args(
        data, max_token_bytes, block_rows, interpret, compact_slots)

    # Column-major view + pad rows to a whole number of blocks, plus one extra
    # pad block so every data row gets an output (outputs trail by one row).
    cols = data.reshape(LANES, seg_len).T
    pad_rows = (-seg_len) % block_rows + block_rows
    cols_padded = jnp.concatenate(
        [cols, jnp.full((pad_rows, LANES), constants.PAD_BYTE, dtype=jnp.uint8)])

    khi, klo, packed, over_cols, n_tokens, spill, _ = _column_pass(
        cols_padded, w, block_rows, data_rows=seg_len, interpret=interpret,
        compact_slots=compact_slots, lane_major=lane_major)

    col_stream = _packed_stream(khi, klo, packed, n_tokens, base_offset)
    seam_stream, over_seams = _seam_pass(data, seg_len, w, base_offset)
    return col_stream, seam_stream, over_cols + over_seams, spill


def _seam_aux(view: jax.Array, w: int, aux_rows: int = AUX_ROWS) -> jax.Array:
    """Build the fused kernel's ``(aux_rows, LANES)`` seam-carry plane from
    the raw ``(LANES, seg_len)`` lane view: rows ``[0, w+1)`` hold byte
    ``lane*L - (w+1) + c`` (the previous lane's tail; PAD for lane 0) and
    rows ``[AUX_HEAD_ROW, aux_rows)`` the next lane's first byte (PAD for
    lane 127).  ~12 KB of static slices — noise next to the chunk.
    ``aux_rows`` (a geometry knob, ISSUE 12) only sizes the tile-aligned
    plane; the head row stays pinned at ``AUX_HEAD_ROW`` = 64, the W <= 63
    bound, so rows past it are interchangeable replication."""
    seg_len = view.shape[1]
    pad = constants.PAD_BYTE
    tails = jnp.concatenate(
        [jnp.full((1, w + 1), pad, jnp.uint8),
         view[:-1, seg_len - (w + 1):]], axis=0)  # (LANES, w+1)
    heads = jnp.concatenate(
        [view[1:, :1], jnp.full((1, 1), pad, jnp.uint8)], axis=0)
    mid = jnp.full((LANES, AUX_HEAD_ROW - (w + 1)), pad, jnp.uint8)
    rep = jnp.broadcast_to(heads, (LANES, aux_rows - AUX_HEAD_ROW))
    return jnp.concatenate([tails, mid, rep], axis=1).T


def tokenize_fused(data: jax.Array, *, compact_slots: int = 0,
                   base_offset: jax.Array | int = 0,
                   max_token_bytes: int = DEFAULT_MAX_TOKEN,
                   block_rows: int | None = None,
                   interpret: bool | None = None,
                   lane_major: bool = False,
                   combiner_slots: int = 0,
                   aux_rows: int | None = None):
    """Fully fused map path (ISSUE 6): ``(stream, overlong, spill)`` from
    ONE kernel pass over the raw chunk bytes — no XLA transpose/pad of the
    input, no seam fix-up pass, no separate seam stream.

    Emission-set parity with :func:`tokenize_split` is exact: the same
    tokens (<= ``max_token_bytes`` bytes, counted once each), the same
    overlong accounting, and the same poison rows at overlong ends — but
    cross-lane-seam tokens are hashed in-kernel from the seam-carry aux
    plane (:func:`_seam_aux`) instead of being deferred to the XLA scan
    over 129 seam windows, so aggregation consumes a single stream.  With
    ``lane_major`` the flattened stream remains in global byte-position
    order (cross-seam tokens land in their end lane's first window, which
    is exactly their start-position slot), preserving the stable2
    aggregation precondition.

    ``spill`` semantics match :func:`tokenize_split_compact`: nonzero
    means the compact planes are incomplete and the caller MUST fall back
    to an exact path under ``lax.cond`` (the fused fallback is this same
    kernel in pair mode — ``compact_slots=0``).

    ``combiner_slots`` = C > 0 (ISSUE 11; requires ``compact_slots``)
    threads the per-lane hot-key cache through the grid and returns
    ``(stream, overlong, spill, cache)``: cached occurrences are counted
    in VMEM and ABSENT from the stream (``stream.total`` counts only
    emitted rows), and the caller folds the flushed :class:`CombinerCache`
    back in exactly (one table row per resident entry).  The occurrence
    union of stream + cache equals the C=0 stream's exactly, cache misses
    included byte-for-byte — the bit-identity contract of
    ``Config.combiner='hot-cache'``.
    """
    w, seg_len, block_rows, interpret = _resolve_args(
        data, max_token_bytes, block_rows, interpret, compact_slots)
    if aux_rows is None:
        aux_rows = AUX_ROWS
    if aux_rows % 32 or aux_rows <= AUX_HEAD_ROW:
        # The plane is uint8 (tile grid (32, 128)) and the head row is
        # pinned at AUX_HEAD_ROW (the W <= 63 bound): a geometry knob,
        # validated like every other kernel envelope (ISSUE 12).
        raise ValueError(f"aux_rows must be a multiple of 32 and > "
                         f"{AUX_HEAD_ROW}, got {aux_rows}")
    if combiner_slots:
        if not compact_slots:
            raise ValueError("combiner_slots requires the compact path "
                             "(the pair fallback is the combiner-free "
                             "exactness escape)")
        if combiner_slots % 8 or not 8 <= combiner_slots <= 32:
            raise ValueError(f"combiner_slots must be a multiple of 8 in "
                             f"[8, 32], got {combiner_slots}")
        if not (isinstance(base_offset, int) and base_offset == 0):
            # The cache's `packed` plane records raw in-chunk positions
            # (the same rule that nulls PackedTokenStream.packed under a
            # nonzero base): offsetting the stream but not the cache would
            # silently skew cached first occurrences by base_offset.
            raise ValueError("combiner_slots requires base_offset == 0 "
                             "(the cache flush records in-chunk positions; "
                             "callers apply chunk bases via pos_hi, the "
                             "wordcount idiom)")
    view = data.reshape(LANES, seg_len)
    # Pad lane columns to a whole number of blocks plus one extra pad block
    # (outputs trail by one row, exactly like the split column view).
    pad_cols = (-seg_len) % block_rows + block_rows
    view_padded = jnp.pad(view, ((0, 0), (0, pad_cols)),
                          constant_values=constants.PAD_BYTE)
    khi, klo, packed, overlong, n_tokens, spill, cache = _column_pass(
        view_padded, w, block_rows, data_rows=seg_len, interpret=interpret,
        compact_slots=compact_slots, lane_major=lane_major,
        fused_aux=_seam_aux(view, w, aux_rows),
        combiner_slots=combiner_slots)
    stream = _packed_stream(khi, klo, packed, n_tokens, base_offset)
    if combiner_slots:
        return stream, overlong, spill, cache
    return stream, overlong, spill


def concat_streams(col: PackedTokenStream, seam: TokenStream) -> PackedTokenStream:
    """Append the (tiny) seam stream to the column stream, preserving the
    packed plane and exact total, so aggregation runs ONCE over both.

    Building a separate seam table and merging it cost ~26 ms/chunk on the
    bench chip (a second searchsorted while-loop plus six fixed-cost device
    copies of the 8.5K-row seam arrays); one concatenated sort absorbs the
    8.5K extra rows for ~free.
    """
    sent = jnp.uint32(0xFFFFFFFF)
    seam_tok = seam.count > 0
    # count=0 rows with a real pos are the seam pass's POISON rows (overlong
    # ends): packed with zero length bits, like the kernel's own.
    seam_poison = ~seam_tok & (seam.pos != jnp.uint32(constants.POS_INF))
    seam_packed = jnp.where(seam_tok, (seam.pos << 6) | seam.length,
                            jnp.where(seam_poison, seam.pos << 6, sent))
    cat = lambda a, b: jnp.concatenate([a, b])
    return PackedTokenStream(
        key_hi=cat(col.key_hi, seam.key_hi),
        key_lo=cat(col.key_lo, seam.key_lo),
        count=cat(col.count, seam.count),
        pos=cat(col.pos, seam.pos),
        length=cat(col.length, seam.length),
        packed=cat(col.packed, seam_packed) if col.packed is not None else None,
        total=col.total + jnp.sum(seam.count),
    )


def tokenize(data: jax.Array, base_offset: jax.Array | int = 0,
             max_token_bytes: int = DEFAULT_MAX_TOKEN,
             block_rows: int | None = None,
             interpret: bool | None = None) -> tuple[TokenStream, jax.Array]:
    """Single-stream view of :func:`tokenize_split`: ``(stream, overlong)``."""
    col, seam, overlong = tokenize_split(data, base_offset, max_token_bytes,
                                         block_rows, interpret)
    return concat_streams(col, seam), overlong
