"""Pallas TPU radix partition/sort over the packed 3-plane aggregation stream.

The single-chip budget is sort-bound: the round-5 opshare puts the XLA
aggregation sort at 37-47 ms of a 72.8 ms chunk program (up to 65% of
device time) on the 11.2M-row (key_hi, key_lo, packed) stream.  This module
is the priced falsifying prototype for the one lever that analysis left
open — replacing that sort with a digit-wise radix partition — built AFTER
the pricing note (BENCHMARKS.md round 6) concluded it loses ~2-3x from
measured rates.  It ships behind ``Config.sort_impl`` so an on-chip A/B can
falsify the arithmetic instead of trusting it.

Why the structure below, and not a textbook LSD radix sort
---------------------------------------------------------
A classic LSD pass needs a STABLE scatter of every row to an exact global
offset.  TPU has no hardware scatter (measured: ~30 ms fixed scatter cost,
~13 us/element gathers — the round-1 findings the whole table layer is
built around), so the reorder here is scatter-free:

1. **Partition kernel** (one grid pass, sequential on TPU): each
   ``(block_rows, 128)`` block classifies rows by a ``bits``-wide MSD digit
   of ``key_hi``, drops dead filler rows (``(sent, sent)`` keys — they are
   interchangeable by the packed-stream contract, so only their count
   matters), and per bucket log-shift-compacts the three planes (the
   chip-proven :func:`...tokenize._compact_planes`) into a STATIC
   per-(block, bucket) slab of ``cap`` rows per lane.  Per-group digit
   histograms accumulate in SMEM; a spill counter records live rows beyond
   any lane's slab budget.
2. **Per-group finishing sort**: bucket slabs are restacked bucket-major
   and each bucket (digit range) is finished with one blocked 3-key
   ``lax.sort`` — pads carry the dead triple and sink to each bucket's
   tail.
3. **Pad compaction**: ascending ``dynamic_update_slice`` writes at the
   exact cumulative real offsets; each slab exactly overwrites the previous
   slab's pad tail, so one ~slack-sized pass re-joins the stream with no
   gather.

``impl='radix_partition'`` runs one partition level (the cheapest
falsifying prototype); ``impl='radix'`` runs two digit levels before the
finishing sorts (the multi-pass path; deeper levels only compound the
slack-write amplification the pricing note quantifies, and a TRUE LSD
chain is unbuildable without stable scatter — documented there).

Exactness: static slabs can overflow under adversarial key skew (every
live row in one digit bucket).  The kernel counts spilled rows exactly and
a ``lax.cond`` falls back to the plain XLA sort — the compact-path spill
idiom — so ANY input stays bit-exact.

Contract: the result is bit-identical to
``jax.lax.sort((key_hi, key_lo, packed), num_keys=3)``.  For aggregation
this single implementation serves both ``sort_mode='sort3'`` (that IS its
definition) and ``sort_mode='stable2'`` (ties resolve by ``packed``, which
under stable2's position-ordered-input precondition is exactly the tie
order stability would deliver).  It relies on the packed-stream dead-row
contract (:func:`...ops.table.from_packed_rows`): a ``(sent, sent)``-keyed
row always carries all-ones ``packed``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mapreduce_tpu import constants
from mapreduce_tpu.ops.pallas import meta
from mapreduce_tpu.ops.pallas.tokenize import LANES, _compact_planes

DEFAULT_BITS = 3  # B = 8 buckets per level
DEFAULT_BLOCK_ROWS = 256
# Slab budget per (block, lane, bucket) as a multiple of the uniform share
# block_rows/B.  4x covers the bench Zipf head (top key ~25% of live rows
# + ~12% uniform background lands ~0.3*block_rows in ONE bucket per lane);
# heavier skew spills into the exact XLA-sort fallback.
DEFAULT_SLAB_SLACK = 4

_IMPLS = ("radix_partition", "radix")

# Analyzer contract (costcheck vmem/race passes): the partition kernel
# ALWAYS emits a spill counter — live rows beyond a lane's slab budget
# mean the slabs are incomplete and radix_sort3's lax.cond MUST fall back
# to the exact XLA sort.
meta.register(meta.KernelMeta(
    name="_partition_kernel",
    spills=lambda num_outputs: True,
    description="MSD digit partition into static slabs; adversarial "
                "bucket skew spills past the slab budget"))


def vmem_plan(bits: int = DEFAULT_BITS,
              block_rows: int = DEFAULT_BLOCK_ROWS,
              slab_slack: int = DEFAULT_SLAB_SLACK) -> meta.VmemPlan:
    """Static VMEM/SMEM footprint of one partition-kernel geometry — the
    analyzer's metadata hook (ops/pallas/meta.py).  Delegates to the
    jax-free :func:`...meta.radix_plan` constructor (ISSUE 12: one
    arithmetic for search candidates, shipped plans, and what
    :func:`_partition_level` binds)."""
    return meta.radix_plan(bits=bits, block_rows=block_rows,
                           slab_slack=slab_slack)


def _partition_kernel(khi_ref, klo_ref, pck_ref, *out_refs, shift: int,
                      bits: int, cap: int, blocks_per_group: int):
    """One grid step: bucket this block's rows by digit into static slabs.

    Outputs (positional, after the three input planes): B per-bucket
    (khi, klo, packed) slab triples, then the per-group digit histogram
    (SMEM ``(1, B)`` row, zeroed at each group's first block) and the
    running spill scalar.  Dead rows — ``(sent, sent)`` keys — are dropped
    here (their count is implied: group rows minus the histogram row), so
    the finishing sorts never pay for the stream's dead fraction twice.
    """
    B = 1 << bits
    hist_ref = out_refs[3 * B]
    spill_ref = out_refs[3 * B + 1]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        spill_ref[0, 0] = jnp.uint32(0)

    @pl.when(i % blocks_per_group == 0)
    def _():
        for b in range(B):
            hist_ref[0, b] = jnp.uint32(0)

    khi = khi_ref[:]
    klo = klo_ref[:]
    pck = pck_ref[:]
    sent = jnp.uint32(constants.SENTINEL_KEY)
    live = ~((khi == sent) & (klo == sent))
    digit = (khi >> jnp.uint32(shift)) & jnp.uint32(B - 1)
    spill = jnp.uint32(0)
    for b in range(B):
        mask = live & (digit == jnp.uint32(b))
        # _compact_planes pads with all-ones on every plane — exactly the
        # dead triple, so slab pads are indistinguishable from stream
        # filler and sink to each bucket's tail in the finishing sort.
        khi_c, klo_c, pck_c, n_sp = _compact_planes(khi, klo, pck, mask, cap)
        out_refs[3 * b][:] = khi_c
        out_refs[3 * b + 1][:] = klo_c
        out_refs[3 * b + 2][:] = pck_c
        hist_ref[0, b] = hist_ref[0, b] + \
            jnp.sum(mask.astype(jnp.int32)).astype(jnp.uint32)
        spill = spill + n_sp
    spill_ref[0, 0] = spill_ref[0, 0] + spill


def _partition_level(khi2d, klo2d, pck2d, *, shift: int, bits: int,
                     block_rows: int, cap: int, n_groups: int,
                     interpret: bool):
    """One scatter-free MSD partition pass over ``(R, 128)`` planes.

    The input stream is ``n_groups`` contiguous groups (digit ranges from
    prior levels; 1 on the first).  Returns the restacked
    (group-major, bucket-major) planes — now ``n_groups * B`` groups, each
    a narrower digit range — plus the per-(group, bucket) real-row
    histogram and the spill scalar.
    """
    B = 1 << bits
    R = khi2d.shape[0]
    if R % block_rows:
        raise ValueError(f"stream rows {R} not a multiple of block_rows "
                         f"{block_rows}")
    G = R // block_rows
    if G % n_groups:
        raise ValueError(f"grid {G} not a multiple of n_groups {n_groups}")
    bpg = G // n_groups
    kern = functools.partial(_partition_kernel, shift=shift, bits=bits,
                             cap=cap, blocks_per_group=bpg)
    slab = jax.ShapeDtypeStruct((G * cap, LANES), jnp.uint32)
    plane_spec = pl.BlockSpec((cap, LANES), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        kern,
        grid=(G,),
        in_specs=[pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)] * 3,
        out_shape=[slab] * (3 * B)
        + [jax.ShapeDtypeStruct((n_groups, B), jnp.uint32),
           jax.ShapeDtypeStruct((1, 1), jnp.uint32)],
        out_specs=[plane_spec] * (3 * B)
        + [pl.BlockSpec((1, B), lambda i: (i // bpg, 0),
                        memory_space=pltpu.SMEM),
           pl.BlockSpec((1, 1), lambda i: (0, 0),
                        memory_space=pltpu.SMEM)],
        interpret=interpret,
    )(khi2d, klo2d, pck2d)
    hist = outs[3 * B]
    spill = outs[3 * B + 1][0, 0]

    def restack(refs):
        # ref_b rows are grid-major = (group, inner-block)-major; stacking
        # buckets per group yields global (group, bucket, inner) order —
        # exactly ascending digit ranges.
        parts = [r.reshape(n_groups, bpg * cap, LANES) for r in refs]
        return jnp.stack(parts, axis=1).reshape(-1, LANES)

    return (restack(outs[0:3 * B:3]), restack(outs[1:3 * B:3]),
            restack(outs[2:3 * B:3]), hist, spill)


def radix_sort3(key_hi: jax.Array, key_lo: jax.Array, packed: jax.Array, *,
                impl: str = "radix_partition", bits: int | None = None,
                block_rows: int | None = None,
                slab_slack: int | None = None,
                interpret: bool | None = None):
    """Radix-partitioned equivalent of
    ``jax.lax.sort((key_hi, key_lo, packed), num_keys=3)`` — bit-identical,
    including tie order (module docstring).

    ``impl='radix_partition'``: one MSD digit level (``bits`` wide) +
    per-bucket blocked XLA sorts.  ``impl='radix'``: two digit levels
    before the (smaller) finishing sorts.  Adversarial bucket skew beyond
    the slab budget falls back to the plain XLA sort under a ``lax.cond``
    (exact always; the partition work is wasted on such inputs, which the
    pricing note accounts for).
    """
    if impl not in _IMPLS:
        raise ValueError(f"unknown radix impl {impl!r}; known: {_IMPLS}")
    if not (key_hi.dtype == key_lo.dtype == packed.dtype == jnp.uint32):
        raise TypeError("radix_sort3 expects three uint32 planes")
    if key_hi.ndim != 1 or not (key_hi.shape == key_lo.shape == packed.shape):
        raise ValueError("radix_sort3 expects equal-length 1-D planes")
    levels = 1 if impl == "radix_partition" else 2
    # None-sentinel resolution against the module defaults AT CALL TIME so
    # geometry is overridable globally (tests shrink it: kernel jaxpr size
    # — and so CPU compile cost — scales with B x log2(block_rows), while
    # semantics are geometry-free).
    bits = DEFAULT_BITS if bits is None else bits
    block_rows = DEFAULT_BLOCK_ROWS if block_rows is None else block_rows
    slab_slack = DEFAULT_SLAB_SLACK if slab_slack is None else slab_slack
    B = 1 << bits
    if bits < 1 or bits > 5:
        # B output-ref triples are unrolled in the kernel; past 32 buckets
        # the jaxpr (and Mosaic's register pressure) outgrows the design.
        raise ValueError(f"bits must be in [1, 5], got {bits}")
    from mapreduce_tpu.config import radix_slab_cap

    cap = radix_slab_cap(bits, block_rows, slab_slack)
    if cap < 8 or cap % 8:
        raise ValueError(
            f"slab cap {cap} (= slab_slack*block_rows/B, clamped to "
            f"block_rows) must be a multiple of 8 and >= 8; adjust "
            f"block_rows/bits/slab_slack")
    if interpret is None:
        # Mosaic only targets TPU; elsewhere (CPU tests, debugging) the
        # interpreter executes the same kernel semantics.
        interpret = jax.default_backend() != "tpu"

    n = key_hi.shape[0]
    if n == 0:
        return key_hi, key_lo, packed
    sent = jnp.uint32(constants.SENTINEL_KEY)
    ones = jnp.uint32(0xFFFFFFFF)
    # Pad to whole blocks; multi-level needs level-1 group lengths (G*cap
    # rows per bucket) divisible by block_rows, which G % B == 0 guarantees
    # for any cap (cap*G/B = slack*block_rows*(G/B)/B ... held by the
    # stricter, simpler G % B == 0).
    unit = (B if levels > 1 else 1) * block_rows * LANES
    m = -(-n // unit) * unit

    def pad(x, fill):
        if m == n:
            return x
        return jnp.concatenate([x, jnp.full((m - n,), fill, jnp.uint32)])

    khi2d = pad(key_hi, sent).reshape(-1, LANES)
    klo2d = pad(key_lo, sent).reshape(-1, LANES)
    pck2d = pad(packed, ones).reshape(-1, LANES)

    n_groups = 1
    shift = 32
    spill_total = jnp.uint32(0)
    hist = None
    for _ in range(levels):
        shift -= bits
        khi2d, klo2d, pck2d, hist, sp = _partition_level(
            khi2d, klo2d, pck2d, shift=shift, bits=bits,
            block_rows=block_rows, cap=cap, n_groups=n_groups,
            interpret=interpret)
        spill_total = spill_total + sp
        n_groups *= B

    R_f = khi2d.shape[0]
    group_rows = R_f // n_groups
    slab_len = group_rows * LANES
    # Exact per-group real-row counts -> exclusive global offsets: the
    # compaction below writes slabs ASCENDING, each exactly overwriting the
    # previous slab's pad tail (off[g+1] = off[g] + real[g] <= off[g] +
    # slab_len always), so pads vanish without any gather.
    real = hist.reshape(-1).astype(jnp.int32)
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(real)])[:n_groups]
    final_groups = n_groups

    def finished(_):
        fh = khi2d.reshape(final_groups, slab_len)
        fl = klo2d.reshape(final_groups, slab_len)
        fp = pck2d.reshape(final_groups, slab_len)
        # Finishing sort per digit range; pads (dead triples) sink to each
        # group's tail.  One blocked sort: the sortbench-measured cheaper
        # shape (rows beat comparator width, BENCHMARKS.md round 4).
        sh, sl, sp_ = jax.lax.sort((fh, fl, fp), dimension=1, num_keys=3)
        oh = jnp.full((n + slab_len,), ones, jnp.uint32)
        ol = jnp.full((n + slab_len,), ones, jnp.uint32)
        op = jnp.full((n + slab_len,), ones, jnp.uint32)
        for g in range(final_groups):
            start = (offs[g],)
            oh = jax.lax.dynamic_update_slice(oh, sh[g], start)
            ol = jax.lax.dynamic_update_slice(ol, sl[g], start)
            op = jax.lax.dynamic_update_slice(op, sp_[g], start)
        # Rows past the last group's real tail were either overwritten by
        # that group's own pads or never written: both are the dead triple,
        # matching the XLA sort's trailing filler segment bit-for-bit.
        return oh[:n], ol[:n], op[:n]

    def fallback(_):
        return jax.lax.sort((key_hi, key_lo, packed), num_keys=3)

    return jax.lax.cond(spill_total == 0, finished, fallback, None)
