"""Data-plane telemetry counters (ISSUE 8 tentpole).

The map path computes spill fallbacks, rescue-tier escalations, dropped-
token accounting, and table shape ON DEVICE — but until now none of it
reached the obs layer: the ledger knew how long a dispatch took, never
what the data did to it.  This module is the seam:

* :class:`DataStats` — a tiny pytree of uint32 scalars (per shard) the
  stats-mode engine step returns NEXT TO the new state.  Counter fields
  are per-dispatch-group deltas (summed over the group's chunks at trace
  time); gauge fields are running values read off the post-group state.
  The output is non-donated and a few dozen bytes per device: the
  executor fetches it at group retirement, where the group's completion
  token already proved the program finished — no host callback, no added
  device sync (the PR-2 discipline the graphcheck host-sync pass
  certifies).
* :class:`DataAggregator` — the host-side fold: per-group summaries for
  ``group`` ledger records and the one per-run ``data`` summary record
  (schema: docs/observability.md), which ``obs/datahealth.py`` classifies
  and the window autotuner (ROADMAP item 1) consumes next to the PR-7
  ``bottleneck`` verdict.

Counter exactness: every counter is a per-chunk uint32 delta bounded by
tokens-per-chunk (< 2**24 at the 64 MB chunk ceiling), summed over at
most a superstep of chunks at trace time and in int64 on the host — no
32-bit wrap anywhere.  The 64-bit running gauges (total tokens, top
count, dropped) ride as lo/hi uint32 lane pairs, the CountTable idiom.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class DataStats(NamedTuple):
    """Per-shard data-plane stats.  All fields are uint32 scalars.

    Counters (per-group deltas, summed over the group's chunks):

    * ``chunks`` — chunks mapped in this dispatch group;
    * ``overlong`` — token occurrences longer than the kernel window W
      (pre-rescue; the pallas backend's only lossy envelope);
    * ``rescued`` — overlong occurrences recovered exactly by the
      bounded rescue pass (``ops/rescue.py``);
    * ``dropped_tokens`` / ``dropped_uniques`` — the per-chunk batch
      tables' ``dropped_*`` scalars (unrescued overlong residue +
      batch-capacity spill), i.e. the same accounting the result carries;
    * ``rescue_invocations`` — chunks whose ``overlong > 0`` cond took
      the rescue branch;
    * ``rescue_escalations`` — chunks whose overlong count exceeded the
      tier-1 budget and escalated to the full extraction
      (``Config.rescue_slots_max``);
    * ``fallback_chunks`` — chunks whose compact/fused kernel spilled a
      (block, lane) window and re-ran at full resolution (the
      ``lax.cond`` fallback branch taken — each one ~doubles that
      chunk's map cost);
    * ``spill_rows`` — emissions past the slot budget (the kernels' SMEM
      spill scalar, summed);
    * ``combiner_hits`` — occurrences the hot-key combiner cache absorbed
      in VMEM (ISSUE 11: rows DELETED from the aggregation sort's input,
      minus the flush rows below — zero when the combiner is off or the
      chunk took the combiner-free spill fallback);
    * ``combiner_flushes`` — resident cache entries re-emitted as exact
      (key, count, first-occurrence) rows at chunk end;
    * ``combiner_evicted`` — flushed entries with count 1: cold keys
      whose slot bought nothing (every entry is evicted at the flush;
      these are the wasted ones — the cache-efficacy signal).

    Gauges (running values off the post-group state, filled by
    ``job.state_stats``):

    * ``table_valid`` — occupied slots in this shard's running table;
    * ``total_lo``/``total_hi`` — exact 64-bit total tokens including
      dropped (``CountTable.total_count64``);
    * ``top_lo``/``top_hi`` — the largest single-key count (top-bucket
      mass: the cheap key-skew proxy — Zipf-hot corpora put a double-
      digit share of all tokens on one key, uniform corpora ~1/distinct);
    * ``dropped_lo``/``dropped_hi`` — cumulative dropped tokens (joins
      resumed history the per-group counters cannot see).
    """

    chunks: jax.Array
    overlong: jax.Array
    rescued: jax.Array
    dropped_tokens: jax.Array
    dropped_uniques: jax.Array
    rescue_invocations: jax.Array
    rescue_escalations: jax.Array
    fallback_chunks: jax.Array
    spill_rows: jax.Array
    combiner_hits: jax.Array
    combiner_flushes: jax.Array
    combiner_evicted: jax.Array
    table_valid: jax.Array
    total_lo: jax.Array
    total_hi: jax.Array
    top_lo: jax.Array
    top_hi: jax.Array
    dropped_lo: jax.Array
    dropped_hi: jax.Array


_N_FIELDS = len(DataStats._fields)
#: Fields summed per chunk at trace time (everything before the gauges).
_COUNTERS = ("chunks", "overlong", "rescued", "dropped_tokens",
             "dropped_uniques", "rescue_invocations", "rescue_escalations",
             "fallback_chunks", "spill_rows", "combiner_hits",
             "combiner_flushes", "combiner_evicted")


def zeros() -> DataStats:
    z = jnp.zeros((), jnp.uint32)
    return DataStats(*([z] * _N_FIELDS))


def _u32(x) -> jax.Array:
    return jnp.asarray(x).astype(jnp.uint32)


def map_stats(*, overlong=0, rescued=0, spill=0, fallback=0,
              invoked=0, escalated=0, dropped_tokens=0,
              dropped_uniques=0, combiner_hits=0, combiner_flushes=0,
              combiner_evicted=0) -> DataStats:
    """One chunk's counter delta (gauges zero; ``state_stats`` fills them
    after the group's last combine).  All arguments accept uint32 scalars
    or Python ints; predicates arrive as 0/1 values."""
    return zeros()._replace(
        chunks=jnp.ones((), jnp.uint32),
        overlong=_u32(overlong), rescued=_u32(rescued),
        spill_rows=_u32(spill), fallback_chunks=_u32(fallback),
        rescue_invocations=_u32(invoked), rescue_escalations=_u32(escalated),
        dropped_tokens=_u32(dropped_tokens),
        dropped_uniques=_u32(dropped_uniques),
        combiner_hits=_u32(combiner_hits),
        combiner_flushes=_u32(combiner_flushes),
        combiner_evicted=_u32(combiner_evicted))


def add(a: DataStats, b: DataStats) -> DataStats:
    """Fold two chunk deltas (the superstep scan's accumulator).  Gauges
    add too — harmless: ``with_table_gauges`` overwrites them after the
    group's last chunk."""
    return DataStats(*(x + y for x, y in zip(a, b)))


def with_table_gauges(stats: DataStats, table) -> DataStats:
    """Fill the running-state gauges from a :class:`...ops.table.CountTable`
    (the post-group running table).  Costs two reductions over the
    capacity-sized count lanes — noise next to the chunk-sized map (the
    hbm-cost pass ERROR-gates the whole instrumentation at <= 1% extra
    effective input passes)."""
    total_lo, total_hi = table.total_count64()
    # Largest per-key 64-bit count without a device uint64: the max hi
    # lane first, then the max lo lane among keys AT that hi lane.
    top_hi = jnp.max(table.count_hi)
    top_lo = jnp.max(jnp.where(table.count_hi == top_hi, table.count, 0))
    return stats._replace(
        table_valid=table.n_valid(),
        total_lo=total_lo, total_hi=total_hi,
        top_lo=top_lo, top_hi=top_hi,
        dropped_lo=table.dropped_count, dropped_hi=table.dropped_count_hi)


def supports(job) -> bool:
    """Does this job emit data-plane stats?  Duck-typed like every other
    job hook; wrappers (sketch composition) forward their base job's
    answer through ``data_stats_supported``."""
    flag = getattr(job, "data_stats_supported", None)
    if flag is not None:
        return bool(flag)
    return (callable(getattr(job, "map_chunk_stats_sharded", None))
            and callable(getattr(job, "state_stats", None)))


# -- host side ---------------------------------------------------------------


def window_slot_capacity(config) -> int | None:
    """Token-emission slot capacity of one chunk's compact kernel windows
    (the stable2 window-occupancy denominator): ``blocks * 128 lanes *
    compact_slots``.  None when the config does not run the compact
    pallas path (nothing to be occupancy-starved about)."""
    try:
        if config.resolved_backend() != "pallas":
            return None
    except Exception:
        return None  # backend resolution may need jax; stats just degrade
    slots = config.resolved_compact_slots
    if not slots:
        return None
    block_rows = config.resolved_block_rows or 256
    seg = config.chunk_bytes // 128
    blocks = -(-seg // block_rows)
    return blocks * 128 * slots


def _pair64(lo, hi) -> int:
    return (int(hi) << 32) | int(lo)


class DataAggregator:
    """Host-side fold of per-group :class:`DataStats` fetches.

    ``group_data`` reduces one group's per-device leaves ([D]-shaped
    numpy) into the small dict the ``group`` ledger record carries and
    accumulates run totals; ``run_record`` emits the per-run ``data``
    summary record.  Pure numpy/int math — never touches a device.
    """

    def __init__(self, *, capacity: int, devices: int,
                 backend: str, map_impl: str,
                 slot_capacity_per_chunk: int | None = None,
                 combiner: str = "off"):
        self.capacity = int(capacity)
        self.devices = int(devices)
        self.backend = backend
        self.map_impl = map_impl
        self.combiner = combiner
        self.slot_capacity = slot_capacity_per_chunk
        self.groups = 0
        self.totals = {k: 0 for k in _COUNTERS}
        self.final: dict = {}

    @classmethod
    def for_run(cls, config, devices: int) -> "DataAggregator":
        return cls(capacity=config.table_capacity, devices=devices,
                   backend=config.resolved_backend(),
                   map_impl=config.map_impl,
                   slot_capacity_per_chunk=window_slot_capacity(config),
                   combiner=config.resolved_combiner)

    def group_data(self, stats_host: DataStats) -> dict:
        """One retired group's [D]-leaf stats -> the ``group`` record's
        ``data`` dict (per-group counters + running occupancy/skew),
        folding the counters into the run totals."""
        s = {f: np.asarray(v) for f, v in zip(DataStats._fields, stats_host)}
        out: dict = {}
        for k in _COUNTERS:
            v = int(s[k].sum(dtype=np.int64))
            self.totals[k] += v
            if k != "chunks" and v:
                out[k] = v
        out["chunks"] = int(s["chunks"].sum(dtype=np.int64))
        valid = int(s["table_valid"].sum(dtype=np.int64))
        total = sum(_pair64(lo, hi) for lo, hi in
                    zip(s["total_lo"].ravel(), s["total_hi"].ravel()))
        top = max((_pair64(lo, hi) for lo, hi in
                   zip(s["top_lo"].ravel(), s["top_hi"].ravel())),
                  default=0)
        dropped = sum(_pair64(lo, hi) for lo, hi in
                      zip(s["dropped_lo"].ravel(), s["dropped_hi"].ravel()))
        self.final = {"table_valid": valid, "tokens": total,
                      "top_count": top, "dropped_cumulative": dropped}
        out["occupancy"] = round(valid / max(self.capacity * self.devices, 1),
                                 4)
        if total:
            out["top_mass"] = round(top / total, 6)
        self.groups += 1
        return out

    def snapshot(self) -> dict:
        """The run summary as of the last retired group (the flight
        recorder's data-health snapshot on the failure path)."""
        return self.run_record()

    def run_record(self) -> dict:
        """The per-run ``data`` ledger record (docs/observability.md)."""
        rec: dict = {"groups": self.groups, "backend": self.backend,
                     "map_impl": self.map_impl, "combiner": self.combiner,
                     "capacity": self.capacity * self.devices}
        rec.update(self.totals)
        f = self.final
        tokens = f.get("tokens", 0)
        rec["tokens"] = tokens
        rec["table_valid"] = f.get("table_valid", 0)
        rec["top_count"] = f.get("top_count", 0)
        rec["dropped_cumulative"] = f.get("dropped_cumulative", 0)
        rec["table_occupancy"] = round(
            rec["table_valid"] / max(rec["capacity"], 1), 4)
        if tokens:
            rec["top_mass"] = round(rec["top_count"] / tokens, 6)
            rec["distinct_ratio"] = round(rec["table_valid"] / tokens, 6)
            rec["dropped_frac"] = round(rec["dropped_tokens"] / tokens, 6)
            if rec["combiner_hits"]:
                # Share of all tokens the cache absorbed, and the sort
                # rows it deleted net of the flush rows it re-emitted.
                rec["combiner_hit_rate"] = round(
                    rec["combiner_hits"] / tokens, 6)
                rec["combiner_rows_deleted"] = \
                    rec["combiner_hits"] - rec["combiner_flushes"]
        if self.slot_capacity and self.totals["chunks"] and tokens:
            cap = self.slot_capacity * self.totals["chunks"]
            rec["window_slot_capacity"] = cap
            # Combiner-absorbed occurrences never occupied a window slot
            # (they were counted in the cache, not emitted): the occupancy
            # numerator is the rows the windows actually carried, so the
            # occupancy-starved signal stays meaningful with the cache on.
            emitted = max(tokens - rec["combiner_hits"], 0)
            rec["window_occupancy"] = round(emitted / cap, 4)
        return rec
