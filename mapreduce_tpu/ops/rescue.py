"""Overlong-token rescue: exact counts for >W-byte tokens on the pallas path.

The fused kernel (:mod:`mapreduce_tpu.ops.pallas.tokenize`) bounds its
on-chip lookback at W bytes; longer tokens leave it *accounted but unhashed*
(``dropped_*``), while the XLA backend counts any length exactly — the one
semantic gap between the backends (VERDICT r3 #6).  Natural web-ish text has
real >W tokens (URLs, markup: ~0.3% of tokens on the webby proxy,
tools/overlong.py), so on such corpora the two backends disagreed.

This module closes the gap with the seam-pass idiom at chunk scale: the
kernel already emits a POISON row (``pos << 6`` with zero length bits) at
the last byte of every overlong run, and the aggregation sort delivers
those rows pre-compacted at the head of its sentinel segment for free
(``rescue_slots`` in :func:`mapreduce_tpu.ops.table.from_packed_rows`).
Re-tokenizing one bounded window ending at each poison position with the
XLA scan — bit-identical hashing by construction (it IS the other backend's
tokenizer) — recovers each token's exact key/length/start, and a tiny table
built from those rows merges into the chunk's batch table.  The whole pass
sits under a ``lax.cond(overlong > 0)`` in the caller: corpora without
overlong tokens (both bench generators, test.txt) never pay for it.

Envelope, by construction rather than silence:
  * tokens longer than ``window - 1`` bytes cannot be verified complete in
    the window and stay dropped-but-accounted (p99.9 token length on the
    webby proxy is 151 bytes — a 192..320-byte window covers essentially
    everything real);
  * at most ``rescue_slots`` poison rows per chunk are rescued, smallest
    positions first (deterministic); the remainder stays accounted.
Both residuals land in ``dropped_*`` exactly as before, so results degrade
to the round-3 accounting, never to corruption.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mapreduce_tpu import constants
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.ops import tokenize as tok_ops


def rescue_table(chunk: jax.Array, rescue_packed: jax.Array, w: int,
                 window: int, pos_hi: jax.Array | int
                 ) -> tuple[table_ops.CountTable, jax.Array]:
    """Build a count table of the rescued overlong tokens.

    Args:
      chunk: the uint8 chunk the poison positions index into (chunk-relative
        positions: the pallas map always tokenizes with base_offset 0 and
        carries global placement in ``pos_hi``).
      rescue_packed: uint32[R] from the aggregation sort — poison rows
        (``last_byte << 6``, zero length bits) first, all-ones filler after;
        any real-token rows a clamped slice pulled in carry nonzero length
        bits and are masked off here.
      w: the kernel's W — every true poison marks a run longer than w.
      window: static lookback bound for the rescue (tokens of length in
        (w, window-1] are rescued; longer ones stay accounted).
      pos_hi: the chunk id, so first-occurrence order stays global.

    Returns:
      ``(table, rescued)``: a capacity-R table of the rescued tokens (their
      exact 64-bit keys, counts, first-occurrence positions and true
      lengths) and the uint32 number of occurrences rescued.
    """
    n = int(chunk.shape[0])
    r = rescue_packed.shape[0]
    ones = jnp.uint32(0xFFFFFFFF)
    is_poison = (rescue_packed != ones) & \
        ((rescue_packed & jnp.uint32(63)) == 0)
    p = (rescue_packed >> 6).astype(jnp.int32)  # last byte of each run

    # Window i = chunk[p_i - window + 1 .. p_i], read from a front-padded
    # copy so early positions need no clamping (PAD is a separator, so the
    # synthetic prefix can never extend a run).  Dead slots index past the
    # end; clip-mode gather returns arbitrary in-range bytes that the
    # is_poison mask discards.
    padded = jnp.concatenate(
        [jnp.full((window,), constants.PAD_BYTE, jnp.uint8), chunk])
    idx = jnp.minimum(p[:, None] + 1 + jnp.arange(window, dtype=jnp.int32),
                      jnp.int32(n + window - 1))
    windows = jnp.take(padded, idx, axis=0)  # (R, window) uint8

    # The XLA backend's own tokenizer, vmapped over windows: hashing is
    # bit-identical to what that backend would have emitted for these very
    # tokens.  Only the last position of each stream matters (the token
    # ending at p); XLA prunes the rest of the planes.
    streams = jax.vmap(tok_ops.tokenize)(windows)
    last = window - 1
    length = streams.length[:, last]
    key_hi = streams.key_hi[:, last]
    key_lo = streams.key_lo[:, last]

    # length == window means the run reaches the window start: possibly
    # truncated, cannot be verified complete — stays accounted.  length <= w
    # on a poison row is impossible by kernel construction; masking it keeps
    # any future drift accounted instead of double-counted.
    valid = is_poison & (streams.count[:, last] > 0) \
        & (length < jnp.uint32(window)) & (length > jnp.uint32(w))
    rescued = jnp.sum(valid.astype(jnp.uint32))

    sent = jnp.uint32(constants.SENTINEL_KEY)
    inf = jnp.uint32(constants.POS_INF)
    start = (p + 1).astype(jnp.uint32) - length  # first byte, chunk-relative
    stream = tok_ops.TokenStream(
        key_hi=jnp.where(valid, key_hi, sent),
        key_lo=jnp.where(valid, key_lo, sent),
        count=valid.astype(jnp.uint32),
        pos=jnp.where(valid, start, inf),
        length=jnp.where(valid, length, jnp.uint32(0)),
    )
    # Generic build (lengths exceed the 6-bit packed bound); R rows, so the
    # sort is noise.  Capacity R: at most R distinct keys, nothing can drop.
    return table_ops.from_stream(stream, r, pos_hi=pos_hi), rescued
