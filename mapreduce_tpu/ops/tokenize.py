"""Device-side tokenization + hashing.

Replaces both the reference's host tokenizer (char-scan loop, ``main.cu:187-202``)
and its device map UDF (per-thread byte-copy loops, ``mapper`` ``main.cu:37-54``)
with one data-parallel pass: a *segmented associative scan* over the raw byte
tensor.

Formulation
-----------
Scanning a token's bytes left-to-right with ``h' = h * B + c`` is composition
of affine maps ``f_c(h) = h*B + c``; affine composition is associative, so the
whole pass runs as ``jax.lax.associative_scan`` (log-depth, VPU-friendly,
static shapes) instead of a serial per-char loop.  Separator bytes insert a
*reset* element, giving the segmented variant: after the scan, every position
holds the rolling hash of the token prefix ending there, and positions where a
non-separator byte is followed by a separator (or end-of-buffer) hold the hash
of a complete token.

Two independent 32-bit lanes (different odd bases) form an effective 64-bit
key, finalized with murmur3's fmix32.  This fixes the reference's prefix-match
comparator defect (``compare``, ``main.cu:57-67``) by construction: equality is
on full-token 64-bit hashes (token length is mixed in as well).

No token strings are materialized on device.  For reporting, each table entry
carries the position/length of its first occurrence so the host can recover
the exact bytes from the source (SURVEY §7 "String recovery").
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu import constants


class TokenStream(NamedTuple):
    """Per-byte-position token emissions (shape = input byte count).

    Positions that do not end a token carry the sentinel key and count 0; they
    are compacted away by :func:`mapreduce_tpu.ops.segment.unique_count`.
    """

    key_hi: jax.Array  # uint32
    key_lo: jax.Array  # uint32
    count: jax.Array  # uint32: 1 at token ends, else 0
    pos: jax.Array  # uint32: byte offset of the token's *first* byte
    length: jax.Array  # uint32: token length in bytes


def separator_mask(data: jax.Array) -> jax.Array:
    """True where the byte is a separator (whitespace / NUL pad)."""
    sep = jnp.zeros(data.shape, dtype=jnp.bool_)
    for b in constants.SEPARATOR_BYTES:
        sep = sep | (data == jnp.uint8(b))
    return sep


def _fmix32(x: jax.Array) -> jax.Array:
    """murmur3 finalizer: bijective avalanche on a uint32 lane."""
    x = x ^ (x >> 16)
    x = x * constants.FMIX_C1
    x = x ^ (x >> 13)
    x = x * constants.FMIX_C2
    x = x ^ (x >> 16)
    return x


def _segmented_combine(a, b):
    """Associative combine for the segmented affine-map scan.

    Element = (reset, v1, p1, v2, p2, length).  ``(v, p)`` represents the
    affine map ``h -> h*p + v`` accumulated since the last reset; ``reset``
    marks that the right operand contains a segment boundary, which discards
    the left operand's contribution.
    """
    a_f, a_v1, a_p1, a_v2, a_p2, a_len = a
    b_f, b_v1, b_p1, b_v2, b_p2, b_len = b
    f = a_f | b_f
    v1 = jnp.where(b_f, b_v1, a_v1 * b_p1 + b_v1)
    p1 = jnp.where(b_f, b_p1, a_p1 * b_p1)
    v2 = jnp.where(b_f, b_v2, a_v2 * b_p2 + b_v2)
    p2 = jnp.where(b_f, b_p2, a_p2 * b_p2)
    ln = jnp.where(b_f, b_len, a_len + b_len)
    return (f, v1, p1, v2, p2, ln)


def tokenize(data: jax.Array, base_offset: jax.Array | int = 0) -> TokenStream:
    """Hash every whitespace-delimited token in a flat uint8 buffer.

    Args:
      data: uint8[N] byte buffer.  N is static.  The buffer is treated as if
        followed by a separator, so a token touching the end is complete —
        ingest must therefore only split shards at separator boundaries.
      base_offset: uint32 added to emitted positions (for global addressing of
        a shard within a larger stream).

    Returns:
      A :class:`TokenStream` of length N.
    """
    if data.dtype != jnp.uint8:
        raise TypeError(f"tokenize expects uint8 bytes, got {data.dtype}")
    if data.ndim != 1:
        raise ValueError(f"tokenize expects a flat buffer, got shape {data.shape}")

    n = data.shape[0]
    sep = separator_mask(data)
    c = data.astype(jnp.uint32)

    one = jnp.uint32(1)
    zero = jnp.uint32(0)
    elems = (
        sep,
        jnp.where(sep, zero, c + one),
        jnp.where(sep, one, jnp.uint32(constants.HASH_BASE_1)),
        jnp.where(sep, zero, c + one),
        jnp.where(sep, one, jnp.uint32(constants.HASH_BASE_2)),
        jnp.where(sep, zero, one),
    )
    _, v1, _, v2, _, length = jax.lax.associative_scan(_segmented_combine, elems)

    # A position ends a token iff it is a non-separator whose successor is a
    # separator or the end of the buffer.
    next_sep = jnp.concatenate([sep[1:], jnp.ones((1,), dtype=jnp.bool_)])
    is_end = (~sep) & next_sep

    key_hi = _fmix32(v1 ^ length)
    key_lo = _fmix32(v2 + jnp.uint32(0x9E3779B9) * length)

    # Clamp away from the two reserved keys (probability 2**-63 per token):
    # (sent, sent) marks dead rows and (sent, sent-1) marks overlong-poison
    # rows (:mod:`..ops.pallas.tokenize`); a real token hashing onto either
    # would be misread structurally, so both remap to (sent, sent-2).  Every
    # backend's clamp MUST share this rule or their keys drift.
    sentinel = jnp.uint32(constants.SENTINEL_KEY)
    at_sentinel = (key_hi == sentinel) & (key_lo >= sentinel - one)
    key_lo = jnp.where(at_sentinel, sentinel - jnp.uint32(2), key_lo)

    # Non-token positions carry the sentinel so they sort to the end.
    key_hi = jnp.where(is_end, key_hi, sentinel)
    key_lo = jnp.where(is_end, key_lo, sentinel)

    idx = jax.lax.broadcasted_iota(jnp.uint32, (n, 1), 0).squeeze(-1)
    base = jnp.asarray(base_offset, dtype=jnp.uint32)
    start = idx + one - length + base  # first byte of the token
    return TokenStream(
        key_hi=key_hi,
        key_lo=key_lo,
        count=is_end.astype(jnp.uint32),
        pos=jnp.where(is_end, start, jnp.uint32(constants.POS_INF)),
        length=jnp.where(is_end, length, zero),
    )


def _last_valid_combine(a, b):
    """Associative combine: rightmost valid element wins (carry-forward)."""
    a_v, a_hi, a_lo, a_pos = a
    b_v, b_hi, b_lo, b_pos = b
    return (
        a_v | b_v,
        jnp.where(b_v, b_hi, a_hi),
        jnp.where(b_v, b_lo, a_lo),
        jnp.where(b_v, b_pos, a_pos),
    )


def _extend_grams(gram: TokenStream, tokens: TokenStream) -> TokenStream:
    """One pairing step: (k)-gram stream = (k-1)-gram stream x token stream.

    For every token end, the (k-1)-gram ending at the *previous* token is
    found with a carry-forward associative scan over the gram stream (the
    bytes of the current token cannot hold a gram end, so "last gram end
    before this position" is exactly "gram ending at the previous token").
    The pairing is order-sensitive: the carried key is multiplied by an odd
    base (bijective) before mixing in the current token's key.
    """
    valid = gram.count > 0
    inc = jax.lax.associative_scan(
        _last_valid_combine, (valid, gram.key_hi, gram.key_lo, gram.pos))

    # Exclusive variant: shift the inclusive result right one position, so a
    # gram ending AT p never pairs with itself.
    def shift(x, fill):
        return jnp.concatenate([jnp.full((1,), fill, x.dtype), x[:-1]])

    c_valid = shift(inc[0], False)
    c_hi = shift(inc[1], jnp.uint32(0))
    c_lo = shift(inc[2], jnp.uint32(0))
    c_pos = shift(inc[3], jnp.uint32(constants.POS_INF))

    is_end = (tokens.count > 0) & c_valid
    key_hi = _fmix32(c_hi * jnp.uint32(constants.HASH_BASE_1) ^ tokens.key_hi)
    key_lo = _fmix32(c_lo * jnp.uint32(constants.HASH_BASE_2) ^ tokens.key_lo)

    sentinel = jnp.uint32(constants.SENTINEL_KEY)
    at_sentinel = (key_hi == sentinel) & (key_lo >= sentinel - jnp.uint32(1))
    key_lo = jnp.where(at_sentinel, sentinel - jnp.uint32(2), key_lo)

    # Span = first byte of the gram's first token .. last byte of the current
    # token (separator bytes in between included), so host string recovery
    # reads the exact source text of the gram.
    length = tokens.pos + tokens.length - c_pos
    return TokenStream(
        key_hi=jnp.where(is_end, key_hi, sentinel),
        key_lo=jnp.where(is_end, key_lo, sentinel),
        count=is_end.astype(jnp.uint32),
        pos=jnp.where(is_end, c_pos, jnp.uint32(constants.POS_INF)),
        length=jnp.where(is_end, length, jnp.uint32(0)),
    )


def ngrams(stream: TokenStream, n: int) -> TokenStream:
    """Derive the n-token-gram stream from a token stream (n >= 1).

    Each emission is keyed by an order-sensitive 64-bit hash of its n
    consecutive tokens and carries the byte span from the first token's first
    byte through the last token's last byte — so the host recovers the exact
    source text (inter-word separators included) the same way it recovers
    single words.  This per-buffer op forms only IN-BUFFER grams (the first
    n-1 tokens start no gram); streamed runs form the cross-chunk ones
    exactly via the seam-carry machinery of
    :class:`mapreduce_tpu.models.wordcount.NGramCountJob`.

    The reference has no n-gram capability (its map UDF emits single words
    only, ``mapper`` ``main.cu:37-54``); this is a beyond-parity model family
    riding the same tokenize -> table -> collective machinery.
    """
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    gram = stream
    for _ in range(n - 1):
        gram = _extend_grams(gram, stream)
    return gram


def token_count(data: jax.Array) -> jax.Array:
    """Total number of tokens in a flat uint8 buffer (uint32 scalar)."""
    sep = separator_mask(data)
    next_sep = jnp.concatenate([sep[1:], jnp.ones((1,), dtype=jnp.bool_)])
    return jnp.sum(((~sep) & next_sep).astype(jnp.uint32))


def pad_to(data: np.ndarray | bytes, size: int) -> np.ndarray:
    """Host-side: right-pad raw bytes with PAD_BYTE to a static size."""
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
    if buf.shape[0] > size:
        raise ValueError(f"buffer of {buf.shape[0]} bytes exceeds static size {size}")
    out = np.full((size,), constants.PAD_BYTE, dtype=np.uint8)
    out[: buf.shape[0]] = buf
    return out
