"""Sorted fixed-capacity count tables: the parallel reduce data plane.

The reference reduces with a single device thread doing an O(pairs x distinct)
linear-scan group-by (``reducer``, ``main.cu:69-108``, launched serially via
the ``i < 1`` loop at ``main.cu:120``).  Here the same group-by-key-and-sum is
a *sort + segment-reduce*: O(n log n) work, fully parallel, static shapes, and
— crucially — the resulting :class:`CountTable` has an **associative merge**,
which is what lets the global reduction become a collective (tree ``ppermute``
/ ``all_gather`` / key-range ``all_to_all``) instead of the reference's serial
device-wide pass.

Invariants of a well-formed table (established by every constructor here):
  * entries are sorted ascending by 64-bit key;
  * occupied slots (``(count | count_hi) > 0``) form a prefix; empty slots
    carry the sentinel key, count 0, pos = +inf, length 0;
  * ``(pos_hi, pos_lo)`` is the lexicographically smallest (i.e. first)
    occurrence of the key, enabling exact insertion-order reporting and
    host-side string recovery (SURVEY §7);
  * overflow past capacity is *accounted* (``dropped_count`` exact,
    ``dropped_uniques`` an upper bound), never silent corruption like the
    reference past MAX_OUTPUT_COUNT (``main.cu:103-104``).

Key-collision envelope: keys are 64-bit hashes (two independent fmix32
lanes, token length mixed in), never the token bytes — so two DISTINCT
words colliding on all 64 bits would silently merge into one entry (first
occurrence's identity, summed count).  Birthday arithmetic: P(any
collision among n distinct words) ~ n^2 / 2^65 — ~3e-8 at 1e6 distinct
(enwik8), ~3e-4 at 1e8 (the 100 GB Zipf target), ~3e-2 at 1e9
(Common-Crawl WET scale).  Undetectable from the table alone (the table
never sees the strings); the DETECTION path is a host-side exact recount
of reported words (:mod:`mapreduce_tpu.utils.verify`, CLI
``--verify-sample K``), where a collision shows as a reported count
exceeding the byte-exact recount.

Count envelope: per-key counts and the ``dropped_*`` scalars are exact
**64-bit** values carried as uint32 lo/hi lane pairs (JAX default-x64 is
off, so device uint64 is unavailable — the grep accumulator idiom,
``models/grep.py``).  Batch tables built from one chunk stream never exceed
2**26 rows, so their hi lanes are structurally zero; the hi lanes earn
their keep in the running-table ``merge``/``merge_batched`` adds, where a
single uint32 would silently wrap at ~4.29e9 occurrences per word (~30 GB
of one repeated word — inside the BASELINE 100 GB envelope).  Wrap is
silent corruption, the exact failure mode this framework exists to never
have; every add/sum in this module carries.  Host-side totals
(:meth:`CountTable.total_count` on fetched tables) are reconstructed
``hi << 32 | lo`` in int64.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu import constants
from mapreduce_tpu.ops.tokenize import TokenStream


class CountTable(NamedTuple):
    """Keyed count state.  A pytree; all fields are device arrays.

    Counts and dropped scalars are exact 64-bit lo/hi uint32 pairs (module
    docstring).  Occupancy is ``(count | count_hi) > 0`` — a key holding
    exactly a multiple of 2**32 occurrences has ``count == 0`` with a
    nonzero hi lane, so ``count > 0`` alone is NOT an occupancy test.
    """

    key_hi: jax.Array  # uint32[V], sorted (with key_lo) ascending
    key_lo: jax.Array  # uint32[V]
    count: jax.Array  # uint32[V]  occurrence count, low word
    count_hi: jax.Array  # uint32[V]  occurrence count, high word
    pos_hi: jax.Array  # uint32[V]  (device,step) buffer id of first occurrence
    pos_lo: jax.Array  # uint32[V]  byte offset within that buffer
    length: jax.Array  # uint32[V]  token length in bytes
    dropped_uniques: jax.Array  # uint32 scalar, >= true number of spilled keys
    dropped_count: jax.Array  # uint32 scalar, exact token count spilled (lo)
    dropped_uniques_hi: jax.Array  # uint32 scalar, high word
    dropped_count_hi: jax.Array  # uint32 scalar, high word

    @property
    def capacity(self) -> int:
        return self.key_hi.shape[0]

    def occupied(self) -> jax.Array:
        """bool[V]: slots holding a live key (the single occupancy rule)."""
        return (self.count | self.count_hi) > 0

    def n_valid(self) -> jax.Array:
        return jnp.sum(self.occupied().astype(jnp.uint32))

    def dropped_totals(self) -> tuple[int, int]:
        """Host-side exact ``(dropped_uniques, dropped_count)`` ints from
        the 64-bit lane pairs (concrete tables only)."""
        return (int(self.dropped_uniques) + (int(self.dropped_uniques_hi) << 32),
                int(self.dropped_count) + (int(self.dropped_count_hi) << 32))

    def total_count64(self) -> tuple[jax.Array, jax.Array]:
        """Exact 64-bit total as ``(lo, hi)`` uint32 lanes — safe under jit.

        Per-key lanes are summed with wrap carry (:func:`sum64`) and the
        ``dropped_*`` lanes folded in (:func:`add64`), so the pair is exact
        at any corpus scale.  Host callers reconstructing an int:
        ``(hi << 32) | lo`` (what :meth:`total_count` does for them).
        """
        lo, hi = sum64(self.count, self.count_hi)
        return add64(lo, hi, self.dropped_count, self.dropped_count_hi)

    def total_count(self) -> int:
        """Total tokens represented, including spilled ones (exact int).

        Host-side only: concrete tables (numpy leaves, or fetched device
        arrays) reconstruct the 64-bit lanes in int64.  Under jit there is
        no device uint64 (x64 off), so a single traced scalar cannot carry
        the exact total — the old behavior summed the low words alone and
        silently wrapped at 2**32, the 32-bit count-path hazard the
        graphcheck overflow lint exists to catch.  Traced callers take the
        exact lane pair from :meth:`total_count64` instead.
        """
        if not isinstance(self.count, jax.core.Tracer):
            lo = np.asarray(self.count).astype(np.int64)
            hi = np.asarray(self.count_hi).astype(np.int64)
            return int((lo + (hi << np.int64(32))).sum()) \
                + int(self.dropped_count) + (int(self.dropped_count_hi) << 32)
        raise TypeError(
            "CountTable.total_count() is host-side (returns an exact int); "
            "under jit use total_count64() -> (lo, hi) uint32 lanes")


def empty(capacity: int) -> CountTable:
    sent = jnp.full((capacity,), constants.SENTINEL_KEY, dtype=jnp.uint32)
    zero = jnp.zeros((capacity,), dtype=jnp.uint32)
    inf = jnp.full((capacity,), constants.POS_INF, dtype=jnp.uint32)
    return CountTable(key_hi=sent, key_lo=jnp.array(sent), count=zero,
                      count_hi=jnp.array(zero), pos_hi=inf,
                      pos_lo=jnp.array(inf), length=jnp.array(zero),
                      dropped_uniques=jnp.uint32(0), dropped_count=jnp.uint32(0),
                      dropped_uniques_hi=jnp.uint32(0),
                      dropped_count_hi=jnp.uint32(0))


def add64(a_lo, a_hi, b_lo, b_hi):
    """(lo, hi) + (lo, hi) with carry: exact uint64 in two uint32 lanes.
    Elementwise — scalars and arrays alike (the grep accumulator idiom)."""
    lo = a_lo + b_lo
    return lo, a_hi + b_hi + (lo < a_lo).astype(jnp.uint32)


def _sub64(a_lo, a_hi, b_lo, b_hi):
    """(lo, hi) - (lo, hi) with borrow; caller guarantees a >= b."""
    return a_lo - b_lo, a_hi - b_hi - (a_lo < b_lo).astype(jnp.uint32)


def sum64(lo: jax.Array, hi: jax.Array | None = None):
    """Exact 64-bit (lo, hi) sum of uint32 lane arrays.

    The low-lane sum wraps; wraps are counted off the running cumsum (a
    partial sum decreases exactly when the add wrapped, since every addend
    is < 2**32) and folded into the high word.  The hi-lane sum itself is a
    plain uint32 sum: overflowing it needs > 2**64 total tokens, i.e. more
    bytes than the corpus can physically contain.
    """
    s = jnp.cumsum(lo)
    wraps = jnp.sum((s[1:] < s[:-1]).astype(jnp.uint32)) if lo.shape[0] > 1 \
        else jnp.uint32(0)
    hi_sum = jnp.sum(hi) if hi is not None else jnp.uint32(0)
    return s[-1], hi_sum + wraps


def _segment_heads(seg: jax.Array, capacity: int) -> jax.Array:
    """First sorted-row index of each of the first capacity+1 segments.

    Equivalent to ``jnp.searchsorted(seg, arange(capacity+1))`` but as an
    UNROLLED binary search: ``jnp.searchsorted``'s while-loop lowering pays
    a fixed per-iteration cost plus loop-carry device copies on TPU
    (~15 ms/chunk measured); the static log-n chain of gathers is both
    cheaper and fusion-friendly.
    """
    n = seg.shape[0]
    q = jnp.arange(capacity + 1, dtype=jnp.int32)
    lo = jnp.zeros((capacity + 1,), jnp.int32)
    hi = jnp.full((capacity + 1,), n, jnp.int32)
    # Range [0, n] holds n+1 candidate answers: n.bit_length() iterations
    # always suffice ((n-1).bit_length() is one short when n is a power of
    # two — exactly the table capacities).
    for _ in range(max(1, n.bit_length())):
        mid = (lo + hi) >> 1
        right = seg[jnp.minimum(mid, n - 1)] < q
        lo = jnp.where(right, mid + 1, lo)
        hi = jnp.where(right, hi, mid)
    return hi


def _first_key_geq(key_hi, key_lo, q_hi, q_lo) -> jax.Array:
    """Index of the first sorted row with 64-bit key >= (q_hi, q_lo)
    (``n`` if none) — an unrolled binary search over the two key lanes,
    the :func:`_segment_heads` idiom (searchsorted's while-loop lowering
    is the expensive path on TPU)."""
    n = key_hi.shape[0]
    q_hi = jnp.uint32(q_hi)
    q_lo = jnp.uint32(q_lo)
    lo = jnp.int32(0)
    hi = jnp.int32(n)
    for _ in range(max(1, n.bit_length())):
        mid = (lo + hi) >> 1
        m = jnp.minimum(mid, n - 1)
        below = (key_hi[m] < q_hi) | ((key_hi[m] == q_hi) & (key_lo[m] < q_lo))
        lo = jnp.where(below, mid + 1, lo)
        hi = jnp.where(below, hi, mid)
    return hi


def _segment_boundaries(key_hi, key_lo):
    """Boundary mask + segment ranks of key-sorted rows (shared by the
    generic and packed reduce paths so their grouping can never diverge)."""
    boundary = (key_hi != jnp.concatenate([key_hi[:1], key_hi[:-1]])) | \
               (key_lo != jnp.concatenate([key_lo[:1], key_lo[:-1]]))
    boundary = boundary.at[0].set(True)
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1  # int32[n], sorted
    return boundary, seg


def _overflow_accounting(sorted_key_hi, sorted_key_lo, seg, capacity: int):
    """dropped_uniques for real segments past capacity.  The two RESERVED
    pseudo-segments — overlong-poison markers (sent, sent-1), then dead
    filler (sent, sent) — sort last (real keys are clamped below both by
    every tokenizer backend) and are excluded via two log-n binary
    searches."""
    sent = jnp.uint32(constants.SENTINEL_KEY)
    n = sorted_key_hi.shape[0]
    s_poison = _first_key_geq(sorted_key_hi, sorted_key_lo,
                              sent, sent - jnp.uint32(1))
    s_filler = _first_key_geq(sorted_key_hi, sorted_key_lo, sent, sent)
    has_poison = (s_poison < s_filler).astype(jnp.uint32)
    has_filler = (s_filler < n).astype(jnp.uint32)
    n_real = (seg[-1] + 1).astype(jnp.uint32) - has_filler - has_poison
    cap = jnp.uint32(capacity)
    return jnp.where(n_real > cap, n_real - cap, jnp.uint32(0))


def _reduce_sorted_rows(key_hi, key_lo, pos_hi, pos_lo, count, count_hi,
                        length, capacity: int):
    """Group-by-key segment reduce of rows already sorted by (key, pos).

    Scatter-free (the TPU cost model: even capacity-sized scatters carry a
    large fixed cost — ~30 ms per merge step measured on v5e — while sorted
    binary search + capacity-sized gathers are ~free): segment heads come
    from one ``searchsorted`` of ``arange(capacity+1)`` against the segment
    ranks, per-segment count sums are prefix-sum differences at the heads,
    and the remaining per-key fields are head-row gathers (rows are sorted
    by (key, pos), so the head row of each segment carries the
    lexicographically-first occurrence).

    Counts are 64-bit lane pairs: the low-word cumsum wraps, so the prefix
    sums carry a running wrap count into the high word (``merge_batched``
    routes running-table rows with large counts through here).
    """
    _, seg = _segment_boundaries(key_hi, key_lo)
    n = key_hi.shape[0]
    sent = jnp.uint32(constants.SENTINEL_KEY)
    inf = jnp.uint32(constants.POS_INF)

    # Segment j occupies sorted rows [head[j], head[j+1]).
    head = _segment_heads(seg, capacity)
    fi = jnp.minimum(head[:capacity], n - 1)

    csum = jnp.cumsum(count)  # uint32 inclusive prefix sums, wrapping
    wrapped = jnp.concatenate([jnp.zeros((1,), jnp.uint32),
                               (csum[1:] < csum[:-1]).astype(jnp.uint32)])
    csum_hi = jnp.cumsum(count_hi) + jnp.cumsum(wrapped)

    def prefix(cs, h):  # lane sum of counts in rows [0, h)
        return jnp.where(h > 0, cs[jnp.maximum(h, 1) - 1], jnp.uint32(0))

    count_u, count_hi_u = _sub64(
        prefix(csum, head[1:]), prefix(csum_hi, head[1:]),
        prefix(csum, head[:capacity]), prefix(csum_hi, head[:capacity]))
    key_hi_u, key_lo_u = key_hi[fi], key_lo[fi]
    occupied = (head[:capacity] < n) & ((count_u | count_hi_u) > 0) \
        & ~((key_hi_u == sent) & (key_lo_u >= sent - jnp.uint32(1)))

    count_u = jnp.where(occupied, count_u, jnp.uint32(0))
    count_hi_u = jnp.where(occupied, count_hi_u, jnp.uint32(0))
    key_hi_u = jnp.where(occupied, key_hi_u, sent)
    key_lo_u = jnp.where(occupied, key_lo_u, sent)
    pos_hi_u = jnp.where(occupied, pos_hi[fi], inf)
    pos_lo_u = jnp.where(occupied, pos_lo[fi], inf)
    len_u = jnp.where(occupied, length[fi], jnp.uint32(0))

    dropped_uniques = _overflow_accounting(key_hi, key_lo, seg, capacity)
    dc_lo, dc_hi = _sub64(csum[-1], csum_hi[-1], *sum64(count_u, count_hi_u))
    return (key_hi_u, key_lo_u, count_u, count_hi_u, pos_hi_u, pos_lo_u,
            len_u, dropped_uniques, dc_lo, dc_hi)


def _build(key_hi, key_lo, pos_hi, pos_lo, count, count_hi, length,
           capacity: int, carry_du, carry_du_hi, carry_dc,
           carry_dc_hi) -> CountTable:
    """Sort rows by (key, first-occurrence) and segment-reduce into a table."""
    key_hi, key_lo, pos_hi, pos_lo, count, count_hi, length = jax.lax.sort(
        (key_hi, key_lo, pos_hi, pos_lo, count, count_hi, length), num_keys=4
    )
    (key_hi_u, key_lo_u, count_u, count_hi_u, pos_hi_u, pos_lo_u, len_u,
     du, dc, dc_hi) = _reduce_sorted_rows(
        key_hi, key_lo, pos_hi, pos_lo, count, count_hi, length, capacity
    )
    du_lo, du_hi = add64(carry_du, carry_du_hi, du, jnp.uint32(0))
    dc_lo2, dc_hi2 = add64(carry_dc, carry_dc_hi, dc, dc_hi)
    return CountTable(
        key_hi=key_hi_u, key_lo=key_lo_u, count=count_u, count_hi=count_hi_u,
        pos_hi=pos_hi_u, pos_lo=pos_lo_u, length=len_u,
        dropped_uniques=du_lo, dropped_count=dc_lo2,
        dropped_uniques_hi=du_hi, dropped_count_hi=dc_hi2,
    )


def from_packed_rows(key_hi: jax.Array, key_lo: jax.Array, packed: jax.Array,
                     total: jax.Array, capacity: int, pos_hi: jax.Array | int,
                     len_bits: int = 6, sort_mode: str = "sort3",
                     rescue_slots: int = 0, sort_impl: str = "xla",
                     salt_bits: int = 0,
                     radix_geometry: tuple | None = None):
    """Aggregate pre-packed single-occurrence rows (the sort-lean path).

    ``packed`` = ``pos << len_bits | length`` per live row (all-ones for
    dead rows, which sorts last); the caller guarantees length fits
    ``len_bits`` bits and pos fits the remaining 32-len_bits.  On a real
    chip, large scatters/gathers cost 300-900 ms while sorts cost
    ~3 ms/M/array and capacity-sized gathers are ~free, so this path:

      1. sorts just 3 arrays with 3 keys — (key_hi, key_lo, packed), so the
         smallest pos (first occurrence) leads each key's segment;
      2. segment-reduces with *no* full-length scatters: segment ranks from a
         cumsum, one ``searchsorted`` of arange(capacity+1) against the rank
         array (binary search = log-n capacity-sized gathers), counts as
         rank-range differences, and per-key fields as capacity-sized gathers
         at the segment heads.

    ``sort_mode='stable2'`` drops the third comparator key entirely: a
    STABLE two-key sort with ``packed`` as payload.  Its precondition is
    that the caller's rows arrive in ascending position order (the
    lane-major kernel layout, or the XLA backend's per-byte streams):
    stability then guarantees each segment's head row is the earliest
    input row = the smallest position — first occurrence for free.  The
    round-4 sortbench measured the comparator-width cut at ~40% of the
    sort's compute (173.8 -> 143.2 ms on 16.8M rows, stability +1.2%)
    where the stream sort is the single-chip floor; sort3 remains for
    slot-major streams, which are NOT position-ordered.

    ``sort_mode='segmin'`` also sorts two keys but recovers first
    occurrence as a segmented running-min of ``packed`` (no input-order
    precondition).  Bit-identical; REFUSED on TPU — its stream-sized
    associative_scan wedges the chip (BENCHMARKS.md round 4).

    With ``rescue_slots = R > 0`` (sort3/stable2), also returns the first
    R ``packed`` values of the POISON segment — overlong-end markers
    (``pos << len_bits`` with zero length bits) carrying the reserved key
    (sent, sent-1), which sorts immediately before the dead-filler
    segment.  Under sort3 the third key orders them by position; under
    stable2 position-ordered input does.  The overlong-rescue pass
    (:mod:`mapreduce_tpu.ops.rescue`) re-tokenizes windows at exactly
    these positions; riding the aggregation sort makes the extraction
    ~free (one log-n binary search plus an R-row slice), where any
    standalone compaction would cost a second stream-sized sort or
    scatter.  Returns ``(table, rescue_packed)`` then; segmin cannot
    order the poison segment (packed rides as payload in arbitrary
    order), so that combination is rejected.

    ``sort_impl`` picks the sort IMPLEMENTATION behind ``sort_mode``
    (``Config.sort_impl``): ``'xla'`` is ``jax.lax.sort``; ``'radix'`` /
    ``'radix_partition'`` route the stream through the Pallas radix
    partition (:func:`mapreduce_tpu.ops.pallas.radix.radix_sort3`), whose
    tie-by-``packed`` contract is bit-identical to sort3 outright and to
    stable2 under its position-ordered-input precondition — so ONE radix
    implementation serves both modes, poison-segment rescue extraction
    included.  segmin is xla-only.

    With ``salt_bits`` = B > 0 (``Config.combiner='salt'``, ISSUE 11: the
    pathological-single-key-stream tier below the hot-key cache), the low
    B position bits are XORed into ``key_lo`` BEFORE the sort — one
    scorching key spreads over 2**B segments, defeating the measured ~4x
    radix hot-key slab amplification — and the built table is de-salted
    (the XOR is recoverable: every row of a salted segment shares
    ``pos & (2**B - 1)``, so the kept head row's position undoes it) and
    re-reduced through the generic build, coalescing the <= 2**B salted
    entries per original key with exact counts and the true minimum
    first occurrence.  Reserved-key rows (``key_hi == sent``: filler and
    poison) are never salted, so the poison-segment rescue extraction is
    untouched.  Exactness envelope, both legs documented: (1) two
    DISTINCT hash keys that differ only by a legal salt XOR would
    coalesce — a 2**B-fold widening of the documented ~n^2/2^65 64-bit
    key-collision envelope (detectable by --verify-sample, as ever); the
    single-key streams salting exists for cannot collide at all.  (2)
    Bit-identity to the unsalted build holds while distinct keys FIT
    ``capacity``: under unique overflow the capacity cutoff falls on the
    SALTED key order, so the kept set — and a straddling key's kept
    count — can differ from the unsalted build's (occurrence
    conservation still holds exactly through ``dropped_count``; this is
    the cross-table-merge dropped-accounting caveat the streamed paths
    already document, not silent loss).  segmin is refused (its payload
    scan keeps no per-salt-segment order to de-salt from).

    Matches :func:`_build` output bit-for-bit under its preconditions (every
    live row has count 1, one shared pos_hi).
    """
    if sort_mode not in ("sort3", "stable2", "segmin"):
        raise ValueError(f"unknown sort_mode {sort_mode!r}")
    if salt_bits and sort_mode == "segmin":
        raise ValueError("salt_bits requires sort_mode='sort3' or 'stable2'")
    if not 0 <= salt_bits <= 6:
        raise ValueError(f"salt_bits must be in [0, 6], got {salt_bits}")
    if sort_impl not in ("xla", "radix", "radix_partition"):
        raise ValueError(f"unknown sort_impl {sort_impl!r}")
    if sort_impl != "xla" and sort_mode == "segmin":
        raise ValueError("sort_impl='radix'/'radix_partition' requires "
                         "sort_mode='sort3' or 'stable2' (segmin keeps "
                         "packed as an unordered payload)")
    if rescue_slots and sort_mode == "segmin":
        raise ValueError("rescue_slots requires sort_mode='sort3' or "
                         "'stable2' (poison extraction needs the poison "
                         "segment position-ordered)")
    if sort_mode == "segmin":
        from mapreduce_tpu.config import (PlatformRefusedError,
                                          SEGMIN_TPU_ERROR, segmin_allowed)

        # Refuse the measured chip-wedge at trace time (the CPU A/B stays
        # alive); config.segmin_allowed owns the deliberate override.
        if jax.default_backend() == "tpu" and not segmin_allowed():
            raise PlatformRefusedError(SEGMIN_TPU_ERROR)
    sent = jnp.uint32(constants.SENTINEL_KEY)
    inf = jnp.uint32(constants.POS_INF)
    n = key_hi.shape[0]
    len_mask = jnp.uint32((1 << len_bits) - 1)

    if salt_bits:
        # Salt = the row's low position bits (every salted segment is then
        # position-homogeneous in those bits — the de-salt invariant).
        # key_hi == sent rows (dead filler, poison, and the rare clamped
        # real keys) pass through unsalted, keeping the reserved segments
        # and the poison binary search byte-identical.
        smask = jnp.uint32((1 << salt_bits) - 1)
        key_lo = jnp.where(key_hi != sent,
                           key_lo ^ ((packed >> len_bits) & smask), key_lo)

    if sort_mode == "segmin":
        key_hi, key_lo, packed = jax.lax.sort(
            (key_hi, key_lo, packed), num_keys=2)
        boundary, rank = _segment_boundaries(key_hi, key_lo)

        def _min_combine(x, y):
            # y is the later element; a boundary row restarts its segment.
            xb, xv = x
            yb, yv = y
            return xb | yb, jnp.where(yb, yv, jnp.minimum(xv, yv))

        _, run_min = jax.lax.associative_scan(_min_combine, (boundary, packed))
    elif sort_impl != "xla":
        # Radix path (Config.sort_impl): bit-identical to BOTH branches
        # below — ties resolve by `packed`, which is sort3's third
        # comparator key outright and, under stable2's position-ordered
        # input, exactly the tie order stability delivers.  Adversarial
        # bucket skew falls back to the XLA sort inside radix_sort3.
        from mapreduce_tpu.ops.pallas import radix as radix_ops

        # radix_geometry (ISSUE 12): an explicit (bits, block_rows,
        # slab_slack) candidate from Config.geometry; None keeps the
        # wrapper's call-time default resolution (the module-global
        # geometry override tests rely on).
        r_bits, r_rows, r_slack = radix_geometry or (None, None, None)
        key_hi, key_lo, packed = radix_ops.radix_sort3(
            key_hi, key_lo, packed, impl=sort_impl, bits=r_bits,
            block_rows=r_rows, slab_slack=r_slack)
        _, rank = _segment_boundaries(key_hi, key_lo)
        run_min = None
    elif sort_mode == "stable2":
        # Stable two-key sort, packed as PAYLOAD: ties keep input order, so
        # with position-ordered input each segment's head row carries the
        # smallest position — the same first-occurrence invariant sort3
        # buys with a third comparator key.
        key_hi, key_lo, packed = jax.lax.sort(
            (key_hi, key_lo, packed), num_keys=2, is_stable=True)
        _, rank = _segment_boundaries(key_hi, key_lo)
        run_min = None
    else:
        key_hi, key_lo, packed = jax.lax.sort(
            (key_hi, key_lo, packed), num_keys=3)
        _, rank = _segment_boundaries(key_hi, key_lo)
        run_min = None

    # Segment j occupies rows [head[j], head[j+1]) in sorted order.
    head = _segment_heads(rank, capacity)
    fi = jnp.minimum(head[:capacity], n - 1)
    count_u = (head[1:] - head[:capacity]).astype(jnp.uint32)

    key_hi_u, key_lo_u = key_hi[fi], key_lo[fi]
    if run_min is None:
        packed_u = packed[fi]  # sorted third key: head row IS min packed
    else:
        # The running min lands on each segment's LAST row (inclusive scan
        # restarting at boundaries).
        tail = jnp.minimum(jnp.maximum(head[1:], 1) - 1, n - 1)
        packed_u = run_min[tail]
    occupied = (head[:capacity] < n) & (count_u > 0) \
        & ((key_hi_u != sent) | (key_lo_u < sent - jnp.uint32(1)))

    count_u = jnp.where(occupied, count_u, jnp.uint32(0))
    key_hi_u = jnp.where(occupied, key_hi_u, sent)
    key_lo_u = jnp.where(occupied, key_lo_u, sent)
    pos_lo_u = jnp.where(occupied, packed_u >> len_bits, inf)
    len_u = jnp.where(occupied, packed_u & len_mask, jnp.uint32(0))
    pos_hi_u = jnp.where(occupied, jnp.asarray(pos_hi, jnp.uint32), inf)

    dropped_uniques = _overflow_accounting(key_hi, key_lo, rank, capacity)
    # Single-occurrence rows, <= 2**26 of them: every count fits the low
    # word, so the hi lanes of this path are structurally zero.
    dropped_count = total - jnp.sum(count_u)
    zero = jnp.uint32(0)
    table = CountTable(
        key_hi=key_hi_u, key_lo=key_lo_u, count=count_u,
        count_hi=jnp.zeros_like(count_u),
        pos_hi=pos_hi_u, pos_lo=pos_lo_u, length=len_u,
        dropped_uniques=dropped_uniques, dropped_count=dropped_count,
        dropped_uniques_hi=zero, dropped_count_hi=zero,
    )
    if salt_bits:
        # De-salt at the reduce seam: each kept row's position carries its
        # own salt (all rows of a salted segment share the low position
        # bits), so one XOR recovers the original key and a capacity-sized
        # generic re-build coalesces the <= 2**salt_bits entries per key —
        # exact counts, the true minimum first occurrence, dropped_*
        # carried through.  Noise next to the stream sort it de-skews.
        smask = jnp.uint32((1 << salt_bits) - 1)
        live = table.key_hi != sent
        desalted_lo = jnp.where(live, table.key_lo ^ (table.pos_lo & smask),
                                table.key_lo)
        table = _build(table.key_hi, desalted_lo, table.pos_hi, table.pos_lo,
                       table.count, table.count_hi, table.length, capacity,
                       table.dropped_uniques, table.dropped_uniques_hi,
                       table.dropped_count, table.dropped_count_hi)
    if not rescue_slots:
        return table
    # Poison-segment head (reserved key (sent, sent-1), immediately before
    # the dead-filler segment): poison rows are position-ordered there — by
    # the third sort key under sort3, by input order under stable2.  A
    # slice shorter than the segment (poisons beyond R) loses only the
    # LARGEST positions — rescue order is deterministic.  When fewer than R
    # poisons exist the slice runs into filler rows (all-ones packed) or,
    # when clamped at the array end, real-key rows; both carry nonzero
    # length bits the consumer masks off.
    r = min(rescue_slots, n)
    s0 = _first_key_geq(key_hi, key_lo, sent, sent - jnp.uint32(1))
    start = jnp.minimum(s0, jnp.int32(n - r))
    rescue_packed = jax.lax.dynamic_slice(packed, (start,), (r,))
    return table, rescue_packed


def _from_stream_packed(stream: TokenStream, capacity: int,
                        pos_hi: jax.Array | int,
                        sort_mode: str = "sort3", rescue_slots: int = 0,
                        sort_impl: str = "xla", salt_bits: int = 0,
                        radix_geometry: tuple | None = None):
    """Packed fast path for token streams: see :func:`from_packed_rows`."""
    # Packed-plane-carrying streams (the pallas kernel's PackedTokenStream)
    # feed their raw plane straight into the sort — repacking from
    # pos/length would re-stream ~67 MB/chunk through HBM for nothing.
    packed = getattr(stream, "packed", None)
    if packed is None:
        is_tok = stream.count > 0
        packed = jnp.where(is_tok, (stream.pos << 6) | stream.length,
                           jnp.uint32(0xFFFFFFFF))
    # Kernel-carried exact totals skip a stream-sized reduction pass.
    total = getattr(stream, "total", None)
    if total is None:
        total = jnp.sum(stream.count)
    return from_packed_rows(stream.key_hi, stream.key_lo, packed, total,
                            capacity, pos_hi, len_bits=6,
                            sort_mode=sort_mode, rescue_slots=rescue_slots,
                            sort_impl=sort_impl, salt_bits=salt_bits,
                            radix_geometry=radix_geometry)


def from_stream(stream: TokenStream, capacity: int, pos_hi: jax.Array | int = 0,
                max_token_bytes: int | None = None,
                max_pos: int | None = None,
                sort_mode: str = "sort3", rescue_slots: int = 0,
                sort_impl: str = "xla", salt_bits: int = 0,
                radix_geometry: tuple | None = None):
    """Aggregate a per-byte :class:`TokenStream` into a fresh table.

    ``pos_hi`` identifies the source buffer (e.g. ``step * n_devices +
    device_index``) so first-occurrence order is globally meaningful.

    ``max_token_bytes`` / ``max_pos`` are optional static bounds on the
    stream's length and pos fields.  When both fit a packed uint32
    (len <= 63, pos < 2**26 — true for the pallas backend's bounded-W
    streams over chunks <= 64 MB), a sort-lean fast path runs instead of
    the generic build; results are identical.  ``sort_mode`` picks that
    path's sort strategy (:func:`from_packed_rows`); ``rescue_slots`` (fast
    path only — the generic build has no poison rows to extract) makes the
    return ``(table, rescue_packed)``.  ``sort_impl`` picks the fast
    path's sort implementation (:func:`from_packed_rows`); the generic
    7-array build below keeps ``lax.sort`` — the radix seam covers the
    packed stream, which is the measured single-chip floor.  ``salt_bits``
    (fast path only, ``Config.combiner='salt'``) spreads hot keys over
    salted sort segments with an exact de-salting re-reduce
    (:func:`from_packed_rows`).  ``radix_geometry`` (ISSUE 12) is an
    explicit (bits, block_rows, slab_slack) candidate for the radix
    implementations; None keeps the module defaults.
    """
    if (max_token_bytes is not None and max_token_bytes <= 63
            and max_pos is not None and max_pos <= (1 << 26)):
        return _from_stream_packed(stream, capacity, pos_hi, sort_mode,
                                   rescue_slots, sort_impl, salt_bits,
                                   radix_geometry)
    if rescue_slots:
        raise ValueError("rescue_slots requires the packed fast path "
                         "(bounded max_token_bytes/max_pos)")
    if salt_bits:
        raise ValueError("salt_bits applies to the packed fast path only "
                         "(the generic 7-array build has no slab "
                         "amplification to de-skew)")
    n = stream.key_hi.shape[0]
    ph = jnp.full((n,), jnp.asarray(pos_hi, dtype=jnp.uint32))
    ph = jnp.where(stream.count > 0, ph, jnp.uint32(constants.POS_INF))
    z = jnp.uint32(0)
    return _build(stream.key_hi, stream.key_lo, ph, stream.pos, stream.count,
                  jnp.zeros_like(stream.count), stream.length, capacity,
                  z, z, z, z)


def merge(a: CountTable, b: CountTable, capacity: int | None = None,
          c: CountTable | None = None) -> CountTable:
    """Associative, commutative merge of two tables (the combiner).

    Exploits the table invariant (keys unique within each input) that a
    generic stream reduce cannot: after concat + sort, every key segment has
    at most TWO rows, so the group-by collapses to elementwise pair-folding
    — fold the follower's count into its head, sentinel the follower, and
    one more sort pushes the holes to the tail.  No segment ranks, no
    ``searchsorted`` (whose while-loop + fixed-cost device copies made the
    per-step combine the single most expensive stage on the bench chip:
    ~130 ms/chunk at 256K capacity, vs two ~5 ms sorts here).

    An optional THIRD table ``c`` folds in the same two sorts (runs grow to
    at most three rows; the fold checks one more neighbor — a few extra
    elementwise planes, no extra sort).  The streamed stable2 path uses
    this to fold the per-chunk seam table into the per-step running merge
    for ~free, where a dedicated pairwise seam merge cost two extra
    (capacity + 8K)-row sorts per chunk.
    """
    tables = [a, b] + ([c] if c is not None else [])
    cap = capacity if capacity is not None \
        else max(t.capacity for t in tables)
    sent = jnp.uint32(constants.SENTINEL_KEY)
    inf = jnp.uint32(constants.POS_INF)
    cat = lambda f: jnp.concatenate([getattr(t, f) for t in tables])
    key_hi, key_lo, pos_hi, pos_lo, count, count_hi, length = jax.lax.sort(
        (cat("key_hi"), cat("key_lo"), cat("pos_hi"), cat("pos_lo"),
         cat("count"), cat("count_hi"), cat("length")),
        num_keys=4,  # (key, pos): the head row of a run carries first occurrence
    )

    eq_next = (key_hi[1:] == key_hi[:-1]) & (key_lo[1:] == key_lo[:-1])
    false1 = jnp.zeros((1,), jnp.bool_)
    follower = jnp.concatenate([false1, eq_next])  # same key as previous row
    has_next = jnp.concatenate([eq_next, false1])  # next row is my follower
    zero1 = jnp.zeros((1,), jnp.uint32)
    next_count = jnp.concatenate([count[1:], zero1])
    next_count_hi = jnp.concatenate([count_hi[1:], zero1])

    is_empty = (key_hi == sent) & (key_lo == sent)
    head = ~follower & ~is_empty & ((count | count_hi) > 0)
    folded_lo, folded_hi = add64(count, count_hi,
                                 jnp.where(has_next, next_count, jnp.uint32(0)),
                                 jnp.where(has_next, next_count_hi, jnp.uint32(0)))
    if c is not None:
        # Three inputs: a key can run three rows; the head also absorbs its
        # follower's follower.  (head & has_next2) implies rows head+1 and
        # head+2 both carry the head's key.
        false2 = jnp.zeros((2,), jnp.bool_)
        zero2 = jnp.zeros((2,), jnp.uint32)
        eq_next2 = eq_next[1:] & eq_next[:-1]  # row i == i+1 == i+2
        has_next2 = jnp.concatenate([eq_next2, false2])
        folded_lo, folded_hi = add64(
            folded_lo, folded_hi,
            jnp.where(has_next2, jnp.concatenate([count[2:], zero2]),
                      jnp.uint32(0)),
            jnp.where(has_next2, jnp.concatenate([count_hi[2:], zero2]),
                      jnp.uint32(0)))
    count_m = jnp.where(head, folded_lo, jnp.uint32(0))
    count_hi_m = jnp.where(head, folded_hi, jnp.uint32(0))
    key_hi_m = jnp.where(head, key_hi, sent)
    key_lo_m = jnp.where(head, key_lo, sent)
    pos_hi_m = jnp.where(head, pos_hi, inf)
    pos_lo_m = jnp.where(head, pos_lo, inf)
    len_m = jnp.where(head, length, jnp.uint32(0))

    # Second sort: unique live keys ascending, sentinel holes to the tail;
    # the first `cap` rows are the result (spill = largest keys, matching the
    # rank-based reduce's drop order).
    key_hi_s, key_lo_s, count_s, count_hi_s, pos_hi_s, pos_lo_s, len_s = \
        jax.lax.sort((key_hi_m, key_lo_m, count_m, count_hi_m, pos_hi_m,
                      pos_lo_m, len_m), num_keys=2)
    n = key_hi_s.shape[0]
    if n < cap:  # explicit capacity above the inputs' sum: pad with holes
        pad = cap - n
        key_hi_s = jnp.concatenate([key_hi_s, jnp.full((pad,), sent)])
        key_lo_s = jnp.concatenate([key_lo_s, jnp.full((pad,), sent)])
        count_s = jnp.concatenate([count_s, jnp.zeros((pad,), jnp.uint32)])
        count_hi_s = jnp.concatenate([count_hi_s, jnp.zeros((pad,), jnp.uint32)])
        pos_hi_s = jnp.concatenate([pos_hi_s, jnp.full((pad,), inf)])
        pos_lo_s = jnp.concatenate([pos_lo_s, jnp.full((pad,), inf)])
        len_s = jnp.concatenate([len_s, jnp.zeros((pad,), jnp.uint32)])

    kept_lo, kept_hi = count_s[:cap], count_hi_s[:cap]
    n_live = jnp.sum(head.astype(jnp.uint32))
    spilled_uniques = jnp.where(n_live > jnp.uint32(cap),
                                n_live - jnp.uint32(cap), jnp.uint32(0))
    spill_lo, spill_hi = _sub64(*sum64(count, count_hi),
                                *sum64(kept_lo, kept_hi))
    # Every input's carried dropped_* folds in — including the optional
    # third table's (dropping c's carries would silently break occurrence
    # conservation whenever a seam table arrives with nonzero accounting).
    du_lo = du_hi = dc_lo = dc_hi = jnp.uint32(0)
    for t in tables:
        du_lo, du_hi = add64(du_lo, du_hi,
                             t.dropped_uniques, t.dropped_uniques_hi)
        dc_lo, dc_hi = add64(dc_lo, dc_hi,
                             t.dropped_count, t.dropped_count_hi)
    du_lo, du_hi = add64(du_lo, du_hi, spilled_uniques, jnp.uint32(0))
    dc_lo, dc_hi = add64(dc_lo, dc_hi, spill_lo, spill_hi)
    return CountTable(
        key_hi=key_hi_s[:cap], key_lo=key_lo_s[:cap],
        count=kept_lo, count_hi=kept_hi,
        pos_hi=pos_hi_s[:cap], pos_lo=pos_lo_s[:cap], length=len_s[:cap],
        dropped_uniques=du_lo, dropped_count=dc_lo,
        dropped_uniques_hi=du_hi, dropped_count_hi=dc_hi,
    )


def merge_batched(table: CountTable, pend_key_hi, pend_key_lo, pend_count,
                  pend_pos_hi, pend_pos_lo, pend_length,
                  capacity: int) -> CountTable:
    """Fold K staged batch tables + the running table in ONE sort + segment
    reduce (``Config.merge_every``): 2*K pairwise-merge sorts of
    (capacity + batch) rows become one 4-key sort of (capacity + K*batch).

    The pending arrays hold up to K batch tables' rows (flushed slots carry
    sentinel keys / zero counts, which the reduce ignores by construction).
    Kept keys, their counts, first-occurrence positions, ``dropped_count``
    and totals are identical to the pairwise fold — the kept set is the
    smallest-``capacity`` distinct keys of the union either way;
    ``dropped_uniques`` can only be TIGHTER (a respilled key counts once
    per flush, not once per step).
    """
    cat = lambda a, b: jnp.concatenate([a, b])
    # Pending rows are staged BATCH-table rows (single-chunk builds), whose
    # hi lanes are structurally zero; only the running table's hi lane
    # carries real bits into the fold.
    return _build(cat(table.key_hi, pend_key_hi),
                  cat(table.key_lo, pend_key_lo),
                  cat(table.pos_hi, pend_pos_hi),
                  cat(table.pos_lo, pend_pos_lo),
                  cat(table.count, pend_count),
                  cat(table.count_hi, jnp.zeros_like(pend_count)),
                  cat(table.length, pend_length),
                  capacity, table.dropped_uniques, table.dropped_uniques_hi,
                  table.dropped_count, table.dropped_count_hi)


def update(table: CountTable, stream: TokenStream, batch_capacity: int,
           pos_hi: jax.Array | int = 0) -> CountTable:
    """Fold one chunk's tokens into the running table (one streaming step)."""
    batch = from_stream(stream, batch_capacity, pos_hi=pos_hi)
    return merge(table, batch, capacity=table.capacity)


def kmv_distinct(table: CountTable) -> float | None:
    """Distinct-count estimate for a FULL table, free of device work.

    Spill order is deterministic (largest keys drop first, in batch builds
    and merges alike), so a full table's kept keys are exactly the
    ``capacity`` smallest distinct 64-bit key hashes ever seen — i.e. the
    table doubles as a k-minimum-values sketch with k = capacity.  The
    classic KMV estimator ``(k-1) / U_(k)`` (``U_(k)`` = the k-th smallest
    hash as a fraction of the hash space) then estimates total distinct
    hashed keys with relative error ~1/sqrt(k) — 0.2% at the default 256K
    capacity, versus the summed per-chunk upper bound ``dropped_uniques``
    degrades to.  Returns None when the table is not full (distinct is
    exact then, no estimate needed).  Host-side only: call on a fetched
    (numpy-leaf) table.

    Caveat: estimates distinct *hashed* words — on the pallas backend,
    >W-byte tokens never hash, so their distinct count (folded into
    ``dropped_uniques``'s bound) is not part of the estimate.
    """
    occ = (np.asarray(table.count) > 0) | (np.asarray(table.count_hi) > 0)
    n_valid = int(occ.sum())
    if n_valid < 1:
        return None
    return kmv_from_snapshot(n_valid,
                             int(np.asarray(table.key_hi)[n_valid - 1]),
                             int(np.asarray(table.key_lo)[n_valid - 1]),
                             table.capacity)


def kmv_snapshot(table: CountTable) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Device-side ``(n_valid, kth_key_hi, kth_key_lo)`` of a key-sorted
    table — everything :func:`kmv_distinct` needs, captured as three scalars.

    Taken BEFORE a terminal :func:`top_k` reorder (which destroys the KMV
    property: the kept keys stop being the smallest ever seen), so top-k
    finalized runs keep a ~1/sqrt(capacity)-error distinct estimate instead
    of degrading to the summed ``dropped_uniques`` upper bound
    (VERDICT r3 weak #6).  Fetch the scalars and feed
    :func:`kmv_from_snapshot` host-side.
    """
    occ = table.occupied()
    n_valid = jnp.sum(occ.astype(jnp.uint32))
    last = jnp.maximum(n_valid.astype(jnp.int32) - 1, 0)
    return n_valid, table.key_hi[last], table.key_lo[last]


def kmv_from_snapshot(n_valid: int, kth_hi: int, kth_lo: int,
                      capacity: int) -> float | None:
    """Host-side KMV estimate from :func:`kmv_snapshot` scalars (None when
    the table was not full — distinct is exact then, no estimate needed)."""
    if n_valid < capacity or n_valid < 2:
        return None
    kth = (int(kth_hi) << 32) | int(kth_lo)
    if kth <= 0:
        return None
    return (n_valid - 1) * float(1 << 64) / float(kth)


def top_k(table: CountTable, k: int) -> CountTable:
    """The k most frequent keys, as a count-descending table of capacity k.

    A *terminal* op: the result is sorted by count, not by key, so it must not
    be merged further.  Evicted entries are folded into ``dropped_*`` so
    ``total_count()`` remains exact (total tokens, not just the top-k's).
    Ties break by first occurrence (ascending ``pos``), matching the host-side
    :func:`mapreduce_tpu.models.wordcount.apply_top_k` so streamed and
    single-buffer runs report identical word sets.
    """
    # Count-descending = ascending bitwise complement, hi lane primary
    # (lexsort's LAST key is the most significant).
    neg_lo = jnp.uint32(0xFFFFFFFF) - table.count
    neg_hi = jnp.uint32(0xFFFFFFFF) - table.count_hi
    order = jnp.lexsort((table.pos_lo, table.pos_hi, neg_lo, neg_hi))[:k]
    take = lambda f: f[order]
    kept_lo, kept_hi = take(table.count), take(table.count_hi)
    ev_lo, ev_hi = _sub64(*sum64(table.count, table.count_hi),
                          *sum64(kept_lo, kept_hi))
    evicted_uniques = table.n_valid() \
        - jnp.sum(((kept_lo | kept_hi) > 0).astype(jnp.uint32))
    du_lo, du_hi = add64(table.dropped_uniques, table.dropped_uniques_hi,
                         evicted_uniques, jnp.uint32(0))
    dc_lo, dc_hi = add64(table.dropped_count, table.dropped_count_hi,
                         ev_lo, ev_hi)
    return CountTable(
        key_hi=take(table.key_hi), key_lo=take(table.key_lo),
        count=kept_lo, count_hi=kept_hi,
        pos_hi=take(table.pos_hi), pos_lo=take(table.pos_lo), length=take(table.length),
        dropped_uniques=du_lo, dropped_count=dc_lo,
        dropped_uniques_hi=du_hi, dropped_count_hi=dc_hi,
    )
