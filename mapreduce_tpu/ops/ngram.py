"""N-gram formation over the pallas token stream via position sort.

The XLA n-gram path (:func:`mapreduce_tpu.ops.tokenize.ngrams`) pairs tokens
with carry-forward associative scans over the flat per-byte stream — correct,
but several log-depth passes over chunk-sized planes, and incompatible with
the fused pallas kernel's lane-column layout (its emissions are not in byte
order).  This module makes the pallas backend's stream pairable with ONE
cheap sort:

  * the kernel's ``packed`` plane is ``start_pos << 6 | length`` — position
    in the high bits — so sorting the stream by ``packed`` ALONE (one sort
    key; non-tokens carry all-ones and sink to the tail) simultaneously
    compacts the stream and recovers global token order;
  * gram formation is then a pure elementwise shift-by-one over adjacent
    rows, iterated n-1 times — no scans, no carry machinery;
  * seam-pass tokens are concatenated in BEFORE the sort, so their positions
    interleave exactly where they belong and grams straddling the kernel's
    128-lane seams form correctly (the XLA fallback this replaces could not
    see the kernel's split streams at all);
  * tokens longer than the kernel window W are *suppressed* by the kernel,
    so two tokens adjacent in the sorted stream could straddle a suppressed
    overlong token and pair into a gram that does not exist in the text.
    The kernel (and seam pass) emit a POISON row per overlong end — last
    byte position, zero length bits — which the position sort places
    exactly between the suppressed token's neighbors: the pairing chain
    crosses a non-live row and the phantom gram self-invalidates.  Grams
    containing a >W token are *dropped and accounted* (``dropped_count``
    exact via the closed-form gram total, ``dropped_uniques`` an upper
    bound), mirroring how the wordcount family treats overlong tokens.
    The XLA backend still counts any token length exactly.  An earlier
    design instead fell back to the whole-chunk XLA scan via ``lax.cond``
    — but both cond branches are always compiled, so every n-gram program
    embedded the associative-scan formulation that compiles pathologically
    slowly at production chunk sizes (VERDICT r2 #4); the poison rows
    delete that branch entirely.

Hashing replicates :func:`...ops.tokenize._extend_grams` exactly (same
composition, same fmix32 finalization, same sentinel clamp), so tables built
from either path merge interchangeably.

The reference has no n-gram capability (its map UDF emits single words only,
``mapper`` ``main.cu:37-54``); this family is beyond-parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu import constants
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.ops import tokenize as tok_ops
from mapreduce_tpu.ops.tokenize import TokenStream

# NumPy, not jnp: a module-level jnp constant is a concrete device array —
# it initializes a backend at import time AND gets shared as a closure
# constant across otherwise-independent jitted programs, where mismatched
# sharding expectations break the second program's dispatch.
_SENT_PACKED = np.uint32(0xFFFFFFFF)


def position_sorted(stream) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sort a packed-plane stream into global token order.

    One single-key sort: live rows (``packed != all-ones``) come first,
    ordered by start position (the high bits of ``packed``); sentinels sink
    to the tail.  Returns ``(key_hi, key_lo, packed)`` in that order.
    """
    if stream.packed is None:
        raise ValueError("stream has no packed plane (nonzero base_offset); "
                         "position sort needs the raw kernel packing")
    packed, key_hi, key_lo = jax.lax.sort(
        (stream.packed, stream.key_hi, stream.key_lo), num_keys=1)
    return key_hi, key_lo, packed


def grams_from_sorted(key_hi: jax.Array, key_lo: jax.Array,
                      packed: jax.Array, n: int) -> TokenStream:
    """Form the n-gram stream from position-sorted token rows.

    Adjacent rows are adjacent tokens, so each of the n-1 extension steps is
    an elementwise shift-by-one + hash mix — the same recurrence as
    :func:`...ops.tokenize._extend_grams`, minus its carry scans.
    """
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    # Zero length bits = a poison row (overlong-token end marker): occupies
    # its position slot so real tokens across it are NOT row-adjacent, but
    # never itself starts or extends a gram.
    live = (packed != _SENT_PACKED) & ((packed & jnp.uint32(63)) != 0)
    start = jnp.where(live, packed >> 6, jnp.uint32(constants.POS_INF))
    end = (packed >> 6) + (packed & jnp.uint32(63))  # exclusive token end

    sentinel = jnp.uint32(constants.SENTINEL_KEY)
    one = jnp.uint32(1)

    def shift(x, fill):
        return jnp.concatenate([jnp.full((1,), fill, x.dtype), x[:-1]])

    g_hi, g_lo, g_pos, g_valid = key_hi, key_lo, start, live
    for _ in range(n - 1):
        p_hi = shift(g_hi, jnp.uint32(0))
        p_lo = shift(g_lo, jnp.uint32(0))
        p_pos = shift(g_pos, jnp.uint32(constants.POS_INF))
        p_valid = shift(g_valid, False)
        g_valid = live & p_valid
        # Order-sensitive pairing, bit-identical to the XLA path.
        g_hi = tok_ops._fmix32(p_hi * jnp.uint32(constants.HASH_BASE_1) ^ key_hi)
        g_lo = tok_ops._fmix32(p_lo * jnp.uint32(constants.HASH_BASE_2) ^ key_lo)
        at_sentinel = (g_hi == sentinel) & (g_lo == sentinel)
        g_lo = jnp.where(at_sentinel, g_lo - one, g_lo)
        g_pos = p_pos

    length = jnp.where(g_valid, end - g_pos, jnp.uint32(0))
    return TokenStream(
        key_hi=jnp.where(g_valid, g_hi, sentinel),
        key_lo=jnp.where(g_valid, g_lo, sentinel),
        count=g_valid.astype(jnp.uint32),
        pos=jnp.where(g_valid, g_pos, jnp.uint32(constants.POS_INF)),
        length=length,
    )


def ngram_table(chunk: jax.Array, n: int, capacity: int,
                pos_hi: jax.Array | int, config) -> table_ops.CountTable:
    """Per-chunk n-gram count table on the pallas backend.

    One straight-line program: fused kernel -> position sort (poison rows
    included) -> elementwise pairing -> generic table build (gram spans
    exceed the 6-bit packed length, so the packed table fast path does not
    apply).  Grams containing a suppressed >W-byte token self-invalidate at
    the poison rows (module docstring) and are accounted exactly: the
    closed-form chunk gram total is ``max(all_tokens - (n-1), 0)`` with
    ``all_tokens`` including overlong ones, so whatever the pairing did not
    form was dropped by suppression.
    """
    from mapreduce_tpu.ops.pallas import tokenize as pallas_tok

    col, seam, overlong = pallas_tok.tokenize_split(
        chunk, max_token_bytes=config.pallas_max_token)
    stream = pallas_tok.concat_streams(col, seam)
    gs = grams_from_sorted(*position_sorted(stream), n)
    t = table_ops.from_stream(gs, capacity, pos_hi=pos_hi)
    all_tokens = stream.total + overlong
    nm1 = jnp.uint32(n - 1)
    full_total = jnp.where(all_tokens > nm1, all_tokens - nm1, jnp.uint32(0))
    missing = full_total - jnp.sum(gs.count)  # grams killed by suppression
    # ``missing`` occurrences are exact; distinct missing grams are unknowable
    # on device (overlong tokens leave the kernel unhashed), so uniques get
    # the same upper-bound treatment as the wordcount family's overlong.
    return t._replace(dropped_uniques=t.dropped_uniques + missing,
                      dropped_count=t.dropped_count + missing)
