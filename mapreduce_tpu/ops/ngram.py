"""N-gram formation over the pallas token stream via position sort.

The XLA n-gram path (:func:`mapreduce_tpu.ops.tokenize.ngrams`) pairs tokens
with carry-forward associative scans over the flat per-byte stream — correct,
but several log-depth passes over chunk-sized planes, and incompatible with
the fused pallas kernel's lane-column layout (its emissions are not in byte
order).  This module makes the pallas backend's stream pairable with ONE
cheap sort:

  * the kernel's ``packed`` plane is ``start_pos << 6 | length`` — position
    in the high bits — so sorting the stream by ``packed`` ALONE (one sort
    key; non-tokens carry all-ones and sink to the tail) simultaneously
    compacts the stream and recovers global token order;
  * gram formation is then a pure elementwise shift-by-one over adjacent
    rows, iterated n-1 times — no scans, no carry machinery;
  * seam-pass tokens are concatenated in BEFORE the sort, so their positions
    interleave exactly where they belong and grams straddling the kernel's
    128-lane seams form correctly (the XLA fallback this replaces could not
    see the kernel's split streams at all);
  * tokens longer than the kernel window W are *suppressed* by the kernel,
    so two tokens adjacent in the sorted stream could straddle a suppressed
    overlong token and pair into a gram that does not exist in the text.
    The kernel (and seam pass) emit a POISON row per overlong end — last
    byte position, zero length bits — which the position sort places
    exactly between the suppressed token's neighbors: the pairing chain
    crosses a non-live row and the phantom gram self-invalidates.  Grams
    containing a >W token are *dropped and accounted* (``dropped_count``
    exact via the closed-form gram total, ``dropped_uniques`` an upper
    bound), mirroring how the wordcount family treats overlong tokens.
    The XLA backend still counts any token length exactly.  An earlier
    design instead fell back to the whole-chunk XLA scan via ``lax.cond``
    — but both cond branches are always compiled, so every n-gram program
    embedded the associative-scan formulation that compiles pathologically
    slowly at production chunk sizes (VERDICT r2 #4); the poison rows
    delete that branch entirely.

Hashing replicates :func:`...ops.tokenize._extend_grams` exactly (same
composition, same fmix32 finalization, same sentinel clamp), so tables built
from either path merge interchangeably.

The reference has no n-gram capability (its map UDF emits single words only,
``mapper`` ``main.cu:37-54``); this family is beyond-parity.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu import constants
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.ops import tokenize as tok_ops
from mapreduce_tpu.ops.tokenize import TokenStream

# NumPy, not jnp: a module-level jnp constant is a concrete device array —
# it initializes a backend at import time AND gets shared as a closure
# constant across otherwise-independent jitted programs, where mismatched
# sharding expectations break the second program's dispatch.
_SENT_PACKED = np.uint32(0xFFFFFFFF)


def position_sorted(stream) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sort a packed-plane stream into global token order.

    One single-key sort: live rows (``packed != all-ones``) come first,
    ordered by start position (the high bits of ``packed``); sentinels sink
    to the tail.  Returns ``(key_hi, key_lo, packed)`` in that order.
    """
    if stream.packed is None:
        raise ValueError("stream has no packed plane (nonzero base_offset); "
                         "position sort needs the raw kernel packing")
    packed, key_hi, key_lo = jax.lax.sort(
        (stream.packed, stream.key_hi, stream.key_lo), num_keys=1)
    return key_hi, key_lo, packed


def grams_from_sorted(key_hi: jax.Array, key_lo: jax.Array,
                      packed: jax.Array, n: int) -> TokenStream:
    """Form the n-gram stream from position-sorted token rows.

    Adjacent rows are adjacent tokens, so each of the n-1 extension steps is
    an elementwise shift-by-one + hash mix — the same recurrence as
    :func:`...ops.tokenize._extend_grams`, minus its carry scans.
    """
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    # Zero length bits = a poison row (overlong-token end marker): occupies
    # its position slot so real tokens across it are NOT row-adjacent, but
    # never itself starts or extends a gram.
    live = (packed != _SENT_PACKED) & ((packed & jnp.uint32(63)) != 0)
    start = jnp.where(live, packed >> 6, jnp.uint32(constants.POS_INF))
    end = (packed >> 6) + (packed & jnp.uint32(63))  # exclusive token end

    sentinel = jnp.uint32(constants.SENTINEL_KEY)
    one = jnp.uint32(1)

    def shift(x, fill):
        return jnp.concatenate([jnp.full((1,), fill, x.dtype), x[:-1]])

    g_hi, g_lo, g_pos, g_valid = key_hi, key_lo, start, live
    for _ in range(n - 1):
        p_hi = shift(g_hi, jnp.uint32(0))
        p_lo = shift(g_lo, jnp.uint32(0))
        p_pos = shift(g_pos, jnp.uint32(constants.POS_INF))
        p_valid = shift(g_valid, False)
        g_valid = live & p_valid
        # Order-sensitive pairing, bit-identical to the XLA path.
        g_hi = tok_ops._fmix32(p_hi * jnp.uint32(constants.HASH_BASE_1) ^ key_hi)
        g_lo = tok_ops._fmix32(p_lo * jnp.uint32(constants.HASH_BASE_2) ^ key_lo)
        at_sentinel = (g_hi == sentinel) & (g_lo >= sentinel - one)
        g_lo = jnp.where(at_sentinel, sentinel - jnp.uint32(2), g_lo)
        g_pos = p_pos

    length = jnp.where(g_valid, end - g_pos, jnp.uint32(0))
    return TokenStream(
        key_hi=jnp.where(g_valid, g_hi, sentinel),
        key_lo=jnp.where(g_valid, g_lo, sentinel),
        count=g_valid.astype(jnp.uint32),
        pos=jnp.where(g_valid, g_pos, jnp.uint32(constants.POS_INF)),
        length=length,
    )


def mark_long_spans(stream: TokenStream) -> TokenStream:
    """Length-plane policy for gram tables, identical in every backend:
    spans < 127 bytes are stored exactly; longer spans (and exactly-127
    ones) store ``SEAM_GRAM_LENGTH`` and the host recovers the span by
    scanning ``n`` entries forward from the start (the cross-chunk seam
    entry idiom, :func:`...data.reader.scan_gram_lengths`).  Inter-token
    separator runs are unbounded, so no static bound on a gram span exists
    — the 7-bit cap is what lets :func:`gram_table` ride the packed
    sort-lean aggregation (``pos << 7 | len`` in one uint32) instead of the
    generic 7-array build (ROADMAP r4 #4)."""
    long = (stream.count > 0) & (stream.length >= jnp.uint32(127))
    return stream._replace(length=jnp.where(
        long, jnp.uint32(constants.SEAM_GRAM_LENGTH), stream.length))


def gram_table(gs: TokenStream, capacity: int, pos_hi: jax.Array | int,
               max_pos: int, sort_mode: str = "stable2",
               sort_impl: str = "xla",
               salt_bits: int = 0,
               radix_geometry: tuple | None = None) -> table_ops.CountTable:
    """Aggregate a position-ordered gram stream into a count table.

    Both backends' gram streams arrive in ascending start-position order
    (the pallas path pairs position-sorted rows; the XLA path's per-byte
    stream is indexed by byte), which is exactly the stable2 packed-path
    precondition — so when every position fits 25 bits (chunks <= 32 MB,
    the production default) the build is the same 3-array 2-key stable
    sort the wordcount family runs, instead of the generic 7-array 4-key
    build (~2.3x the sorted bytes).  Lengths ride packed as
    ``min(span, 127)``; 127 means "long span" and unpacks to the
    ``SEAM_GRAM_LENGTH`` scan-forward sentinel (:func:`mark_long_spans`
    must already have applied the same policy to ``gs`` so the generic
    fallback's length plane is bit-identical).

    ``max_pos`` is the static bound on gram start positions — the padded
    chunk length (NOT the stream row count: the pallas kernel's compacted
    stream has ~3x fewer rows than chunk bytes, but its positions still
    span the whole chunk).

    ``salt_bits`` (``Config.combiner='salt'``, ISSUE 11): a
    single-hot-gram stream is exactly as pathological for the radix slab
    path as a single hot word, so the salt tier rides the shared packed
    build — spread over salted segments, de-salted exactly at the reduce
    (:func:`...ops.table.from_packed_rows`).  The gram family's hot-key
    CACHE tier does not exist: deleting duplicate tokens would break the
    position adjacency grams are formed from, so 'hot-cache' is a
    documented no-op here.
    """
    # pos << 7 needs pos < 2**25; the padded chunk length is a trace-time
    # constant, so the gate is static.  (The generic fallback ignores
    # sort_impl: the radix seam covers the packed build only.)
    if max_pos > (1 << 25):
        return table_ops.from_stream(gs, capacity, pos_hi=pos_hi)
    # Sentinel-collision proof (ADVICE r5): a live row packs to
    # _SENT_PACKED only with pos == 2**25-1 AND len7 == 127 simultaneously
    # — but len7 == 127 means the true span is >= 127 bytes
    # (mark_long_spans stores min(span, 127)), so pos + 127 <= span end <=
    # max_pos <= 2**25, i.e. pos <= 2**25 - 127 < 2**25 - 1.
    # Contradiction: the collision is unreachable at ANY admitted max_pos.
    # (Tightening the gate to `>=` instead would silently kick the
    # production 32 MB chunk — padded length exactly 2**25 — onto the
    # 2.3x-costlier generic build.)  The static assert pins the premise.
    assert max_pos <= (1 << 25), max_pos
    live = gs.count > 0
    len7 = jnp.minimum(gs.length, jnp.uint32(127))
    packed = jnp.where(live, (gs.pos << jnp.uint32(7)) | len7, _SENT_PACKED)
    # sort_mode passes through unchanged: stable2's position-order
    # precondition holds here (docstring), sort3/segmin have none, and
    # from_packed_rows owns the segmin-on-TPU refusal.  sort_impl rides
    # along so the gram family inherits the radix A/B with no extra knob.
    t = table_ops.from_packed_rows(
        gs.key_hi, gs.key_lo, packed, jnp.sum(gs.count), capacity, pos_hi,
        len_bits=7, sort_mode=sort_mode, sort_impl=sort_impl,
        salt_bits=salt_bits, radix_geometry=radix_geometry)
    occ = t.occupied()
    return t._replace(length=jnp.where(
        occ & (t.length == jnp.uint32(127)),
        jnp.uint32(constants.SEAM_GRAM_LENGTH), t.length))


def ngram_table(chunk: jax.Array, n: int, capacity: int,
                pos_hi: jax.Array | int, config) -> table_ops.CountTable:
    """Per-chunk n-gram count table on the pallas backend.

    One straight-line program: fused kernel -> position sort (poison rows
    included) -> elementwise pairing -> packed table build
    (:func:`gram_table`).  Grams containing a suppressed >W-byte token
    self-invalidate at the poison rows (module docstring) and are accounted
    exactly: the closed-form chunk gram total is ``max(all_tokens - (n-1),
    0)`` with ``all_tokens`` including overlong ones, so whatever the
    pairing did not form was dropped by suppression.
    """
    t, _ = ngram_map_with_summary(chunk, n, capacity, pos_hi, config)
    return t


def ngram_map_with_summary(chunk: jax.Array, n: int, capacity: int,
                           pos_hi: jax.Array | int, config):
    """(per-chunk table, :class:`ChunkSummary`) — the streamed exact-seam
    map's device side, sharing one kernel run + one position sort between
    in-chunk gram formation and the seam summary."""
    from mapreduce_tpu.ops.pallas import tokenize as pallas_tok

    if config.map_impl == "fused":
        # Fused map (Config.map_impl): one kernel pass emits the whole
        # stream — cross-lane-seam tokens hashed in-kernel — so the
        # position sort consumes it directly, no seam concat.  The gram
        # family keeps full resolution (pair mode): its consumer is the
        # position sort, which any row order feeds equally well, and the
        # pair path is spill-free by construction (exactness without a
        # fallback cond).  Poison rows ride the same stream.
        stream, overlong, _spill = pallas_tok.tokenize_fused(
            chunk, max_token_bytes=config.pallas_max_token,
            block_rows=config.resolved_pair_block_rows,
            aux_rows=config.resolved_aux_rows)
    else:
        col, seam, overlong = pallas_tok.tokenize_split(
            chunk, max_token_bytes=config.pallas_max_token,
            block_rows=config.resolved_pair_block_rows)
        stream = pallas_tok.concat_streams(col, seam)
    key_hi, key_lo, packed = position_sorted(stream)
    gs = mark_long_spans(grams_from_sorted(key_hi, key_lo, packed, n))
    t = gram_table(gs, capacity, pos_hi, max_pos=chunk.shape[0],
                   sort_mode=config.sort_mode, sort_impl=config.sort_impl,
                   salt_bits=config.resolved_salt_bits,
                   radix_geometry=config.resolved_radix_geometry)
    # Live sorted rows = real tokens + one poison row per overlong end.
    all_tokens = stream.total + overlong
    nm1 = jnp.uint32(n - 1)
    full_total = jnp.where(all_tokens > nm1, all_tokens - nm1, jnp.uint32(0))
    missing = full_total - jnp.sum(gs.count)  # grams killed by suppression
    # ``missing`` occurrences are exact; distinct missing grams are unknowable
    # on device (overlong tokens leave the kernel unhashed), so uniques get
    # the same upper-bound treatment as the wordcount family's overlong.
    t = t._replace(dropped_uniques=t.dropped_uniques + missing,
                   dropped_count=t.dropped_count + missing)
    summ = summary_from_packed(key_hi, key_lo, packed, all_tokens, pos_hi, n)
    return t, summ


# --- Exact cross-chunk grams: carry summaries + seam windows -----------------
#
# A streamed run splits the corpus into chunks; grams whose tokens straddle a
# chunk seam have no single chunk to form in.  Mirroring grep's exact line
# carry (models/grep.py): each chunk's map emits a tiny summary — its first
# and last up-to-(n-1) position-ordered stream ENTRIES (tokens and poison
# markers alike) — the devices share summaries with one small all_gather per
# step, and the job's combine composes them in global chunk order, forming
# every window that crosses a join exactly once (at the join where its final
# token's chunk lands).  The carry composition is the classic sliding-window
# monoid: `compose_carry` keeps the last n-1 entries of a concatenation, so
# chunks with fewer than n-1 tokens (even zero) chain correctly and windows
# spanning 3+ chunks complete at the right join.

KIND_EMPTY = 0  # unoccupied slot
KIND_TOKEN = 1  # real token entry
KIND_POISON = 2  # suppressed >W token: occupies its slot, poisons windows


class GramCarry(NamedTuple):
    """Up-to-(n-1) consecutive stream entries.  All fields uint32[n-1].

    Used both LEFT-aligned (a chunk's first entries, slot 0 oldest) and
    RIGHT-aligned (the running carry / a chunk's last entries, slot n-2
    newest); empty slots carry kind 0 and zeroed fields.
    """

    key_hi: jax.Array
    key_lo: jax.Array
    chunk_id: jax.Array
    pos: jax.Array
    kind: jax.Array


class ChunkSummary(NamedTuple):
    """One chunk's seam-relevant view: first entries (left-aligned) + last
    entries (right-aligned).  A tiny fixed-shape pytree — the per-step
    all_gather moves ~5*(n-1) words per chunk."""

    first: GramCarry
    last: GramCarry


def empty_carry(n: int) -> GramCarry:
    z = jnp.zeros((n - 1,), jnp.uint32)
    return GramCarry(z, jnp.zeros_like(z), jnp.zeros_like(z),
                     jnp.zeros_like(z), jnp.zeros_like(z))


def chunk_summary(key_hi: jax.Array, key_lo: jax.Array, pos: jax.Array,
                  poison: jax.Array, n_entries: jax.Array, chunk_id: jax.Array,
                  n: int) -> ChunkSummary:
    """Summary of a position-sorted stream (live rows first).

    Inputs are position-ordered arrays (live entries occupying the first
    ``n_entries`` rows — real tokens + poison markers; data-dependent).
    Poison rows are kept: an overlong token at a chunk edge must poison
    cross-chunk windows exactly like in-chunk ones.  The pallas caller
    derives ``pos``/``poison`` from the kernel's packed plane; the XLA
    caller has no poison (any token length hashes exactly).
    """
    m = n - 1
    cap = key_hi.shape[0]
    cid = jnp.broadcast_to(jnp.asarray(chunk_id, jnp.uint32), (m,))
    ne = n_entries.astype(jnp.int32)

    def mk(idx, valid):
        idx_c = jnp.clip(idx, 0, cap - 1)
        kind = jnp.where(valid,
                         jnp.where(poison[idx_c], jnp.uint32(KIND_POISON),
                                   jnp.uint32(KIND_TOKEN)),
                         jnp.uint32(KIND_EMPTY))
        live = kind != KIND_EMPTY
        z = jnp.uint32(0)
        return GramCarry(
            key_hi=jnp.where(live, key_hi[idx_c], z),
            key_lo=jnp.where(live, key_lo[idx_c], z),
            chunk_id=jnp.where(live, cid, z),
            pos=jnp.where(live, pos[idx_c], z),
            kind=kind,
        )

    idx_f = jnp.arange(m, dtype=jnp.int32)
    first = mk(idx_f, idx_f < ne)
    idx_l = ne - m + jnp.arange(m, dtype=jnp.int32)
    last = mk(idx_l, idx_l >= 0)
    return ChunkSummary(first=first, last=last)


def summary_from_packed(key_hi: jax.Array, key_lo: jax.Array,
                        packed: jax.Array, n_entries: jax.Array,
                        chunk_id: jax.Array, n: int) -> ChunkSummary:
    """Pallas-path summary: position-sorted packed plane in, summary out."""
    return chunk_summary(key_hi, key_lo, packed >> 6,
                         (packed & jnp.uint32(63)) == 0,
                         n_entries, chunk_id, n)


def summary_from_stream(stream: TokenStream, chunk_id: jax.Array,
                        n: int) -> ChunkSummary:
    """XLA-path summary: one single-key position sort of the per-byte
    stream (non-tokens carry POS_INF and sink), no poison (the XLA
    tokenizer hashes any token length exactly)."""
    pos_key = jnp.where(stream.count > 0, stream.pos,
                        jnp.uint32(constants.POS_INF))
    pos_s, khi_s, klo_s = jax.lax.sort(
        (pos_key, stream.key_hi, stream.key_lo), num_keys=1)
    n_live = jnp.sum(stream.count)
    return chunk_summary(khi_s, klo_s, pos_s, jnp.zeros_like(pos_s, jnp.bool_),
                         n_live, chunk_id, n)


def compose_carry(carry: GramCarry, last: GramCarry) -> GramCarry:
    """Append a chunk's last-entries to the running carry, keeping the most
    recent n-1 entries (right-aligned).  The sliding-window monoid's fold:
    ``sv`` newer entries shift the old carry left by ``sv``."""
    m = carry.kind.shape[0]
    sv = jnp.sum((last.kind != KIND_EMPTY).astype(jnp.int32))
    k = jnp.arange(m, dtype=jnp.int32)
    take_new = k >= (m - sv)
    idx_old = jnp.clip(k + sv, 0, m - 1)
    pick = lambda old, new: jnp.where(take_new, new, old[idx_old])
    return GramCarry(*(pick(o, s) for o, s in zip(carry, last)))


def seam_gram_rows(prefix: GramCarry, first: GramCarry, n: int):
    """Windows crossing the join between ``prefix`` (right-aligned: all
    entries before this chunk) and this chunk's ``first`` entries.

    Returns ``(key_hi, key_lo, chunk_id, pos, count, dropped)`` — n-1 rows,
    row j-1 the window taking j entries from the left.  A window EXISTS when
    all n slots are occupied (otherwise it completes at a later join, or the
    corpus simply ends); an existing window is counted when every entry is a
    real token, and dropped (suppressed >W token inside) otherwise.
    ``dropped`` is the scalar count of such windows.  Hash composition is
    bit-identical to :func:`grams_from_sorted`.
    """
    m = n - 1
    sentinel = jnp.uint32(constants.SENTINEL_KEY)
    one = jnp.uint32(1)
    rows_hi, rows_lo, rows_cid, rows_pos, rows_cnt = [], [], [], [], []
    dropped = jnp.uint32(0)
    for j in range(1, n):
        ents = [(prefix, m - j + t) for t in range(j)] \
            + [(first, t) for t in range(n - j)]
        src0, i0 = ents[0]
        g_hi = src0.key_hi[i0]
        g_lo = src0.key_lo[i0]
        occupied = src0.kind[i0] != KIND_EMPTY
        all_tok = src0.kind[i0] == KIND_TOKEN
        for src, i in ents[1:]:
            occupied = occupied & (src.kind[i] != KIND_EMPTY)
            all_tok = all_tok & (src.kind[i] == KIND_TOKEN)
            g_hi = tok_ops._fmix32(
                g_hi * jnp.uint32(constants.HASH_BASE_1) ^ src.key_hi[i])
            g_lo = tok_ops._fmix32(
                g_lo * jnp.uint32(constants.HASH_BASE_2) ^ src.key_lo[i])
            at_sent = (g_hi == sentinel) & (g_lo >= sentinel - one)
            g_lo = jnp.where(at_sent, sentinel - jnp.uint32(2), g_lo)
        counted = occupied & all_tok
        dropped = dropped + (occupied & ~all_tok).astype(jnp.uint32)
        rows_hi.append(jnp.where(counted, g_hi, sentinel))
        rows_lo.append(jnp.where(counted, g_lo, sentinel))
        rows_cid.append(jnp.where(counted, prefix.chunk_id[m - j],
                                  jnp.uint32(constants.POS_INF)))
        rows_pos.append(jnp.where(counted, prefix.pos[m - j],
                                  jnp.uint32(constants.POS_INF)))
        rows_cnt.append(counted.astype(jnp.uint32))
    stack = lambda xs: jnp.stack(xs)
    return (stack(rows_hi), stack(rows_lo), stack(rows_cid), stack(rows_pos),
            stack(rows_cnt), dropped)


def seam_gram_table(prefix: GramCarry, first: GramCarry,
                    n: int) -> table_ops.CountTable:
    """The join's cross-window contribution as a tiny mergeable table.

    Entries carry ``SEAM_GRAM_LENGTH`` so host recovery knows to scan the
    span forward (its end lies in a later chunk whose row base the device
    cannot know).  Dropped (poisoned) windows land in ``dropped_*``.
    """
    k_hi, k_lo, cid, pos, cnt, dropped = seam_gram_rows(prefix, first, n)
    length = jnp.where(cnt > 0, jnp.uint32(constants.SEAM_GRAM_LENGTH),
                       jnp.uint32(0))
    z = jnp.uint32(0)
    return table_ops._build(k_hi, k_lo, cid, pos, cnt, jnp.zeros_like(cnt),
                            length, capacity=max(n - 1, 2),
                            carry_du=dropped, carry_du_hi=z,
                            carry_dc=dropped, carry_dc_hi=z)
