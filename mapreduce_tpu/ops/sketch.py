"""HyperLogLog distinct-count sketch (a second model family).

The reference can report at most 10 distinct words before silently
corrupting memory (``MAX_OUTPUT_COUNT``, ``main.cu:13,103-104``); the count
table here is exact up to its configured capacity and *accounts* what it
drops, but past capacity the distinct count degrades to an upper bound
(``CountTable.dropped_uniques``).  The sketch closes that gap: a fixed
2**p-register HyperLogLog tracks the number of distinct keys with ~1.04/√m
relative error at any corpus size, in O(KB) of state.

TPU-first shape of the design:

* Registers update from **deduplicated per-chunk table keys** (the ≤64K-row
  batch table the map phase already builds), never from the raw multi-million
  entry token stream — scatter cost scales with input size on TPU, and
  re-scattering duplicate tokens is pure waste.  HLL's register-max is
  idempotent, so cross-chunk duplicates are harmless.
* The register update is one ``scatter-max``; the cross-device/cross-chunk
  merge is elementwise ``maximum`` — an associative, commutative monoid that
  rides :func:`...collectives.tree_merge` (or ``lax.pmax``) like any other
  accumulator in this framework.
* The keys are the tokenizer's 64-bit hashes (khi, klo), already
  avalanche-finalized (murmur fmix, ``ops/tokenize.py``) — no rehashing.

Estimation (host-side, numpy float64) uses the standard bias-corrected HLL
estimator with the small-range (linear counting) correction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu import constants
from mapreduce_tpu.ops.tokenize import _fmix32  # single avalanche owner

DEFAULT_PRECISION = 14  # 2**14 registers = 64 KiB of uint32; ~0.8% error


def empty(precision: int = DEFAULT_PRECISION) -> jax.Array:
    """Zeroed registers, uint32[2**precision]."""
    if not 4 <= precision <= 18:
        raise ValueError(f"precision must be in [4, 18], got {precision}")
    return jnp.zeros((1 << precision,), dtype=jnp.uint32)


def _bit_length(x: jax.Array) -> jax.Array:
    """Per-lane bit length of a uint32 (0 for 0), elementwise (no clz on
    the VPU; 5-step binary search)."""
    n = jnp.zeros(x.shape, jnp.uint32)
    for shift in (16, 8, 4, 2, 1):
        big = x >= (jnp.uint32(1) << shift)
        n = jnp.where(big, n + shift, n)
        x = jnp.where(big, x >> shift, x)
    return n + (x > 0).astype(jnp.uint32)


def update_from_keys(registers: jax.Array, key_hi: jax.Array,
                     key_lo: jax.Array, valid: jax.Array) -> jax.Array:
    """Fold a batch of 64-bit keys into the registers.

    ``valid`` masks real rows (count-table slots may be empty/sentinel).
    Bucket = low p bits of key_hi; rho = leading-zero count of key_lo + 1
    (klo == 0 maps to the max rho, 33, as the all-zero suffix).
    """
    bucket = (key_hi & jnp.uint32(registers.shape[0] - 1)).astype(jnp.int32)
    rho = jnp.uint32(33) - _bit_length(key_lo)
    rho = jnp.where(valid, rho, jnp.uint32(0))  # max with 0 = no-op
    return registers.at[bucket].max(rho, mode="drop")


def merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Associative, commutative, idempotent register merge."""
    return jnp.maximum(a, b)


def estimate(registers: np.ndarray | jax.Array) -> float:
    """Bias-corrected HLL cardinality estimate (host-side)."""
    regs = np.asarray(registers, dtype=np.float64)
    m = regs.shape[0]
    alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(m, 0.7213 / (1 + 1.079 / m))
    raw = alpha * m * m / np.sum(np.exp2(-regs))
    zeros = int(np.sum(regs == 0))
    if raw <= 2.5 * m and zeros:
        return float(m * np.log(m / zeros))  # linear counting, small range
    return float(raw)


# --- Count-Min Sketch --------------------------------------------------------
# The exact CountTable answers "how often did word w occur" only for the words
# it retained; past capacity, spilled words vanish into dropped_* scalars.
# The CMS closes the *frequency* gap the way HLL closes the distinct-count
# gap: a (depth x width) uint32 matrix whose row-wise min upper-bounds any
# key's true count, with error <= total/width per row w.h.p.  Like the HLL,
# it updates from the deduplicated per-chunk batch table (depth
# capacity-sized scatter-adds, never stream-sized), and merges by elementwise
# addition — associative + commutative, riding the same collectives.

CMS_DEPTH = 4
CMS_WIDTH_LOG2 = 16  # 4 x 64K x uint32 = 1 MiB of state

# Odd row salts (xxhash/murmur-family primes) making the per-row bucket
# hashes effectively independent.
_CMS_SALTS = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F,
              0x165667B1, 0xFD7046C5, 0xB55A4F09, 0x2127599B)


def cms_empty(depth: int = CMS_DEPTH, width_log2: int = CMS_WIDTH_LOG2) -> jax.Array:
    """Zeroed sketch, uint32[depth, 2**width_log2]."""
    if not 1 <= depth <= len(_CMS_SALTS):
        raise ValueError(f"depth must be in [1, {len(_CMS_SALTS)}], got {depth}")
    if not 8 <= width_log2 <= 24:
        raise ValueError(f"width_log2 must be in [8, 24], got {width_log2}")
    return jnp.zeros((depth, 1 << width_log2), dtype=jnp.uint32)


def _cms_bucket_jnp(key_hi: jax.Array, key_lo: jax.Array, row: int,
                    width_mask: int) -> jax.Array:
    h = _fmix32((key_hi ^ jnp.uint32(_CMS_SALTS[row])) * constants.FMIX_C1
                + key_lo * constants.FMIX_C2 + jnp.uint32(row))
    return (h & jnp.uint32(width_mask)).astype(jnp.int32)


def cms_update(cms: jax.Array, key_hi: jax.Array, key_lo: jax.Array,
               counts: jax.Array) -> jax.Array:
    """Add a batch of (key, count) rows into the sketch.

    Empty table slots carry count 0, so no validity mask is needed: adding
    zero to an arbitrary bucket is a no-op.  All depth rows go through ONE
    flattened scatter-add: on TPU each scatter carries a large fixed cost
    (BENCHMARKS.md), so one scatter of depth*n updates beats depth scatters
    of n.
    """
    depth, width = cms.shape
    flat_idx = jnp.concatenate([
        _cms_bucket_jnp(key_hi, key_lo, r, width - 1) + jnp.int32(r * width)
        for r in range(depth)])
    updates = jnp.tile(counts.astype(jnp.uint32), depth)
    return cms.reshape(-1).at[flat_idx].add(updates, mode="drop").reshape(depth, width)


def cms_merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Associative, commutative sketch merge."""
    return a + b


# Host-side mirrors (python-int arithmetic, masked to 32 bits) so any word —
# retained or spilled — can be queried after the run without a device trip.

_M32 = 0xFFFFFFFF


def _fmix32_host(x: int) -> int:
    x ^= x >> 16
    x = (x * int(constants.FMIX_C1)) & _M32
    x ^= x >> 13
    x = (x * int(constants.FMIX_C2)) & _M32
    x ^= x >> 16
    return x


def _clamp_sentinel(key_hi: int, key_lo: int) -> tuple[int, int]:
    if key_hi == int(constants.SENTINEL_KEY) and key_lo == int(constants.SENTINEL_KEY):
        key_lo = (key_lo - 1) & _M32
    return key_hi, key_lo


def _hash_token(token: bytes) -> tuple[int, int]:
    v1 = v2 = 0
    for c in token:
        v1 = (v1 * int(constants.HASH_BASE_1) + c + 1) & _M32
        v2 = (v2 * int(constants.HASH_BASE_2) + c + 1) & _M32
    n = len(token)
    return _clamp_sentinel(_fmix32_host(v1 ^ (n & _M32)),
                           _fmix32_host((v2 + 0x9E3779B9 * n) & _M32))


def hash_word(word: bytes) -> tuple[int, int]:
    """The device 64-bit key for ``word`` — a single token OR an n-gram span
    (host mirror).

    A ``word`` containing separator bytes is keyed the way the device keys
    grams: per-token rolling-hash + fmix (mirroring
    :func:`mapreduce_tpu.ops.tokenize.tokenize`), folded left-to-right with
    the gram carry-mix (mirroring ``_extend_grams``).  The device never emits
    a *token* containing a separator, so the interpretations cannot collide.
    Pinned to the device hashes by
    ``tests/test_sketch.py::test_hash_word_matches_device`` (tokens) and
    ``test_hash_word_matches_device_grams`` (spans).
    """
    seps = bytes(constants.SEPARATOR_BYTES)
    tokens, cur = [], bytearray()
    for c in word:
        if c in seps:
            if cur:
                tokens.append(bytes(cur))
                cur = bytearray()
        else:
            cur.append(c)
    if cur:
        tokens.append(bytes(cur))
    if not tokens:
        return _hash_token(b"")
    key_hi, key_lo = _hash_token(tokens[0])
    for tok in tokens[1:]:
        t_hi, t_lo = _hash_token(tok)
        key_hi, key_lo = _clamp_sentinel(
            _fmix32_host(((key_hi * int(constants.HASH_BASE_1)) & _M32) ^ t_hi),
            _fmix32_host(((key_lo * int(constants.HASH_BASE_2)) & _M32) ^ t_lo))
    return key_hi, key_lo


def cms_query(cms: np.ndarray, word: bytes) -> int:
    """Estimated occurrence count of ``word``: min over rows (host-side).

    Never under-estimates a word the sketch saw; over-estimates by at most
    ~total/width per row with probability 1 - 2**-depth.
    """
    sk = np.asarray(cms)
    depth, width = sk.shape
    key_hi, key_lo = hash_word(word)
    est = None
    for r in range(depth):
        h = _fmix32_host(((key_hi ^ _CMS_SALTS[r]) * int(constants.FMIX_C1)
                          + key_lo * int(constants.FMIX_C2) + r) & _M32)
        v = int(sk[r, h & (width - 1)])
        est = v if est is None else min(est, v)
    return est
