"""HyperLogLog distinct-count sketch (a second model family).

The reference can report at most 10 distinct words before silently
corrupting memory (``MAX_OUTPUT_COUNT``, ``main.cu:13,103-104``); the count
table here is exact up to its configured capacity and *accounts* what it
drops, but past capacity the distinct count degrades to an upper bound
(``CountTable.dropped_uniques``).  The sketch closes that gap: a fixed
2**p-register HyperLogLog tracks the number of distinct keys with ~1.04/√m
relative error at any corpus size, in O(KB) of state.

TPU-first shape of the design:

* Registers update from **deduplicated per-chunk table keys** (the ≤64K-row
  batch table the map phase already builds), never from the raw multi-million
  entry token stream — scatter cost scales with input size on TPU, and
  re-scattering duplicate tokens is pure waste.  HLL's register-max is
  idempotent, so cross-chunk duplicates are harmless.
* The register update is one ``scatter-max``; the cross-device/cross-chunk
  merge is elementwise ``maximum`` — an associative, commutative monoid that
  rides :func:`...collectives.tree_merge` (or ``lax.pmax``) like any other
  accumulator in this framework.
* The keys are the tokenizer's 64-bit hashes (khi, klo), already
  avalanche-finalized (murmur fmix, ``ops/tokenize.py``) — no rehashing.

Estimation (host-side, numpy float64) uses the standard bias-corrected HLL
estimator with the small-range (linear counting) correction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu import constants

DEFAULT_PRECISION = 14  # 2**14 registers = 64 KiB of uint32; ~0.8% error


def empty(precision: int = DEFAULT_PRECISION) -> jax.Array:
    """Zeroed registers, uint32[2**precision]."""
    if not 4 <= precision <= 18:
        raise ValueError(f"precision must be in [4, 18], got {precision}")
    return jnp.zeros((1 << precision,), dtype=jnp.uint32)


def _bit_length(x: jax.Array) -> jax.Array:
    """Per-lane bit length of a uint32 (0 for 0), elementwise (no clz on
    the VPU; 5-step binary search)."""
    n = jnp.zeros(x.shape, jnp.uint32)
    for shift in (16, 8, 4, 2, 1):
        big = x >= (jnp.uint32(1) << shift)
        n = jnp.where(big, n + shift, n)
        x = jnp.where(big, x >> shift, x)
    return n + (x > 0).astype(jnp.uint32)


def update_from_keys(registers: jax.Array, key_hi: jax.Array,
                     key_lo: jax.Array, valid: jax.Array) -> jax.Array:
    """Fold a batch of 64-bit keys into the registers.

    ``valid`` masks real rows (count-table slots may be empty/sentinel).
    Bucket = low p bits of key_hi; rho = leading-zero count of key_lo + 1
    (klo == 0 maps to the max rho, 33, as the all-zero suffix).
    """
    bucket = (key_hi & jnp.uint32(registers.shape[0] - 1)).astype(jnp.int32)
    rho = jnp.uint32(33) - _bit_length(key_lo)
    rho = jnp.where(valid, rho, jnp.uint32(0))  # max with 0 = no-op
    return registers.at[bucket].max(rho, mode="drop")


def merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Associative, commutative, idempotent register merge."""
    return jnp.maximum(a, b)


def estimate(registers: np.ndarray | jax.Array) -> float:
    """Bias-corrected HLL cardinality estimate (host-side)."""
    regs = np.asarray(registers, dtype=np.float64)
    m = regs.shape[0]
    alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(m, 0.7213 / (1 + 1.079 / m))
    raw = alpha * m * m / np.sum(np.exp2(-regs))
    zeros = int(np.sum(regs == 0))
    if raw <= 2.5 * m and zeros:
        return float(m * np.log(m / zeros))  # linear counting, small range
    return float(raw)
