"""Runtime configuration.

The reference has no flag system: ``argv`` is ignored (``main.cu:164``) and
every capacity is a compile-time ``#define`` (``main.cu:9-15``).  Here all
sizing is a runtime dataclass; shapes are static *per compiled step* (an XLA
requirement) but chosen freely per run.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


class PlatformRefusedError(ValueError):
    """A config knob refused against the resolved runtime platform (raised
    at trace time, after construction-time validation can no longer see the
    platform).  The CLI maps exactly this to a clean exit instead of
    blanket-catching ValueError around all compute."""


SEGMIN_TPU_ERROR = (
    "sort_mode='segmin' is disabled on the TPU backend: its stream-sized "
    "associative_scan wedges the chip for >30 min (measured 3x, BENCHMARKS.md "
    "round 4) — on a shared device that takes down every tenant.  Use "
    "sort_mode='sort3' (bit-identical results), run the A/B on CPU, or set "
    "MAPREDUCE_ALLOW_SEGMIN=1 to re-measure deliberately.")


#: Salt width for combiner='salt': one hot key spreads over 2**3 = 8 sort
#: segments — enough to defeat the measured ~4x radix hot-key slab
#: amplification while keeping the de-salt coalesce's collision envelope
#: a single-digit multiple of the documented 64-bit key envelope.
COMBINER_SALT_BITS = 3

#: The collective merge strategies the runtime builds, mirrored here so
#: Config stays jax-free (parallel/collectives.py STRATEGIES is the
#: source of truth; the bijection is test-pinned in test_collective.py).
MERGE_STRATEGIES = ("tree", "gather", "keyrange", "hier-kr-tree",
                    "hier-tree-tree")


def radix_slab_cap(bits: int, block_rows: int, slab_slack: int) -> int:
    """Resolved radix slab rows per (block, lane, bucket): the slack
    multiple of the uniform share, clamped to the block — the ONE owner
    of the clamp (Geometry validation, the meta plan constructor, and
    the kernel wrapper all call this, so the certifier can never
    desynchronize from what the kernel binds)."""
    return min(slab_slack * block_rows // (1 << bits), block_rows)


@dataclasses.dataclass(frozen=True)
class Geometry:
    """One complete set of Pallas kernel geometries (ISSUE 12).

    Every field was a hand-picked constant scattered across the kernel
    wrappers until PR 12; collecting them in one validated, hashable
    dataclass is what makes the geometries *searchable*: the jax-free
    enumerator (``mapreduce_tpu/analysis/geometry.py``) walks candidate
    values over the tile-aligned lattice, the vmem/cost certifiers price
    and gate each candidate, and ``Config.geometry`` threads a certified
    winner to every kernel call site and ``vmem_plan`` metadata hook.

    The defaults ARE the shipped constants — a default ``Geometry()``
    reproduces today's kernels bit-for-bit (tested against the checked-in
    ``production_plans`` footprints).  Validation mirrors the kernel
    wrappers' envelopes so an off-lattice candidate fails at construction,
    not mid-trace; the *budget* gate (can the footprint fit VMEM?) is
    deliberately NOT here — that is the certifier's job, and the bounds
    below only encode tile alignment and packing-format limits.
    """

    #: stable2 lane-major compact window height in byte rows.  Multiple of
    #: 128: the fused path's raw lane-view input block is (LANES,
    #: block_rows) and Mosaic needs the minor block dim 128-divisible.
    block_rows: int = 384
    #: Slots per stable2 window.  Pinned to 128 — the only chip-validated
    #: lane-major value (the transposed output block puts SLOTS in the
    #: 128-divisible minor dim; S=120 failed lowering, BENCHMARKS r4).
    compact_slots: int = 128
    #: sort3 compact window height / slot budget (the round-4 shipped
    #: 256/88: 88 covers every measured density at 256 rows).
    sort3_block_rows: int = 256
    sort3_slots: int = 88
    #: Pair-resolution (spill-fallback / full-resolution) window height.
    pair_block_rows: int = 256
    #: Window height when the hot-key combiner runs (the cache absorbs
    #: the dominant duplicates, paying for taller windows — PR 11).
    combiner_block_rows: int = 512
    #: Per-lane hot-key cache entries (whole (8, 128) tiles).
    combiner_slots: int = 8
    #: Fused seam-carry aux plane rows (uint8 tile grid: multiple of 32;
    #: the head row is pinned at 64 = the W <= 63 bound, so 96 is the
    #: smallest tile-aligned plane that holds it).
    aux_rows: int = 96
    #: Radix partition digit width (B = 2**bits buckets per level).
    radix_bits: int = 3
    radix_block_rows: int = 256
    #: Slab budget per (block, lane, bucket) as a multiple of the uniform
    #: share block_rows/B — the write-amplification factor of the round-6
    #: pricing note, now a searchable knob.
    radix_slab_slack: int = 4

    def __post_init__(self) -> None:
        for name in ("block_rows", "combiner_block_rows", "pair_block_rows"):
            v = getattr(self, name)
            if v % 128 or not 128 <= v <= (1 << 20):
                raise ValueError(
                    f"{name} must be a multiple of 128 in [128, 2**20] "
                    f"(the fused lane-view block puts rows in the "
                    f"128-divisible minor dim), got {v}")
        if self.compact_slots != 128:
            raise ValueError(
                "compact_slots must be 128 (the only chip-validated "
                "lane-major slot count: the transposed output block puts "
                f"slots in the 128-divisible minor dim), got "
                f"{self.compact_slots}")
        if self.block_rows < 2 * self.compact_slots:
            raise ValueError(
                f"block_rows {self.block_rows} must be >= 2 * "
                f"compact_slots ({2 * self.compact_slots}): the kernel's "
                "pairwise fold emits at most block_rows/2 live rows")
        if self.combiner_block_rows < 2 * self.compact_slots:
            raise ValueError(
                f"combiner_block_rows {self.combiner_block_rows} must be "
                f">= 2 * compact_slots ({2 * self.compact_slots})")
        if self.sort3_block_rows % 32 \
                or not 64 <= self.sort3_block_rows <= (1 << 20):
            raise ValueError(
                f"sort3_block_rows must be a multiple of 32 in "
                f"[64, 2**20] (uint8 sublane tile), got "
                f"{self.sort3_block_rows}")
        if self.sort3_slots % 8 \
                or not 8 <= self.sort3_slots <= self.sort3_block_rows // 2:
            raise ValueError(
                f"sort3_slots must be a multiple of 8 in [8, "
                f"sort3_block_rows/2={self.sort3_block_rows // 2}], got "
                f"{self.sort3_slots}")
        if self.combiner_slots % 8 or not 8 <= self.combiner_slots <= 32:
            raise ValueError(
                f"combiner_slots must be a multiple of 8 in [8, 32], got "
                f"{self.combiner_slots}")
        if self.aux_rows % 32 or not 96 <= self.aux_rows <= 512:
            raise ValueError(
                f"aux_rows must be a multiple of 32 in [96, 512] (the "
                "pinned head row at 64 needs the plane past it), got "
                f"{self.aux_rows}")
        if not 1 <= self.radix_bits <= 5:
            raise ValueError(
                f"radix_bits must be in [1, 5] (B output-ref triples are "
                f"unrolled in the kernel), got {self.radix_bits}")
        if self.radix_block_rows % 8 \
                or not 64 <= self.radix_block_rows <= (1 << 20):
            raise ValueError(
                f"radix_block_rows must be a multiple of 8 in [64, 2**20], "
                f"got {self.radix_block_rows}")
        if self.radix_slab_slack < 1:
            raise ValueError(
                f"radix_slab_slack must be >= 1, got {self.radix_slab_slack}")
        cap = radix_slab_cap(self.radix_bits, self.radix_block_rows,
                             self.radix_slab_slack)
        if cap < 8 or cap % 8:
            raise ValueError(
                f"radix slab cap {cap} (= slack*block_rows/B, clamped to "
                "block_rows) must be a multiple of 8 and >= 8; adjust "
                "radix_block_rows/radix_bits/radix_slab_slack")

    @property
    def radix_cap(self) -> int:
        """Resolved per-(block, lane, bucket) slab rows (:func:`radix_slab_cap`)."""
        return radix_slab_cap(self.radix_bits, self.radix_block_rows,
                              self.radix_slab_slack)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


DEFAULT_GEOMETRY = Geometry()

#: Named geometry presets: the certified profiles a string
#: ``Config.geometry`` (and the tuner's ``geometry`` knob) can name.
#: 'tall512' is the PR-11 measured pair's other arm — 512-row windows at
#: the same 128 slots WITHOUT the combiner: −25% stable2 sort rows per
#: 32 MB chunk, at a spill risk on dense corpora the exact fallback
#: absorbs (the round-11 dead-end branch, now probe-able instead of
#: hand-written).  'combiner16' doubles the hot-key cache depth.
GEOMETRY_PRESETS = {
    "default": DEFAULT_GEOMETRY,
    "tall512": Geometry(block_rows=512),
    "combiner16": Geometry(combiner_slots=16),
}


def segmin_allowed() -> bool:
    """Single owner of the MAPREDUCE_ALLOW_SEGMIN override parse: the raw
    string truthiness trap ('0' would bypass the wedge guard) is avoided by
    treating only explicit affirmative values as opt-in."""
    import os

    return os.environ.get("MAPREDUCE_ALLOW_SEGMIN", "").lower() \
        in ("1", "true", "yes")


@dataclasses.dataclass(frozen=True)
class Config:
    """Sizing and execution knobs for a MapReduce run.

    Attributes:
      chunk_bytes: bytes per device step per device.  The unit of streaming;
        each jitted step consumes this many bytes on every device.  Must be a
        multiple of 128 for TPU lane alignment.  Default 32 MB: the measured
        sweet spot on v5e (BENCHMARKS.md round 4: 64 MB chunks LOSE ~15-40%
        end-to-end — sort cost is superlinear in rows and HBM pressure grows —
        and 1 MB chunks leave dispatch overhead unamortized).  Single-buffer
        entry points never pad small inputs up to this (padding is to the
        input's own length), so the default only shapes streamed runs.
      table_capacity: distinct keys the running count table can hold (per
        final table).  Beyond this, rarest-by-arrival keys spill and are
        tallied in ``dropped_*`` diagnostics rather than silently corrupting
        memory like the reference does past MAX_OUTPUT_COUNT (main.cu:103-104).
      batch_unique_capacity: distinct keys extracted from one step's chunk
        before merging into the table.  Bounded by tokens-per-chunk; a chunk of
        N bytes has at most ceil(N/2) tokens.
      mesh_axis: name of the data-parallel mesh axis.
      backend: map-phase implementation — 'xla' (segmented associative scan,
        any token length), 'pallas' (fused single-pass TPU kernel; tokens
        longer than ``pallas_max_token`` bytes are dropped into ``dropped_*``
        accounting rather than counted), or 'auto' (the default: pallas on
        TPU when the chunk is large enough for its seam windows, xla
        elsewhere).  'auto' exists because the associative-scan formulation,
        while fine on CPU and for small shapes, compiles pathologically
        slowly on real TPU at multi-MB chunk sizes — the fused kernel is the
        TPU path.
      pallas_max_token: W for the pallas backend's on-chip lookback window.
      superstep: chunks folded into ONE dispatch per device via ``lax.scan``
        (Engine.step_many).  >1 amortizes per-dispatch overhead — decisive on
        high-latency device links — at the cost of staging superstep *
        chunk_bytes input per device per dispatch.  Default 1 (lowest memory,
        per-step checkpoint granularity); on a high-latency link (e.g. a
        tunneled relay, ~0.6 s/dispatch measured) raise it toward
        resident-corpus size — bench.py's timed window uses exactly that.
    """

    chunk_bytes: int = 1 << 25
    table_capacity: int = 1 << 18
    batch_unique_capacity: Optional[int] = None
    mesh_axis: str = "data"
    backend: str = "auto"
    pallas_max_token: int = 32
    superstep: int = 1
    # Sketched runs (HLL/CMS): fold per-chunk sketch updates into a pending
    # buffer and scatter once every K steps.  TPU scatters carry a large
    # fixed cost regardless of size (BENCHMARKS.md), so K amortizes it K-fold
    # at the price of K * batch_uniques rows of extra device state.  1 =
    # scatter every step (the round-1 behavior).
    sketch_flush_every: int = 1
    # Fold per-chunk batch tables into the running table once every K steps
    # instead of every step: batches stage into a pending buffer (cheap
    # dynamic_update_slice) and ONE K-way sort+segment-reduce replaces K
    # pairwise merges — 2*K sorts of (capacity + batch) rows become one
    # sort of (capacity + K*batch), a ~2x cut of the merge share of the
    # chunk budget at K >= 4 (sorts cost ~3 ms/M rows/array, BENCHMARKS.md).
    # Kept keys and their counts, dropped_count, and totals are identical
    # to K=1; only the dropped_uniques upper bound can differ under spill
    # (a key respilled in several steps is counted once per flush, not once
    # per step — a TIGHTER bound).  Costs K * batch_uniques * 6 words of
    # device state.  1 = merge every step.
    merge_every: int = 1
    # Aggregation sort strategy for the packed fast path (the single-chip
    # floor: the 3-array sort over the pair-compacted stream is 25-85 ms of
    # the ~102 ms chunk budget, BENCHMARKS.md).  'sort3' (default) carries
    # `packed` as a third sort key so each key segment's head row is its
    # first occurrence.  'stable2' drops the third comparator key: the
    # kernel writes its compacted planes LANE-MAJOR (flattened stream in
    # global byte-position order) and a STABLE two-key sort recovers first
    # occurrence from tie order — the round-4 sortbench measured the
    # comparator-width cut at ~40% of the sort's compute (173.8 -> 144.9 ms
    # incl. dispatch, 16.8M rows).  Requires the compact kernel path
    # (compact_slots > 0); window geometry moves to block_rows=384 /
    # 128 slots (measured spill-free: max 114 ends per 384-byte window on
    # Zipf, 75 natural — tools/density.py), whose transposed (128, 128)
    # output blocks are fully tile-aligned stores.  'segmin' sorts with
    # only the two key lanes in the
    # comparator (packed rides as payload) and recovers first occurrence
    # with a segmented running-min instead.  Bit-identical results;
    # tools/sortbench.py measures both.  'segmin' is REFUSED on the TPU
    # backend at trace time: its stream-sized associative_scan wedges the
    # chip for >30 min (3 independent observations, BENCHMARKS.md round 4)
    # — a one-flag footgun on a shared device.  The CPU A/B stays alive
    # (tests, sortbench's gated scan path); MAPREDUCE_ALLOW_SEGMIN=1
    # overrides for deliberate re-measurement.  Default 'stable2': measured
    # on-chip 2026-07-31 (round 5) against the same-day sort3 records —
    # zipf 0.4263 vs 0.4024 GB/s, natural 0.3653 vs 0.3448, webby (rescue
    # firing) 0.2748 vs 0.2659 — with the bit-identity suite
    # (tests/test_stable2.py) and an on-chip kernel parity smoke
    # (tools/kernel_smoke.py) holding both modes equal.
    sort_mode: str = "stable2"
    # Aggregation sort IMPLEMENTATION for the packed fast path — orthogonal
    # to sort_mode, which picks the comparator STRATEGY.  'xla' (default):
    # jax.lax.sort, measured at 2.6-3.4 effective HBM passes on the 11.2M-
    # row stream (BENCHMARKS.md round-6 pricing note).  'radix_partition':
    # one Pallas MSD digit partition (per-block VMEM bucket compaction into
    # static slabs + SMEM histograms, ops/pallas/radix.py) finished by
    # per-bucket blocked XLA sorts.  'radix': two digit levels before the
    # (smaller) finishing sorts.  Both radix modes are bit-identical to
    # 'xla' — stable tie order included; adversarial bucket skew falls back
    # to the XLA sort under a lax.cond (the compact-path spill idiom) — and
    # serve sort3 and stable2 alike (ties resolve by `packed`, which is
    # sort3's definition and stable2's tie order under its position-ordered
    # input precondition).  The round-6 pricing from measured rates has
    # them LOSING 2-3x (static slabs pay a slack-factor write amplification
    # that only hardware scatter could avoid, and TPU has none), so 'xla'
    # stays default until an on-chip window falsifies the arithmetic —
    # benchwatch carries the A/B rows.  segmin is xla-only (its scan
    # recovery needs packed as an unordered payload).  Scope — the same as
    # sort_mode's: the PACKED fast path only, i.e. the pallas wordcount
    # family and the packed gram build on both backends; the xla
    # wordcount path runs the generic 7-array build, where neither knob
    # applies (an xla-backend wordcount A/B of sort impls measures the
    # same generic sort twice — run radix A/Bs on the pallas path, as
    # bench.py does).
    sort_impl: str = "xla"
    # Map-phase IMPLEMENTATION for the pallas backend (ISSUE 6) — the seam
    # between "tokenize with an XLA fix-up chain" and "one fused kernel".
    # 'split' (default): the round-4/5 shipped path — the compact kernel
    # emits column planes, 128-lane-seam tokens are re-tokenized by an XLA
    # scan over 129 seam windows (a second read of seam bytes from HBM +
    # a per-chunk seam-table merge), and the input is transposed/padded to
    # the column view in XLA before the kernel (two more materializing
    # passes over the chunk).  'fused' consumes the RAW lane view and
    # resolves lane seams IN-KERNEL from a small seam-carry plane
    # (ops/pallas/tokenize.tokenize_fused): tokenize -> hash -> window
    # compaction in one pallas_call, one stream straight into the
    # aggregation sort — no token-plane fix-up round-trip.  Results are
    # bit-identical (tests/test_fused.py), overlong-rescue and the
    # spill->exact fallback included (the fused fallback is the same
    # kernel in pair mode).  The costcheck hbm-cost pass prices the gap
    # and ERROR-gates `wordcount_fused` strictly below the split baseline;
    # 'split' stays default until an on-chip window confirms the predicted
    # win (BENCHMARKS.md round 9 — the radix round-6 discipline).  Applies
    # to the pallas map paths (wordcount family + n-grams); the xla
    # backend has no kernel to fuse and ignores it.
    map_impl: str = "split"
    # Slot-compact the pallas kernel's column planes to S output rows per
    # block_rows-byte (block, lane) window instead of the pair path's
    # block_rows/2 (VERDICT r4 #2: the sort floor is row-count-bound).  At
    # the default block_rows=256, S=88 cuts the sorted stream 1.45x and
    # covers every window density measured on the bench corpora
    # (tools/density.py: observed max 77 ends / 256 bytes on Zipf, 52 on
    # natural text).  Denser windows (adversarial single-letter runs) spill;
    # the map then falls back to the full-resolution path for that chunk
    # under a lax.cond — always exact, ~2x cost on such chunks.  None
    # (default) resolves to 88: measured on the chip 2026-07-31, compaction
    # wins the identical workload 0.3235 vs 0.2584 GB/s (+25%) end-to-end.
    # 0 = off (the round-3 pair path).  Ignored by the xla backend and the
    # n-gram family (position-ordered consumers keep full resolution).
    compact_slots: Optional[int] = None
    # Overlong-token rescue (pallas backend only; VERDICT r3 #6): re-hash up
    # to this many >W-byte tokens per chunk exactly, via bounded XLA windows
    # at the kernel's poison positions (ops/rescue.py), so TPU runs agree
    # with the XLA backend on natural web-ish text (URLs/markup: ~0.3% of
    # tokens, ~15K per 32 MB chunk on the webby proxy — tools/overlong.py).
    # Guarded by lax.cond(overlong > 0): overlong-free corpora (both bench
    # generators) never pay.  Residuals (counts past the budget, tokens
    # longer than rescue_window - 1) stay in dropped_* accounting.  0 = off
    # (the round-3 behavior).  Requires sort_mode='sort3' (poison rows are
    # extracted off the aggregation sort's third key): None (default)
    # resolves to 1024 under sort3 and 0 under segmin, while an EXPLICIT
    # positive value with segmin is an error, not a silently dropped knob.
    rescue_overlong: Optional[int] = None
    # Rescue lookback bound in bytes: tokens up to rescue_window - 1 bytes
    # are rescued exactly.  192 covers p99.9 of webby-proxy token lengths
    # (151 bytes); raise toward 320+ for URL-heavy corpora.
    rescue_window: int = 192
    # Streaming dispatch window (ISSUE 5): how many superstep groups may be
    # dispatched-but-unretired at once.  >1 pipelines the stream — reader,
    # host staging, async H2D, and device compute of DIFFERENT groups
    # overlap, and the executor blocks only when the window is full (or at
    # checkpoint/file boundaries, where it drains) instead of eagerly per
    # dispatch.  1 = strict serial (the safe fallback and the A/B control:
    # dispatch -> retire -> next group).  With retry > 0 that reproduces
    # the pre-window loop exactly (it synced every dispatch); the retry=0
    # pre-window loop instead rode the device queue's own backpressure
    # (async, no per-group sync), so 1 there is a strictly-more-serial
    # control, not a bug-for-bug baseline.  With
    # retry > 0 the window also sets the replay granularity: known-good
    # snapshots move from per-group to window-drain points, so a mid-window
    # failure replays at most the window (checkpoint boundaries still force
    # a drain, keeping resume replay bounded by checkpoint_every).  Memory
    # cost: up to inflight_groups * superstep * chunk_bytes of staged input
    # per device kept live.
    inflight_groups: int = 4
    # Reader prefetch depth (batches the background reader may run ahead),
    # co-tuned with the window: None (default) resolves to
    # superstep * inflight_groups clamped to [2, 16] — enough host-side
    # batches to keep a full window fed without unbounded buffering.
    prefetch_depth: Optional[int] = None
    # Closed-loop autotuner mode (ISSUE 10).  'off' (default): the knobs
    # above are what you set.  'hint': the executor feeds the run's OWN
    # ledger telemetry (the PR-7 `bottleneck` verdict, the PR-8
    # `data_health` verdict, the window statistics) through the jax-free
    # rule engine in mapreduce_tpu/tuning/ and folds the recommended next
    # config for inflight_groups / prefetch_depth / superstep /
    # chunk_bytes into a `tune` ledger record (ledger v4) and the run
    # summary — the LIVE run is never changed (apply a hint by re-running
    # with the proposed flags, or let tools/autotune.py walk the loop
    # offline).  Hints are a host-local-driver feature like retry and
    # data stats: run_job_global ignores the knob.
    autotune: str = "off"
    # Skew-adaptive map-side combiner (ISSUE 11, ROADMAP item 5): what to
    # do about Zipf-hot keys BEFORE the aggregation sort sees them.
    # 'off' (default): the shipped behavior.  'hot-cache': the fused
    # compact kernel threads a small VMEM-resident hot-key cache through
    # the tile grid (the seam-carry idiom) — per lane, the first
    # ``combiner_slots`` distinct keys are cached, every further
    # occurrence of a cached key is counted IN VMEM and emits nothing,
    # and at chunk end the cache flushes one exact (key, count,
    # first-occurrence) row per resident entry into a tiny table merged
    # with the chunk's batch table.  On Zipf streams the dominant
    # duplicate runs collapse before the stable2 sort materializes them,
    # which pays for a taller kernel window (block_rows 384 -> 512 at the
    # same 128 slots: ~25% fewer sort rows per chunk at the production
    # geometry, priced and ERROR-gated by the costcheck combiner gate);
    # denser-than-budget windows keep the exact spill fallback, so
    # results stay bit-identical to 'off' on EVERY distribution.  Applies
    # to the fused pallas compact path (map_impl='fused'); elsewhere it
    # is a documented no-op, like compact_slots on the xla backend.
    # 'salt': key-salting for pathological single-key streams — the
    # packed table build XORs low position bits into key_lo so one
    # scorching key spreads over 2**COMBINER_SALT_BITS sort segments
    # (radix slab amplification on hot keys measured ~4x, BENCHMARKS.md
    # round 6), then de-salts and re-reduces the capacity-sized table
    # exactly at the reduce seam.  Envelope (ops/table.from_packed_rows
    # documents both legs): exact de-salting widens the documented
    # ~n^2/2^65 64-bit key-collision envelope by the salt factor (8x at
    # the default 3 bits; --verify-sample detects as ever — the
    # single-key streams salting exists for cannot collide at all), and
    # bit-identity to 'off' holds while distinct keys fit the batch
    # capacity (under unique overflow the cutoff falls on salted key
    # order; occurrence totals stay conserved via dropped accounting).
    # Applies to the packed fast path (pallas wordcount family + gram
    # builds on both backends), the sort_mode/sort_impl scope.
    # 'auto': resolve from the PREVIOUS run's data-health verdict — the
    # first config knob chosen by the data, not the operator: skew-hot ->
    # 'hot-cache', anything else (or no ledger history) -> 'off'.  The
    # CLI resolves it against --ledger's existing records before any
    # trace (obs/datahealth.resolve_combiner); an unresolved 'auto'
    # (library callers that never resolve) behaves as 'off'.  The
    # autotuner's `skew-hot -> enable-combiner` rule proposes the same
    # flip from measured ledgers (mapreduce_tpu/tuning/).
    combiner: str = "off"
    # Per-lane hot-key cache entries for combiner='hot-cache' (multiple
    # of 8 for sublane tiling, in [8, 32]).  None resolves to 8: the
    # cache planes stay one (8, 128) tile each, and on Zipf the top
    # handful of keys carries the collapsible mass (PR 8's top_mass
    # proxy measures exactly this).
    combiner_slots: Optional[int] = None
    # Kernel-geometry override (ISSUE 12): which certified set of Pallas
    # kernel geometries this run compiles.  None (default) = the shipped
    # constants (``DEFAULT_GEOMETRY`` — today's kernels bit-for-bit).  A
    # ``Geometry`` instance or a plain dict of its fields (validated and
    # frozen at construction) = an explicit candidate, e.g. one the
    # geometry search shortlisted (tools/geomsearch.py); a preset name
    # from ``GEOMETRY_PRESETS`` ('tall512', 'combiner16', ...) = the same
    # by name, which is how the autotuner's geometry knob round-trips
    # through ledgers and tuned.json.  'auto' = resolve from a searched
    # profile BEFORE compiling — the driver's job, like combiner='auto'
    # (the CLI resolves against tuned.json via
    # analysis/geometry.resolve_auto; an unresolved 'auto' behaves as
    # the default).  Results are BIT-IDENTICAL across certified
    # geometries (the emission set, fallback exactness and accounting
    # are geometry-independent — tested); only the cost moves, which is
    # the point.  Scope: the pallas kernel paths (wordcount family +
    # grams + the radix sort seam); the xla backend has no kernel
    # geometry and ignores it.
    geometry: object = None
    # Second-tier rescue budget (VERDICT r4 weak #4): URL-heavy text carries
    # ~15K overlong occurrences per 32 MB chunk (tools/overlong.py) — far
    # past the 1024-slot primary budget, which silently left >90% of them
    # in dropped_* unless hand-sized.  When a chunk's overlong count
    # exceeds ``rescue_slots``, a lax.cond escalates to this many slots
    # instead (the compact path's spill-fallback idiom): clean corpora pay
    # nothing, lightly-overlong chunks pay the small pass, only genuinely
    # URL-dense chunks pay the big one.  None (default) auto-sizes to
    # chunk_bytes/1024 clamped to [rescue_slots, 65536] — 32768 at the
    # default 32 MB chunk, covering the measured webby density with 2x
    # margin.  Adversarial all-overlong text can still exceed it; the
    # residual stays exactly accounted in dropped_*, as ever.
    rescue_overlong_max: Optional[int] = None
    # Deterministic fault injection (ISSUE 15): a seeded FaultPlan spec
    # string (runtime/faults.py grammar — e.g. 'seed=42,rate=0.02' or
    # 'at=dispatch:3:resource') fired at the executor's named seams
    # (reader read, staging, H2D, dispatch, token wait, checkpoint save,
    # ledger append, collective finish, process kill).  Every fired fault
    # lands as a `fault` ledger record (ledger v9), so a chaotic run can
    # be replayed exactly from its own ledger
    # (faults.FaultPlan.from_ledger).  None (default) is the provably
    # zero-cost disabled path: the executor guards every seam check with
    # one `is not None`, nothing is traced either way, and the compiled
    # programs are bit-identical to fault-plan-free builds.  Host-side
    # only — injection never reaches a jitted program.
    fault_plan: Optional[str] = None
    # Unified failure policy (ISSUE 15): None (default) maps the driver's
    # legacy `retry=N` counter onto transient+resource budgets (the exact
    # pre-ISSUE-15 semantics); a faults.FailurePolicy (or a dict of its
    # fields) sets per-class retry budgets, the exponential-backoff +
    # deterministic-jitter schedule, the completion-token wall-clock
    # timeout (a hung device reads as a typed fault instead of a silent
    # stall), and whether resource-classed exhaustion steps down the
    # degradation ladder (revert-geometry -> combiner-off -> map-split ->
    # sort-xla) before giving up.
    failure_policy: object = None
    # Collective merge strategy for the global reduction (ISSUE 20): a
    # name from ``MERGE_STRATEGIES`` ('tree', 'gather', 'keyrange',
    # 'hier-kr-tree', 'hier-tree-tree' — parallel/collectives.py builds
    # them, analysis/meshcost.py prices them), or 'auto' = resolve from
    # the redplan tuned.json profile BEFORE building the engine — the
    # driver's job, exactly the combiner/geometry 'auto' contract (the
    # CLI resolves via obs/history.resolve_prior; an unresolved 'auto'
    # behaves as 'tree', the incumbent).  The hierarchical placements
    # need a multi-axis mesh; the keyrange family needs a job with a
    # keyrange_merge hook — both checked by the Engine at build.
    merge_strategy: str = "tree"
    # Window-boundary collective overlap (ISSUE 20 leg 2): at every
    # window-drain/checkpoint boundary the executor drains each host's
    # local table into a resident merged accumulator with an async
    # partial collective and resets the local table, so the DCN transfer
    # of window N overlaps the ingest+compute of window N+1 and table
    # pressure stays bounded by the window.  Byte-exact to the
    # monolithic merge (commutative fold + min-position rule; chaos- and
    # gloo-pair-certified).  Requires retry=0 (the replay anchor
    # machinery snapshots the local state, which a partial merge has
    # partially shipped); each partial lands as an op='partial'
    # `collective` ledger record (ledger v10).  Off (default): the old
    # single-finish ledger shape, bit-identical programs.
    merge_overlap: bool = False

    def __post_init__(self) -> None:
        if self.chunk_bytes % 128 != 0:
            raise ValueError(f"chunk_bytes must be a multiple of 128, got {self.chunk_bytes}")
        if self.table_capacity < 2:
            raise ValueError("table_capacity must be >= 2")
        if self.sketch_flush_every < 1:
            raise ValueError(
                f"sketch_flush_every must be >= 1, got {self.sketch_flush_every}")
        if self.backend not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.sort_mode not in ("sort3", "stable2", "segmin"):
            raise ValueError(f"unknown sort_mode {self.sort_mode!r}")
        if self.sort_impl not in ("xla", "radix", "radix_partition"):
            raise ValueError(f"unknown sort_impl {self.sort_impl!r}")
        if self.map_impl not in ("split", "fused"):
            raise ValueError(f"unknown map_impl {self.map_impl!r}")
        if self.sort_impl != "xla" and self.sort_mode == "segmin":
            raise ValueError(
                "sort_impl='radix'/'radix_partition' requires sort_mode "
                "'sort3' or 'stable2': segmin recovers first occurrence "
                "with a segmented scan over packed-as-payload, an order "
                "the radix path's tie-by-packed contract replaces")
        if self.sort_mode == "stable2" and self.compact_slots is not None \
                and self.compact_slots != 128:
            # Mosaic requires the last block dim divisible by 128, and the
            # lane-major layout puts SLOTS last — measured: S=120 fails at
            # lowering ("block shape ... divisible by 8 and 128").  0 (off)
            # is equally invalid: the position-ordered input stable2 needs
            # only exists on the compact lane-major path.
            raise ValueError(
                "sort_mode='stable2' requires compact_slots=128 (the "
                "lane-major kernel layout puts slots in the 128-divisible "
                "block dimension); leave compact_slots unset")
        if self.compact_slots:
            # Mirrors the kernel wrapper's envelope (fail at construction,
            # not mid-trace): sublane-aligned, within the pair-path bound.
            if self.compact_slots % 8 or not 8 <= self.compact_slots <= 128:
                raise ValueError(
                    f"compact_slots must be a multiple of 8 in [8, 128], "
                    f"got {self.compact_slots}")
        if self.merge_every < 1:
            raise ValueError(
                f"merge_every must be >= 1, got {self.merge_every}")
        if self.rescue_overlong is not None and self.rescue_overlong < 0:
            raise ValueError(
                f"rescue_overlong must be >= 0, got {self.rescue_overlong}")
        if self.rescue_overlong_max is not None \
                and self.rescue_overlong_max < 0:
            raise ValueError(f"rescue_overlong_max must be >= 0, "
                             f"got {self.rescue_overlong_max}")
        if self.rescue_overlong:
            if self.sort_mode == "segmin":
                raise ValueError(
                    "rescue_overlong requires sort_mode='sort3' or "
                    "'stable2' (poison extraction needs the poison segment "
                    "position-ordered); set rescue_overlong=0 to use segmin")
        if self.rescue_slots:
            if self.backend != "xla" \
                    and self.rescue_window <= self.pallas_max_token + 1:
                raise ValueError(
                    f"rescue_window ({self.rescue_window}) must exceed "
                    f"pallas_max_token + 1 ({self.pallas_max_token + 1}) "
                    "to rescue anything")
            if self.rescue_window > 4096:
                raise ValueError(
                    f"rescue_window must be <= 4096, got {self.rescue_window}")
        if self.combiner not in ("off", "hot-cache", "salt", "auto"):
            raise ValueError(f"unknown combiner {self.combiner!r} (expected "
                             "'off', 'hot-cache', 'salt' or 'auto')")
        if self.combiner == "salt" and self.sort_mode == "segmin":
            # Fail at construction, not minutes into a trace: segmin keeps
            # packed as an unordered payload, so the de-salt has no
            # per-segment position order to recover the XOR from.
            raise ValueError(
                "combiner='salt' requires sort_mode='sort3' or 'stable2' "
                "(the de-salt reads each kept row's own position; segmin "
                "keeps packed as an unordered payload)")
        if self.combiner_slots is not None:
            # Mirrors the kernel wrapper's envelope (fail at construction,
            # not mid-trace): one or more whole (8, 128) cache tiles.
            if self.combiner_slots % 8 or not 8 <= self.combiner_slots <= 32:
                raise ValueError(
                    f"combiner_slots must be a multiple of 8 in [8, 32], "
                    f"got {self.combiner_slots}")
            if self.combiner not in ("hot-cache", "auto"):
                raise ValueError(
                    "combiner_slots sizes the hot-key cache; set "
                    "combiner='hot-cache' (or 'auto') to use it")
        if isinstance(self.geometry, dict):
            # Accept plain dicts (JSON-shaped candidates from tuned.json /
            # the search tools) but STORE the validated frozen dataclass:
            # Config is hashable (a static jit argument), so the field
            # must be too.
            object.__setattr__(self, "geometry", Geometry(**self.geometry))
        if isinstance(self.geometry, str):
            if self.geometry != "auto" \
                    and self.geometry not in GEOMETRY_PRESETS:
                raise ValueError(
                    f"unknown geometry {self.geometry!r} (expected 'auto', "
                    f"a preset name {sorted(GEOMETRY_PRESETS)}, a Geometry, "
                    "or a dict of its fields)")
        elif self.geometry is not None \
                and not isinstance(self.geometry, Geometry):
            raise ValueError(
                f"geometry must be None, 'auto', a preset name, a Geometry "
                f"or a dict, got {type(self.geometry).__name__}")
        if self.autotune not in ("off", "hint"):
            raise ValueError(f"unknown autotune mode {self.autotune!r} "
                             "(expected 'off' or 'hint')")
        if self.merge_strategy != "auto" \
                and self.merge_strategy not in MERGE_STRATEGIES:
            raise ValueError(
                f"unknown merge_strategy {self.merge_strategy!r} (expected "
                f"'auto' or one of {list(MERGE_STRATEGIES)})")
        if not isinstance(self.merge_overlap, bool):
            raise ValueError(
                f"merge_overlap must be a bool, got "
                f"{type(self.merge_overlap).__name__}")
        if self.superstep < 1:
            raise ValueError(f"superstep must be >= 1, got {self.superstep}")
        if self.inflight_groups < 1:
            raise ValueError(
                f"inflight_groups must be >= 1, got {self.inflight_groups}")
        if self.prefetch_depth is not None and self.prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {self.prefetch_depth}")
        if self.backend != "xla" and not 1 <= self.pallas_max_token <= 63:
            # 'auto' may resolve to pallas at runtime; fail at construction,
            # not mid-trace inside the kernel.  The kernel packs token length
            # into 6 bits of its sort payload, so W <= 63; tokens longer than
            # W are accounted, and the xla backend handles any length exactly.
            raise ValueError(
                f"pallas_max_token must be in [1, 63], got {self.pallas_max_token}")
        if self.backend == "pallas" and self.chunk_bytes < self.pallas_min_chunk:
            # Seam windows must not overlap: lane segment >= 2W+2 bytes.
            # ('auto' instead falls back to xla for chunks this small.)
            raise ValueError(
                f"pallas backend needs chunk_bytes >= {self.pallas_min_chunk} "
                f"for pallas_max_token={self.pallas_max_token}")
        if self.backend == "pallas" and self.chunk_bytes > (1 << 26):
            # Positions pack into 26 bits of the kernel's sort payload.
            # ('auto' instead falls back to xla above this size.)
            raise ValueError(
                f"pallas backend needs chunk_bytes <= {1 << 26} (64 MB), "
                f"got {self.chunk_bytes}")
        if self.fault_plan is not None or self.failure_policy is not None:
            # Validate at construction, not mid-stream (the geometry-dict
            # discipline); runtime/faults.py is jax-free and cheap.
            from mapreduce_tpu.runtime import faults as faults_mod

            if self.fault_plan is not None:
                if not isinstance(self.fault_plan, str):
                    raise ValueError(
                        f"fault_plan must be a spec string (or None), got "
                        f"{type(self.fault_plan).__name__}")
                faults_mod.FaultPlan.from_spec(self.fault_plan)
            if isinstance(self.failure_policy, dict):
                # Accept plain dicts (JSON-shaped) but STORE the frozen
                # dataclass: Config is hashable (a static jit argument),
                # so the field must be too (the geometry precedent).
                object.__setattr__(self, "failure_policy",
                                   faults_mod.FailurePolicy(
                                       **self.failure_policy))
            elif self.failure_policy is not None and not isinstance(
                    self.failure_policy, faults_mod.FailurePolicy):
                raise ValueError(
                    f"failure_policy must be None, a FailurePolicy or a "
                    f"dict of its fields, got "
                    f"{type(self.failure_policy).__name__}")

    @property
    def rescue_slots(self) -> int:
        """The resolved overlong-rescue budget (see ``rescue_overlong``)."""
        if self.rescue_overlong is None:
            return 0 if self.sort_mode == "segmin" else 1024
        return self.rescue_overlong

    @property
    def rescue_slots_max(self) -> int:
        """The resolved second-tier rescue budget (>= rescue_slots; 0 when
        rescue is off).  See ``rescue_overlong_max``."""
        if not self.rescue_slots:
            return 0
        if self.rescue_overlong_max is not None:
            return max(self.rescue_overlong_max, self.rescue_slots)
        # The 64K cap bounds only the AUTO sizing; an explicit primary
        # budget above it is always honored in full (clamping below
        # rescue_slots would silently shrink what the user asked for).
        return max(min(self.chunk_bytes >> 10, 1 << 16), self.rescue_slots)

    @property
    def resolved_geometry(self) -> Geometry:
        """The :class:`Geometry` this config compiles (see ``geometry``).
        An unresolved 'auto' behaves as the default — resolution against a
        searched profile is the driver's job (CLI / tools), never the
        trace's (the combiner='auto' contract)."""
        g = self.geometry
        if g is None or g == "auto":
            return DEFAULT_GEOMETRY
        if isinstance(g, str):
            return GEOMETRY_PRESETS[g]
        return g

    @property
    def geometry_label(self) -> str:
        """Compact name for ledgers / tuned profiles: 'default', a preset
        name, or 'custom' for an explicit non-preset Geometry."""
        g = self.geometry
        if g is None or g == "auto":
            return "default"
        if isinstance(g, str):
            return g
        if g == DEFAULT_GEOMETRY:
            return "default"
        return "custom"

    @property
    def resolved_compact_slots(self) -> int:
        """The resolved slot-compaction budget (see ``compact_slots``):
        88 per 256-byte window, or 128 per 384-byte window under stable2's
        lane-major geometry (both measured spill-free, tools/density.py).
        An explicit ``compact_slots`` wins over the geometry's value (the
        legacy knob precedence)."""
        if self.compact_slots is not None:
            return self.compact_slots
        g = self.resolved_geometry
        return g.compact_slots if self.sort_mode == "stable2" \
            else g.sort3_slots

    @property
    def resolved_merge_strategy(self) -> str:
        """The merge strategy the engine actually builds (see
        ``merge_strategy``): an unresolved 'auto' behaves as 'tree' (the
        incumbent) — resolution against the redplan tuned.json profile is
        the driver's job (CLI / bench), never the engine's."""
        return "tree" if self.merge_strategy == "auto" \
            else self.merge_strategy

    @property
    def resolved_combiner(self) -> str:
        """The combiner mode the trace actually runs (see ``combiner``):
        an unresolved 'auto' behaves as 'off' — resolution against a
        prior ledger is the driver's job (CLI / tools), never the
        trace's."""
        return "off" if self.combiner == "auto" else self.combiner

    @property
    def resolved_combiner_slots(self) -> int:
        """Per-lane hot-key cache entries (0 = no cache).  Nonzero only
        where the cache exists: the fused pallas compact path under
        combiner='hot-cache'.  An explicit ``combiner_slots`` wins over
        the geometry's value (the legacy knob precedence)."""
        if self.resolved_combiner != "hot-cache" or self.map_impl != "fused" \
                or not self.resolved_compact_slots:
            return 0
        return self.combiner_slots if self.combiner_slots is not None \
            else self.resolved_geometry.combiner_slots

    @property
    def resolved_salt_bits(self) -> int:
        """Low position bits XORed into key_lo by the packed table build
        under combiner='salt' (0 = no salting)."""
        return COMBINER_SALT_BITS if self.resolved_combiner == "salt" else 0

    @property
    def resolved_block_rows(self) -> int | None:
        """Compact-kernel window height in byte rows, from the resolved
        geometry: ``block_rows`` (default 384) under stable2 — the
        transposed output block stays a tile-aligned (128, 128) store —
        or ``combiner_block_rows`` (default 512) when the hot-key
        combiner runs (the cache absorbs the dominant duplicates, so
        taller windows — ~25% fewer sort rows per chunk — stay within
        the same 128-slot budget; denser windows keep the exact spill
        fallback).  Under sort3 the geometry's ``sort3_block_rows``
        applies; None (the default 256 there) defers to the kernel's own
        default so geometry-free callers stay byte-identical."""
        g = self.resolved_geometry
        if self.sort_mode != "stable2":
            return g.sort3_block_rows \
                if g.sort3_block_rows != DEFAULT_GEOMETRY.sort3_block_rows \
                else None
        return g.combiner_block_rows if self.resolved_combiner_slots \
            else g.block_rows

    @property
    def resolved_pair_block_rows(self) -> int | None:
        """Pair-resolution (full-resolution / spill-fallback) window
        height; None defers to the kernel's default (256) so the default
        geometry traces the exact pre-ISSUE-12 programs."""
        g = self.resolved_geometry
        return g.pair_block_rows \
            if g.pair_block_rows != DEFAULT_GEOMETRY.pair_block_rows \
            else None

    @property
    def resolved_aux_rows(self) -> int | None:
        """Fused seam-carry plane rows; None defers to the kernel's
        AUX_ROWS default (96)."""
        g = self.resolved_geometry
        return g.aux_rows \
            if g.aux_rows != DEFAULT_GEOMETRY.aux_rows else None

    @property
    def resolved_radix_geometry(self) -> tuple | None:
        """(bits, block_rows, slab_slack) for the radix sort seam, or
        None for the module defaults — the None-sentinel keeps the
        radix wrapper's call-time default resolution (tests shrink the
        module geometry globally) intact on default configs."""
        g = self.resolved_geometry
        d = DEFAULT_GEOMETRY
        if (g.radix_bits, g.radix_block_rows, g.radix_slab_slack) == \
                (d.radix_bits, d.radix_block_rows, d.radix_slab_slack):
            return None
        return (g.radix_bits, g.radix_block_rows, g.radix_slab_slack)

    @property
    def resolved_prefetch_depth(self) -> int:
        """The resolved reader prefetch depth (see ``prefetch_depth``):
        deep enough to feed a full dispatch window, bounded so host memory
        stays O(window)."""
        if self.prefetch_depth is not None:
            return self.prefetch_depth
        return min(16, max(2, self.superstep * self.inflight_groups))

    @property
    def pallas_min_chunk(self) -> int:
        """Smallest chunk the pallas kernel accepts (non-overlapping seam
        windows need lane segments of >= 2W+2 bytes)."""
        return 128 * (2 * self.pallas_max_token + 2)

    def resolved_backend(self) -> str:
        """Resolve 'auto' against the runtime platform.

        Deterministic for a given process (jax.default_backend() is fixed
        once initialized), so jit caches keyed on the Config stay coherent.
        """
        if self.backend != "auto":
            return self.backend
        import jax

        if (jax.default_backend() == "tpu"
                and self.pallas_min_chunk <= self.chunk_bytes <= (1 << 26)):
            return "pallas"
        return "xla"

    @property
    def batch_uniques(self) -> int:
        if self.batch_unique_capacity is not None:
            return self.batch_unique_capacity
        # At most one token per two bytes, +1 slack for the sentinel segment.
        return min(self.chunk_bytes // 2 + 1, self.table_capacity)


DEFAULT_CONFIG = Config()

# A small config for tests / the bundled-fixture CLI path.
SMALL_CONFIG = Config(chunk_bytes=1 << 10, table_capacity=1 << 10)
