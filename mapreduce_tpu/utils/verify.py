"""Host-side exact recount verification: the hash-collision detection path.

The device pipeline never materializes token strings: words are keyed by a
64-bit hash (two independent fmix32 lanes, length mixed — the by-construction
fix for the reference comparator's prefix-match defect, ``main.cu:57-67``).
Exactness therefore carries a quantified envelope: two DISTINCT words
colliding on all 64 key bits would be silently merged into one reported
entry (the identity reported is the first occurrence's; the count is the
sum).  The birthday bound puts the probability of ANY collision among n
distinct words at ~n^2 / 2^65:

  ======================  ========================
  distinct words n        P(any 64-bit collision)
  ======================  ========================
  1e6  (enwik8-scale)     ~3e-8
  1e8  (100 GB Zipf)      ~3e-4
  1e9  (Common-Crawl WET) ~3e-2
  ======================  ========================

At the BASELINE 100 GB scale the risk is real enough to want a DETECTION
path, not just arithmetic (VERDICT r4 missing #4).  This module is that
path: recount a sample of reported words EXACTLY on the host — byte-string
keyed, no hashing anywhere — and compare.  A collision is visible as a
reported count exceeding the true count (the victim word's occurrences were
absorbed); a word whose identity was absorbed shows as a missing report,
caught when its absorber mismatches.  One streaming host pass over the
corpus per verification (chunked; memory is O(sample)).

CLI: ``--verify-sample K`` runs this after any word-count run and fails
loudly on mismatch.  Cost: one host-side pass (~0.02-0.05 GB/s) — a
verification tool, not a hot path.
"""

from __future__ import annotations

import numpy as np

from mapreduce_tpu import constants

_SEP_TABLE = np.zeros(256, dtype=np.bool_)
for _b in constants.SEPARATOR_BYTES:
    _SEP_TABLE[_b] = True


def recount_exact(paths, words: list[bytes],
                  chunk_bytes: int = 1 << 24) -> dict[bytes, int]:
    """Exact occurrence counts of ``words`` across ``paths``, host-side.

    Byte-string comparison only (dict keyed on the exact bytes): immune to
    any hashing the device pipeline does, which is the point.  Streams the
    files in ``chunk_bytes`` pieces with a carry for tokens spanning chunk
    boundaries; files are independent corpora (no token spans a file seam),
    matching the reader's semantics.
    """
    if isinstance(paths, (str, bytes)):
        paths = [paths]
    targets = {w: 0 for w in words}
    for path in paths:
        carry = b""
        with open(path, "rb") as f:
            while True:
                block = f.read(chunk_bytes)
                if not block:
                    break
                buf = carry + block
                arr = np.frombuffer(buf, dtype=np.uint8)
                is_sep = _SEP_TABLE[arr]
                # Hold back the trailing unterminated token for the carry.
                last_sep = int(np.flatnonzero(is_sep)[-1]) + 1 \
                    if is_sep.any() else 0
                carry = buf[last_sep:]
                d = np.diff(np.concatenate(
                    [[True], is_sep[:last_sep], [True]]).astype(np.int8))
                starts = np.flatnonzero(d == -1)
                ends = np.flatnonzero(d == 1)
                for s, e in zip(starts, ends):
                    w = buf[s:e]
                    if w in targets:
                        targets[w] += 1
        if carry:
            w = bytes(carry)
            if w in targets:
                targets[w] += 1
    return targets


def verify_result(words: list[bytes], counts: list[int], paths,
                  sample: int = 64, seed: int = 0) -> list[tuple]:
    """Compare a run's reported (word, count) pairs against an exact host
    recount of a sample; return the mismatches as
    ``[(word, reported, true), ...]`` (empty = verified).

    The sample takes the highest-count words first (a collision's absorber
    carries the summed count, so heavy hitters are where absorbed mass is
    most visible) plus a uniform draw from the tail.

    Only ``reported > exact`` is flagged: that is the collision signature
    (absorbed occurrences inflate the absorber).  ``reported < exact`` is
    a legitimate documented envelope — rescue-budget overflow or
    table-capacity spill report partial counts with the remainder in
    ``dropped_*`` — and must not masquerade as corruption.
    """
    n = len(words)
    if n == 0:
        return []
    k = min(sample, n)
    by_count = sorted(range(n), key=lambda i: -counts[i])
    head = by_count[: k // 2]
    rng = np.random.default_rng(seed)
    tail_pool = by_count[k // 2:]
    tail = list(rng.choice(len(tail_pool), size=min(k - len(head),
                                                    len(tail_pool)),
                           replace=False)) if tail_pool else []
    idx = head + [tail_pool[int(i)] for i in tail]
    chosen = [words[i] for i in idx]
    true = recount_exact(paths, chosen)
    return [(words[i], counts[i], true[words[i]])
            for i in idx if counts[i] > true[words[i]]]
