"""Pure-Python reference implementations ("oracles") for tests.

The reference repo has no tests (SURVEY §4); its implied methodology is one
manual golden run over ``test.txt``.  We instead check every device path
against these host oracles, which implement the *intended* semantics of the
reference (whitespace-split word count, insertion-ordered report,
``main.cu:187-218``) without its defects (prefix compare, capacity overflows).
"""

from __future__ import annotations

from mapreduce_tpu import constants

_SEPARATORS = bytes(constants.SEPARATOR_BYTES)


def split_words(data: bytes) -> list[bytes]:
    """All tokens in order, splitting on the framework's separator set."""
    out = []
    word = bytearray()
    for b in data:
        if b in _SEPARATORS:
            if word:
                out.append(bytes(word))
                word = bytearray()
        else:
            word.append(b)
    if word:
        out.append(bytes(word))
    return out


def word_counts(data: bytes) -> dict[bytes, int]:
    """Insertion-ordered {word: count} — the golden semantics (SURVEY §2)."""
    counts: dict[bytes, int] = {}
    for w in split_words(data):
        counts[w] = counts.get(w, 0) + 1
    return counts


def total_count(data: bytes) -> int:
    return len(split_words(data))
