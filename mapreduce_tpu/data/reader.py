"""Boundary-aligned sharded ingest.

Replaces the reference's host input pipeline — ``fopen``/``fgets`` with a
100-byte line buffer and per-char splitting (``main.cu:166-207``) — with a
memory-mapped, token-boundary-aligned chunker: each streaming step yields a
``uint8[n_shards, chunk_bytes]`` batch (one row per device) plus the absolute
file offset of every row, so device-side token positions can be mapped back to
exact byte ranges for string recovery.

Alignment rule: a row may only end at a separator byte, so no token ever spans
two rows and no cross-chunk fix-up exchange is needed (SURVEY §7 "hard parts":
the seam problem is solved at ingest, where the bytes already are, instead of
with a device-side halo exchange).  Tokens longer than ``max_token_bytes`` are
force-split (and counted as two tokens) rather than stalling the pipeline; the
reference would overflow a stack buffer in that case (``main.cu:184,199``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterator

import numpy as np

from mapreduce_tpu import constants

_SEP_LUT = np.zeros(256, dtype=bool)
for _b in constants.SEPARATOR_BYTES:
    _SEP_LUT[_b] = True


@dataclasses.dataclass(frozen=True)
class Batch:
    """One streaming step's input across all shards."""

    data: np.ndarray  # uint8[n_shards, chunk_bytes], zero-padded rows
    base_offsets: np.ndarray  # int64[n_shards], absolute file offset of row starts
    lengths: np.ndarray  # int64[n_shards], valid bytes per row
    step: int
    file_index: int = 0  # which corpus member this batch came from: a batch
    # never spans files, so jobs with cross-row state (grep's line carry) can
    # reset at the hard file boundary


def _aligned_cuts(buf: np.ndarray, n_shards: int, chunk_bytes: int,
                  max_token_bytes: int, at_eof: bool) -> list[int]:
    """Cut points (ascending, len n_shards) so every row ends at a separator
    (or at a force-split after max_token_bytes of unbroken non-separators).
    Only the file's true end (``at_eof``) may cut mid-buffer unaligned — a
    buffer end mid-file is a carry point, not a token boundary."""
    is_sep = _SEP_LUT[buf]
    cuts = []
    prev = 0
    n = buf.shape[0]
    for i in range(n_shards):
        ideal = min(prev + chunk_bytes, n)
        if ideal >= n and at_eof:
            cuts.append(n)
            prev = n
            continue
        lo = max(prev, ideal - max_token_bytes)
        window = is_sep[lo:ideal]
        hits = np.flatnonzero(window)
        # Cut just after the last separator in the window; if the window is
        # all token bytes, force-split at the ideal point.
        cut = lo + int(hits[-1]) + 1 if hits.size else ideal
        cuts.append(cut)
        prev = cut
    return cuts


def iter_batches(path: str, n_shards: int, chunk_bytes: int,
                 max_token_bytes: int = 4096, start_offset: int = 0,
                 start_step: int = 0, use_native: bool = True,
                 end_offset: int | None = None) -> Iterator[Batch]:
    """Stream a file as boundary-aligned [n_shards, chunk_bytes] batches.

    ``start_offset``/``start_step`` support checkpoint resume: iteration
    continues from a previously reported cursor.  ``end_offset`` bounds the
    stream to the half-open byte range ``[start_offset, end_offset)`` — the
    multi-host case, where each host reads only its own
    :func:`...parallel.distributed.host_byte_range` (pre-aligned via
    ``align_range_to_separator``, so the range end IS a token boundary and
    the usual EOF alignment rule applies at it).  The batch fill runs in the
    native chunker (:mod:`mapreduce_tpu.native`) when available, falling back
    to the pure-numpy path; both produce byte-identical batches
    (tests/test_native.py asserts parity).
    """
    from mapreduce_tpu import native

    mm = np.memmap(path, dtype=np.uint8, mode="r") if _file_size(path) else None
    total = 0 if mm is None else mm.shape[0]
    if end_offset is not None:
        total = min(total, end_offset)
    offset = start_offset
    step = start_step
    stride = n_shards * chunk_bytes
    while offset < total:
        raw = np.asarray(mm[offset: min(offset + stride, total)])
        at_eof = offset + raw.shape[0] >= total
        data = np.empty((n_shards, chunk_bytes), dtype=np.uint8)
        bases = np.empty((n_shards,), dtype=np.int64)
        lengths = np.empty((n_shards,), dtype=np.int64)
        consumed = None
        if use_native:
            consumed = native.fill_batch(raw, at_eof, n_shards, chunk_bytes,
                                         max_token_bytes, data.reshape(-1),
                                         bases, lengths)
        if consumed is None:
            data[:] = 0
            cuts = _aligned_cuts(raw, n_shards, chunk_bytes, max_token_bytes,
                                 at_eof=at_eof)
            prev = 0
            for i, cut in enumerate(cuts):
                row = raw[prev:cut]
                data[i, : row.shape[0]] = row
                bases[i] = prev
                lengths[i] = row.shape[0]
                prev = cut
            consumed = cuts[-1]
        bases += offset
        yield Batch(data=data, base_offsets=bases, lengths=lengths, step=step)
        if consumed == 0:  # defensive: cannot happen (first cut >= 1 byte)
            raise RuntimeError("ingest made no progress")
        offset += consumed
        step += 1


def iter_batches_multi(paths, n_shards: int, chunk_bytes: int,
                       max_token_bytes: int = 4096, start_offset: int = 0,
                       start_step: int = 0, use_native: bool = True,
                       end_offset: int | None = None) -> Iterator[Batch]:
    """Stream a MULTI-FILE corpus (real corpora — e.g. Common Crawl WET
    shards, BASELINE.md — are many files) as one logical byte stream.

    Offsets (``start_offset``/``end_offset``/``Batch.base_offsets``) are
    *virtual*: positions in the concatenation of the files in order.  Files
    are chunked independently — a file's end is a hard token boundary, so no
    token ever spans two files and no join bytes are inserted.  Step
    numbering continues across files (chunk ids stay globally unique).
    """
    if isinstance(paths, (str, bytes, os.PathLike)):
        paths = [paths]
    sizes = [_file_size(p) for p in paths]
    step = start_step
    file_start = 0
    for fi, (path, size) in enumerate(zip(paths, sizes)):
        file_end = file_start + size
        local_lo = max(0, start_offset - file_start)
        local_hi = size if end_offset is None \
            else min(size, max(0, end_offset - file_start))
        if local_lo < local_hi:
            for b in iter_batches(path, n_shards, chunk_bytes,
                                  max_token_bytes=max_token_bytes,
                                  start_offset=local_lo, start_step=step,
                                  end_offset=local_hi, use_native=use_native):
                yield Batch(data=b.data,
                            base_offsets=b.base_offsets + file_start,
                            lengths=b.lengths, step=b.step, file_index=fi)
                step = b.step + 1
        file_start = file_end


def read_words_at_multi(paths, spans: list[tuple[int, int]]) -> list[bytes]:
    """Multi-file :func:`read_words_at`: spans use virtual corpus offsets."""
    if isinstance(paths, (str, bytes, os.PathLike)):
        return read_words_at(paths, spans)
    if not spans:
        return []
    starts = np.cumsum([0] + [_file_size(p) for p in paths])
    offs = np.asarray([s[0] for s in spans], dtype=np.int64)
    file_idx = np.searchsorted(starts, offs, side="right") - 1
    # Group spans by file with one argsort (not a per-file rescan).
    order = np.argsort(file_idx, kind="stable")
    out: list[bytes | None] = [None] * len(spans)
    i = 0
    while i < len(order):
        k = int(file_idx[order[i]])
        j = i
        while j < len(order) and file_idx[order[j]] == k:
            j += 1
        group = order[i:j]
        local = [(int(offs[g] - starts[k]), spans[g][1]) for g in group]
        for g, word in zip(group, read_words_at(paths[k], local)):
            out[g] = word
        i = j
    return out  # type: ignore[return-value]


def prefetch(batches: Iterator[Batch], depth: int = 2) -> Iterator[Batch]:
    """Run an iterator in a background thread, ``depth`` items ahead.

    Double-buffers ingest against device compute (SURVEY §7 step 4): while
    the devices chew on step N, the host memmap-reads and boundary-aligns
    step N+1.  The producer thread is daemonic and bounded by a queue, so an
    abandoned consumer cannot leak unbounded memory; producer exceptions are
    re-raised at the consumer's next pull.

    Wait accounting (ISSUE 2): the producer records per-batch production
    time and time spent blocked on a FULL queue into the process metrics
    registry.  Together with the executor's ``read_wait`` phase (consumer
    blocked on an EMPTY queue) this classifies the pipeline — large
    ``read_wait`` = reader-bound, large ``stall_full_queue`` = producer
    comfortably ahead (device-bound).  Host-side dict updates only.
    """
    import queue
    import threading
    import time as _time

    from mapreduce_tpu.obs import registry as _obs_registry

    reg = _obs_registry.get_registry()
    # The configured depth is part of the pipeline telemetry (ISSUE 5):
    # read_wait with a deep queue means the producer itself is the floor,
    # with a shallow one it may just be the queue size.
    reg.gauge("reader.prefetch_depth").set(depth)
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END, _ERR = object(), object()

    def put(item) -> bool:
        """Bounded put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce() -> None:
        try:
            t_prev = _time.perf_counter()
            for b in batches:
                t_ready = _time.perf_counter()
                reg.observe("reader.produce_seconds", t_ready - t_prev)
                reg.counter("reader.batches_prefetched").inc()
                if not put(b):
                    return  # consumer abandoned the stream
                t_prev = _time.perf_counter()
                # put() returned: anything beyond the enqueue itself was
                # blocking on a full queue — the producer running ahead.
                reg.counter("reader.stall_full_queue_seconds").inc(
                    t_prev - t_ready)
            put(_END)
        except BaseException as e:  # surfaced on the consumer side
            put((_ERR, e))

    t = threading.Thread(target=produce, daemon=True, name="ingest-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
                raise item[1]
            yield item
    finally:
        # Early exit (consumer error/close): release the producer so it does
        # not sit blocked on a full queue holding batches and the memmap.
        stop.set()


def _file_size(path: str) -> int:
    return os.path.getsize(path)


def read_words_at(path: str, spans: list[tuple[int, int]]) -> list[bytes]:
    """Host-side string recovery: exact bytes for (absolute_offset, length)."""
    if not spans:
        return []
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    return [bytes(mm[off: off + ln]) for off, ln in spans]


def scan_gram_lengths_bytes(source: bytes | np.ndarray, offsets,
                            n: int) -> list[int]:
    """In-memory :func:`scan_gram_lengths`: spans of the n-entry grams
    starting at ``offsets`` of one whole buffer (no chunk cuts — the
    single-buffer paths never force-split).  Used by
    ``models.wordcount.recover_result`` for long-span gram entries
    (length = ``SEAM_GRAM_LENGTH``, the >= 127-byte spans the packed gram
    build cannot store).  One vectorized pass over the buffer however many
    offsets."""
    arr = np.frombuffer(source, dtype=np.uint8) if isinstance(source, bytes) \
        else np.asarray(source, dtype=np.uint8)
    if arr.shape[0] == 0:
        return [0 for _ in offsets]
    sep = _SEP_LUT[arr]
    nxt = np.concatenate([sep[1:], np.array([True])])
    epos = np.flatnonzero(~sep & nxt)  # entry end positions (inclusive)
    offs = np.asarray(list(offsets), dtype=np.int64)
    if len(epos) == 0:  # all-separator buffer: spans run to the end
        return [int(arr.shape[0] - o) for o in offs]
    # A gram that exists has n entry ends at/after its start; if the
    # buffer ends mid-stream the remaining bytes are the span.
    j = np.searchsorted(epos, offs) + n - 1
    in_range = j < len(epos)
    ends = np.where(in_range, epos[np.minimum(j, len(epos) - 1)] + 1,
                    arr.shape[0])
    return [int(e - o) for e, o in zip(ends, offs)]


def scan_gram_lengths(paths, offsets, n: int,
                      cut_offsets=None) -> list[int]:
    """Byte lengths of the n-entry grams starting at virtual corpus offsets.

    Host-side recovery for cross-chunk gram entries (length =
    ``SEAM_GRAM_LENGTH``): the device knows each gram's absolute start but
    not its end (it lies in a later chunk whose row base only the host
    tracks), so the host scans forward from the start — which must be an
    entry start — to the end of the n-th stream entry.  Separator runs
    between tokens are unbounded, so the read window doubles until the gram
    completes (or the file ends: the remaining bytes are the span).  Grams
    never cross file boundaries (the executor resets the seam carry there),
    so each scan stays within the file containing its offset.

    ``cut_offsets``: absolute chunk-row base offsets of the run.  The
    reader force-splits a separator-free run longer than its alignment
    window at a row cut, making BOTH halves stream entries — so a cut
    inside a run is an entry end too, and the scan counts it to match the
    device's entry stream (without this, a seam span over a force-split
    run would swallow the whole run plus the following real token).

    Batch API: one file-size pass + one memmap per touched file, however
    many offsets (a full table of seam entries is recovered in one call).
    """
    single = isinstance(paths, (str, bytes, os.PathLike))
    plist = [paths] if single else list(paths)
    starts = np.cumsum([0] + [_file_size(p) for p in plist])
    cuts = np.sort(np.asarray(cut_offsets, dtype=np.int64)) \
        if cut_offsets is not None else np.empty(0, np.int64)
    offs = np.asarray(list(offsets), dtype=np.int64)
    file_idx = np.searchsorted(starts, offs, side="right") - 1
    mms: dict[int, np.memmap] = {}
    out: list[int] = []
    for j, off in enumerate(offs):
        k = int(file_idx[j])
        if k not in mms:
            mms[k] = np.memmap(plist[k], dtype=np.uint8, mode="r")
        mm = mms[k]
        base, local, size = int(starts[k]), int(off - starts[k]), mm.shape[0]
        win = 4096
        while True:
            end = min(local + win, size)
            buf = np.asarray(mm[local:end])
            sep = _SEP_LUT[buf]
            at_eof = end >= size
            # Entry ends: non-separator followed by separator (or EOF)...
            nxt = np.concatenate([sep[1:], np.array([True])]) if at_eof \
                else sep[1:]
            ends = ~sep[: len(nxt)] & nxt
            # ...plus force-split ends: a chunk cut at absolute c ends the
            # entry at byte c-1 when that byte is a non-separator (if the
            # following byte is a separator this is already an end).
            lo_v = base + local
            ci = cuts[(cuts > lo_v) & (cuts <= lo_v + len(nxt))] - lo_v - 1
            if len(ci):
                ends[ci[~sep[ci]]] = True
            epos = np.flatnonzero(ends)
            if len(epos) >= n:
                out.append(int(epos[n - 1]) + 1)
                break
            if at_eof:  # corpus ends mid-gram: remaining bytes are the span
                out.append(int(len(buf)))
                break
            win *= 2
    return out
