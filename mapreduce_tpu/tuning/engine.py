"""Closed-loop config autotuner: a run's own ledger -> the next config
(ISSUE 10 tentpole).

PR 5 built the bounded in-flight dispatch window, PR 7 the measured
critical-path ``bottleneck`` verdict, PR 8 the ``data_health`` verdict —
both documented as "the fitness signal the window autotuner reads".  This
module closes the loop: a **pure, deterministic function of ledger
records** proposes the next values for the tuned knobs

    ``inflight_groups`` / ``prefetch_depth`` / ``superstep`` /
    ``chunk_bytes`` / ``combiner`` / ``geometry``

via a verdict-keyed rule table (below), in the spirit of CUDA-LLM's
search-loop-with-a-certifier-as-fitness-gate and the config-search framing
of "Synthesizing Optimal Parallelism Placement and Reduction Strategies"
(PAPERS.md).  Two driving modes consume it:

* **offline search** (``tools/autotune.py``): :func:`search` walks the
  rule table over N short streamed probe passes until converged, budget-
  exhausted, or the oscillation guard trips, emitting a ``tuned.json``
  profile keyed by (family, backend, corpus shape);
* **online hints** (``Config(autotune='hint')`` / CLI ``--autotune``):
  the executor calls :func:`propose` on the run's own records and folds
  the recommendation into a ``tune`` ledger record (ledger v4) and the
  run summary — the live run itself is never changed.

The rule table (first match wins; every raising rule converges at its
cap instead of proposing a no-op):

==================  =======================================  ============
rule                trigger                                  move
==================  =======================================  ============
no-signal           no phases/pipeline/timeline at all       stop
revert-geometry     data verdict ``spill-bound``, geometry   geometry
                    non-default (the searched window is too  default
                    tall for this corpus's density)
enable-combiner     data verdict ``skew-hot``, combiner off  combiner on
grow-chunk          data verdict ``occupancy-starved``       chunk ×2
shrink-chunk        data verdict ``table-pressure``          chunk ÷2
converged           projected bottleneck saving < 10% span   stop
raise-prefetch      bottleneck resource ``reader``           prefetch ×2
feed-window         h2d/staging-bound, window never filled   prefetch ×2
raise-inflight      bottleneck ``h2d`` or ``staging``        inflight ×2
try-superstep       device-bound AND window always full      superstep ×2
try-geometry        device-bound, window NOT saturated,      geometry
                    window occupancy <= 70%, geometry        'tall512'
                    default, combiner off (compute is the
                    ceiling and the windows have headroom:
                    taller windows delete sort rows —
                    ISSUE 12, the PR-11 arithmetic)
device-bound        device-bound, saturated or no headroom   stop
no-rule             nothing actionable (e.g. ``retire``)     stop
==================  =======================================  ============

Data-shape verdicts whose knobs are OUTSIDE the tuned set (spill-bound →
``--compact-slots``, rescue-heavy → the rescue budgets) are noted in the
decision trail but never produce a move: the tuner must not thrash
pipeline knobs to chase a data problem.  The same discipline covers the
cross-host straggler verdict (ISSUE 13): a merged fleet ledger's
straggler-bound verdict rides the trail as a note — its knob (data
rebalancing across hosts) is ROADMAP item 3's, and chasing it stays
future work.  collective-bound GRADUATED in ISSUE 20: the runtime now
owns two knobs that answer it directly — ``merge_overlap`` (window-
boundary partial merges hide the finish inside the map stream) and
``merge_strategy`` (the placed reduction program) — so the
``fleet-collective-bound`` rule proposes enabling overlap first, then
switching the strategy, instead of just pointing at ROADMAP item 3.
skew-hot GRADUATED the same way
in ISSUE 11: the ``combiner`` knob is tuned now, so the
``enable-combiner`` rule flips the map-side hot-key cache on instead of
just pointing at it.  The
``table-pressure`` move is deliberately modest for the same reason — the
real knob is ``--table-capacity``, which is not tuned here; halving the
chunk shrinks the per-merge batch table that competes for slots, and the
reason string says so.

Every proposal is validated through the real ``Config.__post_init__``
rules (:func:`validate_knobs`), and — in the offline driver — every
ACCEPTED step still runs through the costcheck gate before it can touch
a device (``tools/autotune.py``).  The whole module is deliberately
jax-free (it imports only the jax-free corners of the package: config
validation, ``obs/timeline``, ``obs/datahealth``), so it unit-tests
against synthetic ledgers exactly like ``timeline.py``/``datahealth.py``.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional

from mapreduce_tpu.config import (MERGE_STRATEGIES, Config, DEFAULT_CONFIG,
                                  GEOMETRY_PRESETS)
from mapreduce_tpu.obs import datahealth, history, timeline

#: Bumped when the rule table / proposal schema changes shape.
#: 2 = ISSUE 20: merge_strategy/merge_overlap joined the tuned set and
#: the fleet-collective-bound rule fires instead of noting.
TUNER_VERSION = 2

#: The knobs this tuner owns, in proposal order.  ``combiner`` (ISSUE 11)
#: and ``geometry`` (ISSUE 12) are the non-numeric knobs: mode/preset
#: strings moved by the data-shape and device rules, not doubled/halved
#: by the pipeline ones.  Geometry knob values are 'default' or a
#: ``config.GEOMETRY_PRESETS`` name — the tuned.json / ledger round-trip
#: form (explicit Geometry dicts belong to the offline geomsearch
#: driver, not the rule table).  ``merge_strategy`` / ``merge_overlap``
#: (ISSUE 20) are the placed-reduction knobs the fleet-collective-bound
#: rule moves: a ``config.MERGE_STRATEGIES`` name and an 'off'/'on'
#: string (the tuned.json round-trip form of the Config bool).
KNOBS = ("inflight_groups", "prefetch_depth", "superstep", "chunk_bytes",
         "combiner", "geometry", "merge_strategy", "merge_overlap")

#: Knobs that hold integers (everything result() must int-coerce).
_INT_KNOBS = ("inflight_groups", "prefetch_depth", "superstep",
              "chunk_bytes")

# Move envelopes.  The caps are the measured/documented envelopes, not
# arbitrary: prefetch's auto-resolution clamps at 16 (Config), a >16-deep
# window holds >16 chunks of staged input live (the documented memory
# cost), superstep 32 at the default chunk stages 1 GB per device per
# dispatch, and chunk_bytes beyond 64 MB is refused by the pallas packing
# envelope while below 1 MB dispatch overhead dominates (BENCHMARKS.md
# round 4).
INFLIGHT_MAX = 16
PREFETCH_MAX = 16
SUPERSTEP_MAX = 32
CHUNK_MIN = 1 << 20
CHUNK_MAX = 1 << 26

#: A bottleneck whose projected saving is below this share of the span is
#: not worth a config move: the pipeline is within 10% of its overlap
#: ceiling and further moves chase noise.
CONVERGED_SAVING_FRAC = 0.10
#: ``full_frac`` at or above this = the window hit capacity on nearly
#: every dispatch (the obs_report "always-full" gate).
ALWAYS_FULL_FRAC = 0.9
#: Mean stable2 window occupancy at or below which a taller window is
#: worth probing (ISSUE 12): the 384 -> 512 step grows each window 1.33x,
#: so <= 70% mean occupancy leaves headroom before the slot budget —
#: and the exact spill fallback covers the tail either way.
GEOMETRY_OCC_CEIL = 0.70
#: The taller-window preset try-geometry proposes (config.GEOMETRY_PRESETS).
GEOMETRY_TALL = "tall512"

#: Data-health verdicts whose knob is outside the tuned set: noted in the
#: trail, never moved on (verdict -> the knob that actually owns it).
#: skew-hot left this set in ISSUE 11: the combiner knob now answers it.
_FOREIGN_DATA_KNOBS = {
    "spill-bound": "--compact-slots",
    "rescue-heavy": "--max-token-bytes / the rescue budgets",
}


def default_knobs() -> dict:
    """The shipped defaults as a knob dict (the search starting point)."""
    return {"inflight_groups": DEFAULT_CONFIG.inflight_groups,
            "prefetch_depth": DEFAULT_CONFIG.resolved_prefetch_depth,
            "superstep": DEFAULT_CONFIG.superstep,
            "chunk_bytes": DEFAULT_CONFIG.chunk_bytes,
            "combiner": DEFAULT_CONFIG.combiner,
            "geometry": DEFAULT_CONFIG.geometry_label,
            "merge_strategy": DEFAULT_CONFIG.merge_strategy,
            "merge_overlap": "on" if DEFAULT_CONFIG.merge_overlap
            else "off"}


def validate_knobs(knobs: dict, backend: str = "auto") -> None:
    """Run a knob dict through the REAL ``Config.__post_init__`` rules
    (chunk alignment, window/prefetch bounds, backend envelopes) — every
    proposal must survive this before anything acts on it.  Raises
    ``ValueError`` exactly as Config would."""
    if backend not in ("auto", "xla", "pallas"):
        backend = "auto"  # resolved/CLI names like 'cpu' validate generically
    geometry = str(knobs.get("geometry", "default"))
    overlap = str(knobs.get("merge_overlap", "off"))
    if overlap not in ("off", "on"):
        raise ValueError(f"merge_overlap knob must be 'off' or 'on', "
                         f"got {overlap!r}")
    Config(chunk_bytes=int(knobs["chunk_bytes"]),
           superstep=int(knobs["superstep"]),
           inflight_groups=int(knobs["inflight_groups"]),
           prefetch_depth=int(knobs["prefetch_depth"]),
           combiner=str(knobs.get("combiner", "off")),
           geometry=None if geometry == "default" else geometry,
           merge_strategy=str(knobs.get("merge_strategy", "tree")),
           merge_overlap=overlap == "on",
           backend=backend)


# -- ledger records -> the signal dict the rule table reads -----------------

def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


#: Phase-delta fallback when a run carries no ``group`` records (batch
#: ledgers, pre-v2 ledgers, the ledgerless hint path): which resource
#: each streaming phase blames.  The canonical table lives in
#: ``obs/timeline.py`` (jax-free) so ``tools/obswatch.py``'s
#: bound-so-far fallback reads the exact same rule.
_PHASE_LANE = timeline.PHASE_LANE


def _phase_resource(phases: dict) -> Optional[str]:
    lanes: dict = {}
    for phase, lane in _PHASE_LANE.items():
        v = _num(phases.get(phase))
        if v:
            lanes[lane] = lanes.get(lane, 0.0) + v
    if not lanes:
        return None
    return max(lanes, key=lambda ln: lanes[ln])


def derive_signals(records: Iterable[dict],
                   run_id: Optional[str] = None) -> dict:
    """One run's ledger records -> the flat signal dict the rule table
    reads: the run's config knobs (run_start + run_end ``pipeline``), the
    measured ``bottleneck`` verdict (reconstructed from ``group`` records
    when present, else a phase-delta fallback), the window statistics,
    and the data-health classification.  Missing pieces degrade to None —
    absence of a signal is itself information, never an error (the ledger
    forward-compat contract)."""
    # Run selection + merged-fleet host anchoring live in the run-history
    # warehouse now (ISSUE 14: obs/history.resolve_prior is the one
    # prior-run read): the chosen run's records — and, on a merged fleet
    # stream, ONE host's view of them (reconstructing a timeline from
    # every host's records would fuse the lanes into a chimera no host
    # ran) — come back as the prior's run view.
    prior = history.resolve_prior(records=records, run_id=run_id)
    chosen, recs, fleet = prior["run_id"], prior["run_records"], \
        prior["fleet"]
    start = next((r for r in recs if r.get("kind") == "run_start"), None)
    end = next((r for r in recs if r.get("kind") == "run_end"), None)
    phases = dict((end or {}).get("phases") or {})
    if not phases:  # crashed run: fold the step deltas that DID land
        for r in recs:
            if r.get("kind") == "step":
                for k, v in (r.get("phases") or {}).items():
                    if _num(v) is not None:
                        phases[k] = phases.get(k, 0.0) + float(v)
    pipeline = (end or {}).get("pipeline") or None

    config: dict = {}
    for key in ("chunk_bytes", "superstep"):
        v = _num((start or {}).get(key))
        if v is not None:
            config[key] = int(v)
    for key in ("inflight_groups", "prefetch_depth"):
        v = _num((pipeline or {}).get(key))
        if v is not None:
            config[key] = int(v)
    combiner = (start or {}).get("combiner")
    if isinstance(combiner, str):
        config["combiner"] = combiner
    # Placed-reduction knobs (ISSUE 20): run_start stamps the RESOLVED
    # strategy (never 'auto') and merge_overlap only when true.
    ms = (start or {}).get("merge_strategy")
    if isinstance(ms, str) and ms in MERGE_STRATEGIES:
        config["merge_strategy"] = ms
    if (start or {}).get("merge_overlap") is True:
        config["merge_overlap"] = "on"
    geometry = (start or {}).get("geometry")
    geometry_custom = False
    if isinstance(geometry, str) \
            and (geometry == "default" or geometry in GEOMETRY_PRESETS):
        config["geometry"] = geometry
    elif geometry not in (None, ""):
        # A 'custom' label, a spec dict, or a future shape: the rule
        # table moves preset names only, and a proposal echoing an
        # unknowable value back through validate_knobs would kill the
        # whole hint (Config rejects it).  The knob reads as 'default'
        # for validation purposes and try-geometry is gated off below —
        # an explicit candidate is the operator's (or the geomsearch
        # driver's) choice to keep, not this table's to overwrite.
        geometry_custom = True

    art = timeline.reconstruct(recs, run_id=chosen)
    bottleneck = art["bottleneck"] if art else None
    resource = source = None
    saving_frac = None
    if bottleneck:
        resource, source = bottleneck.get("resource"), "timeline"
        span = _num(bottleneck.get("span_s"))
        saving = _num(bottleneck.get("projected_saving_s"))
        if span and saving is not None:
            saving_frac = round(saving / span, 4)
    elif phases:
        resource, source = _phase_resource(phases), "phases"

    gb_per_s = _num((end or {}).get("gb_per_s"))
    if gb_per_s is None:
        b, el = _num((end or {}).get("bytes")), \
            _num((end or {}).get("elapsed_s"))
        if b and el:
            gb_per_s = round(b / 1e9 / el, 6)

    health = datahealth.classify_run(recs, run_id=chosen)
    window_occ = ((health or {}).get("signals") or {}).get(
        "window_occupancy")
    # Fleet verdict (ISSUE 13; `fleet` was detected above, before the
    # host anchoring): noted in the decision trail, never chased — the
    # knobs that answer a straggler-/collective-bound fleet (data
    # rebalancing, reduction strategy/schedule) are ROADMAP item 3's,
    # not this table's.
    fleet_verdict = ((fleet or {}).get("fleet_bottleneck") or {}).get(
        "verdict")
    return {
        "run_id": chosen,
        "gb_per_s": gb_per_s,
        "config": config,
        "backend": (start or {}).get("backend"),
        "phases": phases,
        "pipeline": pipeline,
        "bottleneck": bottleneck,
        "resource": resource,
        "resource_source": source,
        "saving_frac": saving_frac,
        "overlap_fraction": _num((pipeline or {}).get("overlap_fraction")),
        "depth_max": _num((pipeline or {}).get("depth_max")),
        "full_frac": _num((pipeline or {}).get("full_frac")),
        "data_health": health,
        "data_verdict": (health or {}).get("verdict"),
        "window_occupancy": window_occ,
        "geometry_custom": geometry_custom,
        "fleet_bottleneck": fleet_verdict if isinstance(fleet_verdict, str)
        else None,
    }


# -- the rule table ----------------------------------------------------------

def propose(records: Iterable[dict], run_id: Optional[str] = None,
            current: Optional[dict] = None) -> dict:
    """Ledger records -> the next-config proposal: a pure, deterministic
    function (same records in, same proposal out — the unit-test
    contract).  ``current`` overrides the knob values derived from the
    records (the search loop knows what it actually ran; a ledger may
    predate a knob).

    Returns a dict with ``current``/``proposal`` (all four knobs),
    ``changed`` (knob -> [old, new]), the fired ``rule`` + human
    ``reason``, ``converged``, the compact ``signals`` the rules read,
    and ``trail`` — every rule CONSIDERED, in order, with whether it
    fired and why (the machine-readable decision trail).
    """
    sig = derive_signals(records, run_id)
    cur = default_knobs()
    cur.update({k: v for k, v in sig["config"].items() if k in cur})
    if current:
        cur.update({k: (int(v) if k in _INT_KNOBS else str(v))
                    for k, v in current.items() if k in cur})

    trail: List[dict] = []

    def consider(rule: str, fired: bool, why: str) -> bool:
        trail.append({"rule": rule, "fired": fired, "why": why})
        return fired

    def result(rule: str, reason: str, changes: Optional[dict] = None,
               converged: bool = False) -> dict:
        prop = dict(cur)
        changed = {}
        for k, v in (changes or {}).items():
            v = int(v) if k in _INT_KNOBS else str(v)
            if v != cur[k]:
                changed[k] = [cur[k], v]
                prop[k] = v
        return {
            "tuner_version": TUNER_VERSION,
            "run_id": sig["run_id"],
            "current": cur,
            "proposal": prop,
            "changed": changed,
            "rule": rule,
            "reason": reason,
            "converged": bool(converged or not changed),
            "signals": {k: sig[k] for k in
                        ("resource", "resource_source", "saving_frac",
                         "overlap_fraction", "depth_max", "full_frac",
                         "data_verdict", "window_occupancy", "gb_per_s",
                         "fleet_bottleneck")},
            "trail": trail,
        }

    resource = sig["resource"]
    saving = sig["saving_frac"]
    verdict = sig["data_verdict"]
    depth_max = sig["depth_max"]
    full_frac = sig["full_frac"]

    # 0. Fleet verdict (ISSUE 13 -> ISSUE 20).  A collective-bound fleet
    #    GRADUATED from note to move: the runtime owns the two knobs that
    #    answer it — window-boundary overlap hides the finish inside the
    #    map stream for free (byte-exact; requires retry=0), and the
    #    merge strategy reshapes what is left.  Overlap first: it costs
    #    nothing to try and the verdict already charges only the VISIBLE
    #    collective share, so a still-collective-bound overlapped run has
    #    genuinely unhidable finish time worth a strategy move.
    if sig.get("fleet_bottleneck") == "collective-bound":
        if consider("fleet-collective-bound",
                    cur["merge_overlap"] == "off",
                    "collective-bound fleet; window-boundary overlap off"):
            return result(
                "fleet-collective-bound",
                "the visible collective finish dominates the fleet span: "
                "enable window-boundary overlap so partial merges ride "
                "inside the map stream (byte-exact to the monolithic "
                "merge; requires retry=0)",
                {"merge_overlap": "on"})
        if consider("fleet-collective-bound",
                    cur["merge_strategy"] == "tree",
                    "collective-bound with overlap on; strategy 'tree'"):
            return result(
                "fleet-collective-bound",
                "overlap already hides what it can and the per-level "
                "tree finish still dominates: switch to the keyrange "
                "owner-reduce program (bandwidth-optimal on one axis; "
                "2-D hier-* programs stay redplan/registry territory)",
                {"merge_strategy": "keyrange"})
        consider("fleet-collective-bound", False,
                 "collective-bound but overlap is on and the strategy is "
                 f"{cur['merge_strategy']!r} — the remaining lever (2-D "
                 "hierarchical placement) is redplan's, not this table's")
    # A straggler-bound fleet stays a note, never chased: its knob is
    #    data placement across hosts (ROADMAP item 3), and thrashing
    #    single-host pipeline knobs against it would be the
    #    foreign-data-knob mistake at fleet scale.
    elif sig.get("fleet_bottleneck") not in (None, "balanced"):
        consider(f"fleet-{sig['fleet_bottleneck']}", False,
                 f"fleet verdict {sig['fleet_bottleneck']!r} noted; its "
                 "knobs (host balance / reduction strategy) are outside "
                 "the tuned set — single-host rules proceed")

    # 1. Nothing to read at all: a run with no phases, no pipeline stats
    #    and no timeline gives the rules nothing — stop honestly.
    if consider("no-signal",
                not sig["phases"] and sig["pipeline"] is None
                and sig["bottleneck"] is None,
                "no phases, pipeline stats or timeline in the ledger"):
        return result("no-signal", "no telemetry to tune from",
                      converged=True)

    # 2. A searched geometry that SPILLS (ISSUE 12): the taller window
    #    the search bought is too tall for this corpus's density — every
    #    spilled chunk re-runs at full resolution, ~doubling its map
    #    cost, which poisons every signal downstream.  Revert before any
    #    other rule reads the wreckage.  (Default-geometry spill-bound
    #    runs fall through to the foreign-knob note below: their knob is
    #    --compact-slots, not a geometry this tuner set.)
    if consider("revert-geometry",
                verdict == "spill-bound" and cur["geometry"] != "default",
                f"data verdict {verdict!r}; geometry {cur['geometry']!r}"):
        return result("revert-geometry",
                      "the searched taller-window geometry overflows its "
                      "slot budget on this corpus (spill-bound: each "
                      "fallback ~doubles that chunk's map cost): revert "
                      "to the default geometry",
                      {"geometry": "default"})

    # 3. Skew-hot data (ISSUE 11): the map-side combiner is the knob that
    #    actually answers a Zipf-hot stream — enable it before any
    #    pipeline knob moves (collapsed duplicates change every downstream
    #    signal).  Already-on runs note the fact and fall through: the
    #    remaining skew cost is the sort's to carry.
    if consider("enable-combiner",
                verdict == "skew-hot" and cur["combiner"] == "off",
                f"data verdict {verdict!r}; combiner {cur['combiner']!r}"):
        return result("enable-combiner",
                      "one key carries a double-digit share of the stream "
                      "(skew-hot): enable the map-side hot-key combiner so "
                      "the dominant duplicates collapse in VMEM before the "
                      "aggregation sort sees them",
                      {"combiner": "hot-cache"})
    if verdict == "skew-hot" and cur["combiner"] != "off":
        consider("enable-combiner", False,
                 f"data verdict {verdict!r} but combiner already "
                 f"{cur['combiner']!r} — pipeline rules proceed")

    # 3-4. Data-shape rules outrank pipeline rules: a wrong chunk geometry
    #    poisons every overlap signal downstream of it.
    if consider("grow-chunk",
                verdict == "occupancy-starved"
                and cur["chunk_bytes"] * 2 <= CHUNK_MAX,
                f"data verdict {verdict!r}; chunk {cur['chunk_bytes']}"):
        return result("grow-chunk",
                      "compact kernel windows ran mostly empty "
                      "(occupancy-starved): double chunk_bytes so each "
                      "window sees denser input instead of sorting padding",
                      {"chunk_bytes": cur["chunk_bytes"] * 2})
    if consider("shrink-chunk",
                verdict == "table-pressure"
                and cur["chunk_bytes"] // 2 >= CHUNK_MIN
                and (cur["chunk_bytes"] // 2) % 128 == 0,
                f"data verdict {verdict!r}; chunk {cur['chunk_bytes']}"):
        return result("shrink-chunk",
                      "running table near capacity (table-pressure): halve "
                      "chunk_bytes so smaller per-merge batch tables "
                      "compete for slots — the real knob is "
                      "--table-capacity, which is not autotuned",
                      {"chunk_bytes": cur["chunk_bytes"] // 2})
    if verdict in _FOREIGN_DATA_KNOBS:
        consider(f"data-{verdict}", False,
                 f"data verdict {verdict!r} noted; its knob "
                 f"({_FOREIGN_DATA_KNOBS[verdict]}) is outside the tuned "
                 "set — pipeline rules proceed")

    # 4. Converged: the measured critical path says an infinitely fast
    #    bounding resource would save <10% of the span — the pipeline is
    #    at its overlap ceiling; further knob moves chase noise.
    if consider("converged",
                saving is not None and saving < CONVERGED_SAVING_FRAC,
                f"projected saving {saving} of span"
                if saving is not None else "no timeline saving measured"):
        return result("converged",
                      f"bottleneck {resource!r} projects only "
                      f"{saving:.0%} of the span recoverable "
                      f"(< {CONVERGED_SAVING_FRAC:.0%}): converged",
                      converged=True)

    # 5. Reader-bound: the prefetching reader starves the pipeline.
    if resource == "reader":
        if consider("raise-prefetch", cur["prefetch_depth"] * 2
                    <= PREFETCH_MAX,
                    f"bottleneck reader; prefetch {cur['prefetch_depth']}"):
            return result("raise-prefetch",
                          "the reader is the measured critical path: "
                          "double prefetch_depth so the reader runs "
                          "further ahead of the window",
                          {"prefetch_depth": cur["prefetch_depth"] * 2})
        return result("raise-prefetch-at-cap",
                      f"reader-bound with prefetch_depth "
                      f"{cur['prefetch_depth']} at/past the {PREFETCH_MAX} "
                      "cap: the reader itself (disk/decode) is the floor — "
                      "converged", converged=True)

    # 6. h2d/staging-bound but the window never filled: more inflight buys
    #    nothing until the feed side keeps it full — raise prefetch first.
    window_starved = (depth_max is not None
                     and depth_max < cur["inflight_groups"])
    if resource in ("h2d", "staging") and window_starved:
        if consider("feed-window", cur["prefetch_depth"] * 2 <= PREFETCH_MAX,
                    f"{resource}-bound but depth peaked at {depth_max} < "
                    f"inflight {cur['inflight_groups']}"):
            return result("feed-window",
                          f"{resource}-bound but the window never filled "
                          f"(depth_max {int(depth_max)} < inflight "
                          f"{cur['inflight_groups']}): feed it — double "
                          "prefetch_depth before touching the window",
                          {"prefetch_depth": cur["prefetch_depth"] * 2})
        return result("feed-window-at-cap",
                      f"{resource}-bound, window never filled, prefetch "
                      f"already at {PREFETCH_MAX}: converged",
                      converged=True)

    # 7. h2d/staging-bound with a fed window: deepen it so transfers and
    #    host assembly of MORE groups hide behind device compute.
    if resource in ("h2d", "staging"):
        if consider("raise-inflight",
                    cur["inflight_groups"] * 2 <= INFLIGHT_MAX,
                    f"bottleneck {resource}; "
                    f"inflight {cur['inflight_groups']}"):
            return result("raise-inflight",
                          f"{resource} is the measured critical path: "
                          "double inflight_groups so more transfers/"
                          "staging overlap device compute",
                          {"inflight_groups": cur["inflight_groups"] * 2})
        return result("raise-inflight-at-cap",
                      f"{resource}-bound with inflight_groups "
                      f"{cur['inflight_groups']} at/past the "
                      f"{INFLIGHT_MAX} cap: converged", converged=True)

    # 8. Device-bound + window always full: the device is the ceiling and
    #    the window is doing its job — STOP raising inflight; amortize
    #    per-dispatch overhead instead (decisive on high-latency links).
    if resource == "device":
        always_full = full_frac is not None and full_frac >= ALWAYS_FULL_FRAC
        if always_full and consider(
                "try-superstep", cur["superstep"] * 2 <= SUPERSTEP_MAX,
                f"device-bound, full_frac {full_frac}; "
                f"superstep {cur['superstep']}"):
            return result("try-superstep",
                          "device-bound with the window at capacity on "
                          f"{full_frac:.0%} of dispatches: a deeper window "
                          "cannot help — double superstep to amortize "
                          "per-dispatch overhead instead",
                          {"superstep": cur["superstep"] * 2})
        if always_full:
            return result("try-superstep-at-cap",
                          f"device-bound, window always full, superstep "
                          f"{cur['superstep']} at/past the "
                          f"{SUPERSTEP_MAX} cap: converged", converged=True)
        # Window not saturated: compute itself is the ceiling — which is
        #    exactly where the kernel geometry is the remaining lever
        #    (ISSUE 12).  With measured window headroom, propose the
        #    certified taller-window preset: fewer stable2 sort rows per
        #    chunk at a spill risk the exact fallback bounds (and the
        #    revert-geometry rule above unwinds if the probe spills).
        #    Combiner-on runs already run tall windows; skip them.
        occ = sig["window_occupancy"]
        if consider("try-geometry",
                    occ is not None and occ <= GEOMETRY_OCC_CEIL
                    and cur["geometry"] == "default"
                    and not sig["geometry_custom"]
                    and cur["combiner"] == "off",
                    f"device-bound, window occupancy {occ}, geometry "
                    f"{cur['geometry']!r}, combiner {cur['combiner']!r}"):
            return result("try-geometry",
                          "device-bound with the dispatch window "
                          f"unsaturated and kernel windows {occ:.0%} "
                          "full: compute is the ceiling and the windows "
                          "have headroom — try the certified "
                          f"{GEOMETRY_TALL!r} geometry (taller windows, "
                          "fewer aggregation-sort rows; the exact spill "
                          "fallback bounds the risk)",
                          {"geometry": GEOMETRY_TALL})
        return result("device-bound",
                      "the device is the measured critical path and the "
                      "window never saturated: compute itself is the "
                      "ceiling — converged", converged=True)

    # 9. Nothing actionable (retire-bound bookkeeping, unknown resource).
    return result("no-rule",
                  f"no move rule matches (resource={resource!r}, "
                  f"data={verdict!r}): converged", converged=True)


# -- the search loop ---------------------------------------------------------

def _key(knobs: dict):
    return tuple(int(knobs[k]) if k in _INT_KNOBS else str(knobs.get(k))
                 for k in KNOBS)


def search(measure: Callable[[dict], Iterable[dict]],
           start: Optional[dict] = None, *, budget: int = 6,
           backend: str = "auto") -> dict:
    """Walk the rule table: ``measure(knobs)`` runs one probe pass and
    returns its ledger records; :func:`propose` picks the next config;
    repeat until a proposal converges, a proposed config was already
    visited (the **oscillation guard** — two rules pulling a knob in
    opposite directions terminate instead of ping-ponging), or ``budget``
    passes are exhausted.  Every accepted config is validated through
    :func:`validate_knobs` BEFORE it is measured.

    Returns ``{winner, stopped, passes, trail}``: ``winner`` is a config
    actually MEASURED — a final proposal the budget left no pass to run
    stays in the trail but never becomes the winner (the recorded
    winner/GB-s pair must describe a config that was actually observed).
    ``stopped`` is one of ``converged`` / ``oscillation`` /
    ``budget-exhausted``; on an oscillation stop the tie is real — both
    configs' own verdicts voted to move away from them — so the winner
    is the measured config with the best run_end throughput among the
    passes (falling back to the last measured config when no pass
    carried one).  ``trail`` is the full per-pass proposal list — the
    machine-readable decision trail.
    """
    if budget < 1:
        raise ValueError(f"budget must be >= 1, got {budget}")
    cur = default_knobs()
    if start:
        cur.update({k: (int(v) if k in _INT_KNOBS else str(v))
                    for k, v in start.items() if k in cur})
    validate_knobs(cur, backend)
    seen = {_key(cur)}
    trail: List[dict] = []
    measured: List[tuple] = []  # (knobs, run_end gb_per_s or None) per pass
    win_idx = 0
    stopped = "budget-exhausted"
    for _ in range(budget):
        records = list(measure(dict(cur)))
        prop = propose(records, current=cur)
        trail.append(prop)
        measured.append((dict(cur), prop["signals"].get("gb_per_s")))
        win_idx = len(measured) - 1
        if prop["converged"]:
            stopped = "converged"
            break
        nxt = {k: prop["proposal"][k] for k in KNOBS}
        validate_knobs(nxt, backend)
        if _key(nxt) in seen:
            prop["oscillation"] = True
            stopped = "oscillation"
            # An oscillation is a genuine tie: each side's own verdict
            # voted to leave it.  Break it with the one signal the rule
            # table deliberately ignores — measured throughput (later
            # pass wins a throughput tie).
            rated = [(g, i) for i, (_, g) in enumerate(measured)
                     if g is not None]
            if rated:
                win_idx = max(rated)[1]
            break
        seen.add(_key(nxt))
        if len(trail) >= budget:
            # Budget exhausted: the accepted proposal would never be
            # measured — stop at the measured config instead of advancing.
            break
        cur = nxt
    # winner and winner_gbps come from the SAME pass, so a recorded
    # config/value pair always describes one observed run (on an
    # oscillation stop the last pass's throughput belongs to the losing
    # config — returning it would misprice the winner).
    winner, winner_gbps = measured[win_idx]
    return {"tuner_version": TUNER_VERSION, "winner": winner,
            "winner_gbps": winner_gbps, "stopped": stopped,
            "passes": len(trail), "trail": trail}
