"""Feedback-driven config autotuner (ISSUE 10): the run ledger's own
``bottleneck`` + ``data_health`` verdicts -> the next values for
``inflight_groups`` / ``prefetch_depth`` / ``superstep`` /
``chunk_bytes``, via a deterministic jax-free rule engine.

Entry points: :func:`propose` (one run's records -> one proposal, the
online-hint path), :func:`search` (the offline probe-pass walk,
``tools/autotune.py``).  See :mod:`mapreduce_tpu.tuning.engine`.
"""

from mapreduce_tpu.tuning.engine import (KNOBS, TUNER_VERSION,
                                         default_knobs, derive_signals,
                                         propose, search, validate_knobs)

__all__ = ["KNOBS", "TUNER_VERSION", "default_knobs", "derive_signals",
           "propose", "search", "validate_knobs"]
