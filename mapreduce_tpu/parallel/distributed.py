"""Multi-host runtime: process bootstrap + per-host work partitioning.

The distributed communication backend of the framework (SURVEY §5: the
reference has none — its only transport is single-GPU PCIe ``cudaMemcpy``,
``main.cu:147,157-158``).  Cross-chip data movement itself is expressed as
XLA collectives (:mod:`mapreduce_tpu.parallel.collectives`) compiled over the
ICI/DCN mesh; what remains host-side is (a) bringing every process into one
JAX runtime and (b) deciding which byte-range of the corpus each host reads.
This module owns both.

Two multi-host modes::

    from mapreduce_tpu.parallel import distributed as dist

    dist.initialize()                      # no-op on a single host

    # (a) per-host-driven (tested end-to-end in tests/test_multihost.py):
    #     each host runs the executor over its OWN devices and its own
    #     byte-range, then partial tables are merged (host-side
    #     table_ops.merge, or any reduction transport).
    lo, hi = dist.host_byte_range(os.path.getsize(path))
    lo, hi = dist.align_range_to_separator(path, lo, hi)
    rr = executor.run_job(job, path, byte_range=(lo, hi))   # local mesh

    # (b) one global SPMD program: a global mesh plus per-host staging —
    #     run from ONE entry point, executor.run_job_global (every process
    #     calls it with the same arguments; each stages only its own
    #     host_shards rows via device_put_local, the collective finish
    #     replicates the result, checkpoints are coordinator-written and
    #     resumable — tested end-to-end with a real 2-process gloo run in
    #     tests/test_multihost.py, crash + resume included).
    rr = executor.run_job_global(job, path, config=cfg, checkpoint_path=ck)

``initialize`` wraps :func:`jax.distributed.initialize`, which reads the
cluster-environment variables (coordinator address, process count/index) that
TPU pod launchers export; on a laptop or a single TPU VM it does nothing, so
the same program runs unmodified at every scale.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import jax
import numpy as np

from mapreduce_tpu.obs import registry as obs_registry
from mapreduce_tpu.runtime.logging import get_logger, log_event


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               timeout_s: int = 300) -> None:
    """Join this process to the cluster-wide JAX runtime.

    Arguments default to auto-detection from the launcher environment (the
    behavior of :func:`jax.distributed.initialize`); pass them explicitly for
    bare-metal/SSH launches.  Safe to call on a single host: when no cluster
    environment exists and no arguments are given, it's a no-op.

    Failure detection (SURVEY §5): a host that cannot reach the coordinator
    raises within ``timeout_s`` instead of hanging the pod; the error is
    logged with the process identity so the failing host is identifiable
    from any log stream.
    """
    # NOTE: must not touch jax.process_count()/jax.devices() here — any such
    # call initializes the XLA backend, after which
    # jax.distributed.initialize() refuses to run.
    if _is_initialized():
        return
    explicit = coordinator_address or num_processes or process_id
    env = (os.environ.get("COORDINATOR_ADDRESS")
           or os.environ.get("JAX_COORDINATOR_ADDRESS")
           or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if not explicit and not env and not _on_cloud_tpu():
        return  # single-host run: nothing to join
    # Init wall-clock into the registry: a pod bring-up that creeps from
    # seconds to minutes (DNS, a slow peer, a flaky coordinator) shows up
    # in every run's metrics snapshot instead of being lost to stderr.
    reg = obs_registry.get_registry()
    t0 = time.perf_counter()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=timeout_s)
    except Exception as e:
        reg.counter("distributed.init_failures").inc()
        log_event(get_logger(), "distributed initialization failed",
                  process_id=process_id, coordinator=coordinator_address or env,
                  error=repr(e))
        raise
    init_s = time.perf_counter() - t0
    # Shared run epoch (ISSUE 13): every process stamps its {wall, mono}
    # clock pair the moment the cluster-wide runtime is up — the pair
    # obs/fleet.py uses to rebase per-host monotonic lifecycle stamps
    # onto the (NTP-shared) wall clock when merging ledger shards.
    _stamp_epoch()
    reg.counter("distributed.inits").inc()
    reg.gauge("distributed.init_seconds").set(init_s)
    log_event(get_logger(), "distributed runtime up",
              process=jax.process_index(), processes=jax.process_count(),
              local_devices=len(jax.local_devices()),
              global_devices=len(jax.devices()),
              init_s=round(init_s, 3))


def _is_initialized() -> bool:
    """``jax.distributed.is_initialized`` on any jax: the public predicate
    only exists on newer versions; older ones expose the same fact as the
    distributed client singleton (set exactly while initialized)."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    from jax._src import distributed as _dist

    return getattr(_dist.global_state, "client", None) is not None


def _on_cloud_tpu() -> bool:
    """True when running under a TPU pod launcher that exports multi-worker
    topology env (single-worker VMs lack TPU_WORKER_HOSTNAMES)."""
    return bool(os.environ.get("TPU_WORKER_HOSTNAMES"))


#: {wall, mono} sampled together at jax.distributed init (lazily on
#: single-host runs).  wall - mono is this process's monotonic->wall
#: offset; wall clocks are the cross-host reference (same box in the CPU
#: harness, NTP on pods), so fleet merges align shard timelines with it.
_RUN_EPOCH: Optional[dict] = None


def _stamp_epoch() -> dict:
    global _RUN_EPOCH
    if _RUN_EPOCH is None:
        _RUN_EPOCH = {"wall": round(time.time(), 6),
                      "mono": round(time.perf_counter(), 6)}
    return _RUN_EPOCH


def run_epoch() -> dict:
    """This process's clock-alignment pair (ISSUE 13): wall-clock and
    monotonic seconds sampled together — stamped once at
    :func:`initialize` success, lazily on first use otherwise.  Written
    into every shard ledger's ``run_start`` as ``clock`` so
    ``obs/fleet.py`` can rebase each host's monotonic lifecycle stamps
    to the shared wall clock (``aligned = mono + (wall - mono_epoch)``).
    """
    return dict(_stamp_epoch())


def is_coordinator() -> bool:
    """True on the process that should own singleton side effects
    (checkpoint writes, final report printing)."""
    return jax.process_index() == 0


def global_data_mesh(axis: str = "data"):
    """1-D mesh over every chip of every host (devices are process-major,
    so contiguous index ranges align with hosts)."""
    from mapreduce_tpu.parallel.mesh import data_mesh

    return data_mesh(devices=jax.devices(), axis=axis)


def host_byte_range(file_size: int, process_index: Optional[int] = None,
                    process_count: Optional[int] = None) -> tuple[int, int]:
    """The half-open byte range of the corpus this host ingests.

    Even split by bytes, not lines — the reader aligns chunk boundaries to
    token separators within the range, and range seams are token-exact
    because the split offsets are identical on every host (each host extends
    its range's head to the first separator after the cut, mirroring the
    reader's boundary rule; see :func:`align_range_to_separator`).
    """
    p = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    if not 0 <= p < n:
        raise ValueError(f"process_index {p} outside [0, {n})")
    per = file_size // n
    lo = p * per
    hi = file_size if p == n - 1 else (p + 1) * per
    return lo, hi


def align_range_to_separator(path: str, lo: int, hi: int,
                             max_token_bytes: int = 1 << 16,
                             separators: bytes | None = None) -> tuple[int, int]:
    """Snap a byte range so both ends sit just after a separator byte.

    Every host applies the same deterministic rule to its own ``lo`` and
    ``hi``, so adjacent ranges stay exactly adjacent: a token spanning a raw
    cut is counted by the host whose range contains its first byte, and only
    by it.  ``max_token_bytes`` bounds the scan past the cut (a pathological
    separator-free file falls back to the raw offset, force-splitting the
    token exactly like the in-range reader does).

    ``separators`` overrides the boundary byte class (default: the token
    separator set).  Cross-host grep wants ``separators=b"\\n"`` so no
    logical LINE straddles a range seam — per-host line counts then merge
    exactly (:meth:`...models.grep.GrepJob.merge`).
    """
    from mapreduce_tpu import constants

    sep = bytes(constants.SEPARATOR_BYTES) if separators is None else separators
    size = os.path.getsize(path)

    def snap(off: int) -> int:
        if off <= 0 or off >= size:
            return max(0, min(off, size))
        with open(path, "rb") as f:
            f.seek(off - 1)
            window = f.read(max_token_bytes + 1)
        if window[0] in sep:  # byte off-1 is a separator: already aligned
            return off
        for i, b in enumerate(window[1:]):  # window[1+i] is byte off+i
            if b in sep:
                return off + i + 1  # just past that separator
        return off  # separator-free window: force-split like the reader
    return snap(lo), snap(hi)


def host_shards(n_global_shards: int,
                process_index: Optional[int] = None,
                process_count: Optional[int] = None) -> Sequence[int]:
    """Global shard indices owned by this host (contiguous, process-major —
    matching the device order of :func:`global_data_mesh`)."""
    p = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    if n_global_shards % n:
        raise ValueError(
            f"{n_global_shards} shards do not divide over {n} processes")
    per = n_global_shards // n
    return range(p * per, (p + 1) * per)


def device_put_local(batch: np.ndarray, sharding):
    """Place this host's rows of a [global_shards, ...] batch onto its local
    devices, assembling the global sharded array without materializing other
    hosts' data (``jax.make_array_from_process_local_data``)."""
    return jax.make_array_from_process_local_data(sharding, batch)
