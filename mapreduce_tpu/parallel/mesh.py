"""Device mesh construction.

The reference is single-device/single-stream by construction (SURVEY §2:
"no multi-GPU or multi-node support").  Here the device topology is a
first-class object: a 1-D data-parallel `jax.sharding.Mesh` by default, with
room for multi-axis meshes (e.g. ('replica', 'data')) on multi-slice pods
where the outer axis rides DCN and the inner rides ICI.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_mesh(n_devices: int | None = None, axis: str = "data",
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def two_level_mesh(n_replicas: int, n_data: int | None = None,
                   axes: tuple[str, str] = ("replica", "data"),
                   devices: Sequence[jax.Device] | None = None) -> Mesh:
    """A 2-D mesh for multi-slice/multi-host pods.

    The outer axis (``axes[0]``) is intended to ride the slow link (DCN
    across slices/hosts), the inner axis the fast one (ICI within a slice);
    pair with :func:`...collectives.hierarchical_merge`, which reduces the
    inner axis first.  With ``jax.devices()`` ordered process-major (the JAX
    contract), outer=process boundary gives exactly that layout.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_data is None:
        if len(devs) % n_replicas:
            raise ValueError(
                f"{len(devs)} devices do not divide into {n_replicas} replicas")
        n_data = len(devs) // n_replicas
    need = n_replicas * n_data
    if need > len(devs):
        raise ValueError(f"requested {need} devices, have {len(devs)}")
    grid = np.asarray(devs[:need]).reshape(n_replicas, n_data)
    return Mesh(grid, axes)


def sharded(mesh: Mesh, *axes: str | None) -> NamedSharding:
    """NamedSharding shorthand: sharded(mesh, 'data') == P('data') on mesh."""
    return NamedSharding(mesh, P(*axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
