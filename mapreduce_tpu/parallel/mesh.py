"""Device mesh construction.

The reference is single-device/single-stream by construction (SURVEY §2:
"no multi-GPU or multi-node support").  Here the device topology is a
first-class object: a 1-D data-parallel `jax.sharding.Mesh` by default, with
room for multi-axis meshes (e.g. ('replica', 'data')) on multi-slice pods
where the outer axis rides DCN and the inner rides ICI.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_mesh(n_devices: int | None = None, axis: str = "data",
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def sharded(mesh: Mesh, *axes: str | None) -> NamedSharding:
    """NamedSharding shorthand: sharded(mesh, 'data') == P('data') on mesh."""
    return NamedSharding(mesh, P(*axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
