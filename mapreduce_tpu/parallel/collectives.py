"""Collective reductions over arbitrary mergeable states.

This is the distributed communication backend of the framework — the role the
reference fills with one serial device thread scanning every emitted pair
(``reduceKernel``/``reducer``, ``main.cu:119-123,69-108``) plus PCIe
``cudaMemcpy`` (``main.cu:147,157-158``).  Here cross-device aggregation is
expressed as XLA collectives over the mesh (ICI within a slice, DCN across
slices), in three interchangeable strategies:

* :func:`tree_merge` — butterfly all-reduce built from ``ppermute`` rounds
  with a user merge function.  log2(D) rounds; requires a power-of-two axis.
  The generalization of ``psum`` to non-additive monoids (count tables).
* :func:`gather_merge` — ``all_gather`` + fold.  Works for any axis size;
  O(D) memory; the fallback and the simplest correct form.
* :func:`key_range_merge` — the pod-scale strategy for :class:`CountTable`
  states specifically: reduce-scatter by hash range (one ``all_to_all``,
  capacity/D-sized owner merges) + ``all_gather`` of the already-reduced
  blocks.  One communication round where the butterfly does log2(D)
  sequential full-table rounds; see the function docstring for the traffic
  arithmetic and the exactness argument.
* ``psum`` — used directly wherever the state really is additive (scalar
  totals, sketch matrices, histogram vectors); XLA lowers it to the native
  ICI all-reduce (the BASELINE.json north-star transformation).

All functions take *pytrees* (``key_range_merge``: a CountTable) and must be
called inside ``shard_map``.
"""

from __future__ import annotations

from typing import Callable, TypeVar

import jax
import jax.numpy as jnp

from mapreduce_tpu.obs import registry as obs_registry
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.parallel.compat import axis_size as _axis_size

T = TypeVar("T")
MergeFn = Callable[[T, T], T]


def _count_build(strategy: str, axis) -> None:
    """Trace-time collective accounting into the metrics registry.

    These functions run INSIDE shard_map/jit, so per-execution timing from
    here would require a host callback — exactly the per-step sync the
    graphcheck host-sync pass forbids.  What IS observable host-side is
    each strategy *build* (once per trace, i.e. per compiled program), with
    its axis width: enough to see which reduce strategies a run compiled
    and at what scale, and to correlate a compile-event spike in the run
    ledger with the collective that caused it.  Execution cost belongs to
    the profiler timeline (``obs.span`` regions around the dispatch).
    """
    try:
        d = _axis_size(axis)
    except Exception:
        d = 0
    obs_registry.get_registry().counter(
        "collectives.builds", strategy=strategy, axis_size=d).inc()


def tree_merge(state: T, merge: MergeFn, axis: str) -> T:
    """Butterfly all-reduce: after log2(D) ppermute+merge rounds every device
    holds the merge of all D states.  Deterministic and replicated.
    """
    n = _axis_size(axis)
    if n & (n - 1):
        return gather_merge(state, merge, axis)
    _count_build("tree", axis)
    rounds = n.bit_length() - 1
    for r in range(rounds):
        bit = 1 << r
        perm = [(i, i ^ bit) for i in range(n)]
        partner = jax.tree.map(lambda x: jax.lax.ppermute(x, axis, perm), state)
        state = merge(state, partner)
    return state


def gather_merge(state: T, merge: MergeFn, axis: str) -> T:
    """all_gather every state then fold left.  Any axis size; replicated."""
    n = _axis_size(axis)
    _count_build("gather", axis)
    gathered = jax.tree.map(lambda x: jax.lax.all_gather(x, axis), state)
    take = lambda i: jax.tree.map(lambda x: x[i], gathered)
    acc = take(0)
    for i in range(1, n):
        acc = merge(acc, take(i))
    return acc


def psum(state: T, axis: str) -> T:
    """Additive all-reduce of a pytree (native XLA collective)."""
    return jax.lax.psum(state, axis)


def psum64(lo: jax.Array, hi: jax.Array, axis) -> tuple[jax.Array, jax.Array]:
    """Exact 64-bit all-reduce sum of uint32 (lo, hi) lane-pair scalars.

    A plain ``psum`` of the low lanes would drop inter-device carries
    silently; instead the D scalars are gathered (a few bytes) and folded
    with the wrap-counting :func:`...ops.table.sum64`."""
    return table_ops.sum64(jax.lax.all_gather(lo, axis),
                           jax.lax.all_gather(hi, axis))


def key_range_merge(table: table_ops.CountTable, axis,
                    slack: float = 2.0) -> table_ops.CountTable:
    """Key-range sharded global reduce of per-device CountTables: the
    reduce-scatter formulation of the serial reduce the reference runs on
    one thread (``main.cu:119-123``), for pod scale.

    The tree/butterfly strategy moves each device's FULL table log2(D)
    sequential times and runs log2(D) full-capacity merge sorts.  Here the
    key space is partitioned over the axis, every device routes each row to
    its owner in ONE ``all_to_all``, owners reduce their (capacity/D-scale)
    partition locally, and one ``all_gather`` of the already-reduced blocks
    replicates the result.  Per device, with table bytes M and slack s:

    =========  ====================  =====================================
    strategy   bytes moved           sequential sort rows
    =========  ====================  =====================================
    tree       M * log2(D)           2C * log2(D)
    keyrange   s*M (a2a) + s*M (ag)  C (pack) + s*C (owner) + s*C (final)
    =========  ====================  =====================================

    At D=256, C=256K, M~7 MB, s=2: ~56 MB & 4.2M sequential sort rows
    (tree) vs ~28 MB & ~1.3M rows (keyrange) — and the all_to_all round is
    a single collective XLA schedules across ICI links at once, not log2(D)
    dependent steps.

    Partitioning is by ``key_lo % D``: tables keep the capacity SMALLEST
    (key_hi, key_lo) keys, so key_hi ranges are mass-skewed toward small
    values, while the second hash word stays uniform under that selection.

    Exactness: each destination block has a fixed budget
    ``B = ceil(s*C/D) + 8 + 4*ceil(log2 D)`` rows; a device whose
    partition overflows B spills its LARGEST keys past the budget (rank
    order = key order).  Spilling key k implies >= B smaller distinct
    keys in that partition, all of which reach the owner, whose
    capacity-B reduce then evicts k everywhere it survived — so a spilled
    key is never reported with a partial count: it is fully evicted and
    accounted in ``dropped_*``, the same contract as capacity spill
    (ops/table.py module docstring).  The budget needs BOTH terms: for
    hash-uniform keys the max partition load is ~mean + O(sqrt(mean log
    D) + log D) (balls in bins), so a purely multiplicative slack fails
    exactly when C/D is small — at C=512, D=256 the mean is 2 rows but
    the max is ~9, so ``b = 2*C/D = 4`` spilled real keys and the merge
    (correctly, per the spill contract) diverged from tree on kept keys
    (found by the D=256 scale dryrun, round 5).  The additive term is
    noise at pod scale (+~40 rows on a 2048-row block at C=256K, D=256)
    and makes the no-spill regime — where the result is bit-identical to
    tree/gather — cover every realistic shape including tiny dryruns.

    Works for any axis size (not just powers of two) and for tuple axes
    (the mesh is flattened; the single a2a round trades the ICI/DCN
    hierarchy for one scheduled collective).
    """
    d = _axis_size(axis)
    cap = table.capacity
    if d == 1:
        return table
    _count_build("keyrange", axis)
    b = min(cap, -(-int(slack * cap) // d) + 8 + 4 * (d - 1).bit_length())
    sent = jnp.uint32(table_ops.constants.SENTINEL_KEY)
    inf = jnp.uint32(table_ops.constants.POS_INF)
    zero = jnp.uint32(0)

    # 1. Pack: sort rows by (owner, key); dead rows get owner D (sorts last,
    #    never sent).  Keys are unique within a table, so (owner, key_hi,
    #    key_lo) is already a total order; pos/count lanes ride as payload.
    owner = jnp.where(table.occupied(),
                      table.key_lo % jnp.uint32(d), jnp.uint32(d))
    own_s, khi, klo, cnt, cnth, phi, plo, ln = jax.lax.sort(
        (owner, table.key_hi, table.key_lo, table.count, table.count_hi,
         table.pos_hi, table.pos_lo, table.length), num_keys=3)
    own_i = own_s.astype(jnp.int32)
    # heads[q] = first sorted row with owner >= q (q = 0..D; owner values
    # are sorted, which is all the shared binary search needs).
    heads = table_ops._segment_heads(own_i, d)

    # Destination slot t of block j holds partition j's rank-t row.
    slot = jnp.arange(d * b, dtype=jnp.int32)
    j, r = slot // b, slot % b
    src = heads[j] + r
    valid = src < heads[j + 1]
    srcc = jnp.minimum(src, cap - 1)
    take = lambda a, fill: jnp.where(valid, a[srcc], fill)
    s_khi, s_klo = take(khi, sent), take(klo, sent)
    s_cnt, s_cnth = take(cnt, zero), take(cnth, zero)
    s_phi, s_plo = take(phi, inf), take(plo, inf)
    s_ln = take(ln, zero)

    # Budget spill: within-partition rank >= B — deterministically the
    # partition's largest keys (see docstring for why this stays exact).
    rank = jnp.arange(cap, dtype=jnp.int32) - heads[jnp.minimum(own_i, d)]
    spilled = (own_i < d) & (rank >= b)
    sp_u = jnp.sum(spilled.astype(jnp.uint32))
    sp_lo, sp_hi = table_ops.sum64(jnp.where(spilled, cnt, zero),
                                   jnp.where(spilled, cnth, zero))

    # 2. Exchange: block j goes to device j; block s received from source s.
    def a2a(a):
        return jax.lax.all_to_all(a.reshape(d, b), axis,
                                  split_axis=0, concat_axis=0).reshape(d * b)

    # 3. Owner reduce: all sources' rows of MY partition -> capacity B.
    mine = table_ops._build(a2a(s_khi), a2a(s_klo), a2a(s_phi), a2a(s_plo),
                            a2a(s_cnt), a2a(s_cnth), a2a(s_ln), b,
                            zero, zero, zero, zero)

    # 4. Replicate: gather every owner's reduced block, final reduce to C.
    ag = lambda a: jax.lax.all_gather(a, axis).reshape(d * b)
    du_lo, du_hi = table_ops.add64(table.dropped_uniques,
                                   table.dropped_uniques_hi, sp_u, zero)
    dc_lo, dc_hi = table_ops.add64(table.dropped_count,
                                   table.dropped_count_hi, sp_lo, sp_hi)
    du_lo, du_hi = table_ops.add64(du_lo, du_hi, mine.dropped_uniques,
                                   mine.dropped_uniques_hi)
    dc_lo, dc_hi = table_ops.add64(dc_lo, dc_hi, mine.dropped_count,
                                   mine.dropped_count_hi)
    gdu_lo, gdu_hi = psum64(du_lo, du_hi, axis)
    gdc_lo, gdc_hi = psum64(dc_lo, dc_hi, axis)
    return table_ops._build(ag(mine.key_hi), ag(mine.key_lo), ag(mine.pos_hi),
                            ag(mine.pos_lo), ag(mine.count), ag(mine.count_hi),
                            ag(mine.length), cap,
                            gdu_lo, gdu_hi, gdc_lo, gdc_hi)


def hierarchical_merge(state: T, merge: MergeFn, axes: tuple[str, ...],
                       strategy: str = "tree") -> T:
    """Level-by-level merge over a multi-axis mesh, innermost axis first.

    The multi-slice/multi-host pattern (SURVEY §5 "distributed communication
    backend"): on a mesh like ``('replica', 'data')`` where the inner axis
    rides ICI within a slice and the outer axis rides DCN across slices,
    reducing the fast axis first shrinks what crosses the slow link to one
    already-merged state per slice — the two-level reduction of the build
    plan (SURVEY §7 step 4).  Axes are given outermost-first, matching mesh
    construction order.
    """
    if strategy == "hier-tree-tree":
        strategy = "tree"  # the named 2-D descriptor for the same schedule
    if strategy not in ("tree", "gather"):
        raise ValueError(f"unknown strategy {strategy!r}")
    fn = tree_merge if strategy == "tree" else gather_merge
    for axis in reversed(axes):
        state = fn(state, merge, axis)
    return state


def hier_tree_tree_merge(state: T, merge: MergeFn,
                         axes: tuple[str, ...]) -> T:
    """The named 2-D tree composition (planner descriptor
    ``hier-tree-tree``): butterfly per level, innermost (ICI) axis first,
    so the outer (DCN) level moves one already-merged payload per slice.
    Exactly :func:`hierarchical_merge` with ``strategy='tree'`` — named so
    the planner's descriptor table maps one-to-one onto a runtime builder.
    """
    return hierarchical_merge(state, merge, axes, strategy="tree")


def hier_kr_tree_merge(state: T, keyrange_fn, result_merge: MergeFn,
                       axes: tuple[str, ...]) -> T:
    """Placed 2-D reduction (planner descriptor ``hier-kr-tree``):
    key-range reduce-scatter on the INNERMOST axis (the ICI level, where
    the budgeted all_to_all's 2sM bytes are cheap and the owner merges are
    capacity/D-sized), then butterfly tree over the OUTER axes (the DCN
    level crosses once per round with the already-reduced payload).

    ``keyrange_fn(state, axis)`` is the job's ``keyrange_merge`` hook: it
    folds any batched shape and returns the replicated REDUCED result
    (wordcount family: a plain CountTable).  ``result_merge`` must be a
    merge valid on that result shape (the job's ``keyrange_result_merge``
    hook) — the outer tree legs run on keyrange's output, not on the raw
    accumulator shape.
    """
    if len(axes) < 2:
        raise ValueError(
            f"hier-kr-tree composes two mesh levels; got axes {axes!r}")
    merged = keyrange_fn(state, axes[-1])
    for axis in reversed(axes[:-1]):
        merged = tree_merge(merged, result_merge, axis)
    return merged


# Reduction-strategy descriptors (ISSUE 16): the machine-readable surface
# the static planner enumerates.  Names are the Engine ``merge_strategy``
# values; ``builder`` is the function this module actually dispatches.
# ``analysis/meshcost.py`` (jax-free, so it cannot import this module)
# carries a mirrored table with the same names/builders/constraints — a
# test asserts the two stay in bijection, so the planner can never rank a
# strategy the runtime does not build (or miss one it does).
STRATEGIES: dict[str, dict] = {
    "tree": {
        "builder": f"{__name__}.tree_merge",
        "power_of_two_only": True,  # non-pow2 axes fall back to gather
        "needs_keyrange_hook": False,
        "per_axis": True,  # hierarchical_merge runs it innermost-first
    },
    "gather": {
        "builder": f"{__name__}.gather_merge",
        "power_of_two_only": False,
        "needs_keyrange_hook": False,
        "per_axis": True,
    },
    "keyrange": {
        "builder": f"{__name__}.key_range_merge",
        "power_of_two_only": False,
        "needs_keyrange_hook": True,  # Engine requires job.keyrange_merge
        "per_axis": False,  # flattens the whole mesh into one collective
    },
    # The 2-D placed compositions (ISSUE 20): whole-mesh builders that
    # assign a strategy per link level the way the planner prices them.
    "hier-kr-tree": {
        "builder": f"{__name__}.hier_kr_tree_merge",
        "power_of_two_only": True,  # the outer tree legs (gather fallback)
        "needs_keyrange_hook": True,  # inner leg is the job keyrange hook
        "per_axis": False,  # fixed placement: keyrange inner, tree outer
    },
    "hier-tree-tree": {
        "builder": f"{__name__}.hier_tree_tree_merge",
        "power_of_two_only": True,
        "needs_keyrange_hook": False,
        "per_axis": False,  # the named whole-mesh composition
    },
}
