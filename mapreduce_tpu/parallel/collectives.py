"""Collective reductions over arbitrary mergeable states.

This is the distributed communication backend of the framework — the role the
reference fills with one serial device thread scanning every emitted pair
(``reduceKernel``/``reducer``, ``main.cu:119-123,69-108``) plus PCIe
``cudaMemcpy`` (``main.cu:147,157-158``).  Here cross-device aggregation is
expressed as XLA collectives over the mesh (ICI within a slice, DCN across
slices), in three interchangeable strategies:

* :func:`tree_merge` — butterfly all-reduce built from ``ppermute`` rounds
  with a user merge function.  log2(D) rounds; requires a power-of-two axis.
  The generalization of ``psum`` to non-additive monoids (count tables).
* :func:`gather_merge` — ``all_gather`` + fold.  Works for any axis size;
  O(D) memory; the fallback and the simplest correct form.
* ``psum`` — used directly wherever the state really is additive (scalar
  totals, sketch matrices, histogram vectors); XLA lowers it to the native
  ICI all-reduce (the BASELINE.json north-star transformation).

All functions take *pytrees* and must be called inside ``shard_map``.
"""

from __future__ import annotations

from typing import Any, Callable, TypeVar

import jax
import jax.numpy as jnp

T = TypeVar("T")
MergeFn = Callable[[T, T], T]


def tree_merge(state: T, merge: MergeFn, axis: str) -> T:
    """Butterfly all-reduce: after log2(D) ppermute+merge rounds every device
    holds the merge of all D states.  Deterministic and replicated.
    """
    n = jax.lax.axis_size(axis)
    if n & (n - 1):
        return gather_merge(state, merge, axis)
    rounds = n.bit_length() - 1
    for r in range(rounds):
        bit = 1 << r
        perm = [(i, i ^ bit) for i in range(n)]
        partner = jax.tree.map(lambda x: jax.lax.ppermute(x, axis, perm), state)
        state = merge(state, partner)
    return state


def gather_merge(state: T, merge: MergeFn, axis: str) -> T:
    """all_gather every state then fold left.  Any axis size; replicated."""
    n = jax.lax.axis_size(axis)
    gathered = jax.tree.map(lambda x: jax.lax.all_gather(x, axis), state)
    take = lambda i: jax.tree.map(lambda x: x[i], gathered)
    acc = take(0)
    for i in range(1, n):
        acc = merge(acc, take(i))
    return acc


def psum(state: T, axis: str) -> T:
    """Additive all-reduce of a pytree (native XLA collective)."""
    return jax.lax.psum(state, axis)


def hierarchical_merge(state: T, merge: MergeFn, axes: tuple[str, ...],
                       strategy: str = "tree") -> T:
    """Level-by-level merge over a multi-axis mesh, innermost axis first.

    The multi-slice/multi-host pattern (SURVEY §5 "distributed communication
    backend"): on a mesh like ``('replica', 'data')`` where the inner axis
    rides ICI within a slice and the outer axis rides DCN across slices,
    reducing the fast axis first shrinks what crosses the slow link to one
    already-merged state per slice — the two-level reduction of the build
    plan (SURVEY §7 step 4).  Axes are given outermost-first, matching mesh
    construction order.
    """
    if strategy not in ("tree", "gather"):
        raise ValueError(f"unknown strategy {strategy!r}")
    fn = tree_merge if strategy == "tree" else gather_merge
    for axis in reversed(axes):
        state = fn(state, merge, axis)
    return state
