"""The MapReduce engine: the framework core.

TPU-first replacement for ``runMapReduce`` (``main.cu:133-162``).  Where the
reference hand-sequences device malloc / H2D copy / map launch / reduce launch
/ D2H copy on the default CUDA stream, here the whole map+combine step is one
jitted SPMD program over a device mesh, and the global reduction is a
collective.  The user-visible contract is a small functional protocol:

  * ``init_state()``  — per-device accumulator (a pytree);
  * ``map_chunk(chunk, chunk_id)`` — the map UDF: one device's chunk of bytes
    to an update pytree (reference analogue: ``mapper``, ``main.cu:37-54``);
  * ``combine(state, update)`` — fold an update into the local accumulator
    (the "combiner" classic MapReduce runs map-side);
  * ``merge(a, b)`` — associative+commutative merge of two accumulators,
    used by the collective global reduce (reference analogue: the serial
    ``reducer``, ``main.cu:69-108``);
  * ``finalize(state)`` — device-side post-processing of the fully merged
    state (e.g. top-k selection).

Execution model: every step feeds each device one ``chunk_bytes`` slice of the
corpus (data parallelism over the 'data' mesh axis — the same axis the
reference parallelizes, lines->chunks, ``main.cu:113``), accumulators stay
device-resident across steps (no per-step host round-trips, unlike the
reference's per-call cudaMemcpy pattern), and ``finish`` runs the collective
tree-merge + finalize once at the end.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from mapreduce_tpu.parallel import collectives
from mapreduce_tpu.parallel.compat import axis_size, shard_map
from mapreduce_tpu.parallel import mesh as mesh_mod


def _map_with_axis(job, chunk, chunk_id, axis, device_index):
    """Dispatch to the job's axis-aware map hook when it defines one.

    Jobs are duck-typed (WordCountJob and friends don't inherit the base
    class), so the optional hook is resolved by name at trace time.
    ``device_index`` is the Engine's row-major linear shard index — passed
    through so jobs never re-derive (and risk diverging from) the axis
    linearization their gathered data is ordered by.
    """
    fn = getattr(job, "map_chunk_sharded", None)
    if fn is not None:
        return fn(chunk, chunk_id, axis, device_index)
    return job.map_chunk(chunk, chunk_id)


class MapReduceJob:
    """Base class for jobs.  Subclasses override the five hooks.

    All hooks are traced under jit: they must be pure, static-shaped JAX.
    """

    def init_state(self) -> Any:
        raise NotImplementedError

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array) -> Any:
        raise NotImplementedError

    def map_chunk_sharded(self, chunk: jax.Array, chunk_id: jax.Array,
                          axis, device_index: jax.Array) -> Any:
        """Optional axis-aware map: runs inside ``shard_map``, so it may use
        collectives over ``axis`` (a mesh axis name or tuple of them).  Jobs
        whose per-chunk updates need neighbor/seam context (e.g. grep's
        exact matching-line count across row boundaries) override this; the
        default is the plain per-device :meth:`map_chunk`.  ``device_index``
        is the row-major linear shard index over the sharded axes (uint32
        scalar) — it matches the row order of ``jax.lax.all_gather(...,
        axis_name=axis)`` output."""
        return self.map_chunk(chunk, chunk_id)

    def combine(self, state: Any, update: Any) -> Any:
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def finalize(self, state: Any) -> Any:
        return state

    def identity(self) -> str:
        """Stable description of what this job computes, for checkpoint
        fingerprints: resuming a snapshot under a job with a different
        identity is refused (e.g. a grep for a different pattern, whose
        state SHAPE is identical but whose accumulated numbers mean
        something else).  Subclasses with parameters that change the
        meaning of accumulated state must include them."""
        return type(self).__name__.lower()


class Engine:
    """Compiles and runs a :class:`MapReduceJob` over a mesh.

    Usage::

        eng = Engine(job, mesh)
        state = eng.init_states()
        for step, batch in enumerate(reader):   # batch: uint8[D, chunk_bytes]
            state = eng.step(state, batch, step)
        result = eng.finish(state)              # merged + finalized, replicated
    """

    def __init__(self, job: MapReduceJob, mesh: Mesh,
                 axis: str | tuple[str, ...] = "data",
                 merge_strategy: str = "tree", data_stats: bool = False):
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        for a in axes:
            if a not in mesh.axis_names:
                raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")
        self.job = job
        self.mesh = mesh
        self.axis = axes[0] if len(axes) == 1 else axes
        self.axes = axes
        self.n_devices = 1
        for a in axes:
            self.n_devices *= mesh.shape[a]
        if merge_strategy == "auto":
            raise ValueError(
                "merge_strategy='auto' reaches the Engine unresolved: "
                "resolution (via the redplan tuned.json profile) is the "
                "driver's job — pass the resolved strategy name")
        if merge_strategy not in collectives.STRATEGIES:
            raise ValueError(f"unknown merge_strategy {merge_strategy!r}")
        if merge_strategy in ("keyrange", "hier-kr-tree") \
                and getattr(job, "keyrange_merge", None) is None:
            raise ValueError(
                f"merge_strategy={merge_strategy!r} needs a job with a "
                "keyrange_merge hook (the CountTable wordcount family); "
                f"use 'tree'/'gather' for {type(job).__name__}")
        if merge_strategy.startswith("hier-") and len(axes) < 2:
            raise ValueError(
                f"merge_strategy={merge_strategy!r} composes two mesh "
                f"levels; the mesh has one axis ({axes[0]!r}) — use "
                "'tree'/'gather'/'keyrange' on single-axis meshes")
        # Data-plane telemetry (ISSUE 8): when on, step/step_many return
        # ``(state, DataStats)`` — the stats leaves are tiny uint32 scalars
        # per shard, a NON-donated second output the executor fetches at
        # group retirement (the completion token already proved the program
        # finished, so the fetch observes, never syncs).  Off (default):
        # the built programs are bit-identical to pre-ISSUE-8.  Support is
        # duck-typed by ``ops.datastats.supports`` (the hooks, or a
        # wrapper's forwarded ``data_stats_supported``).
        if data_stats:
            from mapreduce_tpu.ops import datastats

            if not datastats.supports(job):
                raise ValueError(
                    f"data_stats=True but {type(job).__name__} has no "
                    "map_chunk_stats_sharded/state_stats hooks")
        self.data_stats = bool(data_stats)
        self.merge_strategy = merge_strategy
        self._keyrange = merge_strategy == "keyrange"
        # The keyrange-family strategies return the job's keyrange RESULT
        # shape (wordcount family: a plain replicated CountTable), so any
        # further fold of their output — the hier outer tree legs, the
        # overlap accumulator — must use the job's result-shape merge.
        self._kr_family = merge_strategy in ("keyrange", "hier-kr-tree")
        self._result_merge = getattr(job, "keyrange_result_merge", None) \
            if self._kr_family else None
        if self._kr_family and self._result_merge is None:
            self._result_merge = job.merge
        # Multi-axis meshes reduce level by level (innermost = fastest link
        # first); single-axis meshes use the chosen strategy directly.
        # Keyrange flattens the axes inside its single all_to_all round
        # (the job hook receives the full axis tuple); the hier-*
        # placements compose a strategy per level (_merge_local).
        self._collective = None if self._kr_family else (
            functools.partial(
                collectives.hierarchical_merge, strategy=merge_strategy)
            if len(axes) > 1 else
            (collectives.tree_merge if merge_strategy
             in ("tree", "hier-tree-tree") else collectives.gather_merge))
        self._sharded = mesh_mod.sharded(mesh, axes if len(axes) > 1 else axes[0])
        self._replicated = mesh_mod.replicated(mesh)
        self._step_fn = None
        self._step_many_fns: dict[tuple[int, int], Any] = {}  # (K, repeats)
        self._finish_fn = None
        self._partial_fns: dict[bool, Any] = {}  # with_accum -> program
        self._residual_fn = None
        self._reset_fn = None
        self._rep_fn = None

    def _device_index(self):
        """Linear index of this shard across all sharded axes (row-major)."""
        idx = jax.lax.axis_index(self.axes[0])
        for a in self.axes[1:]:
            idx = idx * axis_size(a) + jax.lax.axis_index(a)
        return idx.astype(jnp.uint32)

    @property
    def sharding(self):
        """The NamedSharding for per-device inputs/states (public: callers
        staging inputs ahead of step/step_many should place them with this)."""
        return self._sharded

    # -- state ---------------------------------------------------------------

    def init_states(self) -> Any:
        """Stacked per-device states, leading axis = mesh axis, sharded."""
        one = self.job.init_state()
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n_devices,) + x.shape), one)
        return jax.device_put(stacked, self._sharded)

    def init_states_global(self) -> Any:
        """Sharded initial state for multi-controller SPMD (a mesh spanning
        several processes): no process can ``device_put`` to another
        process's devices, so the global program itself materializes the
        state and ``out_shardings`` places it.  Identical result to
        :meth:`init_states` on a single-process mesh."""
        job, n = self.job, self.n_devices

        def init():
            one = job.init_state()
            return jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), one)

        return jax.jit(init, out_shardings=self._sharded)()

    def replicate_to_host(self, state: Any) -> Any:
        """Fetch a (possibly non-addressable) sharded state as host numpy:
        one jitted identity with replicated out_shardings (an all_gather
        over the mesh) makes every shard addressable on every process —
        the multi-host checkpoint fetch.  Costs one collective round."""
        if self._rep_fn is None:
            self._rep_fn = jax.jit(lambda s: s,
                                   out_shardings=self._replicated)
        return jax.tree.map(np.asarray, self._rep_fn(state))

    # -- compiled programs ---------------------------------------------------

    def _build_step(self):
        axis, job, n = self.axis, self.job, self.n_devices

        def local_step(state, chunks, step):
            local = jax.tree.map(lambda x: x[0], state)
            chunk = chunks[0]
            dev = self._device_index()
            chunk_id = step * jnp.uint32(n) + dev
            if self.data_stats:
                update, stats = job.map_chunk_stats_sharded(
                    chunk, chunk_id, axis, dev)
                new = job.combine(local, update)
                stats = job.state_stats(new, stats)
                return (jax.tree.map(lambda x: x[None], new),
                        jax.tree.map(lambda x: x[None], stats))
            update = _map_with_axis(job, chunk, chunk_id, axis, dev)
            new = job.combine(local, update)
            return jax.tree.map(lambda x: x[None], new)

        fn = shard_map(
            local_step, mesh=self.mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=(P(axis), P(axis)) if self.data_stats else P(axis),
            check_vma=False,
        )
        # Explicit in_shardings: without them XLA may propagate a sharding
        # onto the 0-d step scalar (observed with data-dependent lax.cond in
        # a job's map), and a partitioned spec on a rank-0 input breaks the
        # second dispatch's argument resharding.
        return jax.jit(fn, donate_argnums=(0,),
                       in_shardings=(self._sharded, self._sharded,
                                     self._replicated))

    def _build_step_many(self, k: int, repeats: int = 1):
        axis, job, n = self.axis, self.job, self.n_devices

        def local_many(state, chunks, step0):
            local = jax.tree.map(lambda x: x[0], state)
            my = chunks[0]  # (k, chunk_bytes) after shard_map
            dev = self._device_index()

            def chunk_at(j):
                # Cycle over the k resident chunks: pass r of `repeats`
                # re-reads them with fresh step indices (epoch semantics).
                return jax.lax.dynamic_index_in_dim(
                    my, (j % jnp.uint32(k)).astype(jnp.int32), keepdims=False)

            if self.data_stats:
                from mapreduce_tpu.ops import datastats

                def body_stats(carry, j):
                    st, acc = carry
                    chunk_id = (step0 + j) * jnp.uint32(n) + dev
                    update, stats = job.map_chunk_stats_sharded(
                        chunk_at(j), chunk_id, axis, dev)
                    return (job.combine(st, update),
                            datastats.add(acc, stats)), None

                (new, acc), _ = jax.lax.scan(
                    body_stats, (local, datastats.zeros()),
                    jnp.arange(k * repeats, dtype=jnp.uint32))
                acc = job.state_stats(new, acc)
                return (jax.tree.map(lambda x: x[None], new),
                        jax.tree.map(lambda x: x[None], acc))

            def body(st, j):
                chunk_id = (step0 + j) * jnp.uint32(n) + dev
                return job.combine(
                    st, _map_with_axis(job, chunk_at(j), chunk_id, axis,
                                       dev)), None

            new, _ = jax.lax.scan(
                body, local, jnp.arange(k * repeats, dtype=jnp.uint32))
            return jax.tree.map(lambda x: x[None], new)

        fn = shard_map(
            local_many, mesh=self.mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=(P(axis), P(axis)) if self.data_stats else P(axis),
            check_vma=False,
        )
        return jax.jit(fn, donate_argnums=(0,),
                       in_shardings=(self._sharded, self._sharded,
                                     self._replicated))

    def _merge_local(self, local):
        """The configured cross-device reduction of one local state —
        traced inside shard_map.  Returns the REPLICATED merged value:
        the job state shape for tree/gather, the job's keyrange RESULT
        shape for the keyrange family (finalize accepts both)."""
        job, axis = self.job, self.axis
        if self._keyrange:
            return job.keyrange_merge(local, axis)
        if self.merge_strategy == "hier-kr-tree":
            return collectives.hier_kr_tree_merge(
                local, job.keyrange_merge, self._result_merge, self.axes)
        return self._collective(local, job.merge, axis)

    def _fold_merged(self, latest, accum):
        """Fold the latest merged window into the accumulator — the
        overlap accumulator's monoid: the result-shape merge for the
        keyrange family, the job merge otherwise.  The LATEST value is
        operand ``a`` deliberately: counters are commutative, but jobs
        that keep one operand's coordination leaves (grep's line_carry,
        NGram's seam carry) keep ``a``'s — and the monolithic finish
        would report the stream-end value of those leaves."""
        return self._result_merge(latest, accum) if self._kr_family \
            else self.job.merge(latest, accum)

    def _build_finish(self):
        axis, job = self.axis, self.job

        def final(state):
            local = jax.tree.map(lambda x: x[0], state)
            return job.finalize(self._merge_local(local))

        fn = shard_map(
            final, mesh=self.mesh,
            in_specs=(P(axis),), out_specs=P(),
            check_vma=False,
        )
        return jax.jit(fn)

    def _build_partial(self, with_accum: bool):
        """The window-boundary partial collective (ISSUE 20 leg 2): merge
        the current local states across the mesh with the SAME configured
        strategy the finish uses, folding into the resident accumulator
        when one exists.  Replicated output; nothing finalized."""
        axis = self.axis

        def first(state):
            local = jax.tree.map(lambda x: x[0], state)
            return self._merge_local(local)

        def fold(accum, state):
            local = jax.tree.map(lambda x: x[0], state)
            return self._fold_merged(self._merge_local(local), accum)

        fn = shard_map(
            fold if with_accum else first, mesh=self.mesh,
            in_specs=(P(), P(axis)) if with_accum else (P(axis),),
            out_specs=P(),
            check_vma=False,
        )
        # No donation: the executor dispatches this async at a window
        # boundary and then resets the local table from the same buffers;
        # tables are small next to the staged input stream.
        return jax.jit(fn)

    def _build_residual(self):
        """Stream-end finish under overlap: merge the residual local
        states, fold the accumulator in, finalize — one program, so the
        final collective record stays one span like the monolithic path."""
        axis, job = self.axis, self.job

        def final(accum, state):
            local = jax.tree.map(lambda x: x[0], state)
            return job.finalize(
                self._fold_merged(self._merge_local(local), accum))

        fn = shard_map(
            final, mesh=self.mesh,
            in_specs=(P(), P(axis)), out_specs=P(),
            check_vma=False,
        )
        return jax.jit(fn)

    def _build_reset(self):
        """Post-partial local reset: every device returns to its init
        state — except jobs carrying cross-step seam context, whose
        ``partial_reset`` hook preserves it (NGram keeps the carry; the
        gram table itself was shipped by the partial merge)."""
        job = self.job
        axis = self.axis
        fn_hook = getattr(job, "partial_reset", None)

        def reset(state):
            local = jax.tree.map(lambda x: x[0], state)
            new = fn_hook(local) if fn_hook is not None else job.init_state()
            return jax.tree.map(lambda x: x[None], new)

        fn = shard_map(
            reset, mesh=self.mesh,
            in_specs=(P(axis),), out_specs=P(axis),
            check_vma=False,
        )
        return jax.jit(fn)

    # -- public API ----------------------------------------------------------

    def step(self, state: Any, chunks: jax.Array, step_index: int) -> Any:
        """One map+combine step.  ``chunks``: uint8[n_devices, chunk_bytes].

        With ``data_stats=True`` (construction-time) the return value is
        ``(new_state, DataStats)`` — the stats pytree's leaves are [D]
        uint32 scalars, non-donated, ready together with the state."""
        if self._step_fn is None:
            self._step_fn = self._build_step()
        chunks = jax.device_put(chunks, self._sharded)
        return self._step_fn(state, chunks, jnp.uint32(step_index))

    def step_many(self, state: Any, chunks: jax.Array, step_index: int,
                  repeats: int = 1) -> Any:
        """K map+combine steps in ONE dispatch via ``lax.scan``.

        ``chunks``: uint8[n_devices, K, chunk_bytes].  Equivalent to K calls
        of :meth:`step` with step indices ``step_index .. step_index+K-1``
        (chunk_ids match exactly), but amortizes per-dispatch overhead —
        which dominates under high-latency device links — over K steps.
        Compiles once per distinct (K, repeats).

        ``repeats > 1`` folds the K device-resident chunks ``repeats`` times
        (epoch semantics: pass r re-reads every chunk with fresh step
        indices ``step_index + r*K ..``), processing K*repeats chunks in one
        dispatch without re-staging — the multi-pass analogue of a training
        loop's epochs, and the lever that keeps per-dispatch overhead out of
        throughput measurements on high-latency links.
        """
        k = chunks.shape[1]
        key = (k, repeats)
        if key not in self._step_many_fns:
            self._step_many_fns[key] = self._build_step_many(k, repeats)
        chunks = jax.device_put(chunks, self._sharded)
        return self._step_many_fns[key](state, chunks, jnp.uint32(step_index))

    def finish(self, state: Any) -> Any:
        """Collective global merge + finalize.  Result is replicated."""
        if self._finish_fn is None:
            self._finish_fn = self._build_finish()
        return self._finish_fn(state)

    def partial_merge(self, accum: Any, state: Any) -> Any:
        """Window-boundary partial collective (ISSUE 20 leg 2): reduce
        the current per-device states across the mesh with the configured
        strategy and fold into ``accum`` (pass ``None`` for the first
        window).  Returns the new replicated accumulator — dispatched
        async by the executor so the DCN transfer overlaps the next
        window's ingest."""
        key = accum is not None
        if key not in self._partial_fns:
            self._partial_fns[key] = self._build_partial(key)
        return self._partial_fns[key](accum, state) if key \
            else self._partial_fns[key](state)

    def finish_residual(self, accum: Any, state: Any) -> Any:
        """Stream-end finish under overlap: merge the residual states,
        fold ``accum`` in, finalize.  With ``accum=None`` (no partial was
        ever dispatched) this is exactly :meth:`finish`."""
        if accum is None:
            return self.finish(state)
        if self._residual_fn is None:
            self._residual_fn = self._build_residual()
        return self._residual_fn(accum, state)

    def partial_reset(self, state: Any) -> Any:
        """Fresh per-device states after a partial merge shipped the old
        ones (jobs with cross-step seam context override ``partial_reset``
        to keep it — NGram's carry)."""
        if self._reset_fn is None:
            self._reset_fn = self._build_reset()
        return self._reset_fn(state)

    def run(self, batches, progress: Callable[[int], None] | None = None) -> Any:
        """Convenience: fold an iterable of [D, C] uint8 batches and finish."""
        state = self.init_states()
        for i, batch in enumerate(batches):
            state = self.step(state, batch, i)
            if progress is not None:
                progress(i)
        return self.finish(state)
