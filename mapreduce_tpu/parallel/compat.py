"""JAX API compatibility shims for the parallel layer.

The framework targets the modern ``jax.shard_map`` (jax >= 0.6, where it
moved out of ``jax.experimental`` and renamed ``check_rep`` to
``check_vma``), but the baked toolchain may carry an older jax where only
``jax.experimental.shard_map.shard_map`` exists.  One adapter owns the
difference so every call site (engine builds, tests) uses the modern
keyword surface unconditionally.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, check_vma keyword
    from jax import shard_map as _shard_map

    _VMA_KW = "check_vma"
except ImportError:  # older jax: experimental module, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    _VMA_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern keyword surface on any jax."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_VMA_KW: check_vma})


def axis_size(axis) -> int:
    """Static size of a bound mesh axis (or axis tuple), on any jax.

    ``jax.lax.axis_size`` only exists on newer jax; ``psum(1, axis)`` is
    the classic spelling and constant-folds to a Python int on every
    version (callers rely on the result being static: merge-round counts
    and power-of-two checks happen at trace time).
    """
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)
