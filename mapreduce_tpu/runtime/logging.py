"""Structured logging.

The reference's only observability is raw ``printf`` (``main.cu:166-218``,
SURVEY §5).  Here: a standard ``logging`` logger with an optional one-line
JSON formatter for machine consumption, plus helpers for progress lines.
"""

from __future__ import annotations

import json
import logging
import sys
import time


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if extra:
            obj.update(extra)
        return json.dumps(obj)


_TEXT_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def get_logger(name: str = "mapreduce_tpu", json_lines: bool | None = None,
               level: int | None = None) -> logging.Logger:
    """Named logger with the package's stderr handler attached once.

    ``json_lines`` and ``level`` RECONFIGURE the existing handler when
    passed explicitly; ``None`` (the default) keeps the current
    configuration.  Before this was a sentinel, both arguments were
    silently ignored on every call after the first (the handler was cached
    with the first caller's settings — ISSUE 2 satellite): a CLI asking
    for JSON lines after any library code had touched the logger kept
    human-format forever.  First call defaults: text format, INFO.
    """
    logger = logging.getLogger(name)
    ours = [h for h in logger.handlers if getattr(h, "_mr_handler", False)]
    if not ours:
        h = logging.StreamHandler(sys.stderr)
        h._mr_handler = True
        h._mr_json_lines = bool(json_lines)
        h.setFormatter(JsonFormatter() if json_lines else
                       logging.Formatter(_TEXT_FORMAT))
        logger.addHandler(h)
        logger.setLevel(logging.INFO if level is None else level)
        logger.propagate = False
        return logger
    h = ours[0]
    if json_lines is not None and bool(json_lines) != h._mr_json_lines:
        h._mr_json_lines = bool(json_lines)
        h.setFormatter(JsonFormatter() if json_lines else
                       logging.Formatter(_TEXT_FORMAT))
    if level is not None:
        logger.setLevel(level)
    return logger


def log_event(logger: logging.Logger, msg: str, **fields) -> None:
    logger.info(msg, extra={"fields": fields})
