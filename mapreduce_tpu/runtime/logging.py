"""Structured logging.

The reference's only observability is raw ``printf`` (``main.cu:166-218``,
SURVEY §5).  Here: a standard ``logging`` logger with an optional one-line
JSON formatter for machine consumption, plus helpers for progress lines.
"""

from __future__ import annotations

import json
import logging
import sys
import time


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if extra:
            obj.update(extra)
        return json.dumps(obj)


def get_logger(name: str = "mapreduce_tpu", json_lines: bool = False,
               level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(JsonFormatter() if json_lines else
                       logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s"))
        logger.addHandler(h)
        logger.setLevel(level)
        logger.propagate = False
    return logger


def log_event(logger: logging.Logger, msg: str, **fields) -> None:
    logger.info(msg, extra={"fields": fields})
