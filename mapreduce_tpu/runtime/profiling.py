"""Profiler hooks: XProf/Perfetto traces and named trace regions.

The reference has no tracing of any kind (``time.h`` is a dead include,
``main.cu:6``; SURVEY §5).  Here any run can capture a ``jax.profiler`` trace
— device timelines, XLA op breakdown, HBM usage — viewable in XProf /
Perfetto, plus cheap named host regions that show up on the same timeline.

Usage::

    with profiling.trace("/tmp/trace"):     # no-op when path is falsy
        with profiling.region("step"):
            state = engine.step(state, batch.data, batch.step)
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(path: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace under ``path`` (a directory).  Falsy path
    = no-op, so call sites can pass the flag through unconditionally."""
    if not path:
        yield
        return
    import jax

    with jax.profiler.trace(path):
        yield


@contextlib.contextmanager
def region(name: str) -> Iterator[None]:
    """A named region on the profiler timeline (cheap when not tracing)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def enable_compile_cache(cache_dir: Optional[str] = None) -> None:
    """Point JAX at a persistent compilation cache.

    First compiles of the streaming step are multi-minute programs; the cache
    makes every later same-shape run (CLI or bench, same process or not)
    skip them.  Default location: ``~/.cache/jax_mapreduce``, overridable via
    ``MAPREDUCE_COMPILE_CACHE`` (set it empty to disable).  Best-effort: a
    cache failure must never take down a run.
    """
    import os

    if cache_dir is None:
        cache_dir = os.environ.get(
            "MAPREDUCE_COMPILE_CACHE",
            os.path.expanduser("~/.cache/jax_mapreduce"))
    if not cache_dir:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
