"""Checkpoint / resume for streaming runs.

The reference is single-shot batch with no persistence (SURVEY §5: all state
freed at exit, ``main.cu:219-220``).  For 100 GB-scale corpora the executor
periodically saves the per-device job state plus the ingest cursor, so a
failed run resumes from the last shard boundary instead of restarting.

Format: a single ``.npz`` (atomic rename on write) holding the job state's
flattened pytree leaves (ANY MapReduceJob state — count tables, sketched
states, grep scalars — not just tables), the ingest cursor (file offset +
step index), and the per-step row base offsets needed for string recovery.
Loading validates the leaves against a template of the running job's state,
so structural drift (different job kind, changed table capacity, sketched vs
plain) surfaces as :class:`CheckpointMismatch` instead of silent corruption.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Any

import jax
import numpy as np

from mapreduce_tpu.obs import registry as obs_registry


class CheckpointMismatch(RuntimeError):
    """The checkpoint was produced by an incompatible run configuration."""


class CheckpointCorrupt(RuntimeError):
    """The checkpoint file is torn or fails its integrity checksum
    (ISSUE 15 satellite): the bytes on disk are not the bytes that were
    written — a crash mid-save, bit rot, a truncating filesystem.
    Distinct from :class:`CheckpointMismatch` (a semantically DIFFERENT
    run's valid snapshot): corruption falls back to the previous good
    snapshot (:func:`load_resilient`), mismatch never does."""


def run_fingerprint(input_path: str, n_devices: int, chunk_bytes: int,
                    backend: str = "xla", pallas_max_token: int = 0,
                    byte_range: tuple[int, int] | None = None,
                    job_identity: str = "") -> dict:
    """Identity of a run: resuming under a different identity is an error.

    The input file is fingerprinted by size + a head/tail content hash, so a
    replaced or appended corpus is detected without rehashing 100 GB.  The
    backend (and its token-length envelope) is part of the identity because
    it changes counting semantics: the pallas backend drops >W tokens into
    ``dropped_*``, so resuming under the other backend would mix semantics
    mid-run.  Capacities are deliberately not in the dict: they are validated
    against the saved leaves' actual shapes (ground truth) at load.
    """
    paths = [input_path] if isinstance(input_path, (str, bytes, os.PathLike)) \
        else list(input_path)
    multi = len(paths) > 1
    size = 0
    h = hashlib.sha256()
    for p in paths:
        psize = os.path.getsize(p)
        size += psize
        if multi:  # member boundaries matter; single-file stays bit-compatible
            h.update(str(psize).encode())
        with open(p, "rb") as f:
            h.update(f.read(1 << 16))
            if psize > (1 << 16):
                f.seek(max(0, psize - (1 << 16)))
                h.update(f.read(1 << 16))
    return {"input_size": size, "input_hash": h.hexdigest(),
            "n_devices": n_devices, "chunk_bytes": chunk_bytes,
            "backend": backend,
            "pallas_max_token": pallas_max_token if backend == "pallas" else 0,
            "byte_range": list(byte_range) if byte_range else None,
            # What the accumulated numbers MEAN: two jobs can share a state
            # shape (any two grep patterns) yet be unresumable across each
            # other (MapReduceJob.identity).
            "job": job_identity}


# Values assumed for fingerprint keys absent from an older checkpoint's meta
# (i.e. the only behavior that existed before the key was introduced).
_FINGERPRINT_DEFAULTS = {"backend": "xla", "pallas_max_token": 0,
                         "byte_range": None}

# Snapshot format version, written into __meta.  v1 (pre-versioning) stored
# leaves under field names; v2 stores them as positional __leaf_i.  Bump on
# any layout change so load() can name the real cause instead of misreporting
# an old snapshot as "different state structure".
_FORMAT = 2


def save(path: str, state: Any, step: int, offset: int,
         bases: np.ndarray, fingerprint: dict | None = None,
         file_index: int | None = None) -> None:
    """Atomically persist a run snapshot.

    Args:
      state: the job's stacked per-device state — any pytree of arrays
        (leaves shaped [D, ...]).
      step: next step index to execute.
      offset: file offset ingest should resume from.
      bases: int64[steps_done, D] absolute row base offsets so far.
      fingerprint: run identity from :func:`run_fingerprint`.
      file_index: corpus-member index of the last batch folded into
        ``state`` (multi-file runs).  Jobs with cross-row sequential state
        (grep's line carry) reset it at file boundaries; a resumed run needs
        this to know the snapshot sits at a boundary — without it the
        boundary hook silently never fires after resume and the carry leaks
        across the seam (round-2 advisor finding).
    """
    leaves = jax.tree.leaves(state)
    payload = {f"__leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    payload["__step"] = np.int64(step)
    payload["__offset"] = np.int64(offset)
    payload["__bases"] = np.asarray(bases, dtype=np.int64)
    payload["__file_index"] = np.int64(-1 if file_index is None else file_index)
    payload["__meta"] = np.frombuffer(
        json.dumps({**(fingerprint or {}), "format": _FORMAT}).encode(),
        dtype=np.uint8)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    t0 = time.perf_counter()
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        # Integrity (ISSUE 15 satellite): checksum the snapshot as
        # written, BEFORE it becomes the live checkpoint, and keep the
        # previous good snapshot as `.prev` — a torn/corrupt file at
        # resume falls back to it instead of crashing the relaunch.
        digest, nbytes = _file_sha256(tmp)
        if os.path.exists(path):
            _rotate_previous(path)
        os.replace(tmp, path)
        _write_integrity(path, digest, nbytes)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    # Checkpoint cadence cost, visible in the same snapshot as the stream
    # phases (a save that rivals a superstep means checkpoint_every is too
    # fine for the state size).
    reg = obs_registry.get_registry()
    reg.counter("checkpoint.saves").inc()
    reg.observe("checkpoint.save_seconds", time.perf_counter() - t0)
    try:
        reg.counter("checkpoint.bytes_written").inc(os.path.getsize(path))
    except OSError:
        pass


def load(path: str, template: Any = None,
         expect_fingerprint: dict | None = None
         ) -> tuple[Any, int, int, np.ndarray, int | None]:
    """Load a snapshot; returns (state, step, offset, bases, file_index).

    ``file_index`` is the corpus-member index the snapshot's last folded
    batch came from (None for snapshots predating the field, or single-file
    runs saved before any batch).

    ``template`` is a pytree with the running job's state structure (e.g.
    ``Engine.init_states()`` output); the snapshot's leaves are validated
    against its leaves' shapes and dtypes and unflattened into the same
    structure.  Raises :class:`CheckpointMismatch` when the snapshot has a
    different state structure — a different job kind, a sketched run
    resuming a plain run's snapshot (or vice versa), a changed table
    capacity or device count — or, with ``expect_fingerprint``, a different
    input file / chunk geometry.  Silently resuming across any of those
    would corrupt counts.

    ``template=None`` skips validation and returns the state as the flat
    list of saved leaves (inspection/debugging only).
    """
    t_leaves, treedef = (None, None) if template is None \
        else jax.tree.flatten(template)
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta"]).decode() or "{}") if "__meta" in z else {}
        fmt = meta.get("format", 1)
        if fmt > _FORMAT:
            raise CheckpointMismatch(
                f"checkpoint {path} was written by a newer version of this "
                f"framework (snapshot format {fmt}, this build reads up to "
                f"{_FORMAT}); upgrade, or delete the checkpoint")
        legacy_keys = [k for k in z.files if not k.startswith("__")]
        if legacy_keys:
            raise CheckpointMismatch(
                f"checkpoint {path} was written by an older version of this "
                f"framework (format {fmt}: field-named leaves "
                f"{sorted(legacy_keys)[:4]}); delete it and restart the run")
        if expect_fingerprint:
            for key, want in expect_fingerprint.items():
                # Checkpoints written before a key joined the fingerprint get
                # that key's historical default (there was only one behavior
                # then), so upgrading mid-run never forces a restart.
                got = meta.get(key, _FINGERPRINT_DEFAULTS.get(key))
                if got != want:
                    raise CheckpointMismatch(
                        f"checkpoint {path} was written with {key}={got!r}, "
                        f"this run has {key}={want!r}; delete the checkpoint "
                        f"or rerun with the original configuration")
        n_saved = sum(1 for k in z.files if k.startswith("__leaf_"))
        fi = int(z["__file_index"]) if "__file_index" in z.files else -1
        file_index = None if fi < 0 else fi
        if template is None:
            leaves = [z[f"__leaf_{i}"] for i in range(n_saved)]
            return (leaves, int(z["__step"]), int(z["__offset"]), z["__bases"],
                    file_index)
        if n_saved != len(t_leaves):
            raise CheckpointMismatch(
                f"checkpoint {path} holds a different state structure "
                f"({n_saved} leaves vs this job's {len(t_leaves)} — e.g. a "
                f"sketched run resuming a plain run's snapshot, or a "
                f"different job kind); delete the checkpoint or rerun with "
                f"the original configuration")
        leaves = []
        for i, want in enumerate(t_leaves):
            got = z[f"__leaf_{i}"]
            if tuple(got.shape) != tuple(want.shape) or got.dtype != np.dtype(want.dtype):
                raise CheckpointMismatch(
                    f"checkpoint {path} leaf {i} is {got.dtype}{got.shape}, "
                    f"this run expects {np.dtype(want.dtype)}{tuple(want.shape)} "
                    f"(changed capacity, device count, or sketch precision); "
                    f"delete the checkpoint or rerun with the original "
                    f"configuration")
            leaves.append(got)
        state = jax.tree.unflatten(treedef, leaves)
        return (state, int(z["__step"]), int(z["__offset"]), z["__bases"],
                file_index)


def exists(path: str) -> bool:
    """Resume gate: True when a resumable snapshot is present — the live
    ``path``, or only the previous good ``.prev`` (a crash landed inside
    :func:`save`'s rename-fallback rotation, leaving ``path`` absent);
    :func:`load_resilient` then loads whichever is intact."""
    return os.path.exists(path) or os.path.exists(previous_path(path))


# -- snapshot integrity (ISSUE 15 satellite) ---------------------------------


def integrity_path(path: str) -> str:
    """The checksum sidecar next to a snapshot: ``ck.npz`` ->
    ``ck.npz.sum`` (JSON: sha256, bytes, format)."""
    return path + ".sum"


def previous_path(path: str) -> str:
    """The previous good snapshot, rotated aside by :func:`save`."""
    return path + ".prev"


def _rotate_previous(path: str) -> None:
    """Rotate the live snapshot (and its sidecar) aside to ``.prev``
    without ever leaving ``path`` empty: hard-link the current inode to
    a temp name and rename the link over ``.prev``, so the caller's
    final rename of the new snapshot over ``path`` is the only mutation
    of ``path`` — a hard kill anywhere in the sequence leaves a loadable
    snapshot at ``path``.  Where the filesystem refuses hard links,
    falls back to the rename rotation, whose crash window (``path``
    absent, good ``.prev``) is covered by :func:`exists` and
    :func:`load_resilient` consulting ``.prev``.

    The ``.sum`` sidecar rotates by RENAME deliberately: a missing
    sidecar is safe (:func:`verify` -> None, the snapshot still loads)
    but a stale one is not — were the old sidecar left at
    ``integrity_path(path)``, a kill between the caller's npz rename
    and its new-sidecar write would pair the NEW snapshot with the OLD
    digest, and a perfectly good checkpoint would read as corrupt."""
    prev = previous_path(path)
    tmp_link = prev + ".tmp"
    try:
        if os.path.exists(tmp_link):
            os.unlink(tmp_link)
        os.link(path, tmp_link)
        os.replace(tmp_link, prev)
    except OSError:
        os.replace(path, prev)
    if os.path.exists(integrity_path(path)):
        os.replace(integrity_path(path), integrity_path(prev))


def _file_sha256(path: str) -> tuple[str, int]:
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            h.update(block)
            n += len(block)
    return h.hexdigest(), n


def _write_integrity(path: str, digest: str, nbytes: int) -> None:
    """Atomic sidecar write (tmp + rename, like the snapshot itself)."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".sum.tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump({"sha256": digest, "bytes": nbytes,
                       "format": _FORMAT}, f)
        os.replace(tmp, integrity_path(path))
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def verify(path: str) -> bool | None:
    """Checksum a snapshot against its sidecar: True (intact), False
    (torn/corrupt — size or sha256 mismatch, unreadable sidecar), or
    None when no sidecar exists (a pre-integrity snapshot: unknown, and
    :func:`load_verified` falls back to np.load being able to parse it)."""
    sp = integrity_path(path)
    if not os.path.exists(sp):
        return None
    try:
        with open(sp, encoding="utf-8") as f:
            want = json.load(f)
        digest, nbytes = _file_sha256(path)
        return digest == want.get("sha256") and nbytes == want.get("bytes")
    except (OSError, ValueError):
        return False


def load_verified(path: str, template: Any = None,
                  expect_fingerprint: dict | None = None):
    """:func:`load` behind the integrity gate: a failing checksum — or a
    file so torn np.load cannot parse it — raises
    :class:`CheckpointCorrupt` (never a raw zipfile/OS error), while
    semantic rejections stay :class:`CheckpointMismatch`."""
    if verify(path) is False:
        raise CheckpointCorrupt(
            f"checkpoint {path} fails its integrity checksum "
            f"({integrity_path(path)}): the file on disk is not the file "
            "that was saved")
    try:
        return load(path, template=template,
                    expect_fingerprint=expect_fingerprint)
    except CheckpointMismatch:
        raise
    except Exception as e:  # torn zip/npz, short read, bad member
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable ({type(e).__name__}: {e}); "
            "likely torn by a crash mid-save") from e


def load_resilient(path: str, template: Any = None,
                   expect_fingerprint: dict | None = None):
    """Resume read with the previous-good fallback (ISSUE 15 satellite):
    returns ``(load-result-tuple, fallback)`` where ``fallback`` is None
    on the happy path, or a dict naming the corrupt file and the ``.prev``
    snapshot actually loaded.  Raises :class:`CheckpointCorrupt` only
    when the previous snapshot is also missing/corrupt (the caller then
    chooses between deleting the checkpoint and restarting)."""
    try:
        return (load_verified(path, template=template,
                              expect_fingerprint=expect_fingerprint), None)
    except CheckpointCorrupt as e:
        prev = previous_path(path)
        if not os.path.exists(prev):
            raise
        result = load_verified(prev, template=template,
                               expect_fingerprint=expect_fingerprint)
        reg = obs_registry.get_registry()
        reg.counter("checkpoint.corrupt_fallbacks").inc()
        return (result, {"corrupt": path, "loaded": prev, "error": str(e)})
