"""Checkpoint / resume for streaming runs.

The reference is single-shot batch with no persistence (SURVEY §5: all state
freed at exit, ``main.cu:219-220``).  For 100 GB-scale corpora the executor
periodically saves the per-device count state plus the ingest cursor, so a
failed run resumes from the last shard boundary instead of restarting.

Format: a single ``.npz`` (atomic rename on write) holding the stacked
CountTable leaves, the ingest cursor (file offset + step index), and the
per-step row base offsets needed for string recovery.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

import numpy as np

from mapreduce_tpu.ops.table import CountTable

_FIELDS = list(CountTable._fields)


class CheckpointMismatch(RuntimeError):
    """The checkpoint was produced by an incompatible run configuration."""


def run_fingerprint(input_path: str, n_devices: int, chunk_bytes: int,
                    backend: str = "xla", pallas_max_token: int = 0,
                    byte_range: tuple[int, int] | None = None) -> dict:
    """Identity of a run: resuming under a different identity is an error.

    The input file is fingerprinted by size + a head/tail content hash, so a
    replaced or appended corpus is detected without rehashing 100 GB.  The
    backend (and its token-length envelope) is part of the identity because
    it changes counting semantics: the pallas backend drops >W tokens into
    ``dropped_*``, so resuming under the other backend would mix semantics
    mid-run.  Table capacity is deliberately not in the dict: it is validated
    against the saved arrays' actual shape (ground truth) by the executor.
    """
    paths = [input_path] if isinstance(input_path, (str, bytes, os.PathLike)) \
        else list(input_path)
    multi = len(paths) > 1
    size = 0
    h = hashlib.sha256()
    for p in paths:
        psize = os.path.getsize(p)
        size += psize
        if multi:  # member boundaries matter; single-file stays bit-compatible
            h.update(str(psize).encode())
        with open(p, "rb") as f:
            h.update(f.read(1 << 16))
            if psize > (1 << 16):
                f.seek(max(0, psize - (1 << 16)))
                h.update(f.read(1 << 16))
    return {"input_size": size, "input_hash": h.hexdigest(),
            "n_devices": n_devices, "chunk_bytes": chunk_bytes,
            "backend": backend,
            "pallas_max_token": pallas_max_token if backend == "pallas" else 0,
            "byte_range": list(byte_range) if byte_range else None}


# Values assumed for fingerprint keys absent from an older checkpoint's meta
# (i.e. the only behavior that existed before the key was introduced).
_FINGERPRINT_DEFAULTS = {"backend": "xla", "pallas_max_token": 0,
                         "byte_range": None}


def save(path: str, state: CountTable, step: int, offset: int,
         bases: np.ndarray, fingerprint: dict | None = None,
         extras: dict[str, np.ndarray] | None = None) -> None:
    """Atomically persist a run snapshot.

    Args:
      state: stacked per-device CountTable (leaves shaped [D, ...]).
      step: next step index to execute.
      offset: file offset ingest should resume from.
      bases: int64[steps_done, D] absolute row base offsets so far.
      fingerprint: run identity from :func:`run_fingerprint`.
      extras: additional named arrays riding the snapshot (e.g. HLL sketch
        registers).  Round-tripped verbatim by :func:`load`.
    """
    payload = {f: np.asarray(getattr(state, f)) for f in _FIELDS}
    for k, v in (extras or {}).items():
        payload[f"__extra_{k}"] = np.asarray(v)
    payload["__step"] = np.int64(step)
    payload["__offset"] = np.int64(offset)
    payload["__bases"] = np.asarray(bases, dtype=np.int64)
    payload["__meta"] = np.frombuffer(
        json.dumps(fingerprint or {}).encode(), dtype=np.uint8)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str, expect_fingerprint: dict | None = None
         ) -> tuple[CountTable, int, int, np.ndarray, dict[str, np.ndarray]]:
    """Load a snapshot; returns (state, step, offset, bases, extras).

    ``extras`` round-trips whatever :func:`save` was given (empty dict for
    snapshots written without extras).  If ``expect_fingerprint`` is given,
    raises :class:`CheckpointMismatch` when the snapshot came from a
    different input file, device count, or chunk size — silently resuming
    across those would corrupt counts.
    """
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta"]).decode() or "{}") if "__meta" in z else {}
        if expect_fingerprint:
            for key, want in expect_fingerprint.items():
                # Checkpoints written before a key joined the fingerprint get
                # that key's historical default (there was only one behavior
                # then), so upgrading mid-run never forces a restart.
                got = meta.get(key, _FINGERPRINT_DEFAULTS.get(key))
                if got != want:
                    raise CheckpointMismatch(
                        f"checkpoint {path} was written with {key}={got!r}, "
                        f"this run has {key}={want!r}; delete the checkpoint "
                        f"or rerun with the original configuration")
        state = CountTable(**{f: z[f] for f in _FIELDS})
        extras = {k[len("__extra_"):]: z[k] for k in z.files
                  if k.startswith("__extra_")}
        return state, int(z["__step"]), int(z["__offset"]), z["__bases"], extras


def exists(path: str) -> bool:
    return os.path.exists(path)
