"""Platform forcing and reporting — the force-CPU idiom, in ONE place.

The ambient environment may pin JAX to a remote accelerator platform at
interpreter startup (a sitecustomize registering a remote PJRT plugin calls
``jax.config.update("jax_platforms", ...)`` before any user code runs), which
makes the ``JAX_PLATFORMS`` env var alone too late to redirect a run.  The
backend itself still initializes lazily, so ``jax.config.update`` lands as
long as no device has been touched yet.  That two-step — set the env var for
child processes, update the config for this process — previously lived as
three divergent copies (``__graft_entry__``, ``tests/conftest.py`` via the
former, ``tests/multihost_worker.py``); they now all call :func:`force_cpu`.

Reference analogue: none — the reference runs wherever nvcc pointed it
(``main.cu`` has no device selection at all); SURVEY §5 config/flag system.
"""

from __future__ import annotations

import os
import re


def force_cpu(min_devices: int = 0, verify: bool = True):
    """Force the CPU platform hermetically; verify the force landed.

    ``min_devices > 0`` additionally guarantees that many virtual CPU devices
    (``--xla_force_host_platform_device_count``, raised but never lowered —
    an ambient larger value keeps working).  Returns the imported ``jax``
    module.  Raises ``RuntimeError`` if a non-CPU backend was already
    initialized (the config update then cannot redirect device resolution —
    proceeding would silently dial the platform the caller asked to escape).

    ``verify=False`` skips the check for callers that must not initialize
    the backend yet (``jax.distributed.initialize()`` requires a pristine
    runtime); they own verifying the platform after their own init.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"  # children inherit the request
    if min_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None:
            flags = (flags +
                     f" --xla_force_host_platform_device_count={min_devices}").strip()
        elif int(m.group(1)) < min_devices:
            flags = flags[: m.start(1)] + str(min_devices) + flags[m.end(1):]
        os.environ["XLA_FLAGS"] = flags

    import jax

    if (jax.config.jax_platforms or "") != "cpu":
        jax.config.update("jax_platforms", "cpu")
    if not verify:
        return jax
    backend = jax.default_backend()  # initializes the (cpu) backend: verify
    if backend != "cpu":
        raise RuntimeError(
            f"cpu was requested but the {backend!r} JAX backend was already "
            "initialized before the platform could be forced; set "
            "JAX_PLATFORMS=cpu in the environment before starting python")
    if min_devices and len(jax.devices()) < min_devices:
        raise RuntimeError(
            f"need {min_devices} virtual CPU devices, have "
            f"{len(jax.devices())}: xla_force_host_platform_device_count "
            "landed after backend init")
    return jax


def effective_platforms() -> str:
    """The platform string JAX will actually dial, lowercase ('' = resolve a
    local backend).  Reads the CONFIG first — the env var neither redirects a
    pinned process nor predicts what an unpinned one resolves."""
    import jax

    return (jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")).lower()
