"""Throughput metrics and per-phase timing.

The reference has no timing at all (``time.h`` included at ``main.cu:6`` but
never called — SURVEY §5 "Tracing/profiling: absent").  The TPU build reports
the driver-defined BASELINE metrics: bytes ingested, words counted, GB/s and
words/sec per phase and end-to-end.
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class PhaseTimer:
    """Accumulates wall-clock per named phase.

    ``stop`` is safe on a never-started (or already-stopped) phase: it
    returns 0.0 and accumulates nothing.  Exception paths hit this
    constantly — the executor's dispatch stops ``"dispatch"`` in a
    ``finally`` that also runs when ``start`` itself never executed, and
    the bare ``KeyError`` the old ``_open.pop(name)`` raised there would
    REPLACE the real device failure being propagated (ISSUE 2 satellite).
    Restarting an open phase discards the earlier start (last wins).
    """

    phases: dict = dataclasses.field(default_factory=dict)
    _open: dict = dataclasses.field(default_factory=dict)

    def start(self, name: str) -> None:
        self._open[name] = time.perf_counter()

    def stop(self, name: str) -> float:
        t0 = self._open.pop(name, None)
        if t0 is None:
            return 0.0
        dt = time.perf_counter() - t0
        self.phases[name] = self.phases.get(name, 0.0) + dt
        return dt

    def running(self, name: str) -> bool:
        return name in self._open

    def __getitem__(self, name: str) -> float:
        return self.phases.get(name, 0.0)


@dataclasses.dataclass(frozen=True)
class RunMetrics:
    """End-of-run throughput summary (the BASELINE.json headline numbers)."""

    bytes_processed: int
    words_counted: int
    elapsed_s: float
    phases: dict

    @property
    def gb_per_s(self) -> float:
        return self.bytes_processed / 1e9 / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def words_per_s(self) -> float:
        return self.words_counted / self.elapsed_s if self.elapsed_s else 0.0

    def as_dict(self) -> dict:
        return {
            "bytes": self.bytes_processed,
            "words": self.words_counted,
            "elapsed_s": round(self.elapsed_s, 4),
            "gb_per_s": round(self.gb_per_s, 4),
            "words_per_s": round(self.words_per_s, 1),
            "phases": {k: round(v, 4) for k, v in self.phases.items()},
        }
