"""Device reachability probing with hard deadlines (wedged-relay defense).

The bench/dev chip sits behind a shared relay that can wedge indefinitely (a
killed client leaving a claimed session blocks every subsequent device op,
including ``jax.devices()``) — and a hung device op is not interruptible from
Python.  So reachability is tested in a *subprocess* with a deadline, and the
child is NEVER killed on timeout: killing a client mid-claim is exactly what
wedges the relay for everyone.  A slow-but-alive probe is left running and
re-checked on later attempts; on final give-up it is left to finish (and
release its claim) on its own.

Used by ``bench.py`` (retry/backoff before staging) and the CLI
(pre-flight deadline so ``./main`` fails fast with a message instead of
hanging forever — the reference program at least runs unattended,
``main.cu:164-222``).
"""

from __future__ import annotations

import subprocess
import sys
import time

PROBE_CODE = ("import jax, jax.numpy as jnp; "
              "jnp.zeros(8).block_until_ready(); "
              "print('PLATFORM=' + jax.devices()[0].platform)")


def _probe_outcome(proc) -> tuple[str | None, str | None]:
    """(platform | None, error) from a finished probe process."""
    out, err = proc.stdout.read(), proc.stderr.read()
    if proc.returncode != 0:
        lines = (err or "").strip().splitlines() or ["(no stderr)"]
        # Prefer the actual exception line over JAX's traceback-filtering
        # notice (which lands last in filtered tracebacks).
        msg = next((ln for ln in reversed(lines) if "Error" in ln), lines[-1])
        return None, f"probe rc={proc.returncode}: {msg.strip()[:200]}"
    for line in (out or "").splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], None
    return None, "probe printed no platform"


def probe_once(timeout_s: float) -> tuple[str | None, str | None]:
    """One bounded probe attempt: (platform | None, error | None).

    The CLI's pre-flight check: a definitive fast failure (bad platform
    config) and a hang (wedged relay) both surface within the deadline with
    no retry loop.  The child is left running on timeout (see module
    docstring).
    """
    proc = subprocess.Popen([sys.executable, "-c", PROBE_CODE],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"no response after {timeout_s:.0f}s; wedged TPU relay?"
    return _probe_outcome(proc)


def wait_for_device(budget_s: float, probe_timeout_s: float,
                    log=None) -> tuple[str | None, list[dict]]:
    """Probe until the device answers or the budget runs out.

    Returns (platform | None, attempts): attempts is a structured record
    (elapsed seconds, outcome) suitable for a failure report, so a wedged
    window shows N dated retries rather than one silent death.  ``log``
    (optional callable) receives progress strings between retries.
    """
    attempts: list[dict] = []
    t_start = time.perf_counter()
    delay, deadline = 30.0, time.monotonic() + budget_s
    proc = None
    while True:
        if proc is None:
            proc = subprocess.Popen([sys.executable, "-c", PROBE_CODE],
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.PIPE, text=True)
        try:
            proc.wait(timeout=min(probe_timeout_s,
                                  max(1.0, deadline - time.monotonic())))
        except subprocess.TimeoutExpired:
            platform, err = None, "probe still pending (left running, not killed)"
        else:
            platform, err = _probe_outcome(proc)
            proc = None  # finished: next attempt spawns fresh
        attempts.append({"t_s": round(time.perf_counter() - t_start, 1),
                         "ok": platform is not None,
                         **({"platform": platform} if platform else {"error": err})})
        if platform is not None:
            return platform, attempts
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None, attempts
        pause = min(delay, remaining)
        if log is not None:
            log(f"device probe failed ({err}); retrying in {pause:.0f}s "
                f"({remaining:.0f}s of retry budget left)")
        time.sleep(pause)
        delay = min(delay * 2, 300.0)
