"""Device reachability probing with hard deadlines (wedged-relay defense).

The bench/dev chip sits behind a shared relay that can wedge indefinitely (a
killed client leaving a claimed session blocks every subsequent device op,
including ``jax.devices()``) — and a hung device op is not interruptible from
Python.  So reachability is tested in a *subprocess* with a deadline, and the
child is NEVER killed on timeout: killing a client mid-claim is exactly what
wedges the relay for everyone.  A slow-but-alive probe is left running and
re-checked on later attempts; on final give-up it is left to finish (and
release its claim) on its own.

Used by ``bench.py`` (retry/backoff before staging) and the CLI
(pre-flight deadline so ``./main`` fails fast with a message instead of
hanging forever — the reference program at least runs unattended,
``main.cu:164-222``).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

PROBE_CODE = ("import jax, jax.numpy as jnp; "
              "jnp.zeros(8).block_until_ready(); "
              "print('PLATFORM=' + jax.devices()[0].platform)")


def _spawn(platforms: str | None):
    """Launch one probe child.  ``platforms`` pins the child's JAX_PLATFORMS
    so the probe dials the same platform the caller's run will — the caller's
    pin may live only in jax.config (in-process), which a child inheriting
    the bare env would not see."""
    env = None if platforms is None else {**os.environ,
                                          "JAX_PLATFORMS": platforms}
    return subprocess.Popen([sys.executable, "-c", PROBE_CODE],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env)


def _probe_outcome(proc) -> tuple[str | None, str | None]:
    """(platform | None, error) from a finished probe process."""
    out, err = proc.stdout.read(), proc.stderr.read()
    if proc.returncode != 0:
        lines = (err or "").strip().splitlines() or ["(no stderr)"]
        # Prefer the actual exception line over JAX's traceback-filtering
        # notice (which lands last in filtered tracebacks).
        msg = next((ln for ln in reversed(lines) if "Error" in ln), lines[-1])
        return None, f"probe rc={proc.returncode}: {msg.strip()[:200]}"
    for line in (out or "").splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], None
    return None, "probe printed no platform"


def probe_once(timeout_s: float,
               platforms: str | None = None) -> tuple[str | None, str | None]:
    """One bounded probe attempt: (platform | None, error | None).

    The CLI's pre-flight check: a definitive fast failure (bad platform
    config) and a hang (wedged relay) both surface within the deadline with
    no retry loop.  The child is left running on timeout (see module
    docstring).
    """
    proc = _spawn(platforms)
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"no response after {timeout_s:.0f}s; wedged TPU relay?"
    return _probe_outcome(proc)


def wait_for_device(budget_s: float, probe_timeout_s: float,
                    log=None, platforms: str | None = None
                    ) -> tuple[str | None, list[dict]]:
    """Probe until the device answers or the budget runs out.

    EVERY attempt spawns a fresh probe child (VERDICT round 2: re-waiting on
    one hung child turns the whole budget into N observations of the same
    wedged claim, so a relay that recovers mid-budget is never caught).
    Hung children are left running, never killed — killing a client
    mid-claim is what wedges the relay — and any of them finishing
    successfully counts: before each verdict the older pending probes are
    polled too.

    Returns (platform | None, attempts): attempts is a structured record
    (elapsed seconds, outcome) suitable for a failure report, so a wedged
    window shows N dated retries rather than one silent death.  ``log``
    (optional callable) receives progress strings between retries.
    """
    attempts: list[dict] = []
    t_start = time.perf_counter()
    delay, deadline = 30.0, time.monotonic() + budget_s
    pending: list = []
    spawned = 0
    while True:
        proc = _spawn(platforms)
        pending.append(proc)
        spawned += 1
        try:
            proc.wait(timeout=min(probe_timeout_s,
                                  max(1.0, deadline - time.monotonic())))
        except subprocess.TimeoutExpired:
            platform, err = None, (
                f"probe still pending ({spawned} spawned so far, "
                f"{len(pending)} unfinished, left running, not killed)")
        else:
            platform, err = _probe_outcome(proc)
            pending.remove(proc)
        if platform is None:
            # An OLDER probe may have gotten through while we waited on the
            # newest (e.g. the relay drained its claim queue in order).
            for p in [p for p in pending if p.poll() is not None]:
                pending.remove(p)
                got, _ = _probe_outcome(p)
                if got is not None:
                    platform, err = got, None
                    break
        attempts.append({"t_s": round(time.perf_counter() - t_start, 1),
                         "ok": platform is not None,
                         **({"platform": platform} if platform else {"error": err})})
        if platform is not None:
            return platform, attempts
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None, attempts
        pause = min(delay, remaining)
        if log is not None:
            log(f"device probe failed ({err}); retrying in {pause:.0f}s "
                f"({remaining:.0f}s of retry budget left)")
        time.sleep(pause)
        delay = min(delay * 2, 300.0)
