"""Fault injection + unified failure policy (ISSUE 15).

The reference's only failure mode was "the CUDA call returned an error and
the program died" (``main.cu``: unchecked ``cudaMalloc``/``cudaMemcpy``).
This module is the robustness layer the long-lived service needs instead:

* an **error taxonomy** (:func:`classify`): every exception crossing a
  named executor seam is one of ``transient`` / ``resource`` /
  ``permanent`` / ``preemption`` — the class, not the exception type,
  decides the policy outcome;
* a :class:`FailurePolicy`: per-class retry budgets with exponential
  backoff + deterministic jitter (replacing the executor's bare ``retry``
  counter), a wall-clock timeout on completion-token waits (a hung device
  reads as a typed fault instead of a silent stall), and the pre-registered
  **degradation ladder** for resource-classed failures
  (:data:`DEGRADATION_LADDER`);
* a :class:`FaultPlan`: seeded, deterministic fault injection at each
  named seam (:data:`SEAMS`).  Every fired fault is recorded as a
  ``fault`` ledger record (ledger v9), and :meth:`FaultPlan.from_ledger`
  rebuilds the exact plan from those records — any chaotic run can be
  replayed fault-for-fault from its own ledger.

Deliberately jax-free and stdlib-only (the ``obs/datahealth.py``
contract): ``tools/chaos.py`` loads this module by file path on boxes
with neither jax nor the package installed, and the chaos selftest checks
the backoff/ladder arithmetic against hand-computed values.

Determinism contract: every decision (does crossing ``(seam, index)``
fire?  which class?  how much jitter?) is a pure function of the plan /
policy seed and the crossing identity, via SHA-256 — no global RNG, no
wall clock — so a replay under the same plan produces the identical
fault sequence.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import Iterable, Optional

#: The named seams a streamed run crosses, in stream order.  The executor
#: checks the active :class:`FaultPlan` at each crossing; the plan counts
#: crossings PER SEAM, so ``(seam, index)`` names one exact moment of the
#: run deterministically.
SEAMS = (
    "reader-read",       # a batch leaving the prefetching reader
    "stage-acquire",     # host staging-buffer assembly for a group
    "h2d",               # host->device placement of the staged group
    "dispatch",          # the engine.step/step_many enqueue
    "token-wait",        # blocking on a group's completion token
    "checkpoint-save",   # the atomic snapshot write
    "checkpoint-load",   # resume-time snapshot read (real faults only)
    "ledger-append",     # a telemetry ledger record write
    "collective-finish", # the collective merge + finalize
    "process-kill",      # whole-process kill (multi-host chaos; os._exit)
)

#: The error taxonomy: every exception at a seam classifies to exactly one.
FAULT_CLASSES = ("transient", "resource", "permanent", "preemption")

#: The pre-registered graceful-degradation ladder (tentpole (3)): each
#: step names the config change a resource-classed failure storm buys,
#: cheapest capability given up first.  Every knob on it is bit-identical
#: by construction (PRs 6/11/12/3 each shipped the identity tests), so a
#: degraded run is SLOWER, never WRONG.
DEGRADATION_LADDER = (
    # (step name, config field, degraded value): applicable when the
    # field's current value differs from the degraded one.
    ("revert-geometry", "geometry", "default"),
    ("combiner-off", "combiner", "off"),
    ("map-split", "map_impl", "split"),
    ("sort-xla", "sort_impl", "xla"),
)


# ---------------------------------------------------------------------------
# typed faults
# ---------------------------------------------------------------------------


class FaultError(RuntimeError):
    """A typed fault at a named seam.  ``injected=True`` marks faults the
    :class:`FaultPlan` fired (chaos); real exceptions are *classified*
    (:func:`classify`) rather than wrapped, so their tracebacks survive."""

    fault_class = "transient"

    def __init__(self, message: str, *, seam: str = "",
                 index: Optional[int] = None, injected: bool = False):
        super().__init__(message)
        self.seam = seam
        self.index = index
        self.injected = injected


class TransientFault(FaultError):
    """Worth retrying as-is: flaky I/O, a dropped dispatch, a one-off."""

    fault_class = "transient"


class ResourceFault(FaultError):
    """The configuration is too hungry for the hardware right now (OOM,
    VMEM spill storm, repeated kernel fault): retrying the same program
    tends to fail the same way — the degradation ladder is the cure."""

    fault_class = "resource"


class PermanentFault(FaultError):
    """Retrying is useless (bad config, corrupt input, programming
    error): fail loudly and immediately."""

    fault_class = "permanent"


class PreemptionFault(FaultError):
    """The platform is taking the machine back: drain the in-flight
    window, checkpoint, and exit cleanly with a resumable cursor."""

    fault_class = "preemption"


class TokenTimeout(FaultError):
    """A completion-token wait exceeded ``FailurePolicy.token_timeout_s``:
    the device (or its relay link) is hung.  Transient — the replay path
    re-dispatches from the window anchor."""

    fault_class = "transient"


class Preempted(Exception):
    """Clean preemption exit (NOT a failure): the stream drained, the
    snapshot (if configured) was saved, and ``cursor_bytes``/``step`` say
    exactly where a relaunch resumes.  Drivers treat this as an orderly
    shutdown — no flight dump, no failure record."""

    def __init__(self, *, step: int, cursor_bytes: int,
                 checkpoint_path: Optional[str] = None,
                 checkpointed: bool = False):
        self.step = int(step)
        self.cursor_bytes = int(cursor_bytes)
        self.checkpoint_path = checkpoint_path
        self.checkpointed = bool(checkpointed)
        where = f"step {step}, cursor {cursor_bytes}"
        how = (f"checkpointed to {checkpoint_path}; relaunch to resume"
               if checkpointed else
               "no checkpoint configured; relaunch restarts the stream")
        super().__init__(f"preempted at {where} ({how})")


#: Exception types that classify as permanent without message matching:
#: config/programming errors where a retry re-runs the same bug.
_PERMANENT_TYPES = (ValueError, TypeError, KeyError, IndexError,
                    AttributeError, AssertionError, NotImplementedError)

#: Substrings (lowercased) that mark a resource-classed failure in real
#: runtime errors (XLA raises RESOURCE_EXHAUSTED through RuntimeError).
_RESOURCE_MARKERS = ("resource_exhausted", "resource exhausted",
                     "out of memory", "vmem", "allocation failure",
                     "failed to allocate")

#: 'OOM' only as a whole word ('OOM when allocating'), never as a
#: substring of 'bloom'/'room'/'zoom' — a bare `in` test misclassified
#: those as resource and walked the degradation ladder over them.
_OOM_RE = re.compile(r"\boom\b")

_PREEMPTION_MARKERS = ("preempt", "maintenance event", "sigterm")


def classify(exc: BaseException) -> str:
    """Exception -> taxonomy class.  Typed faults carry their class;
    real exceptions classify by type then by message markers; anything
    unrecognized is ``transient`` — the optimistic default that preserves
    the legacy ``retry=N`` semantics (the old counter retried *any*
    exception)."""
    if isinstance(exc, FaultError):
        return exc.fault_class
    if isinstance(exc, KeyboardInterrupt):
        return "preemption"
    # Type beats message: a ValueError('bad bloom_bits') or
    # KeyError('room_id') is a programming error whatever substrings its
    # message happens to contain — real OOM/preemption signals arrive as
    # RuntimeError-shaped runtime exceptions, never these types.
    if isinstance(exc, _PERMANENT_TYPES):
        return "permanent"
    msg = str(exc).lower()
    if any(marker in msg for marker in _RESOURCE_MARKERS) \
            or _OOM_RE.search(msg):
        return "resource"
    for marker in _PREEMPTION_MARKERS:
        if marker in msg:
            return "preemption"
    return "transient"


_FAULT_TYPES = {"transient": TransientFault, "resource": ResourceFault,
                "permanent": PermanentFault, "preemption": PreemptionFault}


def make_fault(fault_class: str, seam: str, index: int) -> FaultError:
    """The injected-fault constructor the plan fires."""
    cls = _FAULT_TYPES[fault_class]
    return cls(f"injected {fault_class} fault at seam {seam!r} "
               f"(crossing {index})", seam=seam, index=index, injected=True)


# ---------------------------------------------------------------------------
# deterministic randomness
# ---------------------------------------------------------------------------


def unit_hash(*parts) -> float:
    """Deterministic uniform in [0, 1) from the SHA-256 of the joined
    parts — the one randomness primitive of this module (plan firing
    decisions, class draws, backoff jitter all come through here, so a
    replay reproduces every decision bit-for-bit)."""
    h = hashlib.sha256(":".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


# ---------------------------------------------------------------------------
# failure policy
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FailurePolicy:
    """Per-class retry budgets + backoff schedule (tentpole (2)).

    Replaces the executor's bare ``retry`` integer: ``retry=N`` resolves
    to a policy with transient and resource budgets of N (exactly the
    legacy semantics — unrecognized exceptions classify transient), while
    permanent failures never retry and preemption drains + checkpoints
    instead of retrying at all.

    Backoff before retry ``attempt`` (1-based) of a ``fault_class`` at a
    ``seam``::

        base   = min(backoff_max_s, backoff_base_s * backoff_factor**(attempt-1))
        jitter = 1 + jitter_frac * (2 * u - 1)      # u = unit_hash(...)
        sleep  = base * jitter

    Deterministic: ``u`` comes from :func:`unit_hash` over
    ``(seed, seam, fault_class, attempt)``, so two runs of the same plan
    back off identically (the chaos byte-identity harness relies on it,
    and ``tools/chaos.py --selftest`` checks the arithmetic by hand with
    ``jitter_frac=0``).

    ``token_timeout_s``: wall-clock bound on a completion-token wait; a
    wait past it raises :class:`TokenTimeout` (transient) instead of
    stalling forever.  ``None`` (default) keeps the plain blocking wait.

    ``degrade``: whether resource-classed exhaustion steps down the
    :data:`DEGRADATION_LADDER` (where the driver can rebuild the engine)
    before giving up.
    """

    transient_retries: int = 0
    resource_retries: int = 0
    permanent_retries: int = 0
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 5.0
    jitter_frac: float = 0.1
    token_timeout_s: Optional[float] = None
    degrade: bool = True
    seed: int = 0

    def __post_init__(self) -> None:
        for name in ("transient_retries", "resource_retries",
                     "permanent_retries"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0, "
                                 f"got {getattr(self, name)}")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff seconds must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_factor must be >= 1, got {self.backoff_factor}")
        if not 0.0 <= self.jitter_frac < 1.0:
            raise ValueError(
                f"jitter_frac must be in [0, 1), got {self.jitter_frac}")
        if self.token_timeout_s is not None and self.token_timeout_s <= 0:
            raise ValueError(
                f"token_timeout_s must be > 0 (or None), "
                f"got {self.token_timeout_s}")

    @classmethod
    def resolve(cls, obj, retry: int = 0) -> "FailurePolicy":
        """Normalize ``Config.failure_policy`` (None | dict | policy):
        ``None`` maps the legacy ``retry`` counter onto transient +
        resource budgets — the exact pre-ISSUE-15 semantics."""
        if obj is None:
            return cls(transient_retries=int(retry),
                       resource_retries=int(retry))
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            return cls(**obj)
        raise ValueError(
            f"failure_policy must be None, a FailurePolicy or a dict of "
            f"its fields, got {type(obj).__name__}")

    def budget(self, fault_class: str) -> int:
        """Retries allowed for one group/operation failing with this
        class.  Preemption never retries: the policy outcome is
        drain -> checkpoint -> clean exit, not another attempt."""
        return {"transient": self.transient_retries,
                "resource": self.resource_retries,
                "permanent": self.permanent_retries,
                "preemption": 0}.get(fault_class, self.transient_retries)

    @property
    def dispatch_budget(self) -> int:
        """The snapshot/replay machinery is armed when ANY retryable
        class has budget (the executor's legacy ``retry > 0`` gate)."""
        return max(self.transient_retries, self.resource_retries,
                   self.permanent_retries)

    def backoff_s(self, fault_class: str, attempt: int,
                  seam: str = "") -> float:
        """Deterministic backoff seconds before retry ``attempt``
        (1-based).  See the class docstring for the formula."""
        if attempt < 1:
            return 0.0
        base = min(self.backoff_max_s,
                   self.backoff_base_s * self.backoff_factor ** (attempt - 1))
        if not self.jitter_frac:
            return round(base, 6)
        u = unit_hash(self.seed, seam, fault_class, attempt)
        return round(base * (1.0 + self.jitter_frac * (2.0 * u - 1.0)), 6)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def next_degrade(current: dict) -> Optional[tuple[str, str, str]]:
    """The first :data:`DEGRADATION_LADDER` step still applicable to a
    config summary ``{geometry, combiner, map_impl, sort_impl}`` (label
    values, e.g. ``Config.geometry_label`` for geometry), or None when
    the ladder is exhausted.  Returns ``(step_name, field, degraded_value)``.
    Jax-free on purpose: ``tools/chaos.py`` walks ladders from fixture
    dicts, the executor applies the same step to the real Config."""
    for step, field, degraded in DEGRADATION_LADDER:
        value = current.get(field)
        if value is not None and value != degraded:
            return (step, field, degraded)
    return None


def ladder_walk(current: dict) -> list:
    """Every step the ladder would take from ``current`` until
    exhaustion, in order — the selftest's hand-checkable walk."""
    cur = dict(current)
    steps = []
    while True:
        nxt = next_degrade(cur)
        if nxt is None:
            return steps
        step, field, degraded = nxt
        cur[field] = degraded
        steps.append(step)


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------


def _parse_bool(v: str) -> bool:
    return v.lower() in ("1", "true", "yes")


class FaultPlan:
    """A seeded, deterministic injection schedule over the named seams.

    Spec grammar (comma-separated ``key=value`` tokens)::

        seed=42,rate=0.05                      # random: 5% of crossings
        seed=7,rate=1.0,seams=dispatch,max=3   # only dispatch, 3 faults
        classes=transient+resource             # classes the RNG draws from
        at=dispatch:3:resource                 # explicit one-shot events
        at=token-wait:1:preemption             # (repeatable)

    Random firing decides per crossing via
    ``unit_hash(seed, seam, index) < rate``; the class is a second
    deterministic draw.  Explicit ``at=`` events fire exactly at their
    ``(seam, crossing-index)`` regardless of ``rate``, which is how
    :meth:`from_ledger` replays a chaotic run fault-for-fault from its
    own ``fault`` records.  ``process-kill`` never fires from the random
    rate — only an explicit ``at=`` event (or ``seams=process-kill``)
    asks for a hard kill.

    The plan object carries runtime state (per-seam crossing counters,
    the fired-event log) — the CONFIG stores only the spec string, which
    stays hashable; :meth:`resolve` builds a fresh plan per run.
    """

    def __init__(self, *, seed: int = 0, rate: float = 0.0,
                 seams: Optional[Iterable[str]] = None,
                 classes: Iterable[str] = ("transient",),
                 max_faults: int = 0,
                 events: Iterable[tuple[str, int, str]] = ()):
        self.seed = int(seed)
        self.rate = float(rate)
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")
        # Random firing never targets process-kill unless asked by name.
        default_seams = tuple(s for s in SEAMS
                              if s not in ("process-kill", "checkpoint-load"))
        self.seams = tuple(seams) if seams is not None else default_seams
        for s in self.seams:
            if s not in SEAMS:
                raise ValueError(f"unknown seam {s!r} (expected one of "
                                 f"{', '.join(SEAMS)})")
        self.classes = tuple(classes)
        for c in self.classes:
            if c not in FAULT_CLASSES:
                raise ValueError(f"unknown fault class {c!r} (expected one "
                                 f"of {', '.join(FAULT_CLASSES)})")
        if not self.classes:
            raise ValueError("classes must not be empty")
        self.max_faults = int(max_faults)
        self.events: dict[tuple[str, int], str] = {}
        for seam, index, cls in events:
            if seam not in SEAMS:
                raise ValueError(f"unknown seam {seam!r} in event")
            if cls not in FAULT_CLASSES:
                raise ValueError(f"unknown fault class {cls!r} in event")
            self.events[(seam, int(index))] = cls
        # -- runtime state --
        self.counts: dict[str, int] = {}
        self.fired: list[dict] = []

    # -- construction ------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the spec grammar (see class docstring)."""
        if not isinstance(spec, str) or not spec.strip():
            raise ValueError(f"fault plan spec must be a non-empty string, "
                             f"got {spec!r}")
        kw: dict = {"events": []}
        for token in spec.split(","):
            token = token.strip()
            if not token:
                continue
            if "=" not in token:
                raise ValueError(f"bad fault-plan token {token!r} "
                                 "(expected key=value)")
            key, value = token.split("=", 1)
            key, value = key.strip(), value.strip()
            try:
                if key == "seed":
                    kw["seed"] = int(value)
                elif key == "rate":
                    kw["rate"] = float(value)
                elif key == "max":
                    kw["max_faults"] = int(value)
                elif key == "seams":
                    kw["seams"] = tuple(value.split("+"))
                elif key == "classes":
                    kw["classes"] = tuple(value.split("+"))
                elif key == "at":
                    seam, index, fcls = value.split(":")
                    kw["events"].append((seam, int(index), fcls))
                else:
                    raise ValueError(f"unknown fault-plan key {key!r}")
            except ValueError:
                raise
            except Exception as e:  # int()/split() shape errors
                raise ValueError(f"bad fault-plan token {token!r}: {e}")
        return cls(**kw)

    @classmethod
    def resolve(cls, spec) -> "Optional[FaultPlan]":
        """``Config.fault_plan`` -> a fresh plan (None stays None — the
        zero-cost disabled path: the executor guards every seam check
        with one ``is not None``)."""
        if spec is None:
            return None
        if isinstance(spec, cls):
            return spec
        return cls.from_spec(spec)

    @classmethod
    def from_ledger(cls, records: Iterable[dict],
                    run_id: Optional[str] = None) -> "FaultPlan":
        """Rebuild the exact plan a chaotic run executed, from its own
        ``fault`` ledger records (``injected: true`` only — classified
        real faults are observations, not schedule).  Replaying the
        returned plan over the same run reproduces the identical fault
        sequence (tested), because crossing indices are deterministic."""
        events = []
        for rec in _iter_injected_faults(records, run_id):
            seam, index = rec.get("seam"), rec.get("index")
            fcls = rec.get("fault_class")
            if seam in SEAMS and index is not None and fcls in FAULT_CLASSES:
                events.append((seam, int(index), fcls))
        return cls(events=events)

    @property
    def spec(self) -> str:
        """Canonical spec string (round-trips through :meth:`from_spec`);
        what ``run_start`` stamps so a ledger names its own chaos."""
        parts = [f"seed={self.seed}"]
        if self.rate:
            parts.append(f"rate={self.rate}")
            parts.append("seams=" + "+".join(self.seams))
            parts.append("classes=" + "+".join(self.classes))
        if self.max_faults:
            parts.append(f"max={self.max_faults}")
        for (seam, index), fcls in sorted(self.events.items()):
            parts.append(f"at={seam}:{index}:{fcls}")
        return ",".join(parts)

    # -- runtime -----------------------------------------------------------

    def decide(self, seam: str, index: int) -> Optional[str]:
        """Pure decision for one crossing (no state change): the fault
        class to fire, or None.  Explicit events win; then the seeded
        rate over the plan's seams, bounded by ``max_faults``."""
        explicit = self.events.get((seam, index))
        if explicit is not None:
            return explicit
        if not self.rate or seam not in self.seams:
            return None
        if self.max_faults and len(self.fired) >= self.max_faults:
            return None
        if unit_hash(self.seed, seam, index) >= self.rate:
            return None
        draw = unit_hash(self.seed, "class", seam, index)
        return self.classes[int(draw * len(self.classes)) % len(self.classes)]

    def check(self, seam: str) -> Optional[FaultError]:
        """One seam crossing: count it, and return the typed fault to
        raise when the plan says this crossing fails (the caller records
        the ``fault`` ledger record, then raises).  Returns None on the
        overwhelmingly common no-fault path."""
        index = self.counts.get(seam, 0)
        self.counts[seam] = index + 1
        fcls = self.decide(seam, index)
        if fcls is None:
            return None
        self.fired.append({"seam": seam, "index": index,
                           "fault_class": fcls})
        return make_fault(fcls, seam, index)


def _iter_injected_faults(records: Iterable[dict],
                          run_id: Optional[str]) -> Iterable[dict]:
    """The injected ``fault`` records of ONE run, in ledger order: the
    named ``run_id``, or the FIRST run found in an append-mode ledger
    (records without a ``run_id`` ride along — pre-election headers).
    The single selection rule :meth:`FaultPlan.from_ledger` and
    :func:`fired_sequence` both consume, so the rebuilt plan and the
    compared fired-sequence can never disagree on which records count."""
    chosen = run_id
    for rec in records:
        if not isinstance(rec, dict) or rec.get("kind") != "fault" \
                or not rec.get("injected"):
            continue
        if chosen is None:
            chosen = rec.get("run_id")
        if chosen is not None and rec.get("run_id") not in (None, chosen):
            continue
        yield rec


def fired_sequence(records: Iterable[dict],
                   run_id: Optional[str] = None) -> list:
    """The ``(seam, index, fault_class)`` tuples of a run's injected
    ``fault`` records, in ledger order — what the replay test compares
    between a chaotic run and its ledger-rebuilt rerun."""
    return [(rec.get("seam"), rec.get("index"), rec.get("fault_class"))
            for rec in _iter_injected_faults(records, run_id)]
