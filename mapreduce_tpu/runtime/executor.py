"""Streaming executor: files -> sharded device stream -> merged result.

The orchestration layer of the framework (reference analogue: the body of
``main()`` plus ``runMapReduce``, ``main.cu:133-222``), with the capabilities
the reference lacks (SURVEY §5): step retry on transient failure, periodic
checkpoint/resume, structured progress logging, and throughput metrics.

Flow per run:
  1. build (or accept) a data mesh and an Engine for the job;
  2. stream boundary-aligned [D, chunk_bytes] batches from the reader,
     folding each into device-resident per-device states (one jitted SPMD
     step; accumulators never round-trip to host);
  3. collectively merge + finalize;
  4. recover exact strings host-side from (chunk_id, pos, len) first-
     occurrence records against the memory-mapped source file.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import weakref
from typing import Any, Optional

import jax
import numpy as np

from mapreduce_tpu import constants
from mapreduce_tpu import obs
from mapreduce_tpu.config import Config, DEFAULT_CONFIG
from mapreduce_tpu.data import reader as reader_mod
from mapreduce_tpu.runtime import faults as faults_mod
from mapreduce_tpu.models.wordcount import (WordCountJob, TopKWordCountJob,
                                            NGramCountJob, TopKTable,
                                            SketchedState, SketchedWordCountJob,
                                            FreqSketchedState, FreqSketchedWordCountJob,
                                            WordCountResult, apply_top_k,
                                            _reported_distinct)
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.parallel.mapreduce import Engine, MapReduceJob
from mapreduce_tpu.parallel.mesh import data_mesh
from mapreduce_tpu.runtime import checkpoint as ckpt_mod
from mapreduce_tpu.runtime import metrics as metrics_mod
from mapreduce_tpu.runtime.logging import get_logger, log_event


@dataclasses.dataclass
class RunResult:
    """Generic job result + run metrics."""

    value: Any
    metrics: metrics_mod.RunMetrics
    bases: np.ndarray  # int64[steps, D] row base offsets (string recovery)
    # Streamed runs: the dispatch-window statistics the run-end ledger
    # record carries (configured/observed in-flight depth, drain counts,
    # overlap_fraction).  None for drivers that never streamed.
    pipeline: Optional[dict] = None
    # Config(autotune='hint') runs: the window autotuner's recommendation
    # for this run (the `tune` ledger record's payload — proposal,
    # fired rule, decision trail).  None when autotuning is off or the
    # hint path failed (it is advisory and must never fail the run).
    tune: Optional[dict] = None


def _overlap_fraction(timer) -> Optional[float]:
    """``1 - blocked_time / stream_time``: the share of streamed wall-clock
    the driver loop was NOT sitting in an explicit wait (reader empty,
    full-window retires, retry-anchor snapshot fetches, end-of-stream
    tails).  A fully serialized loop trends toward 0; a pipeline hiding
    H2D behind compute trends toward 1.  ``stage``/``dispatch`` are host
    WORK, not waits — they count as overlapped.  None before the stream
    phase has been timed."""
    stream = timer["stream"]
    if not stream:
        return None
    blocked = sum(timer[p] for p in ("read_wait", "retire_wait",
                                     "snapshot", "h2d_tail",
                                     "compute_tail"))
    return round(max(0.0, 1.0 - blocked / stream), 4)


def _finalize_pipeline(pipe: dict, timer, tel) -> None:
    """Attach the run's overlap fraction to the window stats and export it
    through the registry — shared by both drivers, so the two entry points
    can never drift apart on overlap semantics."""
    pipe["overlap_fraction"] = _overlap_fraction(timer)
    if pipe["overlap_fraction"] is not None:
        tel.registry.gauge("executor.overlap_fraction").set(
            pipe["overlap_fraction"])


def _autotune_hint(config: Config, tel, pipe: dict, timer,
                   data_rec: Optional[dict], logger) -> Optional[dict]:
    """Online autotune hint (ISSUE 10): run the jax-free tuning engine
    over THIS run's own ledger records and fold the recommendation into a
    ``tune`` ledger record + the run summary — the live run is never
    changed.  The records are read back from the run's ledger file (it is
    flushed per record, and the tuner is a pure function of ledger
    records — exactly the offline path); with no ledger attached, the
    in-memory run_end view (phases + window stats + data summary) still
    yields a phase-classified hint.  Advisory by contract: any failure is
    logged and swallowed, never surfaced as a run failure."""
    try:
        from mapreduce_tpu import tuning

        if tel.enabled and tel.ledger is not None:
            records = [r for r in obs.read_ledger(tel.ledger.path)
                       if r.get("run_id") == tel.run_id]
        else:
            records = []
            if data_rec is not None:
                records.append({"run_id": tel.run_id, "kind": "data",
                                **data_rec})
        # run_end is written AFTER the tune record (the "no run_end = did
        # not complete" invariant): synthesize its view so the proposal
        # reads this run's phases and window statistics either way.
        records.append({"run_id": tel.run_id, "kind": "run_end",
                        "phases": dict(timer.phases), "pipeline": pipe})
        prop = tuning.propose(records, run_id=tel.run_id, current={
            "chunk_bytes": config.chunk_bytes,
            "superstep": config.superstep,
            "inflight_groups": config.inflight_groups,
            "prefetch_depth": config.resolved_prefetch_depth})
        # Belt over the engine's own clamps: a proposal that cannot pass
        # Config validation must never reach the ledger.
        tuning.validate_knobs(prop["proposal"], config.backend)
        prop["mode"] = "hint"
        tel.ledger_write("tune", **prop)
        tel.note_tune(prop)
        log_event(logger, "autotune hint", rule=prop["rule"],
                  changed=prop["changed"], converged=prop["converged"])
        return prop
    except Exception as e:  # noqa: BLE001 — advisory, never fatal
        log_event(logger, "autotune hint failed", error=repr(e))
        return None


@dataclasses.dataclass
class _StreamHooks:
    """The strategy seams between the host-local (:func:`run_job`) and
    global-SPMD (:func:`run_job_global`) drivers.  Everything else about
    streaming — superstep grouping, checkpoint-boundary splitting, file-
    boundary hooks, the retry loop, progress/checkpoint cadence — is ONE
    shared loop (:func:`_drive_stream`), so a fix to that machinery lands
    in both entry points by construction."""

    stage_single: Any  # Batch -> engine.step chunks argument
    stage_group: Any  # list[Batch] -> engine.step_many stacked argument
    snapshot: Any  # device state -> host pytree (checkpoint fetch / retry)
    restage: Any  # host pytree -> sharded device state (retry; None = n/a)
    write_gate: Any  # () -> bool: this process writes checkpoint files
    retry: int = 0
    # Optional staged-input recycler: called with a group's staged value
    # when the group RETIRES (its program provably consumed the input), so
    # host staging buffers return to a pool instead of being reallocated
    # per group (ISSUE 5 satellite).  Retirement is the safe recycle point
    # even where device_put may alias host memory (CPU backend): a retired
    # group's program has finished every read of its input.
    stage_release: Any = None
    # Optional Batch -> Batch applied the moment a batch leaves the reader:
    # run_job uses it to device_put each [D, C] chunk array immediately
    # (async H2D starts right away and overlaps the PREVIOUS group's
    # compute), so superstep groups stack already-resident device arrays
    # instead of shipping one K-times-larger host array at dispatch time —
    # measured through the relay tunnel, a single 128 MB staged array moved
    # ~7x slower per byte than 32 MB chunk arrays (BENCHMARKS.md round 5).
    stage_arrival: Any = None
    # Multi-host (ISSUE 13): the shard-row indices THIS process stages
    # (run_job_global's `host_shards`).  When set, each group's lifecycle
    # record also carries `host_bytes` — the bytes of real data this host
    # staged, the per-host balance signal obs/fleet.py reads (group_bytes
    # is the GLOBAL batch size, identical on every process).
    host_rows: Any = None
    # Degradation ladder (ISSUE 15): Config -> fresh Engine for a
    # degraded config.  The ladder only moves knobs that keep state
    # shapes (and results — each is bit-identical-tested) intact, so the
    # anchor snapshot restages into the rebuilt engine unchanged.  None
    # (run_job_global) disables the ladder: resource exhaustion there
    # fails over to checkpoint/resume like every other global failure.
    rebuild: Any = None
    # Window-boundary collective overlap (ISSUE 20 leg 2): the driver's
    # :class:`_OverlapMerger`, or None (default — the old single-finish
    # shape, bit-identical programs and ledger).  Only valid with
    # retry=0: the replay anchor snapshots local state a partial merge
    # has partially shipped.
    overlap: Any = None


class _StagePool:
    """Reusable host staging buffers, keyed by (shape, dtype).

    A streamed run re-allocates an identical staging buffer for every
    superstep group (``np.stack`` in ``stage_group``, shard-row gathers in
    the global driver) — pure allocator churn on the ingest hot path.  The
    pool recycles each buffer when its group retires (see
    ``_StreamHooks.stage_release``), so a run holds O(window) staging
    buffers total instead of one fresh allocation per group.
    """

    def __init__(self) -> None:
        self._free: dict = {}
        # id -> weakref of every outstanding issued buffer.  Weak, with a
        # purge callback: a buffer dropped on an exception path (never
        # given back) must not leave a dangling id behind — CPython reuses
        # addresses, and a stale id would make give() adopt a foreign
        # (e.g. reader-owned) array into the free list.
        self._issued: dict = {}

    def take(self, shape, dtype) -> np.ndarray:
        free = self._free.get((tuple(shape), np.dtype(dtype)))
        buf = free.pop() if free else np.empty(shape, dtype)
        self._issued[id(buf)] = weakref.ref(
            buf, lambda _r, _i=id(buf): self._issued.pop(_i, None))
        return buf

    def give(self, arr) -> None:
        # Only re-pool buffers THIS pool issued (verified by identity, not
        # just id): retirement also hands back reader-owned single-batch
        # arrays, and adopting any of those would retain the whole corpus
        # in the free list.
        if not isinstance(arr, np.ndarray):
            return
        ref = self._issued.get(id(arr))
        if ref is None or ref() is not arr:
            return
        del self._issued[id(arr)]
        self._free.setdefault((arr.shape, arr.dtype), []).append(arr)


def _probe_body(leaf):
    """The completion-probe program: a jitted copy of ONE small state leaf.

    All outputs of a dispatch become ready together and are poisoned by the
    same error, so this token is ready exactly when its group's step program
    finished — while SURVIVING the donation of the state into the next
    group's dispatch (a non-donated jit output never aliases its input; the
    state arrays themselves are deleted the moment the next step consumes
    them).  The graphcheck host-sync pass traces this body and certifies it
    stays free of host coupling: the window adds one tiny async program per
    group, never a hidden sync.
    """
    return leaf


_probe_jit = jax.jit(_probe_body)

#: Barrier-copy for any state about to enter the DONATING step programs.
#: A state built by ``jax.device_put`` (replay restage, checkpoint resume)
#: must not be donated as-is: donating a transfer-created buffer corrupts
#: the process heap on the CPU backend (glibc double-free aborts — the
#: chaos harness's token-wait plans reproduce it deterministically; an
#: XLA-produced buffer is donation-safe).  ``optimization_barrier`` is a
#: real equation, so jit cannot prune it to a pass-through and the output
#: is a fresh XLA-owned allocation with the input's sharding.
_owned_state = jax.jit(jax.lax.optimization_barrier)


def _state_token(state):
    """Per-group completion token: the smallest state leaf, copied through
    :func:`_probe_body`.  Blocking on it observes (and attributes) exactly
    one group's completion; it is never donated, so it outlives the state.
    """
    leaves = jax.tree.leaves(state)
    leaf = min(leaves, key=lambda x: getattr(x, "nbytes", 1 << 62))
    return _probe_jit(leaf)


def _wait_token(token) -> None:
    """The window's completion wait, as a seam: tests poison this to
    emulate a device error that surfaces ASYNCHRONOUSLY at the blocking
    fetch (the CPU backend executes callbacks at dispatch, so the real
    late-surfacing failure mode cannot be produced natively here)."""
    jax.block_until_ready(token)


def _wait_token_timed(token, timeout_s: float) -> None:
    """:func:`_wait_token` under a wall-clock deadline
    (``FailurePolicy.token_timeout_s``, ISSUE 15): a wait past the
    deadline raises a typed :class:`...runtime.faults.TokenTimeout`
    (transient — the replay path re-dispatches from the window anchor)
    instead of stalling the driver forever on a hung device or wedged
    relay link.  ``jax.block_until_ready`` has no timeout of its own, so
    the wait runs on a daemon worker thread; an abandoned wait costs one
    parked thread, which the recovery replay's fresh dispatch obsoletes."""
    if not timeout_s:
        return _wait_token(token)
    box: list = []

    def run() -> None:
        try:
            _wait_token(token)
            box.append(None)
        except BaseException as e:  # surfaced at the fetch: deliver as-is
            box.append(e)

    t = threading.Thread(target=run, daemon=True,
                         name="mapreduce-token-wait")
    t.start()
    t.join(timeout_s)
    if not box:
        raise faults_mod.TokenTimeout(
            f"completion token not ready within {timeout_s}s "
            "(hung device or wedged relay link)", seam="token-wait")
    if box[0] is not None:
        raise box[0]


def _record_fault(tel, write: bool, exc: BaseException, *, seam: str,
                  injected: bool, index: Optional[int] = None,
                  step: Optional[int] = None) -> str:
    """One typed-fault observation (ISSUE 15, ledger v9): the taxonomy
    class lands in the ``executor.faults`` registry counter, the flight
    ring, and a ``fault`` ledger record.  The ledger write is
    best-effort — the ledger may be the very seam that is failing — and
    a fault record must never mask the fault itself.  Returns the class."""
    cls = faults_mod.classify(exc)
    tel.registry.counter("executor.faults", seam=seam, fault_class=cls).inc()
    tel.event("fault", seam=seam, fault_class=cls, injected=injected,
              error=repr(exc))
    try:
        rec: dict = {"seam": seam, "fault_class": cls, "injected": injected,
                     "error": repr(exc)}
        if index is not None:
            rec["index"] = int(index)
        if step is not None:
            rec["step"] = int(step)
        tel.ledger_write("fault", write=write, **rec)
    except Exception:
        pass
    return cls


class _DegradeSignal(Exception):
    """Internal: a resource-classed failure exhausted its budget inside
    the recovery replay and the degradation ladder may still have a step
    — ``recover()``'s ladder loop owns the decision."""

    def __init__(self, error: BaseException):
        self.error = error


def _config_summary(config: Config) -> dict:
    """The degradation ladder's view of a config (label values only;
    ``faults.next_degrade`` consumes exactly this shape)."""
    return {"geometry": config.geometry_label,
            "combiner": config.resolved_combiner,
            "map_impl": config.map_impl,
            "sort_impl": config.sort_impl}


def _apply_degrade(config: Config, field: str, value: str) -> Config:
    """One ladder step applied to the real Config.  revert-geometry maps
    to the None sentinel (the shipped constants); combiner-off also drops
    the cache sizing knob, which only validates with the cache on."""
    if field == "geometry":
        return dataclasses.replace(config, geometry=None)
    kw: dict = {field: value}
    if field == "combiner":
        kw["combiner_slots"] = None
    return dataclasses.replace(config, **kw)


def _job_with_config(job, config: Config):
    """Shallow-rebind a job's Config for a degradation-ladder step.  The
    ladder moves only knobs that leave state SHAPES untouched (geometry/
    combiner/map_impl/sort_impl swap kernels, not pytrees — each shipped
    with a bit-identity suite), so a copied job with the degraded config
    drives the same state through cheaper programs.  Composed jobs
    (sketch wrappers) rebind their base job too."""
    import copy

    j = copy.copy(job)
    base = getattr(j, "base", None)
    if base is not None:
        j.base = _job_with_config(base, config)
    if hasattr(j, "config"):
        j.config = config
    return j


def _collective_call(thunk, plan, policy, tel, write: bool, logger):
    """A collective dispatch behind the collective-finish seam (ISSUE 15
    refactored for ISSUE 20: the stream-end finish AND the window-boundary
    partial merges cross the SAME seam, so chaos plans written against the
    old grammar exercise both).

    Injected faults fire BEFORE the collective runs, so retrying them on
    the transient/resource budget is always safe; a real collective
    failure is classified + recorded and propagates — in a fleet, peer
    processes are blocked mid-program, and checkpoint/resume is the
    recovery path (the run_job_global no-retry contract)."""
    attempt = 0
    while True:
        try:
            if plan is not None:
                exc = plan.check("collective-finish")
                if exc is not None:
                    _record_fault(tel, write, exc, seam="collective-finish",
                                  injected=True, index=exc.index)
                    raise exc
            return thunk()
        except faults_mod.FaultError as fe:
            if not fe.injected or fe.fault_class == "preemption":
                raise
            if attempt >= policy.budget(fe.fault_class):
                raise
            attempt += 1
            tel.registry.counter("executor.retry_attempts").inc()
            tel.registry.counter("executor.retries_by_class",
                                 fault_class=fe.fault_class).inc()
            tel.ledger_write("retry", attempt=attempt, error=repr(fe),
                             fault_class=fe.fault_class,
                             seam="collective-finish", write=write)
            log_event(logger, "collective finish fault; retrying",
                      attempt=attempt, fault_class=fe.fault_class)
            s = policy.backoff_s(fe.fault_class, attempt,
                                 seam="collective-finish")
            if s > 0:
                time.sleep(s)
        except Exception as e:
            _record_fault(tel, write, e, seam="collective-finish",
                          injected=False)
            raise


def _collective_finish(engine, state, plan, policy, tel, write: bool,
                       logger):
    """``engine.finish`` through the collective-finish seam (the
    monolithic stream-end merge)."""
    return _collective_call(lambda: engine.finish(state), plan, policy,
                            tel, write, logger)


class _OverlapMerger:
    """Window-boundary collective overlap (ISSUE 20 leg 2).

    One resident replicated accumulator plus at most one in-flight
    partial collective.  At a window-drain/checkpoint boundary the driver
    calls :meth:`boundary`: the previous partial is retired lazily (it
    owns a completion token exactly like a window group, and it had a
    whole window of ingest to hide behind), the current local tables are
    drained into the accumulator by an async-dispatched partial merge
    through the collective-finish seam, and the local tables are reset —
    so the DCN transfer of window N overlaps the ingest+compute of
    window N+1 and table pressure stays bounded by the window.  Each
    retired partial lands as an op='partial' ``collective`` ledger record
    (ledger v10) carrying its real dispatch->token-ready interval: the
    in-stream interconnect time obs/timeline's collective lane,
    ``fleet_bottleneck`` and obswatch read.  Byte-exact to the monolithic
    merge: the fold is the job's commutative merge (min-position rule),
    certified by the chaos harness and the 2-process gloo pair."""

    def __init__(self, engine, tel, write_gate, plan, policy, logger,
                 strategy: str, window_cap: int):
        self.engine = engine
        self.tel = tel
        self.write_gate = write_gate
        self.plan = plan
        self.policy = policy
        self.logger = logger
        self.strategy = strategy
        self.window_cap = max(1, int(window_cap))
        self.accum = None
        self.partials = 0
        self._retired_at_last = 0
        self._inflight = None  # (token, started_at, step)

    def disarm(self) -> None:
        """Preemption shutdown: no further injected faults (the stream
        loop disarms its own plan reference the same way)."""
        self.plan = None

    def due(self, retired_groups: int) -> bool:
        """A partial fires when a full window's worth of groups retired
        since the last boundary — a pure function of the group sequence,
        so every process of a fleet dispatches the same partial at the
        same point (the partial is one SPMD program)."""
        return retired_groups - self._retired_at_last >= self.window_cap

    def retire(self) -> None:
        """Observe the previous partial's completion (usually long since
        ready) and write its ledger record with the real interval."""
        if self._inflight is None:
            return
        token, t0, step = self._inflight
        self._inflight = None
        _wait_token(token)
        self.tel.ledger_write(
            "collective", op="partial", strategy=self.strategy,
            step=step, started_at=t0,
            ended_at=round(time.perf_counter(), 6),
            write=self.write_gate())

    def boundary(self, state, step: int, retired_groups: int):
        """Async-dispatch a partial merge of ``state`` into the
        accumulator and return the reset local state."""
        self.retire()
        t0 = round(time.perf_counter(), 6)
        self.accum = _collective_call(
            lambda: self.engine.partial_merge(self.accum, state),
            self.plan, self.policy, self.tel, self.write_gate(),
            self.logger)
        self._inflight = (_state_token(self.accum), t0, step)
        self.partials += 1
        self._retired_at_last = retired_groups
        self.tel.event("partial_merge", step=step)
        return self.engine.partial_reset(state)

    def host_accum(self):
        """The accumulator as host numpy (checkpoint packing).  The
        partial's output is fully replicated, so the fetch is addressable
        on every process; retire() first so the fetch never waits."""
        self.retire()
        if self.accum is None:
            return None
        return jax.tree.map(lambda x: np.array(x, copy=True), self.accum)

    def accum_template(self):
        """Abstract accumulator shapes (checkpoint resume template): the
        first partial's output for this engine's strategy and job."""
        eng = self.engine
        return jax.eval_shape(lambda: eng.partial_merge(
            None, eng.init_states()))


@dataclasses.dataclass
class _Inflight:
    """One dispatched-but-unretired superstep group (the window unit)."""

    token: Any  # completion probe output: ready <=> the group's program ran
    staged: Any  # staged chunks handle (h2d tail timing; pool recycling)
    step_first: int
    cursor_before: int  # bytes_done before this group (honest failure cursor)
    life: dict  # lifecycle timestamps + sizes (the `group` ledger record)
    # Data-plane stats output of the group's step program (ISSUE 8):
    # a tiny non-donated DataStats pytree, ready together with the
    # completion token; fetched at retirement.  None when telemetry is
    # off or the job has no stats hooks.
    stats: Any = None


def _group_life(group, read_at: Optional[float], group_bytes: int) -> dict:
    """Start a group's lifecycle record (ISSUE 7): identity, sizes, and the
    monotonic-clock timestamps stamped so far.  ``staged_at`` is stamped
    here — the caller invokes this immediately before staging begins.
    ``group_bytes`` is computed once by the caller and shared with the
    step-record accounting (no second pass over the batch lengths)."""
    return {"step_first": group[0].step, "step_last": group[-1].step,
            "steps": group[-1].step - group[0].step + 1,
            "group_bytes": group_bytes,
            "read_at": round(read_at, 6) if read_at is not None else None,
            "staged_at": round(time.perf_counter(), 6)}


#: group-record ``data`` counters mirrored into registry counters at
#: retirement (per-group deltas; names match the ledger fields).
_DATA_COUNTER_METRICS = (
    ("overlong", "data.overlong_tokens"),
    ("rescued", "data.rescued_tokens"),
    ("dropped_tokens", "data.dropped_tokens"),
    ("fallback_chunks", "data.spill_fallback_chunks"),
    ("rescue_escalations", "data.rescue_escalations"),
    ("spill_rows", "data.spill_rows"),
)


def _group_record(tel, write: bool, life: dict, token_ready_at: float,
                  retired_at: float, wait_s: float, retries: int = 0,
                  data: Optional[dict] = None) -> None:
    """Emit one ``group`` ledger record for a RETIRED group — the lifecycle
    raw material ``obs/timeline.py`` reconstructs lanes from.  Pure
    host-side bookkeeping: a handful of ``perf_counter`` stamps and one
    JSONL append (same cost class as the step record written at dispatch);
    a unit test holds the non-I/O part under 1 ms per group.  ``data``
    (ISSUE 8): the group's data-plane counter dict, already reduced
    host-side by :class:`...ops.datastats.DataAggregator` — attached to
    the record and mirrored into the registry's ``data.*`` instruments."""
    tel.registry.counter("executor.groups_retired").inc()
    d = life.get("dispatched_at")
    if d is not None:
        tel.registry.observe("executor.group_device_seconds",
                             max(0.0, token_ready_at - d))
    tel.registry.observe("executor.retire_wait_seconds", max(0.0, wait_s))
    rec = {k: v for k, v in life.items() if v is not None}
    rec["token_ready_at"] = round(token_ready_at, 6)
    rec["retired_at"] = round(retired_at, 6)
    rec["retire_wait_s"] = round(max(0.0, wait_s), 6)
    if retries:
        rec["retries"] = retries
    if data is not None:
        rec["data"] = data
        for field, metric in _DATA_COUNTER_METRICS:
            v = data.get(field)
            if v:
                tel.registry.counter(metric).inc(v)
        if data.get("occupancy") is not None:
            tel.registry.gauge("data.table_occupancy").set(data["occupancy"])
        if data.get("top_mass") is not None:
            tel.registry.gauge("data.top_mass").set(data["top_mass"])
    # write gates the main (coordinator) file only: the per-host shard
    # keeps every retired group's lifecycle (ISSUE 13).
    tel.ledger_write("group", write=write, **rec)


def _stream_total_bytes(path, start_offset, end_offset) -> Optional[int]:
    """Best-effort total bytes this stream will ingest — the denominator
    of the heartbeat's completion fraction / ETA (ISSUE 14).  A byte
    range answers exactly; otherwise the file size(s).  None (no
    fraction, no ETA — the heartbeat degrades to cursor + rate) when the
    input is not stat-able (pipes, exotic path objects)."""
    try:
        if end_offset is not None:
            return max(0, int(end_offset) - int(start_offset))
        import os

        paths = path if isinstance(path, (list, tuple)) else [path]
        total = sum(os.path.getsize(p) for p in paths)
        return max(0, int(total) - int(start_offset))
    except (OSError, TypeError, ValueError):
        return None


def _drive_stream(engine, job, config: Config, path, state,
                  hooks: _StreamHooks, *, start_step: int, start_offset: int,
                  end_offset, bases_list: list, checkpoint_path,
                  checkpoint_every: int, fingerprint, resumed_file,
                  logger, progress_every: int, timer=None, telemetry=None,
                  data_agg=None, plan=None, policy=None):
    """The shared streaming loop: reader -> prefetch -> superstep groups ->
    a bounded in-flight dispatch window (ISSUE 5), with checkpoint cadence
    and file-boundary hooks.  Returns ``(state, bytes_done, step_index,
    pipe)``; ``bytes_done`` is the absolute stream cursor (starts at
    ``start_offset``) and ``pipe`` the window statistics the run-end ledger
    record carries (configured/observed depth, drain counts).

    The window (``Config.inflight_groups``): up to W superstep groups stay
    dispatched-but-unretired, so the reader/prefetch thread, host staging,
    async H2D, and device compute of DIFFERENT groups overlap instead of
    the old dispatch -> (retry-mode sync) -> next-group lockstep.  Each
    dispatch also launches a tiny non-donated completion probe
    (:func:`_state_token`); groups retire lazily — when the window is full,
    at checkpoint/file boundaries, and at stream end — by blocking on those
    tokens in dispatch order, which attributes an asynchronously surfaced
    device failure to exactly the group that caused it (the old loop could
    only attribute by syncing EVERY dispatch).  ``inflight_groups=1`` is
    strict serial — one group in flight, retired before the next dispatch:
    with retry this is exactly the pre-window loop's per-dispatch sync;
    with retry=0 the pre-window loop was async behind the device queue, so
    1 is the A/B control's serial floor, not a bug-for-bug baseline.

    Retry (``hooks.retry > 0``): the known-good snapshot moves from
    per-group to window-drain cadence — the window fills, drains as one
    batch, and a fresh host snapshot anchors the next window.  A failure
    mid-window replays the window's still-alive host batches serially from
    the anchor (the failed group charged one attempt, predecessors replayed
    free), so retry-from-snapshot semantics survive the async window while
    replay stays bounded by the window — and by ``checkpoint_every``, since
    checkpoint boundaries force a drain.

    ``timer`` (a :class:`...runtime.metrics.PhaseTimer`) decomposes the
    stream wall-clock into the phases the ingest number is made of:
    ``read_wait`` (blocking on the prefetching reader), ``stage`` (host
    assembly + host->device placement of a group), ``dispatch`` (program
    enqueue; blocks only when the device queue is full), ``retire_wait``
    (blocking on a full window's oldest completion token), ``snapshot``
    (retry-anchor fetches), and the end-of-stream tail split ``h2d_tail``
    (last group's input still in transfer) vs ``compute_tail`` (queued
    compute after the last enqueue) — the decomposition of what the old
    single ``drain`` phase lumped together.  The phases are timed through
    :func:`...obs.spans.span`, which also drops a profiler TraceAnnotation
    per phase so XProf timelines line up with the ledger.

    ``telemetry`` (:class:`...obs.telemetry.Telemetry`): exactly one ledger
    step record per dispatched group, written at dispatch in step order
    (completion is observed later under pipelining), carrying phase deltas,
    bytes, the in-flight depth after the dispatch, and device memory stats;
    plus exactly one ``group`` record per RETIRED group (ISSUE 7), written
    at retirement, carrying the group's monotonic-clock lifecycle
    (``read_at``/``staged_at``/``dispatched_at``/``token_ready_at``/
    ``retired_at``) — the per-resource timeline ``obs/timeline.py``
    reconstructs lanes, overlap matrices and the critical-path verdict
    from; flight-recorder events per dispatch / retry / checkpoint, dumped
    with a state summary when the failure path runs; plus the live-run
    ``progress`` heartbeat (ISSUE 14, ledger v8) — a wall-clock-cadenced
    record from the dispatch/retire points carrying the byte cursor,
    groups dispatched/retired, in-flight depth and the cursor-derived
    ETA, so ``tools/obswatch.py`` can tail the run before it ends (the
    not-due path is one monotonic read; nothing here is traced).
    Disabled telemetry
    (the ``None`` default) does no per-step work and — the invariant the
    graphcheck host-sync pass certifies — never adds a host sync to the
    dispatch pipeline either way: everything here is host-side bookkeeping
    around async enqueues (the lifecycle adds ~5 ``perf_counter`` stamps
    per group, never a device wait that was not already there).
    """
    bytes_done = int(start_offset)
    step_index = start_step
    last_ckpt = start_step // checkpoint_every if checkpoint_every else 0
    k = config.superstep
    pending: list = []
    timer = timer if timer is not None else metrics_mod.PhaseTimer()
    tel = obs.maybe(telemetry)
    # Unified failure policy + fault plan (ISSUE 15).  `plan is None` is
    # the provably zero-cost disabled path: every seam check below is
    # guarded by that one identity test, and nothing here is traced.
    # `cur_config` is the degradation ladder's moving target — the ladder
    # only moves kernel-choice knobs, so the loop's own reads of `config`
    # (superstep, window, prefetch) stay pinned to what the caller set.
    policy = policy if policy is not None \
        else faults_mod.FailurePolicy.resolve(None, retry=hooks.retry)
    cur_config = config
    window_cap = max(1, config.inflight_groups)
    overlap = hooks.overlap
    window: collections.deque = collections.deque()
    # retry > 0: host snapshot of the state at the current anchor point —
    # the replay source.  (Re)taken lazily before the first dispatch of a
    # window and at every drain; invalidated by file-boundary state hooks.
    # ``since_anchor`` keeps every ``(group, pre-group cursor)`` dispatched
    # SINCE that snapshot (including groups already retired mid-drain): a
    # failure replays all of them — a group retired inside the current
    # drain has no snapshot of its own, so the anchor is the only rebuild
    # point — and the paired cursor keeps a replay failure's ledger record
    # honest about where the failed group started.
    anchor = None
    since_anchor: list = []
    last_file_dispatched = resumed_file or 0
    # step -> monotonic arrival time of the batch out of the prefetching
    # reader: a group's `read_at` is its FIRST batch's arrival (the reader
    # lane of the timeline spans read + superstep accumulation).
    read_t: dict = {}
    pipe = {"inflight_groups": window_cap,
            "prefetch_depth": config.resolved_prefetch_depth,
            "dispatch_groups": 0, "depth_sum": 0, "depth_max": 0,
            "full_retires": 0, "boundary_drains": 0}
    # Live-run heartbeat raw material (ISSUE 14, ledger v8): the stream's
    # total-byte denominator (None degrades the heartbeat to cursor+rate)
    # and the retired-group counter the `progress` records carry.
    stream_total = _stream_total_bytes(path, start_offset, end_offset) \
        if tel.enabled else None
    retired_groups = 0

    def heartbeat() -> None:
        """One call per dispatch/retire point; Telemetry.progress gates
        on its wall-clock cadence, so the not-due cost is one monotonic
        read — never a device wait, never a traced-program change."""
        tel.progress(step=step_index, cursor_bytes=bytes_done,
                     streamed_bytes=bytes_done - int(start_offset),
                     total_bytes=stream_total,
                     groups_dispatched=pipe["dispatch_groups"],
                     groups_retired=retired_groups,
                     inflight_depth=len(window),
                     write=hooks.write_gate())

    def cross(seam):
        """One named seam crossing of the fault plan (ISSUE 15; call
        sites guard on ``plan is not None``): count it, and when the plan
        says this crossing fails, record the typed ``fault`` ledger
        record and raise.  ``process-kill`` is not an exception — it is
        the machine going away (``os._exit``, no cleanup, no flush beyond
        the already-flushed ledger): the multi-host chaos scenario."""
        exc = plan.check(seam)
        if exc is None:
            return
        _record_fault(tel, hooks.write_gate(), exc, seam=seam,
                      injected=True, index=exc.index, step=step_index)
        if seam == "process-kill":
            import os

            os._exit(113)
        raise exc

    def backoff(fault_class, attempt, seam):
        """Policy backoff before retry ``attempt`` (exponential +
        deterministic jitter — runtime/faults.py owns the formula)."""
        s = policy.backoff_s(fault_class, attempt, seam=seam)
        if s > 0:
            time.sleep(s)

    def dispatch(state, group):
        with obs.span("stage", timer):
            if plan is not None:
                cross("stage-acquire")
            staged = hooks.stage_single(group[0]) if len(group) == 1 \
                else hooks.stage_group(group)
            if plan is not None:
                cross("h2d")
        with obs.span("dispatch", timer):
            if plan is not None:
                cross("dispatch")
            if len(group) == 1:
                out = engine.step(state, staged, group[0].step)
            else:
                out = engine.step_many(state, staged, group[0].step)
        stats = None
        if engine.data_stats:
            out, stats = out
        return out, stats, staged

    def group_stats_data(stats):
        """Fetch one retired group's DataStats leaves and fold them into
        the run aggregate (ISSUE 8).  Called only after the group's
        completion token was observed ready — the stats arrays are
        outputs of the same program, so the fetch copies a few dozen
        ready bytes, it never waits on the device."""
        if stats is None or data_agg is None:
            return None
        data = data_agg.group_data(jax.tree.map(np.asarray, stats))
        tel.note_data(data_agg.snapshot())
        return data

    def split_at_checkpoints(group):
        """Cut a superstep group at checkpoint boundaries, so resume
        granularity is governed by ``checkpoint_every`` even when it is
        finer than the superstep: a crash then replays at most
        ``checkpoint_every`` chunks per device, not a whole superstep
        (set ``checkpoint_every >= superstep`` to keep the full dispatch
        amortization)."""
        if not (checkpoint_every and checkpoint_path):
            return [group]
        subs, cur = [], []
        for b in group:
            cur.append(b)
            if (b.step + 1) % checkpoint_every == 0:
                subs.append(cur)
                cur = []
        if cur:
            subs.append(cur)
        return subs

    def final_failure(e, step, attempts, snapshot=None, cursor=None,
                      fault_class=None):
        """Failure detection (SURVEY §5): out of retries (or none
        requested).  Surface loudly with the resume cursor;
        checkpoint/resume is the recovery path.  The flight recorder dumps
        its ring + state summary FIRST, so a run that dies here leaves
        forensics on disk (the benchwatch wedge scenario) before the raise
        unwinds.  The dump fires on EVERY host (ISSUE 13 bugfix: each
        process owns a host-suffixed flight path, so a non-coordinator
        failure leaves forensics from the host that actually failed
        instead of being swallowed by the write gate — N processes no
        longer race one file); the failure record rides the gate into
        the main ledger and lands in the per-host shard regardless.
        ``fault_class`` (ISSUE 15): the taxonomy class the policy decided
        on, stamped into the failure record."""
        cursor = bytes_done if cursor is None else cursor
        fault_class = fault_class or faults_mod.classify(e)
        tel.event("step_failed", step=step, attempt=attempts - 1,
                  error=repr(e))
        dump = tel.flight_dump(
            context={"step": step, "offset": cursor,
                     "attempts": attempts, "error": repr(e),
                     "fault_class": fault_class,
                     "checkpoint_path": checkpoint_path},
            state=snapshot)
        tel.ledger_write("failure", step=step, cursor_bytes=cursor,
                         error=repr(e), fault_class=fault_class,
                         flight_dump=dump, write=hooks.write_gate())
        log_event(logger, "step failed", step=step, offset=cursor,
                  fault_class=fault_class,
                  resume_hint=checkpoint_path
                  or "enable checkpointing to resume")
        raise e

    def retry_record(step, attempt, e, fault_class="transient", seam=None):
        tel.registry.counter("executor.retry_attempts").inc()
        # Satellite (ISSUE 15): per-class retry accounting in the
        # registry — the service-level "how flaky is this fleet" signal.
        tel.registry.counter("executor.retries_by_class",
                             fault_class=fault_class).inc()
        tel.event("retry", step=step, attempt=attempt, error=repr(e))
        rec = {"step": step, "attempt": attempt, "error": repr(e),
               "fault_class": fault_class}
        if seam:
            rec["seam"] = seam
        tel.ledger_write("retry", write=hooks.write_gate(), **rec)
        log_event(logger, "step failed; retrying", step=step,
                  attempt=attempt, fault_class=fault_class)

    def serial_dispatch(state, group, attempts_used=0, used_out=None,
                        cursor=None, charged_class="transient"):
        """The serialized dispatch: snapshot -> dispatch -> block, retrying
        from the snapshot on failure under the PER-CLASS policy budgets
        (ISSUE 15) — the window's recovery path (and the exact pre-window
        semantics when the policy is the legacy ``retry=N`` mapping).
        ``attempts_used`` pre-charges the attempt the failed group already
        burned inside the window, against ``charged_class``; ``used_out``
        (a 1-slot list) reports the final total attempt count; ``cursor``
        is the stream offset BEFORE this group, so a replay that exhausts
        its retries reports an honest failure cursor (``bytes_done``
        already includes later groups accounted at their original
        dispatch).  A resource-classed exhaustion raises
        :class:`_DegradeSignal` when the ladder can still step down —
        ``recover()``'s ladder loop owns that choice."""
        snapshot = hooks.snapshot(state)
        used = {c: 0 for c in faults_mod.FAULT_CLASSES}
        used[charged_class] = attempts_used
        total = attempts_used
        while True:
            staged = None
            try:
                out, stats, staged = dispatch(state, group)
                with obs.span("retire_wait", timer):
                    jax.block_until_ready(out)
                if hooks.stage_release is not None:
                    hooks.stage_release(staged)
                if used_out is not None:
                    used_out[0] = total
                return out, stats
            except Exception as e:
                # Return the failed attempt's staging buffer so its id
                # never dangles in the pool (the doomed H2D may still read
                # it — harmless, its output is discarded).
                if staged is not None and hooks.stage_release is not None:
                    hooks.stage_release(staged)
                cls = faults_mod.classify(e)
                if cls == "preemption":
                    raise
                if not (isinstance(e, faults_mod.FaultError)
                        and e.injected):
                    _record_fault(tel, hooks.write_gate(), e,
                                  seam=getattr(e, "seam", None)
                                  or "dispatch",
                                  injected=False, step=group[0].step)
                if used[cls] >= policy.budget(cls):
                    if cls == "resource" and policy.degrade \
                            and hooks.rebuild is not None:
                        raise _DegradeSignal(e)
                    final_failure(e, group[0].step, attempts=total + 1,
                                  snapshot=snapshot, cursor=cursor,
                                  fault_class=cls)
                used[cls] += 1
                total += 1
                retry_record(group[0].step, total, e, fault_class=cls)
                backoff(cls, used[cls], "dispatch")
                # Transient-failure recovery: rebuild a fresh sharded state
                # from the snapshot and re-dispatch the same host batches.
                state = hooks.restage(snapshot)

    def reanchor(state):
        """Fresh known-good snapshot: everything before it is durable,
        everything after it is replayable from it."""
        nonlocal anchor
        with obs.span("snapshot", timer):
            anchor = hooks.snapshot(state)
        del since_anchor[:]

    def recover(state, e, entry=None, sync_group=None, sync_life=None):
        """A group's program failed — either surfaced at its completion
        token (``entry``: the oldest in-flight group; tokens are blocked in
        dispatch order, so it is provably the EARLIEST failure) or raised
        by the dispatch call itself (``sync_group``: dispatched but never
        accounted).  Attribution is to that group's first step, never to
        whichever later group happened to block first.

        ISSUE 15: the exception is CLASSIFIED first (transient / resource
        / permanent / preemption) and the class decides the outcome —
        preemption re-raises to the stream-level drain-checkpoint-exit
        handler (the window is healthy, the signal is not a device
        error); permanent fails immediately; transient/resource replay
        every group since the anchor snapshot serially on their per-class
        budgets (groups before the failure re-dispatch free — the anchor
        is their only rebuild point — and the failed group is charged one
        attempt); a resource-classed budget exhaustion steps down the
        degradation ladder (rebuild the engine on a cheaper config,
        replay again) until the ladder runs out."""
        nonlocal retired_groups, engine, cur_config
        cls = faults_mod.classify(e)
        fail_step = (entry.step_first if entry is not None
                     else sync_group[0].step)
        if not (isinstance(e, faults_mod.FaultError) and e.injected):
            _record_fault(tel, hooks.write_gate(), e,
                          seam=getattr(e, "seam", None)
                          or ("token-wait" if entry is not None
                              else "dispatch"),
                          injected=False, step=fail_step)
        if cls == "preemption":
            # Not a device error: the window's other groups are healthy
            # and an unenrolled sync group simply replays after resume.
            raise e
        cursor = entry.cursor_before if entry is not None else bytes_done
        budget = policy.budget(cls)
        can_ladder = (cls == "resource" and policy.degrade
                      and hooks.rebuild is not None)
        if hooks.retry <= 0 or hooks.restage is None \
                or (budget <= 0 and not can_ladder):
            final_failure(e, fail_step, attempts=1, cursor=cursor,
                          fault_class=cls)
        replay = list(since_anchor)
        if sync_group is not None:
            replay.append((sync_group, cursor))
        fail_idx = next(i for i, (g, _) in enumerate(replay)
                        if g[0].step == fail_step)
        # Lifecycle records still owed: the doomed window's groups never
        # retired (their records are emitted after the replay below, with
        # coarse serialized timestamps — the replay IS when they actually
        # completed); groups in `since_anchor` but NOT in the window
        # retired earlier and already own a record, so the replay must not
        # emit a second one for them (exactly-one-per-retired-group).
        lost = {en.step_first: en.life for en in window}
        if sync_group is not None and sync_life is not None:
            lost[sync_group[0].step] = sync_life
        # Quiesce the doomed window before replaying: the OTHER in-flight
        # groups' programs may still be RUNNING — an injected token-wait
        # fault abandons a healthy window — and a serial replay racing
        # them contends for staging buffers and the backend's execution
        # machinery (the interpret-mode pallas runtime is not safe under
        # that concurrency; observed corrupting replay outputs).  Their
        # tokens resolve promptly — the programs complete or fail, and a
        # real hang is bounded by token_timeout_s — and any error they
        # surface is subsumed by the replay below.
        # A REAL TokenTimeout already spent the full timeout on the
        # failed entry's token (and on a genuinely hung device would
        # spend it again): its quiesce outcome is known, skip it.  An
        # INJECTED token-wait fault raised before the wait ever ran —
        # that entry's program may still be executing, so it must be
        # quiesced like the rest.
        already_waited = (entry is not None
                          and isinstance(e, faults_mod.TokenTimeout)
                          and not e.injected)
        for doomed in window:
            if already_waited and doomed is entry:
                continue
            try:
                if policy.token_timeout_s:
                    _wait_token_timed(doomed.token, policy.token_timeout_s)
                else:
                    _wait_token(doomed.token)
            except Exception:
                pass
        # Drop the doomed window, returning pool-issued staging buffers so
        # their ids never dangle in the pool's issued set (a freed buffer's
        # id can be reused by a reader-owned array, which give() would then
        # wrongly adopt).  A doomed dispatch's H2D may still read a buffer
        # we later refill — harmless: its output is discarded and the
        # replay restages fresh device state from the anchor.
        while window:
            dropped = window.popleft()
            if hooks.stage_release is not None:
                hooks.stage_release(dropped.staged)
        # The windowed failure charges one attempt against its class —
        # unless the class has no budget at all (the pure-ladder path,
        # where the first resource fault goes straight to a degrade).
        charged = 1 if budget > 0 else 0
        if charged:
            retry_record(fail_step, 1, e, fault_class=cls)
            backoff(cls, 1, "dispatch")
        used = [charged]
        while True:  # degradation-ladder loop (one pass when no degrade)
            try:
                state = hooks.restage(anchor)
                done: list = []
                for i, (group, group_cursor) in enumerate(replay):
                    replay_t0 = time.perf_counter()
                    state, replay_stats = serial_dispatch(
                        state, group,
                        attempts_used=charged if i == fail_idx else 0,
                        used_out=used if i == fail_idx else None,
                        cursor=group_cursor, charged_class=cls)
                    done.append((i, group, replay_t0,
                                 time.perf_counter(), replay_stats))
                break
            except _DegradeSignal as ds:
                nd = faults_mod.next_degrade(_config_summary(cur_config))
                if nd is None:
                    final_failure(ds.error, fail_step,
                                  attempts=used[0] + 1, cursor=cursor,
                                  fault_class="resource")
                step_name, field, degraded = nd
                was = _config_summary(cur_config)[field]
                cur_config = _apply_degrade(cur_config, field, degraded)
                pipe.setdefault("degrade_steps", []).append(step_name)
                tel.registry.counter("executor.degrade_steps",
                                     ladder_step=step_name).inc()
                tel.event("degrade", ladder_step=step_name, field=field)
                tel.ledger_write(
                    "degrade", step=fail_step, ladder_step=step_name,
                    field=field, **{"from": was, "to": degraded},
                    fault_class="resource", error=repr(ds.error),
                    write=hooks.write_gate())
                log_event(logger, "degradation ladder step",
                          ladder_step=step_name, field=field,
                          to=degraded)
                engine = hooks.rebuild(cur_config)
        # Emit the owed lifecycle records only for the FINAL successful
        # round: an aborted ladder round's groups were invalidated with
        # their state, so emitting them would duplicate records (and
        # double-fold data stats).  Coarse serialized stamps: the
        # original enqueue was doomed with the window, so the replay's
        # blocking re-dispatch is the group's real completion interval.
        # Data stats fold only for groups that never retired: a group
        # replayed from the anchor but retired earlier already
        # contributed its counters once.
        for i, group, t0, t1, replay_stats in done:
            life = lost.pop(group[0].step, None)
            if life is not None:
                life = dict(life, staged_at=round(t0, 6),
                            dispatched_at=round(t0, 6))
                _group_record(tel, hooks.write_gate(), life,
                              token_ready_at=t1, retired_at=t1,
                              wait_s=t1 - t0,
                              retries=used[0] if i == fail_idx else 0,
                              data=group_stats_data(replay_stats))
                retired_groups += 1
                heartbeat()
        tel.registry.counter("executor.retry_recoveries").inc()
        if sync_group is not None:
            # The sync-failed group raised inside `dispatch` itself, so it
            # was never enrolled: account it now that it landed.  It ran
            # serially, alone — depth 1, the serialized-window contract
            # (ledger consumers rely on inflight_depth >= 1, and the depth
            # mean divides by dispatch_groups).  Its charged attempts live
            # on its GROUP record — the one place replay retries are
            # charged on BOTH recovery paths (ISSUE 15 satellite: the
            # async path's step record is written at dispatch, before any
            # retry can exist, so the group record is the only consistent
            # carrier).
            record_depth(1)
            account(sync_group, depth=1,
                    group_bytes=sync_life["group_bytes"] if sync_life
                    else int(sum(int(b.lengths.sum()) for b in sync_group)))
        reanchor(state)
        return state

    def token_wait(entry):
        """The window's completion wait behind the token-wait seam
        (ISSUE 15): the plan may inject here (the mid-window ASYNC fault
        — it surfaces at the oldest group's retire, exactly like a real
        late device error), and ``policy.token_timeout_s`` bounds the
        wall-clock so a hung device reads as a typed TokenTimeout."""
        if plan is not None:
            cross("token-wait")
        if policy.token_timeout_s:
            _wait_token_timed(entry.token, policy.token_timeout_s)
        else:
            _wait_token(entry.token)

    def retire_oldest(state, phase="retire_wait"):
        """Block until the oldest in-flight group's program completed (its
        completion token is ready); recycle its staging buffer.  An error
        surfacing here belongs to exactly this group."""
        entry = window[0]
        wait_t0 = time.perf_counter()
        try:
            if phase is not None:
                with obs.span(phase, timer):
                    token_wait(entry)
            else:
                token_wait(entry)
        except Exception as e:
            return recover(state, e, entry=entry)
        token_ready_at = time.perf_counter()
        window.popleft()
        if hooks.stage_release is not None:
            hooks.stage_release(entry.staged)
        _group_record(tel, hooks.write_gate(), entry.life,
                      token_ready_at=token_ready_at,
                      retired_at=time.perf_counter(),
                      wait_s=token_ready_at - wait_t0,
                      data=group_stats_data(entry.stats))
        nonlocal retired_groups
        retired_groups += 1
        heartbeat()
        return state

    def drain_window(state, phase="retire_wait", do_reanchor=True):
        """Retire every in-flight group (checkpoint/file boundaries, full
        retry-mode windows, stream end); with retry, re-anchor the next
        window on a fresh known-good snapshot.  ``since_anchor`` empty
        means the anchor is already current (recover() just replayed and
        re-anchored, or nothing was dispatched since) — skip the redundant
        device->host fetch."""
        while window:
            state = retire_oldest(state, phase)
        if hooks.retry > 0 and do_reanchor and since_anchor:
            reanchor(state)
        return state

    def record_depth(depth):
        """The window-depth statistics behind the run-end `pipeline` dict
        and the `executor.inflight_depth` histogram — one sample per
        dispatched group (enroll and the sync-recover path alike, so the
        depth mean's numerator and denominator can never drift)."""
        pipe["dispatch_groups"] += 1
        pipe["depth_sum"] += depth
        pipe["depth_max"] = max(pipe["depth_max"], depth)
        tel.registry.observe("executor.inflight_depth", depth)

    def account(group, depth, group_bytes):
        """Advance the cursor, bases, and telemetry for one dispatched
        group: the ledger step record is written at dispatch, in step
        order — one per dispatched group, completion observed later.
        ``group_bytes`` comes from the caller's lifecycle record: the
        batch lengths are summed exactly once per group.  Replay retries
        are NOT stamped here (ISSUE 15 satellite): the async recovery
        path's step record is written at dispatch, before any retry can
        exist, so charging them here on the sync path only made the two
        paths disagree — the group record is the one consistent carrier.

        The ledger-append seam crosses here (ISSUE 15): an injected
        append fault is recorded and ABSORBED — observing must never take
        down the observed run, so the policy outcome for the telemetry
        plane is always degrade-to-unobserved, not death."""
        nonlocal bytes_done, step_index, last_file_dispatched
        last_file_dispatched = group[-1].file_index
        for b in group:
            bases_list.append(b.base_offsets)
        bytes_done += group_bytes
        step_index = group[-1].step + 1
        skip_record = False
        if plan is not None:
            try:
                cross("ledger-append")
            except faults_mod.FaultError as fe:
                if fe.fault_class == "preemption":
                    raise
                skip_record = True
                log_event(logger, "ledger append fault absorbed",
                          error=repr(fe))
        if not skip_record:
            tel.step_record(step_first=group[0].step,
                            step_last=group[-1].step,
                            group_bytes=group_bytes,
                            cursor_bytes=bytes_done, timer=timer,
                            inflight_depth=depth,
                            write=hooks.write_gate())
        heartbeat()
        if progress_every and step_index % progress_every < len(group):
            log_event(logger, "progress", step=step_index, bytes=bytes_done)

    def enroll(out, stats, staged, group, cursor_before, life):
        """Window bookkeeping + accounting for a DISPATCHED group.  Runs
        outside the recover() routing on purpose: a failure here (say the
        ledger's disk filling up mid step-record) is host bookkeeping, not
        a device fault — routing it through recover would replay a group
        that is already in the window and partially accounted, dispatching
        and counting it twice.  It propagates loudly instead, exactly as
        the pre-window loop's accounting (outside its retry try) did."""
        window.append(_Inflight(
            token=_state_token(out), staged=staged,
            step_first=group[0].step, cursor_before=cursor_before,
            life=life, stats=stats))
        if hooks.retry > 0:
            # Paired with the pre-group cursor, so a replay that later
            # exhausts its retries can report where THIS group started.
            since_anchor.append((group, cursor_before))
        depth = len(window)
        record_depth(depth)
        account(group, depth, life["group_bytes"])

    def stack_bases():
        return np.stack(bases_list) if bases_list \
            else np.zeros((0, engine.n_devices), np.int64)

    def save_snapshot(state_host):
        """The checkpoint write behind the checkpoint-save seam +
        policy (ISSUE 15): injected AND real save failures retry on the
        per-class budget (the save is idempotent — atomic tmp+rename),
        and an exhausted budget DEGRADES — fault recorded, loud log, the
        run continues without this snapshot — instead of killing a
        healthy stream.  Durability is reduced; results are not.
        Returns True when the snapshot landed."""
        attempt = 0
        while True:
            try:
                if plan is not None:
                    cross("checkpoint-save")
                if hooks.write_gate():
                    ckpt_mod.save(checkpoint_path, state_host, step_index,
                                  bytes_done, stack_bases(),
                                  fingerprint=fingerprint,
                                  file_index=last_file_dispatched)
                return True
            except faults_mod.PreemptionFault:
                raise
            except Exception as ce:
                ccls = faults_mod.classify(ce)
                if not (isinstance(ce, faults_mod.FaultError)
                        and ce.injected):
                    _record_fault(tel, hooks.write_gate(), ce,
                                  seam="checkpoint-save", injected=False,
                                  step=step_index)
                if attempt >= policy.budget(ccls):
                    log_event(logger,
                              "checkpoint save failed; continuing "
                              "without this snapshot",
                              error=repr(ce), fault_class=ccls)
                    return False
                attempt += 1
                retry_record(step_index, attempt, ce, fault_class=ccls,
                             seam="checkpoint-save")
                backoff(ccls, attempt, "checkpoint-save")

    def flush(state, group):
        """Dispatch a group of consecutive batches (one superstep, split at
        any interior checkpoint boundaries)."""
        for sub in split_at_checkpoints(group):
            state = flush_one(state, sub)
        return state

    def flush_one(state, group):
        """Dispatch one group of consecutive batches as a single program,
        keeping at most ``window_cap`` groups in flight."""
        nonlocal last_ckpt, anchor
        # Make room FIRST, so the device never holds more than the window.
        # retry=0 slides (retire just the oldest: continuous pipeline);
        # retry>0 drains the full window and re-anchors (the snapshot that
        # makes a replay possible is only fetchable when nothing is in
        # flight — the state array is donated into every next dispatch).
        if hooks.retry > 0:
            if len(window) >= window_cap:
                # One count PER RETIRED GROUP (the drain retires the whole
                # window), so full_frac = full_retires/dispatch_groups means
                # the same thing in both modes: the share of groups retired
                # because the window was at capacity (~1 = device-bound).
                pipe["full_retires"] += len(window)
                state = drain_window(state)
            if anchor is None:
                reanchor(state)
        else:
            while len(window) >= window_cap:
                pipe["full_retires"] += 1
                state = retire_oldest(state)
            # Window-boundary partial merge (ISSUE 20 leg 2): a full
            # window's worth of groups has retired since the last
            # boundary — drain the local tables into the resident
            # accumulator (async; the DCN transfer hides behind the
            # next window) and reset them.  The dispatch is host work
            # ("dispatch" phase); the previous partial's lazy retire is
            # a wait ("retire_wait"), normally instant.
            if overlap is not None and overlap.due(retired_groups):
                with obs.span("retire_wait", timer):
                    overlap.retire()
                with obs.span("dispatch", timer):
                    state = overlap.boundary(state, step_index,
                                             retired_groups)
        cursor_before = bytes_done
        # Lifecycle (ISSUE 7): read_at = the group's first batch leaving
        # the reader; staged_at is stamped by _group_life right here, just
        # before staging begins; later steps' arrival stamps are dropped
        # (the reader lane spans read + superstep accumulation).
        read_at = read_t.pop(group[0].step, None)
        for b in group[1:]:
            read_t.pop(b.step, None)
        life = _group_life(group, read_at,
                           int(sum(int(b.lengths.sum()) for b in group)))
        if hooks.host_rows is not None:
            life["host_bytes"] = int(sum(
                int(b.lengths[hooks.host_rows].sum()) for b in group))
        try:
            out, stats, staged = dispatch(state, group)
        except Exception as e:
            # Only the dispatch itself routes here: a device/staging fault
            # for a group that was never enrolled (see enroll()).
            state = recover(state, e, sync_group=group, sync_life=life)
        else:
            life["dispatched_at"] = round(time.perf_counter(), 6)
            enroll(out, stats, staged, group, cursor_before, life)
            state = out
        if plan is not None:
            # Whole-process kill (ISSUE 15, multi-host chaos): crossed
            # once per dispatched group, AFTER the group is enrolled and
            # accounted — the hard-kill lands between groups, exactly
            # where a platform reclaim would.
            cross("process-kill")
        if (checkpoint_every and checkpoint_path
                and step_index // checkpoint_every > last_ckpt):
            # Checkpoint boundary: retire everything (a failure discovered
            # here is attributed per group by the token order, instead of
            # surfacing inside the snapshot fetch blamed on the boundary),
            # then snapshot the state and ingest cursor.  The snapshot
            # format holds ANY job state pytree (tables, sketched states,
            # grep scalars alike).  Multi-host: every process pays the
            # fetch (it is a collective there), only the gate-holder
            # touches the filesystem.
            state = drain_window(state)
            pipe["boundary_drains"] += 1
            last_ckpt = step_index // checkpoint_every
            # Checkpoint boundaries are window boundaries too (ISSUE 20
            # leg 2): drain the local tables into the accumulator so the
            # snapshot packs {"s": reset local state, "a": accumulator}
            # — resume restores both and the stream stays byte-exact.
            if overlap is not None:
                with obs.span("retire_wait", timer):
                    overlap.retire()
                with obs.span("dispatch", timer):
                    state = overlap.boundary(state, step_index,
                                             retired_groups)
            ck_before = timer["checkpoint"]
            with obs.span("checkpoint", timer):
                # retry mode just re-anchored on this very state: reuse the
                # fetch instead of paying a second device->host round.
                # file_index makes the snapshot boundary-aware: resuming
                # a checkpoint that ends a corpus member must still fire
                # the job's on_input_boundary hook on the next member's
                # first batch (the carry reset happens AFTER this save
                # in the stream loop).
                state_host = anchor if hooks.retry > 0 \
                    else hooks.snapshot(state)
                if overlap is not None:
                    state_host = {"s": state_host,
                                  "a": overlap.host_accum()}
                saved = save_snapshot(state_host)
            tel.event("checkpoint", step=step_index, cursor_bytes=bytes_done)
            if saved:
                tel.ledger_write(
                    "checkpoint", step=step_index, cursor_bytes=bytes_done,
                    save_s=round(timer["checkpoint"] - ck_before, 6),
                    path=checkpoint_path, write=hooks.write_gate())
                log_event(logger, "checkpoint", step=step_index,
                          path=checkpoint_path, writer=hooks.write_gate())
        return state

    # Jobs with cross-row sequential state (grep's line carry) reset it at
    # file boundaries — files are independent corpora.  Optional, duck-typed
    # like the other hooks; transitions are rare (once per corpus member),
    # so the early superstep flush they force costs nothing measurable.
    boundary_hook = getattr(job, "on_input_boundary", None)
    # Resume restores which corpus member the snapshot's last batch came
    # from, so a snapshot saved at a file seam still triggers the boundary
    # hook on the next file's first batch (advisor round 2: last_file=None
    # after resume silently skipped the reset and leaked grep's line carry).
    last_file: Optional[int] = resumed_file
    # Prefetch: host-side chunking runs ahead of device compute, co-tuned
    # with the window (Config.prefetch_depth: deep enough to feed a full
    # window).  The manual iterator lets read_wait be timed: time spent
    # HERE is the reader failing to keep ahead of the device.
    def read_guarded():
        """One reader read behind the reader-read seam (ISSUE 15): the
        injected fault fires BEFORE the underlying ``next``, so retrying
        it on the policy budget is always safe.  A REAL reader error is
        recorded as a typed fault and propagates — the prefetch iterator
        is dead after raising, and re-nexting a dead generator would read
        as a silent end-of-stream (a truncation, the one unforgivable
        outcome)."""
        attempt = 0
        while True:
            try:
                cross("reader-read")
                return next(it, None)
            except faults_mod.FaultError as fe:
                if not fe.injected or fe.fault_class == "preemption":
                    raise
                if attempt >= policy.budget(fe.fault_class):
                    raise
                attempt += 1
                retry_record(step_index, attempt, fe,
                             fault_class=fe.fault_class, seam="reader-read")
                backoff(fe.fault_class, attempt, "reader-read")
            except Exception as re_:
                _record_fault(tel, hooks.write_gate(), re_,
                              seam="reader-read", injected=False,
                              step=step_index)
                raise

    it = iter(reader_mod.prefetch(
        reader_mod.iter_batches_multi(path, engine.n_devices,
                                      config.chunk_bytes,
                                      start_offset=start_offset,
                                      start_step=start_step,
                                      end_offset=end_offset),
        depth=config.resolved_prefetch_depth))
    try:
        while True:
            with obs.span("read_wait", timer):
                batch = next(it, None) if plan is None else read_guarded()
            if batch is None:
                break
            read_t[batch.step] = time.perf_counter()
            if hooks.stage_arrival is not None:
                with obs.span("stage", timer):
                    batch = hooks.stage_arrival(batch)
            if (boundary_hook is not None and last_file is not None
                    and batch.file_index != last_file):
                if pending:
                    state = flush(state, pending)
                    pending = []
                # Retire at the file boundary: a failure in the old file's
                # groups is attributed there, and the boundary hook's state
                # edit invalidates the replay anchor (re-taken lazily).
                state = drain_window(state, do_reanchor=False)
                pipe["boundary_drains"] += 1
                # File boundaries are window boundaries too: ship the old
                # corpus member's counts before the hook edits the carry
                # (partial_reset preserves seam context; the hook then
                # zeroes it exactly as it would on the monolithic state).
                if overlap is not None:
                    with obs.span("retire_wait", timer):
                        overlap.retire()
                    with obs.span("dispatch", timer):
                        state = overlap.boundary(state, step_index,
                                                 retired_groups)
                state = boundary_hook(state)
                anchor = None
                del since_anchor[:]
            last_file = batch.file_index
            pending.append(batch)
            if len(pending) == k:
                state = flush(state, pending)
                pending = []
        for batch in pending:  # remainder: single steps (no extra jit keys)
            state = flush(state, [batch])
        # End-of-stream tail decomposition (the old opaque `drain`):
        # h2d_tail = the last group's staged input still in transfer when
        # the reader ran dry; compute_tail = device work still queued
        # behind it.  Spanned even when empty so the phase keys always
        # exist for reports.
        with obs.span("h2d_tail", timer):
            if window:
                jax.block_until_ready(window[-1].staged)
                # The one per-group H2D completion the loop DOES observe
                # (the reader ran dry, so this wait serializes nothing):
                # the last group's record carries it, giving the timeline
                # a measured h2d lane interval instead of pure inference.
                window[-1].life["h2d_done_at"] = \
                    round(time.perf_counter(), 6)
        with obs.span("compute_tail", timer):
            state = drain_window(state, phase=None, do_reanchor=False)
    except BaseException as pe:
        # Preemption (ISSUE 15): drain the in-flight window (the groups
        # are healthy — the signal is not a device error; their bytes are
        # already accounted), snapshot if a checkpoint is configured, and
        # exit CLEANLY with the resumable cursor.  Caught by CLASS, not
        # type: recover() re-raises REAL preemption-shaped exceptions
        # (SIGTERM/maintenance-event markers) unwrapped, and
        # KeyboardInterrupt — classified preemption — is a BaseException
        # that never even routes through recover().  Anything not
        # preemption-classed re-raises untouched.  The plan is disarmed
        # first so no second injected fault can interrupt the orderly
        # shutdown (a real platform sends one SIGTERM, not a stream).
        if faults_mod.classify(pe) != "preemption":
            raise
        plan = None
        if overlap is not None:
            overlap.disarm()
        state = drain_window(state, do_reanchor=False)
        checkpointed = False
        if checkpoint_path:
            ck_before = timer["checkpoint"]
            with obs.span("checkpoint", timer):
                # The state fetch rides the same absorb-and-continue
                # discipline as the save: under a real preemption the
                # device may already be going away, and an unfetchable
                # state degrades to an uncheckpointed (still orderly)
                # exit, never a crash inside the drain handler.
                try:
                    if overlap is not None:
                        # Preemption is a boundary too: ship the local
                        # tables so the packed snapshot resumes exactly.
                        state = overlap.boundary(state, step_index,
                                                 retired_groups)
                    state_host = hooks.snapshot(state)
                    if overlap is not None:
                        state_host = {"s": state_host,
                                      "a": overlap.host_accum()}
                except Exception as se:
                    _record_fault(tel, hooks.write_gate(), se,
                                  seam="checkpoint-save", injected=False,
                                  step=step_index)
                    log_event(logger,
                              "preemption snapshot fetch failed; "
                              "exiting without checkpoint",
                              step=step_index, error=str(se))
                    state_host = None
                if state_host is not None:
                    checkpointed = save_snapshot(state_host)
            if checkpointed:
                tel.ledger_write(
                    "checkpoint", step=step_index, cursor_bytes=bytes_done,
                    save_s=round(timer["checkpoint"] - ck_before, 6),
                    path=checkpoint_path, preempt=True,
                    write=hooks.write_gate())
        log_event(logger, "preempted; drained and exiting cleanly",
                  step=step_index, cursor=bytes_done,
                  checkpointed=checkpointed)
        raise faults_mod.Preempted(
            step=step_index, cursor_bytes=bytes_done,
            checkpoint_path=checkpoint_path,
            checkpointed=checkpointed) from pe
    n_groups = pipe["dispatch_groups"]
    pipe["depth_mean"] = round(pipe.pop("depth_sum") / n_groups, 2) \
        if n_groups else 0.0
    pipe["window_filled"] = pipe["depth_max"] >= window_cap
    pipe["full_frac"] = round(pipe["full_retires"] / n_groups, 3) \
        if n_groups else 0.0
    # Only stamped when overlap ran: overlap-off runs keep the exact old
    # pipeline dict shape (the ledger A/B control).
    if overlap is not None:
        pipe["partial_merges"] = overlap.partials
    return state, bytes_done, step_index, pipe


def _path_names(path) -> list[str]:
    """Input path(s) as a list of strings for the run-ledger header."""
    import os

    if isinstance(path, (str, bytes, os.PathLike)):
        return [os.fspath(path) if not isinstance(path, bytes)
                else path.decode(errors="backslashreplace")]
    return [_path_names(p)[0] for p in path]


def _geometry_stamp(config) -> dict:
    """run_start kernel-geometry fields (ISSUE 12, ledger v6): the compact
    label always — 'default', a preset name, or 'custom' — plus the full
    field dict on custom runs (a preset/default label already names its
    spec; the A/B compare and the tuner knob read the label)."""
    label = config.geometry_label
    stamp = {"geometry": label}
    if label == "custom":
        stamp["geometry_spec"] = config.resolved_geometry.as_dict()
    return stamp


def _metrics_word_count(value) -> int:
    """Total words inside any finalize result shape, for RunMetrics.

    Finalize results nest: sketch wrappers hold a ``.table`` that may itself
    be a :class:`TopKTable` (top-k + sketch compositions).  Unwrap until the
    CountTable appears; non-table jobs (grep, sample) report 0 here — their
    metrics live in their own result fields.
    """
    for _ in range(3):
        if isinstance(value, (SketchedState, FreqSketchedState, TopKTable)):
            value = value.table
        else:
            break
    return int(value.total_count()) \
        if isinstance(value, table_ops.CountTable) else 0


def run_job(job: MapReduceJob, path, config: Config = DEFAULT_CONFIG,
            mesh=None, merge_strategy: Optional[str] = None,
            checkpoint_path: Optional[str] = None, checkpoint_every: int = 0,
            logger=None, progress_every: int = 50,
            byte_range: Optional[tuple[int, int]] = None,
            retry: int = 0, telemetry=None) -> RunResult:
    """Stream ``path`` through ``job`` over the mesh; see module docstring.

    ``telemetry`` (:class:`...obs.telemetry.Telemetry`, optional): per-step
    run-ledger records, flight-recorder forensics on failure, and metrics-
    registry counters for the run.  For jobs with data-stats hooks
    (the wordcount family) a telemetered run also runs the engine in
    stats mode (ISSUE 8): per-group data-plane counters ride the
    ``group`` records and one per-run ``data`` summary record lands —
    results stay byte-identical.  ``None`` disables all of it at zero
    per-step cost and keeps the exact uninstrumented step programs.  The
    caller owns the handle's lifetime (``tel.close()`` flushes the
    ledger).

    ``retry``: retries per step group on a transient dispatch failure.  The
    device state is donated into each step, so with ``retry > 0`` the
    executor keeps a host-side leaf-copy of the known-good state — anchored
    per dispatch window (``Config.inflight_groups``; one device->host fetch
    per window drain, the amortized cost of replayability) — plus the
    still-alive host batches, rebuilds a fresh sharded state from the
    anchor, and replays the window with the failed group charged one
    attempt (``inflight_groups=1``: exactly the old per-group snapshot +
    retry).  ``retry=0`` (default) keeps the full async pipeline and
    surfaces the failure with the resume cursor, attributed to the right
    step by its completion token; checkpoint/resume is then the recovery
    path.

    ``byte_range``: restrict ingestion to ``[lo, hi)`` — this host's slice of
    a multi-host corpus (:func:`...parallel.distributed.host_byte_range`,
    pre-aligned with ``align_range_to_separator``).  The returned value is
    then this host's *partial* state, to be merged host-side
    (``table_ops.merge``) across hosts.  Note this per-host-driven mode uses
    a host-LOCAL mesh: run_job stages plain numpy batches, so a mesh spanning
    non-addressable devices is not supported here — for one global SPMD
    program over all hosts use :func:`run_job_global`.
    """
    if retry < 0:
        raise ValueError(f"retry must be >= 0, got {retry}")
    logger = logger or get_logger()
    tel = obs.maybe(telemetry)
    # The strategy the engine builds: an explicit argument wins (the
    # pre-ISSUE-20 call convention); None defers to the config, whose
    # unresolved 'auto' behaves as 'tree' — 'auto' resolution against
    # the redplan profile is the CLI/bench driver's job.
    merge_strategy = merge_strategy if merge_strategy is not None \
        else config.resolved_merge_strategy
    # Unified failure policy + fault plan (ISSUE 15): the legacy `retry`
    # counter resolves into per-class budgets (None policy = exactly the
    # old semantics), and the policy's dispatch budget is what arms the
    # snapshot/replay machinery below — an explicit policy with budgets
    # enables replay without the caller touching `retry`.
    plan = faults_mod.FaultPlan.resolve(config.fault_plan)
    policy = faults_mod.FailurePolicy.resolve(config.failure_policy,
                                              retry=retry)
    retry = policy.dispatch_budget
    if config.merge_overlap and retry > 0:
        if config.failure_policy is None:
            raise ValueError(
                "merge_overlap requires retry=0: the replay anchor "
                "snapshots local state that a window-boundary partial "
                "merge has already shipped into the accumulator — "
                "checkpoint/resume is the recovery path for overlapped "
                "runs")
        # An explicit policy keeps its per-class budgets on the seams
        # that never replay shipped state (reader, checkpoint-save, and
        # collective-finish — injected collective faults fire BEFORE the
        # program runs, so retrying re-dispatches nothing the
        # accumulator already holds), matching run_job_global's
        # contract.  Window replay alone stays disarmed: its anchor
        # would snapshot local tables a partial merge already drained.
        retry = 0
    mesh = mesh if mesh is not None else data_mesh()
    # Shard over EVERY mesh axis: a 2-D ('replica','data') mesh contributes
    # all its devices to the data-parallel stream (the Engine linearizes the
    # axes row-major; hierarchical merge reduces innermost-first).
    axes = tuple(mesh.axis_names)
    n_dev = mesh.size  # == product over all axes, which we shard in full
    # Data-plane telemetry (ISSUE 8): telemetered runs of jobs with stats
    # hooks run the engine in stats mode — each step also returns a tiny
    # DataStats pytree fetched at group retirement and folded into the
    # `group` records + the per-run `data` summary record.  Results stay
    # byte-identical; telemetry=None keeps the exact pre-ISSUE-8 programs.
    from mapreduce_tpu.ops import datastats as datastats_ops

    data_stats = tel.enabled and datastats_ops.supports(job)
    engine = Engine(job, mesh, axis=axes if len(axes) > 1 else axes[0],
                    merge_strategy=merge_strategy, data_stats=data_stats)
    overlap = _OverlapMerger(engine, tel, lambda: True, plan, policy,
                             logger, merge_strategy,
                             config.inflight_groups) \
        if config.merge_overlap else None
    data_agg = datastats_ops.DataAggregator.for_run(config, n_dev) \
        if data_stats else None
    range_lo, range_hi = byte_range if byte_range is not None else (0, None)

    timer = metrics_mod.PhaseTimer()
    timer.start("total")

    start_step, start_offset = 0, range_lo
    bases_list: list[np.ndarray] = []
    fingerprint = ckpt_mod.run_fingerprint(
        path, n_dev, config.chunk_bytes, backend=config.resolved_backend(),
        pallas_max_token=config.pallas_max_token, byte_range=byte_range,
        job_identity=job.identity()) \
        if checkpoint_path else None
    ck_fallback = None
    if checkpoint_path and ckpt_mod.exists(checkpoint_path):
        # An abstract state (shapes/dtypes only, no device allocation) is
        # the structural template: any drift in job kind, capacities,
        # sketch precision, or device count surfaces as CheckpointMismatch
        # (shapes are ground truth).  A torn/corrupt snapshot falls back
        # to the previous good one (ISSUE 15 satellite; the fallback is
        # noted in the ledger after run_start) instead of crashing.
        # Overlapped runs pack {"s": local state, "a": accumulator}
        # (checkpoint boundaries always ship a partial first, so the
        # accumulator exists in every overlap snapshot); the packed
        # structure itself guards against resuming across an overlap
        # on/off flip.
        template = jax.eval_shape(engine.init_states)
        if overlap is not None:
            template = {"s": template, "a": overlap.accum_template()}
        (state_np, start_step, start_offset, bases_arr, resumed_file), \
            ck_fallback = ckpt_mod.load_resilient(
                checkpoint_path, template=template,
                expect_fingerprint=fingerprint)
        if overlap is not None:
            overlap.accum = jax.device_put(state_np["a"],
                                           engine._replicated)
            state_np = state_np["s"]
        state = _owned_state(jax.device_put(state_np, engine._sharded))
        bases_list = list(bases_arr)
        log_event(logger, "resumed from checkpoint", step=start_step,
                  offset=start_offset)
        if ck_fallback is not None:
            log_event(logger, "corrupt checkpoint; resumed from previous "
                      "good snapshot", **ck_fallback)
    else:
        state = engine.init_states()
        resumed_file = None

    # Each batch is staged to the device the moment the reader hands it
    # over (stage_arrival): the async H2D overlaps the previous group's
    # compute, the phase decomposition attributes placement to "stage",
    # and superstep groups stack ALREADY-RESIDENT [D, C] arrays on device
    # — shipping one K-times-larger stacked host array at dispatch time
    # measured ~7x slower per byte through the relay tunnel (round 5).
    import jax.numpy as jnp

    # With retry > 0 the batches must stay HOST numpy: the replay contract
    # re-dispatches the still-alive host buffers with a FRESH H2D per
    # attempt — an arrival-staged device array could itself be the failed
    # (error-poisoned) object, making every retry re-raise.  The stacked
    # staging buffer comes from a pool recycled at group retirement, so the
    # window costs O(inflight_groups) buffers, not one alloc per group.
    pool = _StagePool() if retry > 0 else None

    def stage_group_np(g):
        buf = pool.take((g[0].data.shape[0], len(g), g[0].data.shape[1]),
                        g[0].data.dtype)
        np.stack([b.data for b in g], axis=1, out=buf)
        return buf

    def rebuild(new_config: Config):
        """Degradation-ladder engine rebuild (ISSUE 15): same mesh, same
        state SHAPES (the ladder only moves kernel-choice knobs — each
        bit-identity-tested), cheaper programs.  The job is rebound so
        every map call site reads the degraded knobs; the anchor snapshot
        restages into the new engine unchanged."""
        nonlocal job, engine
        job = _job_with_config(job, new_config)
        engine = Engine(job, mesh, axis=axes if len(axes) > 1 else axes[0],
                        merge_strategy=merge_strategy, data_stats=data_stats)
        return engine

    hooks = _StreamHooks(
        stage_single=lambda b: b.data,
        stage_group=stage_group_np if retry > 0 else
        (lambda g: jnp.stack([b.data for b in g], axis=1)),
        # An honest COPY, not np.asarray: on the CPU backend np.asarray
        # of a jax array is a zero-copy VIEW of the live buffer, and the
        # state it views is donated into the next dispatch — a snapshot
        # that can be overwritten is not a known-good anchor.
        snapshot=lambda s: jax.tree.map(lambda x: np.array(x, copy=True),
                                        s),
        # _owned_state: the restaged tree is donated into the next step —
        # a raw device_put result is not donation-safe (see _owned_state).
        restage=lambda s_np: _owned_state(
            jax.device_put(s_np, engine._sharded)),
        write_gate=lambda: True,
        retry=retry,
        stage_release=pool.give if retry > 0 else None,
        stage_arrival=None if retry > 0 else (lambda b: dataclasses.replace(
            b, data=jax.device_put(b.data, engine.sharding))),
        rebuild=rebuild,
        overlap=overlap)
    if jax.process_count() > 1:
        # Per-host-driven multi-host (mode a): each host owns its whole
        # ledger file already, so no second shard file — but the records
        # get the v7 host stamp + clock so obs/fleet.py can merge the
        # per-host ledgers into one fleet timeline (ISSUE 13).
        from mapreduce_tpu.parallel import distributed as dist

        tel.attach_host(jax.process_index(), jax.process_count(),
                        local_devices=len(jax.local_devices()),
                        clock=dist.run_epoch(), shard=False)
    tel.registry.counter("executor.runs", driver="run_job").inc()
    # run_start stamps the fault plan's canonical spec (ISSUE 15, ledger
    # v9) so a chaotic ledger names its own chaos; absent when injection
    # is off, keeping fault-free records byte-identical to v8 shapes.
    chaos_stamp = {"fault_plan": plan.spec} if plan is not None else {}
    tel.ledger_write("run_start", driver="run_job", job=job.identity(),
                     devices=n_dev, chunk_bytes=config.chunk_bytes,
                     superstep=config.superstep,
                     backend=config.resolved_backend(),
                     map_impl=config.map_impl,
                     combiner=config.resolved_combiner,
                     **_geometry_stamp(config), **chaos_stamp,
                     merge_strategy=merge_strategy,
                     **({"merge_overlap": True} if config.merge_overlap
                        else {}),
                     input=_path_names(path),
                     resume_step=start_step, resume_offset=start_offset,
                     retry=retry)
    if ck_fallback is not None:
        # The corrupt-checkpoint fallback's ledger note (ISSUE 15
        # satellite): a real checkpoint-load fault, observed and healed.
        tel.ledger_write("fault", seam="checkpoint-load",
                         fault_class="transient", injected=False,
                         error=ck_fallback["error"],
                         fallback=ck_fallback["loaded"],
                         corrupt=ck_fallback["corrupt"])
    timer.start("stream")
    try:
        state, bytes_done, _, pipe = _drive_stream(
            engine, job, config, path, state, hooks,
            start_step=start_step, start_offset=start_offset,
            end_offset=range_hi, bases_list=bases_list,
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, resumed_file=resumed_file,
            logger=logger, progress_every=progress_every, timer=timer,
            telemetry=tel, data_agg=data_agg, plan=plan, policy=policy)
        # Residual drain: the stream loop already retired every in-flight
        # group (h2d_tail/compute_tail decompose what this phase used to
        # lump together); this keeps the stream/reduce boundary honest.
        with obs.span("drain", timer):
            jax.block_until_ready(state)
        timer.stop("stream")

        with obs.span("reduce", timer):
            # Overlap: retire the last in-flight partial (its ledger
            # record lands with its real interval), then the residual
            # finish merges only what arrived after the last boundary.
            if overlap is not None:
                overlap.retire()
            fin_t0 = time.perf_counter()
            if overlap is not None:
                value = _collective_call(
                    lambda: engine.finish_residual(overlap.accum, state),
                    plan, policy, tel, True, logger)
            else:
                value = _collective_finish(engine, state, plan, policy,
                                           tel, True, logger)
            value = jax.tree.map(np.asarray, value)  # block + fetch the result
            # One op='finish' `collective` record per run (ISSUE 13;
            # op='partial' records joined it in ledger v10): the observed
            # finish interval + merge strategy — the fleet timeline's
            # `collective` lane (strategy builds stay registry metrics).
            tel.ledger_write("collective", op="finish",
                             strategy=merge_strategy,
                             started_at=round(fin_t0, 6),
                             ended_at=round(time.perf_counter(), 6))
    except faults_mod.Preempted:
        # Orderly preemption shutdown (ISSUE 15), not a failure: the
        # stream drained, the snapshot (if configured) landed, and the
        # exception carries the resumable cursor — no flight dump.
        raise
    except Exception as e:
        # Dispatch failures already dumped inside _drive_stream (with step
        # context); this catches everything else on the streaming path —
        # reader errors, drain/finish failures — so ANY crashed telemetered
        # run leaves forensics.  flight_dump is idempotent per run: the
        # first (most specific) dump wins.
        tel.flight_dump(context={"where": "run_job", "error": repr(e)})
        raise
    total_s = timer.stop("total")

    _finalize_pipeline(pipe, timer, tel)
    data_rec = None
    if data_agg is not None and data_agg.groups:
        # One per-run data-plane summary record (ISSUE 8) — written before
        # run_end so "no run_end = did not complete" stays the last-record
        # invariant.  obs/datahealth.py classifies this dict; the window
        # autotuner (ISSUE 10) reads it next to the PR-7 bottleneck.
        data_rec = data_agg.run_record()
        tel.ledger_write("data", **data_rec)
        tel.note_data(data_rec)
    # Online autotune hint (ISSUE 10): written after the data record and
    # before run_end, so the tune record can read everything this run
    # measured while "no run_end = did not complete" stays true.
    tune = _autotune_hint(config, tel, pipe, timer, data_rec, logger) \
        if config.autotune == "hint" else None
    words = _metrics_word_count(value)
    # bytes_done is the absolute resume CURSOR (checkpoints store it); the
    # throughput metric counts only bytes this run actually streamed.
    m = metrics_mod.RunMetrics(bytes_processed=bytes_done - range_lo, words_counted=words,
                               elapsed_s=total_s, phases=dict(timer.phases))
    tel.ledger_write("run_end", **m.as_dict(), pipeline=pipe)
    log_event(logger, "run complete", **m.as_dict())
    bases = np.stack(bases_list) if bases_list else np.zeros((0, n_dev), np.int64)
    return RunResult(value=value, metrics=m, bases=bases, pipeline=pipe,
                     tune=tune)


def run_job_global(job: MapReduceJob, path, config: Config = DEFAULT_CONFIG,
                   mesh=None, merge_strategy: Optional[str] = None,
                   checkpoint_path: Optional[str] = None,
                   checkpoint_every: int = 0,
                   logger=None, progress_every: int = 50,
                   telemetry=None) -> RunResult:
    """Multi-host mode (b) as one entry point: ONE global SPMD program over
    every chip of every process (VERDICT r3 #5; the 100 GB / v5e-256
    BASELINE config runs through this).

    Every process calls this with the same arguments after
    :func:`...parallel.distributed.initialize`.  Per process:

      * the mesh spans ALL processes' devices
        (:func:`...parallel.distributed.global_data_mesh` by default);
      * the reader runs identically everywhere (same deterministic chunk
        geometry — cut offsets must agree across processes), but each
        process STAGES only its own contiguous block of shard rows
        (``host_shards``) via ``device_put_local``, so no process ships
        another's data over DCN;
      * the engine step is the same jitted SPMD program on every process
        (multi-controller SPMD: identical programs, local data);
      * the collective ``finish`` replicates the merged result to every
        process — the returned ``RunResult`` is identical everywhere;
        report/print on :func:`...parallel.distributed.is_coordinator`.

    Checkpointing: the sharded state is fetched with one all-gather round
    (:meth:`Engine.replicate_to_host`) and ONLY the coordinator writes the
    snapshot (``checkpoint_path`` should be on storage the coordinator owns;
    resume requires every process to read it — shared filesystem, or
    distribute the file before relaunch).  Resume re-stages each process's
    own shard rows from the snapshot.  Step retry is not offered here: a
    failed collective leaves peer processes blocked mid-program, so the
    recovery path for global runs IS checkpoint/resume (SURVEY §5 failure
    detection: the jax.distributed heartbeat surfaces dead peers).

    Differences from :func:`run_job`: no ``byte_range`` (the global program
    consumes the whole corpus; per-host byte ranges are mode (a)), no
    ``retry``, and single-buffer convenience staging is replaced by
    ``device_put_local``.
    """
    from mapreduce_tpu.parallel import distributed as dist

    logger = logger or get_logger()
    tel = obs.maybe(telemetry)
    # Fault plan + failure policy (ISSUE 15): the global driver gets the
    # full seam set (incl. process-kill — the multi-host chaos scenario)
    # but NO window replay (restage=None below: a failed collective
    # leaves peers blocked mid-program, checkpoint/resume is the recovery
    # path) and no degradation ladder (rebuild=None: every process would
    # have to step in lockstep).  The policy still drives reader/
    # checkpoint-save/collective-finish retries and the token timeout.
    merge_strategy = merge_strategy if merge_strategy is not None \
        else config.resolved_merge_strategy
    plan = faults_mod.FaultPlan.resolve(config.fault_plan)
    policy = faults_mod.FailurePolicy.resolve(config.failure_policy,
                                              retry=0)
    mesh = mesh if mesh is not None else dist.global_data_mesh()
    axes = tuple(mesh.axis_names)
    n_dev = mesh.size
    # No data-stats mode here (like no retry): the stats leaves are [D]
    # per-shard scalars, and fetching them on a mesh spanning other
    # processes' devices would need a collective round per retirement.
    # Data-plane telemetry is the per-host-driven / single-host story.
    engine = Engine(job, mesh, axis=axes if len(axes) > 1 else axes[0],
                    merge_strategy=merge_strategy)
    # Window-boundary overlap (ISSUE 20 leg 2) — THE fleet scenario: the
    # partial merge is one SPMD program every process dispatches at the
    # same deterministic boundary, so the DCN transfer of window N rides
    # under window N+1's ingest.  The global driver has no retry, so no
    # gating is needed here.
    overlap = _OverlapMerger(engine, tel, dist.is_coordinator, plan,
                             policy, logger, merge_strategy,
                             config.inflight_groups) \
        if config.merge_overlap else None
    mine = np.asarray(dist.host_shards(n_dev), dtype=np.int64)

    timer = metrics_mod.PhaseTimer()
    timer.start("total")

    start_step, start_offset = 0, 0
    bases_list: list[np.ndarray] = []
    fingerprint = ckpt_mod.run_fingerprint(
        path, n_dev, config.chunk_bytes, backend=config.resolved_backend(),
        pallas_max_token=config.pallas_max_token, byte_range=None,
        job_identity=job.identity()) if checkpoint_path else None

    # Shard-row staging buffers come from a pool recycled when their group
    # retires (the program consumed the input), instead of a fresh gather
    # allocation per group; ``_staged_bufs`` pairs each staged device array
    # with the host buffer it was transferred from.
    pool = _StagePool()
    _staged_bufs: dict[int, np.ndarray] = {}

    def stage(host_rows: np.ndarray):
        """This process's rows -> one globally-sharded array."""
        arr = dist.device_put_local(host_rows, engine.sharding)
        _staged_bufs[id(arr)] = host_rows
        return arr

    def stage_release(staged) -> None:
        pool.give(_staged_bufs.pop(id(staged), None))

    def stage_single(b):
        buf = pool.take((len(mine), b.data.shape[1]), b.data.dtype)
        np.take(b.data, mine, axis=0, out=buf)
        return stage(buf)

    def stage_group(g):
        buf = pool.take((len(mine), len(g), g[0].data.shape[1]),
                        g[0].data.dtype)
        for j, b in enumerate(g):
            buf[:, j] = b.data[mine]
        return stage(buf)

    ck_fallback = None
    if checkpoint_path and ckpt_mod.exists(checkpoint_path):
        template = jax.eval_shape(engine.init_states_global)
        if overlap is not None:
            # Overlap snapshots pack {"s": local state, "a": accumulator}
            # (every checkpoint boundary ships a partial first).
            template = {"s": template, "a": overlap.accum_template()}
        (state_np, start_step, start_offset, bases_arr, resumed_file), \
            ck_fallback = ckpt_mod.load_resilient(
                checkpoint_path, template=template,
                expect_fingerprint=fingerprint)
        if overlap is not None:
            # The accumulator is fully replicated: every process holds
            # the identical host value, so local data = global value.
            overlap.accum = jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(
                    engine._replicated, np.asarray(x)), state_np["a"])
            state_np = state_np["s"]
        # _owned_state: the resumed tree is donated into the first global
        # step — a raw transfer-created buffer is not donation-safe.
        state = _owned_state(
            jax.tree.map(lambda x: stage(np.asarray(x)[mine]), state_np))
        bases_list = list(bases_arr)
        log_event(logger, "resumed from checkpoint (global)",
                  step=start_step, offset=start_offset)
        if ck_fallback is not None:
            log_event(logger, "corrupt checkpoint; resumed from previous "
                      "good snapshot", **ck_fallback)
    else:
        state = engine.init_states_global()
        resumed_file = None

    hooks = _StreamHooks(
        stage_single=stage_single,
        stage_group=stage_group,
        # The checkpoint fetch is a collective (one all-gather round makes
        # the sharded state addressable everywhere); only the coordinator
        # touches the filesystem.  No retry (see docstring).
        snapshot=engine.replicate_to_host,
        restage=None,
        write_gate=dist.is_coordinator,
        retry=0,
        stage_release=stage_release,
        host_rows=mine,
        overlap=overlap)
    # Pod-scale observability (ISSUE 13, ledger v7): every process writes
    # its own `<ledger>.h<p>.jsonl` shard (host-stamped records, the
    # run-epoch clock pair in run_start, per-host flight dumps); the
    # coordinator keeps the merged-authoritative main file it always
    # wrote.  Pass the SAME ledger path (and ideally the same run_id) on
    # every process; obs/fleet.py merges the shards.
    if jax.process_count() > 1:
        tel.attach_host(jax.process_index(), jax.process_count(),
                        local_devices=len(jax.local_devices()),
                        clock=dist.run_epoch())
    tel.registry.counter("executor.runs", driver="run_job_global").inc()
    # The main ledger rides the same gate as checkpoints: one file,
    # written by the coordinator; the per-host shard gets every record.
    chaos_stamp = {"fault_plan": plan.spec} if plan is not None else {}
    tel.ledger_write("run_start", driver="run_job_global",
                     job=job.identity(), devices=n_dev,
                     chunk_bytes=config.chunk_bytes,
                     superstep=config.superstep,
                     backend=config.resolved_backend(),
                     map_impl=config.map_impl,
                     combiner=config.resolved_combiner,
                     **_geometry_stamp(config), **chaos_stamp,
                     merge_strategy=merge_strategy,
                     **({"merge_overlap": True} if config.merge_overlap
                        else {}),
                     input=_path_names(path),
                     resume_step=start_step, resume_offset=start_offset,
                     write=dist.is_coordinator())
    if ck_fallback is not None:
        tel.ledger_write("fault", seam="checkpoint-load",
                         fault_class="transient", injected=False,
                         error=ck_fallback["error"],
                         fallback=ck_fallback["loaded"],
                         corrupt=ck_fallback["corrupt"],
                         write=dist.is_coordinator())
    timer.start("stream")
    try:
        state, bytes_done, _, pipe = _drive_stream(
            engine, job, config, path, state, hooks,
            start_step=start_step, start_offset=start_offset,
            end_offset=None, bases_list=bases_list,
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, resumed_file=resumed_file,
            logger=logger, progress_every=progress_every, timer=timer,
            telemetry=tel, plan=plan, policy=policy)
        with obs.span("drain", timer):
            jax.block_until_ready(state)
        timer.stop("stream")

        with obs.span("reduce", timer):
            if overlap is not None:
                overlap.retire()
            fin_t0 = time.perf_counter()
            # Replicated finish: addressable everywhere.  The collective-
            # finish seam + injected-fault retry budget wrap it (ISSUE
            # 15); real collective failures classify, record, propagate.
            if overlap is not None:
                value = _collective_call(
                    lambda: engine.finish_residual(overlap.accum, state),
                    plan, policy, tel, dist.is_coordinator(), logger)
            else:
                value = _collective_finish(engine, state, plan, policy,
                                           tel, dist.is_coordinator(),
                                           logger)
            value = jax.tree.map(np.asarray, value)
            # Every host times the SAME collective finish from its own
            # side (ISSUE 13): the fleet `collective` lane + the
            # collective-bound half of the fleet_bottleneck verdict.
            tel.ledger_write("collective", op="finish",
                             strategy=merge_strategy,
                             started_at=round(fin_t0, 6),
                             ended_at=round(time.perf_counter(), 6),
                             write=dist.is_coordinator())
    except faults_mod.Preempted:
        # Orderly preemption shutdown (ISSUE 15): resumable, not a
        # failure — no flight dump.
        raise
    except Exception as e:
        # Each process dumps to its OWN (host-suffixed) flight path —
        # no shared-file race, and the failing host's forensics survive
        # (ISSUE 13 bugfix: this used to ride the coordinator gate).
        tel.flight_dump(context={"where": "run_job_global",
                                 "error": repr(e)})
        raise
    total_s = timer.stop("total")

    _finalize_pipeline(pipe, timer, tel)
    words = _metrics_word_count(value)
    m = metrics_mod.RunMetrics(bytes_processed=bytes_done, words_counted=words,
                               elapsed_s=total_s, phases=dict(timer.phases))
    # The shard's run_end carries THIS host's phase totals — the per-host
    # straggler raw material; the coordinator's main record is unchanged.
    tel.ledger_write("run_end", **m.as_dict(), pipeline=pipe,
                     write=dist.is_coordinator())
    log_event(logger, "global run complete", **m.as_dict())
    bases = np.stack(bases_list) if bases_list else np.zeros((0, n_dev), np.int64)
    return RunResult(value=value, metrics=m, bases=bases, pipeline=pipe)


def absolute_offsets(chunk_id: np.ndarray, pos: np.ndarray,
                     bases: np.ndarray, n_devices: int) -> np.ndarray:
    """Decode (chunk_id = step * n_devices + device, per-chunk pos) into
    absolute corpus offsets via the recorded row bases — the single host-
    side owner of the Engine's chunk-id linearization (every job recovering
    source spans goes through this)."""
    step, dev = chunk_id // n_devices, chunk_id % n_devices
    return bases[step, dev] + pos


def recover_from_file(tbl: table_ops.CountTable, path, bases: np.ndarray,
                      n_devices: int, ngram: int = 1,
                      estimate_distinct: bool = True) -> WordCountResult:
    """Host-side string recovery for a streamed run.

    ``pos_hi`` encodes chunk_id = step * n_devices + device; its absolute file
    base is ``bases[step, device]``.  Entries are reported in file order
    (first occurrence), the reference's insertion order (main.cu:212-215).

    Entries whose length is ``SEAM_GRAM_LENGTH`` are cross-chunk grams: the
    device knew the start but not the end (it lies in a later chunk), so the
    span length is recovered here by scanning ``ngram`` tokens forward.
    """
    count = np.asarray(tbl.count).astype(np.int64)
    count_hi = np.asarray(tbl.count_hi).astype(np.int64)
    valid = (count > 0) | (count_hi > 0)
    chunk_id = np.asarray(tbl.pos_hi)[valid].astype(np.int64)
    pos = np.asarray(tbl.pos_lo)[valid].astype(np.int64)
    length = np.asarray(tbl.length)[valid].astype(np.int64)
    cnt = (count + (count_hi << np.int64(32)))[valid]
    absolute = absolute_offsets(chunk_id, pos, bases, n_devices)
    seam = np.flatnonzero(length == int(constants.SEAM_GRAM_LENGTH))
    if len(seam):
        # Row bases mark force-split entry ends (the reader cuts separator-
        # free runs there); one batch call maps each touched file once.
        length[seam] = reader_mod.scan_gram_lengths(
            path, absolute[seam], ngram, cut_offsets=bases.ravel())
    order = np.argsort(absolute, kind="stable")
    spans = [(int(absolute[i]), int(length[i])) for i in order]
    words = reader_mod.read_words_at_multi(path, spans)
    dropped_uniques, dropped_count = tbl.dropped_totals()
    return WordCountResult(
        words=words,
        counts=[int(c) for c in cnt[order]],
        total=int(np.asarray(tbl.total_count())),
        distinct=_reported_distinct(tbl, len(words), dropped_uniques,
                                    estimate_distinct),
        dropped_uniques=dropped_uniques,
        dropped_count=dropped_count,
    )


def count_file(path, config: Config = DEFAULT_CONFIG, mesh=None,
               top_k: Optional[int] = None, distinct_sketch: bool = False,
               count_sketch: bool = False, ngram: int = 1, **kw) -> WordCountResult:
    """WordCount over a file via the streaming sharded pipeline.

    ``distinct_sketch`` composes a HyperLogLog over the run, populating
    ``result.distinct_estimate`` — accurate (~0.8%) even when distinct words
    spill past table capacity.  Sketched runs checkpoint like plain ones
    (snapshots hold the whole state pytree); resuming a checkpoint across
    sketched/unsketched configurations raises CheckpointMismatch.

    ``count_sketch`` composes a Count-Min sketch instead, populating
    ``result.cms`` so ``result.estimate_count(word)`` answers frequency
    queries for any word — including ones the exact table spilled.  The two
    sketches are mutually exclusive per run (their states checkpoint
    differently); pick the one matching the question being asked.

    ``ngram > 1`` counts n-token grams instead of single words — exactly,
    including grams spanning chunk seams (the seam-carry machinery of
    :class:`...models.wordcount.NGramCountJob`); streamed results match
    single-buffer runs bit-for-bit.
    """
    if distinct_sketch and count_sketch:
        raise ValueError("distinct_sketch and count_sketch are mutually "
                         "exclusive per run; run twice to get both")
    mesh = mesh if mesh is not None else data_mesh()
    if ngram > 1:
        job = NGramCountJob(ngram, config, top_k=top_k or None)
    else:
        job = TopKWordCountJob(top_k, config) if top_k else WordCountJob(config)
    if distinct_sketch:
        job = SketchedWordCountJob(job)
    elif count_sketch:
        job = FreqSketchedWordCountJob(job)
    rr = run_job(job, path, config=config, mesh=mesh, **kw)
    n_dev = mesh.size
    value, registers, cms = rr.value, None, None
    if isinstance(value, SketchedState):
        value, registers = value.table, value.registers
    elif isinstance(value, FreqSketchedState):
        value, cms = value.table, np.asarray(value.cms)
    # Top-k finalize reorders the table on device, destroying the KMV
    # property — but it snapshots the estimator's scalars first
    # (TopKTable), so spilled top-k runs still get the tight distinct
    # estimate instead of the summed upper bound.
    kmv_est = None
    if isinstance(value, TopKTable):
        kmv_est = table_ops.kmv_from_snapshot(
            int(value.kmv_n_valid), int(value.kmv_kth_hi),
            int(value.kmv_kth_lo), config.table_capacity)
        value = value.table
    result = recover_from_file(value, path, rr.bases, n_dev, ngram=ngram,
                               estimate_distinct=not top_k)
    if kmv_est is not None:
        result = dataclasses.replace(
            result, distinct=max(len(result.words), int(round(kmv_est))))
    if registers is not None:
        from mapreduce_tpu.ops import sketch as sketch_ops

        result = dataclasses.replace(
            result, distinct_estimate=sketch_ops.estimate(registers))
    if cms is not None:
        result = dataclasses.replace(result, cms=cms)
    if top_k:
        result = apply_top_k(result, top_k)
    return result
