"""Streaming executor: files -> sharded device stream -> merged result.

The orchestration layer of the framework (reference analogue: the body of
``main()`` plus ``runMapReduce``, ``main.cu:133-222``), with the capabilities
the reference lacks (SURVEY §5): step retry on transient failure, periodic
checkpoint/resume, structured progress logging, and throughput metrics.

Flow per run:
  1. build (or accept) a data mesh and an Engine for the job;
  2. stream boundary-aligned [D, chunk_bytes] batches from the reader,
     folding each into device-resident per-device states (one jitted SPMD
     step; accumulators never round-trip to host);
  3. collectively merge + finalize;
  4. recover exact strings host-side from (chunk_id, pos, len) first-
     occurrence records against the memory-mapped source file.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import numpy as np

from mapreduce_tpu import constants
from mapreduce_tpu import obs
from mapreduce_tpu.config import Config, DEFAULT_CONFIG
from mapreduce_tpu.data import reader as reader_mod
from mapreduce_tpu.models.wordcount import (WordCountJob, TopKWordCountJob,
                                            NGramCountJob, TopKTable,
                                            SketchedState, SketchedWordCountJob,
                                            FreqSketchedState, FreqSketchedWordCountJob,
                                            WordCountResult, apply_top_k,
                                            _reported_distinct)
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.parallel.mapreduce import Engine, MapReduceJob
from mapreduce_tpu.parallel.mesh import data_mesh
from mapreduce_tpu.runtime import checkpoint as ckpt_mod
from mapreduce_tpu.runtime import metrics as metrics_mod
from mapreduce_tpu.runtime.logging import get_logger, log_event


@dataclasses.dataclass
class RunResult:
    """Generic job result + run metrics."""

    value: Any
    metrics: metrics_mod.RunMetrics
    bases: np.ndarray  # int64[steps, D] row base offsets (string recovery)


@dataclasses.dataclass
class _StreamHooks:
    """The strategy seams between the host-local (:func:`run_job`) and
    global-SPMD (:func:`run_job_global`) drivers.  Everything else about
    streaming — superstep grouping, checkpoint-boundary splitting, file-
    boundary hooks, the retry loop, progress/checkpoint cadence — is ONE
    shared loop (:func:`_drive_stream`), so a fix to that machinery lands
    in both entry points by construction."""

    stage_single: Any  # Batch -> engine.step chunks argument
    stage_group: Any  # list[Batch] -> engine.step_many stacked argument
    snapshot: Any  # device state -> host pytree (checkpoint fetch / retry)
    restage: Any  # host pytree -> sharded device state (retry; None = n/a)
    write_gate: Any  # () -> bool: this process writes checkpoint files
    retry: int = 0
    # Optional Batch -> Batch applied the moment a batch leaves the reader:
    # run_job uses it to device_put each [D, C] chunk array immediately
    # (async H2D starts right away and overlaps the PREVIOUS group's
    # compute), so superstep groups stack already-resident device arrays
    # instead of shipping one K-times-larger host array at dispatch time —
    # measured through the relay tunnel, a single 128 MB staged array moved
    # ~7x slower per byte than 32 MB chunk arrays (BENCHMARKS.md round 5).
    stage_arrival: Any = None


def _drive_stream(engine, job, config: Config, path, state,
                  hooks: _StreamHooks, *, start_step: int, start_offset: int,
                  end_offset, bases_list: list, checkpoint_path,
                  checkpoint_every: int, fingerprint, resumed_file,
                  logger, progress_every: int, timer=None, telemetry=None):
    """The shared streaming loop: reader -> prefetch -> superstep groups ->
    engine dispatch, with checkpoint cadence and file-boundary hooks.
    Returns ``(state, bytes_done, step_index)``; ``bytes_done`` is the
    absolute stream cursor (starts at ``start_offset``).

    ``timer`` (a :class:`...runtime.metrics.PhaseTimer`) decomposes the
    stream wall-clock into the phases the ingest number is made of
    (VERDICT r4 next #2 — without this the 3x streamed-vs-H2D gap was
    unattributable): ``read_wait`` (blocking on the prefetching reader),
    ``stage`` (host assembly + host->device placement of a group),
    ``dispatch`` (program enqueue; under async dispatch this blocks only
    when the device queue is full, so a large value means compute-bound,
    a small one link/host-bound).  The phases are timed through
    :func:`...obs.spans.span`, which also drops a profiler TraceAnnotation
    per phase so XProf timelines line up with the ledger.

    ``telemetry`` (:class:`...obs.telemetry.Telemetry`): one ledger step
    record per dispatched group carrying those phase deltas plus bytes and
    device memory stats; flight-recorder events per dispatch / retry /
    checkpoint, dumped with a state summary when the failure path runs.
    Disabled telemetry (the ``None`` default) does no per-step work and —
    the invariant the graphcheck host-sync pass certifies — never adds a
    host sync to the dispatch pipeline either way: everything here is
    host-side bookkeeping around the async enqueue.
    """
    bytes_done = int(start_offset)
    step_index = start_step
    last_ckpt = start_step // checkpoint_every if checkpoint_every else 0
    k = config.superstep
    pending: list = []
    timer = timer if timer is not None else metrics_mod.PhaseTimer()
    tel = obs.maybe(telemetry)

    def dispatch(state, group):
        with obs.span("stage", timer):
            staged = hooks.stage_single(group[0]) if len(group) == 1 \
                else hooks.stage_group(group)
        with obs.span("dispatch", timer):
            if len(group) == 1:
                return engine.step(state, staged, group[0].step)
            return engine.step_many(state, staged, group[0].step)

    def split_at_checkpoints(group):
        """Cut a superstep group at checkpoint boundaries, so resume
        granularity is governed by ``checkpoint_every`` even when it is
        finer than the superstep: a crash then replays at most
        ``checkpoint_every`` chunks per device, not a whole superstep
        (set ``checkpoint_every >= superstep`` to keep the full dispatch
        amortization)."""
        if not (checkpoint_every and checkpoint_path):
            return [group]
        subs, cur = [], []
        for b in group:
            cur.append(b)
            if (b.step + 1) % checkpoint_every == 0:
                subs.append(cur)
                cur = []
        if cur:
            subs.append(cur)
        return subs

    def flush(state, group):
        """Dispatch a group of consecutive batches (one superstep, split at
        any interior checkpoint boundaries)."""
        for sub in split_at_checkpoints(group):
            state = flush_one(state, sub)
        return state

    def flush_one(state, group):
        """Dispatch one group of consecutive batches as a single program."""
        nonlocal bytes_done, step_index, last_ckpt
        # The dispatch donates `state`; a known-good host snapshot (taken
        # BEFORE donation) is what makes a retry possible at all.
        snapshot = hooks.snapshot(state) if hooks.retry > 0 else None
        retries_used = 0
        for attempt in range(hooks.retry + 1):
            try:
                state = dispatch(state, group)
                if hooks.retry > 0:
                    # Device failures surface asynchronously at the next
                    # blocking fetch — which without this sync would be the
                    # NEXT group's snapshot, outside this try: the failure
                    # would skip retry entirely and be blamed on the wrong
                    # step.  Blocking here attributes it to the dispatch
                    # that caused it.  (retry=0 keeps the async pipeline:
                    # there is nothing to attribute a failure to.)
                    jax.block_until_ready(state)
                break
            except Exception as e:
                if attempt >= hooks.retry:
                    # Failure detection (SURVEY §5): out of retries (or none
                    # requested).  Surface loudly with the resume cursor;
                    # checkpoint/resume is the recovery path.  The flight
                    # recorder dumps its ring + state summary FIRST, so a
                    # run that dies here leaves forensics on disk (the
                    # benchwatch wedge scenario) before the raise unwinds.
                    # Dump + failure record ride the write gate like every
                    # other ledger artifact: in multi-host runs N processes
                    # racing one flight.json would shred the forensics.
                    tel.event("step_failed", step=group[0].step,
                              attempt=attempt, error=repr(e))
                    if hooks.write_gate():
                        dump = tel.flight_dump(
                            context={"step": group[0].step,
                                     "offset": bytes_done,
                                     "attempts": attempt + 1,
                                     "error": repr(e),
                                     "checkpoint_path": checkpoint_path},
                            state=snapshot)
                        tel.ledger_write("failure", step=group[0].step,
                                         cursor_bytes=bytes_done,
                                         error=repr(e), flight_dump=dump)
                    log_event(logger, "step failed", step=group[0].step,
                              offset=bytes_done,
                              resume_hint=checkpoint_path
                              or "enable checkpointing to resume")
                    raise
                # Transient-failure recovery: rebuild a fresh sharded state
                # from the snapshot and re-dispatch the same host batches.
                retries_used += 1
                tel.registry.counter("executor.retry_attempts").inc()
                tel.event("retry", step=group[0].step, attempt=attempt + 1,
                          error=repr(e))
                if hooks.write_gate():
                    tel.ledger_write("retry", step=group[0].step,
                                     attempt=attempt + 1, error=repr(e))
                log_event(logger, "step failed; retrying",
                          step=group[0].step, attempt=attempt + 1)
                state = hooks.restage(snapshot)
        if retries_used:
            tel.registry.counter("executor.retry_recoveries").inc()
        for b in group:
            bases_list.append(b.base_offsets)
            bytes_done += int(b.lengths.sum())
        step_index = group[-1].step + 1
        tel.step_record(step_first=group[0].step, step_last=group[-1].step,
                        group_bytes=int(sum(int(b.lengths.sum())
                                            for b in group)),
                        cursor_bytes=bytes_done, timer=timer,
                        retries=retries_used, write=hooks.write_gate())
        if progress_every and step_index % progress_every < len(group):
            log_event(logger, "progress", step=step_index, bytes=bytes_done)
        if (checkpoint_every and checkpoint_path
                and step_index // checkpoint_every > last_ckpt):
            last_ckpt = step_index // checkpoint_every
            # Synchronize, then snapshot the state and ingest cursor.  The
            # snapshot format holds ANY job state pytree (tables, sketched
            # states, grep scalars alike).  Multi-host: every process pays
            # the fetch (it is a collective there), only the gate-holder
            # touches the filesystem.
            ck_before = timer["checkpoint"]
            with obs.span("checkpoint", timer):
                state_host = hooks.snapshot(state)
                if hooks.write_gate():
                    # file_index makes the snapshot boundary-aware: resuming
                    # a checkpoint that ends a corpus member must still fire
                    # the job's on_input_boundary hook on the next member's
                    # first batch (the carry reset happens AFTER this save
                    # in the stream loop).
                    ckpt_mod.save(checkpoint_path, state_host, step_index,
                                  bytes_done, np.stack(bases_list),
                                  fingerprint=fingerprint,
                                  file_index=group[-1].file_index)
            tel.event("checkpoint", step=step_index, cursor_bytes=bytes_done)
            if hooks.write_gate():
                tel.ledger_write(
                    "checkpoint", step=step_index, cursor_bytes=bytes_done,
                    save_s=round(timer["checkpoint"] - ck_before, 6),
                    path=checkpoint_path)
            log_event(logger, "checkpoint", step=step_index,
                      path=checkpoint_path, writer=hooks.write_gate())
        return state

    # Jobs with cross-row sequential state (grep's line carry) reset it at
    # file boundaries — files are independent corpora.  Optional, duck-typed
    # like the other hooks; transitions are rare (once per corpus member),
    # so the early superstep flush they force costs nothing measurable.
    boundary_hook = getattr(job, "on_input_boundary", None)
    # Resume restores which corpus member the snapshot's last batch came
    # from, so a snapshot saved at a file seam still triggers the boundary
    # hook on the next file's first batch (advisor round 2: last_file=None
    # after resume silently skipped the reset and leaked grep's line carry).
    last_file: Optional[int] = resumed_file
    # Prefetch: host-side chunking of step N+1 overlaps device compute of
    # step N (the double-buffering of SURVEY §7 step 4).  The manual
    # iterator lets read_wait be timed: time spent HERE is the reader
    # failing to keep ahead of the device.
    it = iter(reader_mod.prefetch(
        reader_mod.iter_batches_multi(path, engine.n_devices,
                                      config.chunk_bytes,
                                      start_offset=start_offset,
                                      start_step=start_step,
                                      end_offset=end_offset)))
    while True:
        with obs.span("read_wait", timer):
            batch = next(it, None)
        if batch is None:
            break
        if hooks.stage_arrival is not None:
            with obs.span("stage", timer):
                batch = hooks.stage_arrival(batch)
        if (boundary_hook is not None and last_file is not None
                and batch.file_index != last_file):
            if pending:
                state = flush(state, pending)
                pending = []
            state = boundary_hook(state)
        last_file = batch.file_index
        pending.append(batch)
        if len(pending) == k:
            state = flush(state, pending)
            pending = []
    for batch in pending:  # remainder: single steps (no extra jit cache keys)
        state = flush(state, [batch])
    return state, bytes_done, step_index


def _path_names(path) -> list[str]:
    """Input path(s) as a list of strings for the run-ledger header."""
    import os

    if isinstance(path, (str, bytes, os.PathLike)):
        return [os.fspath(path) if not isinstance(path, bytes)
                else path.decode(errors="backslashreplace")]
    return [_path_names(p)[0] for p in path]


def _metrics_word_count(value) -> int:
    """Total words inside any finalize result shape, for RunMetrics.

    Finalize results nest: sketch wrappers hold a ``.table`` that may itself
    be a :class:`TopKTable` (top-k + sketch compositions).  Unwrap until the
    CountTable appears; non-table jobs (grep, sample) report 0 here — their
    metrics live in their own result fields.
    """
    for _ in range(3):
        if isinstance(value, (SketchedState, FreqSketchedState, TopKTable)):
            value = value.table
        else:
            break
    return int(value.total_count()) \
        if isinstance(value, table_ops.CountTable) else 0


def run_job(job: MapReduceJob, path, config: Config = DEFAULT_CONFIG,
            mesh=None, merge_strategy: str = "tree",
            checkpoint_path: Optional[str] = None, checkpoint_every: int = 0,
            logger=None, progress_every: int = 50,
            byte_range: Optional[tuple[int, int]] = None,
            retry: int = 0, telemetry=None) -> RunResult:
    """Stream ``path`` through ``job`` over the mesh; see module docstring.

    ``telemetry`` (:class:`...obs.telemetry.Telemetry`, optional): per-step
    run-ledger records, flight-recorder forensics on failure, and metrics-
    registry counters for the run.  ``None`` disables all of it at zero
    per-step cost.  The caller owns the handle's lifetime (``tel.close()``
    flushes the ledger).

    ``retry``: retries per step group on a transient dispatch failure.  The
    device state is donated into each step, so with ``retry > 0`` the
    executor keeps a host-side leaf-copy of the known-good state from just
    before the dispatch (one extra device->host fetch per group — the cost
    of replayability) plus the still-alive host batches, rebuilds a fresh
    sharded state from the snapshot, and re-dispatches the same group.
    ``retry=0`` (default) surfaces the failure immediately with the resume
    cursor; checkpoint/resume is then the recovery path.

    ``byte_range``: restrict ingestion to ``[lo, hi)`` — this host's slice of
    a multi-host corpus (:func:`...parallel.distributed.host_byte_range`,
    pre-aligned with ``align_range_to_separator``).  The returned value is
    then this host's *partial* state, to be merged host-side
    (``table_ops.merge``) across hosts.  Note this per-host-driven mode uses
    a host-LOCAL mesh: run_job stages plain numpy batches, so a mesh spanning
    non-addressable devices is not supported here — for one global SPMD
    program over all hosts use :func:`run_job_global`.
    """
    if retry < 0:
        raise ValueError(f"retry must be >= 0, got {retry}")
    logger = logger or get_logger()
    tel = obs.maybe(telemetry)
    mesh = mesh if mesh is not None else data_mesh()
    # Shard over EVERY mesh axis: a 2-D ('replica','data') mesh contributes
    # all its devices to the data-parallel stream (the Engine linearizes the
    # axes row-major; hierarchical merge reduces innermost-first).
    axes = tuple(mesh.axis_names)
    n_dev = mesh.size  # == product over all axes, which we shard in full
    engine = Engine(job, mesh, axis=axes if len(axes) > 1 else axes[0],
                    merge_strategy=merge_strategy)
    range_lo, range_hi = byte_range if byte_range is not None else (0, None)

    timer = metrics_mod.PhaseTimer()
    timer.start("total")

    start_step, start_offset = 0, range_lo
    bases_list: list[np.ndarray] = []
    fingerprint = ckpt_mod.run_fingerprint(
        path, n_dev, config.chunk_bytes, backend=config.resolved_backend(),
        pallas_max_token=config.pallas_max_token, byte_range=byte_range,
        job_identity=job.identity()) \
        if checkpoint_path else None
    if checkpoint_path and ckpt_mod.exists(checkpoint_path):
        # An abstract state (shapes/dtypes only, no device allocation) is
        # the structural template: any drift in job kind, capacities,
        # sketch precision, or device count surfaces as CheckpointMismatch
        # (shapes are ground truth).
        template = jax.eval_shape(engine.init_states)
        state_np, start_step, start_offset, bases_arr, resumed_file = \
            ckpt_mod.load(checkpoint_path, template=template,
                          expect_fingerprint=fingerprint)
        state = jax.device_put(state_np, engine._sharded)
        bases_list = list(bases_arr)
        log_event(logger, "resumed from checkpoint", step=start_step, offset=start_offset)
    else:
        state = engine.init_states()
        resumed_file = None

    # Each batch is staged to the device the moment the reader hands it
    # over (stage_arrival): the async H2D overlaps the previous group's
    # compute, the phase decomposition attributes placement to "stage",
    # and superstep groups stack ALREADY-RESIDENT [D, C] arrays on device
    # — shipping one K-times-larger stacked host array at dispatch time
    # measured ~7x slower per byte through the relay tunnel (round 5).
    import jax.numpy as jnp

    # With retry > 0 the batches must stay HOST numpy: the replay contract
    # re-dispatches the still-alive host buffers with a FRESH H2D per
    # attempt — an arrival-staged device array could itself be the failed
    # (error-poisoned) object, making every retry re-raise.
    hooks = _StreamHooks(
        stage_single=lambda b: b.data,
        stage_group=(lambda g: np.stack([b.data for b in g], axis=1))
        if retry > 0 else
        (lambda g: jnp.stack([b.data for b in g], axis=1)),
        snapshot=lambda s: jax.tree.map(np.asarray, s),
        restage=lambda s_np: jax.device_put(s_np, engine._sharded),
        write_gate=lambda: True,
        retry=retry,
        stage_arrival=None if retry > 0 else (lambda b: dataclasses.replace(
            b, data=jax.device_put(b.data, engine.sharding))))
    tel.registry.counter("executor.runs", driver="run_job").inc()
    tel.ledger_write("run_start", driver="run_job", job=job.identity(),
                     devices=n_dev, chunk_bytes=config.chunk_bytes,
                     superstep=config.superstep,
                     backend=config.resolved_backend(),
                     merge_strategy=merge_strategy, input=_path_names(path),
                     resume_step=start_step, resume_offset=start_offset,
                     retry=retry)
    timer.start("stream")
    try:
        state, bytes_done, _ = _drive_stream(
            engine, job, config, path, state, hooks,
            start_step=start_step, start_offset=start_offset,
            end_offset=range_hi, bases_list=bases_list,
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, resumed_file=resumed_file,
            logger=logger, progress_every=progress_every, timer=timer,
            telemetry=tel)
        # Drain: under async dispatch the loop can run ahead of the device;
        # blocking here splits queued compute ("drain") from enqueue time
        # ("dispatch") and keeps the stream/reduce boundary honest.
        with obs.span("drain", timer):
            jax.block_until_ready(state)
        timer.stop("stream")

        with obs.span("reduce", timer):
            value = engine.finish(state)
            value = jax.tree.map(np.asarray, value)  # block + fetch the result
    except Exception as e:
        # Dispatch failures already dumped inside _drive_stream (with step
        # context); this catches everything else on the streaming path —
        # reader errors, drain/finish failures — so ANY crashed telemetered
        # run leaves forensics.  flight_dump is idempotent per run: the
        # first (most specific) dump wins.
        tel.flight_dump(context={"where": "run_job", "error": repr(e)})
        raise
    total_s = timer.stop("total")

    words = _metrics_word_count(value)
    # bytes_done is the absolute resume CURSOR (checkpoints store it); the
    # throughput metric counts only bytes this run actually streamed.
    m = metrics_mod.RunMetrics(bytes_processed=bytes_done - range_lo, words_counted=words,
                               elapsed_s=total_s, phases=dict(timer.phases))
    tel.ledger_write("run_end", **m.as_dict())
    log_event(logger, "run complete", **m.as_dict())
    bases = np.stack(bases_list) if bases_list else np.zeros((0, n_dev), np.int64)
    return RunResult(value=value, metrics=m, bases=bases)


def run_job_global(job: MapReduceJob, path, config: Config = DEFAULT_CONFIG,
                   mesh=None, merge_strategy: str = "tree",
                   checkpoint_path: Optional[str] = None,
                   checkpoint_every: int = 0,
                   logger=None, progress_every: int = 50,
                   telemetry=None) -> RunResult:
    """Multi-host mode (b) as one entry point: ONE global SPMD program over
    every chip of every process (VERDICT r3 #5; the 100 GB / v5e-256
    BASELINE config runs through this).

    Every process calls this with the same arguments after
    :func:`...parallel.distributed.initialize`.  Per process:

      * the mesh spans ALL processes' devices
        (:func:`...parallel.distributed.global_data_mesh` by default);
      * the reader runs identically everywhere (same deterministic chunk
        geometry — cut offsets must agree across processes), but each
        process STAGES only its own contiguous block of shard rows
        (``host_shards``) via ``device_put_local``, so no process ships
        another's data over DCN;
      * the engine step is the same jitted SPMD program on every process
        (multi-controller SPMD: identical programs, local data);
      * the collective ``finish`` replicates the merged result to every
        process — the returned ``RunResult`` is identical everywhere;
        report/print on :func:`...parallel.distributed.is_coordinator`.

    Checkpointing: the sharded state is fetched with one all-gather round
    (:meth:`Engine.replicate_to_host`) and ONLY the coordinator writes the
    snapshot (``checkpoint_path`` should be on storage the coordinator owns;
    resume requires every process to read it — shared filesystem, or
    distribute the file before relaunch).  Resume re-stages each process's
    own shard rows from the snapshot.  Step retry is not offered here: a
    failed collective leaves peer processes blocked mid-program, so the
    recovery path for global runs IS checkpoint/resume (SURVEY §5 failure
    detection: the jax.distributed heartbeat surfaces dead peers).

    Differences from :func:`run_job`: no ``byte_range`` (the global program
    consumes the whole corpus; per-host byte ranges are mode (a)), no
    ``retry``, and single-buffer convenience staging is replaced by
    ``device_put_local``.
    """
    from mapreduce_tpu.parallel import distributed as dist

    logger = logger or get_logger()
    tel = obs.maybe(telemetry)
    mesh = mesh if mesh is not None else dist.global_data_mesh()
    axes = tuple(mesh.axis_names)
    n_dev = mesh.size
    engine = Engine(job, mesh, axis=axes if len(axes) > 1 else axes[0],
                    merge_strategy=merge_strategy)
    mine = np.asarray(dist.host_shards(n_dev), dtype=np.int64)

    timer = metrics_mod.PhaseTimer()
    timer.start("total")

    start_step, start_offset = 0, 0
    bases_list: list[np.ndarray] = []
    fingerprint = ckpt_mod.run_fingerprint(
        path, n_dev, config.chunk_bytes, backend=config.resolved_backend(),
        pallas_max_token=config.pallas_max_token, byte_range=None,
        job_identity=job.identity()) if checkpoint_path else None

    def stage(host_rows: np.ndarray):
        """This process's rows -> one globally-sharded array."""
        return dist.device_put_local(host_rows, engine.sharding)

    if checkpoint_path and ckpt_mod.exists(checkpoint_path):
        template = jax.eval_shape(engine.init_states_global)
        state_np, start_step, start_offset, bases_arr, resumed_file = \
            ckpt_mod.load(checkpoint_path, template=template,
                          expect_fingerprint=fingerprint)
        state = jax.tree.map(lambda x: stage(np.asarray(x)[mine]), state_np)
        bases_list = list(bases_arr)
        log_event(logger, "resumed from checkpoint (global)",
                  step=start_step, offset=start_offset)
    else:
        state = engine.init_states_global()
        resumed_file = None

    hooks = _StreamHooks(
        stage_single=lambda b: stage(b.data[mine]),
        stage_group=lambda g: stage(np.stack([b.data[mine] for b in g],
                                             axis=1)),
        # The checkpoint fetch is a collective (one all-gather round makes
        # the sharded state addressable everywhere); only the coordinator
        # touches the filesystem.  No retry (see docstring).
        snapshot=engine.replicate_to_host,
        restage=None,
        write_gate=dist.is_coordinator,
        retry=0)
    tel.registry.counter("executor.runs", driver="run_job_global").inc()
    # The ledger rides the same gate as checkpoints: one file, written by
    # the coordinator (every process still advances its delta baselines).
    if dist.is_coordinator():
        tel.ledger_write("run_start", driver="run_job_global",
                         job=job.identity(), devices=n_dev,
                         chunk_bytes=config.chunk_bytes,
                         superstep=config.superstep,
                         backend=config.resolved_backend(),
                         merge_strategy=merge_strategy,
                         input=_path_names(path),
                         resume_step=start_step, resume_offset=start_offset)
    timer.start("stream")
    try:
        state, bytes_done, _ = _drive_stream(
            engine, job, config, path, state, hooks,
            start_step=start_step, start_offset=start_offset,
            end_offset=None, bases_list=bases_list,
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, resumed_file=resumed_file,
            logger=logger, progress_every=progress_every, timer=timer,
            telemetry=tel)
        with obs.span("drain", timer):
            jax.block_until_ready(state)
        timer.stop("stream")

        with obs.span("reduce", timer):
            value = engine.finish(state)  # replicated: addressable everywhere
            value = jax.tree.map(np.asarray, value)
    except Exception as e:
        if dist.is_coordinator():  # same gate as every other ledger artifact
            tel.flight_dump(context={"where": "run_job_global",
                                     "error": repr(e)})
        raise
    total_s = timer.stop("total")

    words = _metrics_word_count(value)
    m = metrics_mod.RunMetrics(bytes_processed=bytes_done, words_counted=words,
                               elapsed_s=total_s, phases=dict(timer.phases))
    if dist.is_coordinator():
        tel.ledger_write("run_end", **m.as_dict())
    log_event(logger, "global run complete", **m.as_dict())
    bases = np.stack(bases_list) if bases_list else np.zeros((0, n_dev), np.int64)
    return RunResult(value=value, metrics=m, bases=bases)


def absolute_offsets(chunk_id: np.ndarray, pos: np.ndarray,
                     bases: np.ndarray, n_devices: int) -> np.ndarray:
    """Decode (chunk_id = step * n_devices + device, per-chunk pos) into
    absolute corpus offsets via the recorded row bases — the single host-
    side owner of the Engine's chunk-id linearization (every job recovering
    source spans goes through this)."""
    step, dev = chunk_id // n_devices, chunk_id % n_devices
    return bases[step, dev] + pos


def recover_from_file(tbl: table_ops.CountTable, path, bases: np.ndarray,
                      n_devices: int, ngram: int = 1,
                      estimate_distinct: bool = True) -> WordCountResult:
    """Host-side string recovery for a streamed run.

    ``pos_hi`` encodes chunk_id = step * n_devices + device; its absolute file
    base is ``bases[step, device]``.  Entries are reported in file order
    (first occurrence), the reference's insertion order (main.cu:212-215).

    Entries whose length is ``SEAM_GRAM_LENGTH`` are cross-chunk grams: the
    device knew the start but not the end (it lies in a later chunk), so the
    span length is recovered here by scanning ``ngram`` tokens forward.
    """
    count = np.asarray(tbl.count).astype(np.int64)
    count_hi = np.asarray(tbl.count_hi).astype(np.int64)
    valid = (count > 0) | (count_hi > 0)
    chunk_id = np.asarray(tbl.pos_hi)[valid].astype(np.int64)
    pos = np.asarray(tbl.pos_lo)[valid].astype(np.int64)
    length = np.asarray(tbl.length)[valid].astype(np.int64)
    cnt = (count + (count_hi << np.int64(32)))[valid]
    absolute = absolute_offsets(chunk_id, pos, bases, n_devices)
    seam = np.flatnonzero(length == int(constants.SEAM_GRAM_LENGTH))
    if len(seam):
        # Row bases mark force-split entry ends (the reader cuts separator-
        # free runs there); one batch call maps each touched file once.
        length[seam] = reader_mod.scan_gram_lengths(
            path, absolute[seam], ngram, cut_offsets=bases.ravel())
    order = np.argsort(absolute, kind="stable")
    spans = [(int(absolute[i]), int(length[i])) for i in order]
    words = reader_mod.read_words_at_multi(path, spans)
    dropped_uniques, dropped_count = tbl.dropped_totals()
    return WordCountResult(
        words=words,
        counts=[int(c) for c in cnt[order]],
        total=int(np.asarray(tbl.total_count())),
        distinct=_reported_distinct(tbl, len(words), dropped_uniques,
                                    estimate_distinct),
        dropped_uniques=dropped_uniques,
        dropped_count=dropped_count,
    )


def count_file(path, config: Config = DEFAULT_CONFIG, mesh=None,
               top_k: Optional[int] = None, distinct_sketch: bool = False,
               count_sketch: bool = False, ngram: int = 1, **kw) -> WordCountResult:
    """WordCount over a file via the streaming sharded pipeline.

    ``distinct_sketch`` composes a HyperLogLog over the run, populating
    ``result.distinct_estimate`` — accurate (~0.8%) even when distinct words
    spill past table capacity.  Sketched runs checkpoint like plain ones
    (snapshots hold the whole state pytree); resuming a checkpoint across
    sketched/unsketched configurations raises CheckpointMismatch.

    ``count_sketch`` composes a Count-Min sketch instead, populating
    ``result.cms`` so ``result.estimate_count(word)`` answers frequency
    queries for any word — including ones the exact table spilled.  The two
    sketches are mutually exclusive per run (their states checkpoint
    differently); pick the one matching the question being asked.

    ``ngram > 1`` counts n-token grams instead of single words — exactly,
    including grams spanning chunk seams (the seam-carry machinery of
    :class:`...models.wordcount.NGramCountJob`); streamed results match
    single-buffer runs bit-for-bit.
    """
    if distinct_sketch and count_sketch:
        raise ValueError("distinct_sketch and count_sketch are mutually "
                         "exclusive per run; run twice to get both")
    mesh = mesh if mesh is not None else data_mesh()
    if ngram > 1:
        job = NGramCountJob(ngram, config, top_k=top_k or None)
    else:
        job = TopKWordCountJob(top_k, config) if top_k else WordCountJob(config)
    if distinct_sketch:
        job = SketchedWordCountJob(job)
    elif count_sketch:
        job = FreqSketchedWordCountJob(job)
    rr = run_job(job, path, config=config, mesh=mesh, **kw)
    n_dev = mesh.size
    value, registers, cms = rr.value, None, None
    if isinstance(value, SketchedState):
        value, registers = value.table, value.registers
    elif isinstance(value, FreqSketchedState):
        value, cms = value.table, np.asarray(value.cms)
    # Top-k finalize reorders the table on device, destroying the KMV
    # property — but it snapshots the estimator's scalars first
    # (TopKTable), so spilled top-k runs still get the tight distinct
    # estimate instead of the summed upper bound.
    kmv_est = None
    if isinstance(value, TopKTable):
        kmv_est = table_ops.kmv_from_snapshot(
            int(value.kmv_n_valid), int(value.kmv_kth_hi),
            int(value.kmv_kth_lo), config.table_capacity)
        value = value.table
    result = recover_from_file(value, path, rr.bases, n_dev, ngram=ngram,
                               estimate_distinct=not top_k)
    if kmv_est is not None:
        result = dataclasses.replace(
            result, distinct=max(len(result.words), int(round(kmv_est))))
    if registers is not None:
        from mapreduce_tpu.ops import sketch as sketch_ops

        result = dataclasses.replace(
            result, distinct_estimate=sketch_ops.estimate(registers))
    if cms is not None:
        result = dataclasses.replace(result, cms=cms)
    if top_k:
        result = apply_top_k(result, top_k)
    return result
