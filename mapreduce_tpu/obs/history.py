#!/usr/bin/env python3
"""Run-history warehouse: ingest many run ledgers into one queryable
longitudinal index (ISSUE 14 tentpole, half 1).

Every obs surface before this one was post-hoc AND single-run: a ledger
had to exist and be complete, and the ``combiner='auto'`` resolver, the
``geometry='auto'`` resolver, and ``tuning.derive_signals`` each read
exactly one file.  The system had no memory across runs — yet ROADMAP
item 2 needs a billing/audit archive and per-tenant warm-start priors.
This module is that memory:

* **ingest** a directory / glob / list of append-mode ledgers (any
  ledger version v2..v8; unknown kinds/fields skip — the forward-compat
  contract), per-host shard files merged through the existing
  ``obs/fleet.py`` path, run-INSTANCE-aware exactly like ``fleet`` /
  ``obs_report`` (a crash+relaunch appending a second run under one
  run_id never fuses with its crashed attempt);
* write a small on-disk index — ``<dir>/history.json`` (one compact row
  per run instance, grouped under its **config key**) plus one full
  per-run digest under ``<dir>/runs/<id>.json`` — deterministic and
  byte-stable across re-ingests of the same files;
* answer **longitudinal queries**: throughput (GB/s) series, phase-share
  series, trailing verdict streaks, per config key;
* classify **drift** with the same rule-table discipline as
  ``datahealth``: machine verdicts ``regressing`` / ``improving`` /
  ``steady`` / ``config-drift`` (+ ``no-history`` for a group too young
  to judge), each flag carrying the measured numbers;
* expose :func:`resolve_prior` — THE one place "what did runs like this
  one do before" is answered.  ``combiner='auto'``, ``geometry='auto'``
  and ``tuning.derive_signals`` all resolve through it now (bit-identical
  outcomes to the three hand-rolled latest-record reads it replaced);
  index-backed callers (the serving layer, bench drift rows) get the
  latest digest row + drift verdict for a config key.

The **config key** groups "runs like this one":
``family/backend/corpus/geometry/combiner/map_impl`` where ``corpus`` is
a power-of-two size bucket plus the chunk geometry
(:func:`corpus_bucket`).  Drift is judged inside the wider
``family/backend/corpus`` **group**: a stamp change (geometry, combiner,
map_impl) between consecutive runs of a group reads as ``config-drift``
— the series is not comparable and no throughput verdict should pretend
it is.

Deliberately jax-free and stdlib-only (the ``obs/timeline.py``
contract): runnable as a script on a box with neither jax nor the
package installed — sibling modules load by file path.  ``--selftest``
runs the checked-in fixtures against hand arithmetic; it is wired into
``tools/tier1.sh`` and ``tools/smoke.sh``.

Usage::

    python mapreduce_tpu/obs/history.py --index DIR LEDGER...   # ingest
    python mapreduce_tpu/obs/history.py --index DIR             # report
    python mapreduce_tpu/obs/history.py --index DIR --drift     # verdicts
    python mapreduce_tpu/obs/history.py --index DIR --series gb_per_s \
        --key wordcount/pallas/b28-c4194304/default/off/split
    python mapreduce_tpu/obs/history.py --selftest
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import hashlib
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Tuple

if __package__:
    from mapreduce_tpu.obs import datahealth, timeline
    from mapreduce_tpu.obs import fleet as fleet_mod
    from mapreduce_tpu.obs import ledger as ledger_mod
else:  # script / by-path execution: load the jax-free siblings by path
    import importlib.util

    def _load_sibling(name: str):
        p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         name + ".py")
        spec = importlib.util.spec_from_file_location(
            f"_mapreduce_tpu_history_{name}", p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    timeline = _load_sibling("timeline")
    datahealth = _load_sibling("datahealth")
    ledger_mod = _load_sibling("ledger")
    fleet_mod = _load_sibling("fleet")

#: Bumped when the index/digest schema changes shape.
HISTORY_VERSION = 1

#: |delta| of the latest run's GB/s vs the same-key baseline median that
#: makes a series ``regressing``/``improving`` (below it: ``steady`` —
#: run-to-run weather, not a trend worth a verdict).
DRIFT_FRAC = 0.10
#: How many prior same-key runs feed the baseline median.
DRIFT_WINDOW = 5

#: The streaming phases whose shares the digest keeps (the obs_report
#: bound-classification set — end-of-stream tails and reduce time the
#: stream END, not the steady state).
_STREAMING_PHASES = ("read_wait", "stage", "dispatch", "retire_wait")

#: Config stamps that participate in the config key beyond the group
#: (family/backend/corpus).  A change in any of them between consecutive
#: group runs is ``config-drift``.
_KEY_STAMPS = ("geometry", "combiner", "map_impl")


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def read_jsonl(path: str) -> List[dict]:
    """One ledger file through the one tolerant reader (unparseable
    lines are crash forensics, not errors), dict records only."""
    return [r for r in ledger_mod.read_ledger(path) if isinstance(r, dict)]


# -- run-instance splitting ---------------------------------------------------

def split_instances(records: Iterable[dict]) \
        -> List[Tuple[Optional[str], int, List[dict]]]:
    """An append-mode record stream -> ``[(run_id, instance, records)]``
    in first-appearance order.  Every ``run_start`` opens a NEW
    instance, so a crash+relaunch appending a second run under a shared
    run_id never fuses with its crashed attempt.  Delegates to the ONE
    canonical splitter in ``obs/fleet.py`` (the rule fleet shard
    selection, ``obs_report`` and ``obswatch`` all share)."""
    return fleet_mod.split_instances(records)


# -- resolve_prior: the one prior-run read ------------------------------------

def run_view(records: Iterable[dict],
             run_id: Optional[str] = None) -> dict:
    """One run's view of a record stream — the selection
    ``tuning.derive_signals`` used to hand-roll: the chosen run_id (the
    first stamped record's when not given), every record carrying it,
    and — on a merged fleet stream (a synthesized ``fleet`` record
    present) — the records anchored on ONE host (the coordinator when
    present), because reconstructing a timeline from every host's
    records fuses the lanes into a chimera no host ran."""
    records = [r for r in records if isinstance(r, dict)]
    chosen = run_id
    if chosen is None:
        for r in records:
            if r.get("run_id") is not None:
                chosen = r.get("run_id")
                break
    recs = [r for r in records if r.get("run_id") == chosen]
    fleet = next((r for r in recs if r.get("kind") == "fleet"), None)
    if fleet is not None:
        stamped = sorted({r.get("host") for r in recs
                          if isinstance(r.get("host"), int)
                          and not isinstance(r.get("host"), bool)})
        if stamped:
            anchor = 0 if 0 in stamped else stamped[0]
            recs = [r for r in recs if r.get("host") in (anchor, None)]
    return {"run_id": chosen, "run_records": recs, "fleet": fleet}


def freshest_profile_geometry(profile_path: str, family: str = "wordcount",
                              presets=None, geometry_ok=None):
    """The geometry a searched ``tuned.json`` profile warm-starts
    (the ``geometry='auto'`` read, ISSUE 12): the freshest profile for
    ``family`` whose config carries a non-default geometry — its preset
    label (must be in ``presets`` when given) or spec dict (must pass
    ``geometry_ok`` when given).  No profile / no entry / unreadable
    file resolves to ``'default'`` — the degrade-to-off contract."""
    try:
        with open(profile_path, encoding="utf-8") as f:
            profiles = json.load(f).get("profiles", {})
    except (OSError, ValueError):
        return "default"
    mine = {key: entry for key, entry in profiles.items()
            if isinstance(entry, dict) and key.startswith(family)}
    for _key, entry in sorted(mine.items(),
                              key=lambda kv: kv[1].get("recorded_at") or "",
                              reverse=True):
        geom = (entry.get("config") or {}).get("geometry")
        if geom in (None, "default"):
            continue
        if isinstance(geom, str) and (presets is None or geom in presets):
            return geom
        if isinstance(geom, dict) and (geometry_ok is None
                                       or geometry_ok(geom)):
            return geom
    return "default"


def freshest_profile_merge_strategy(profile_path: str,
                                    mesh_label: Optional[str] = None,
                                    allowed=None,
                                    family: str = "wordcount-redplan"):
    """The merge strategy a planned ``tuned.json`` profile warm-starts
    (the ``merge_strategy='auto'`` read, ISSUE 20): the freshest
    ``tools/redplan.py --out`` winner — keys
    ``wordcount-redplan/static/<mesh-label>-cap<capacity>`` — whose
    planned mesh geometry matches.  ``mesh_label`` (a
    ``meshcost.MeshSpec.label()`` like ``'2dx4i'``) pins the exact
    geometry; ``allowed`` filters to strategies the RUNTIME mesh can
    execute (a ``hier-*`` winner planned over a 2-D fleet mesh is
    invalid on a 1-D runtime mesh, so a 1-D caller passes the
    single-axis set).  Returns ``(strategy, profile_key)``;
    ``(None, None)`` when nothing matches — the caller owns the loud
    fallback to ``'tree'``, so "no prior" stays distinguishable from
    "the planner picked tree"."""
    try:
        with open(profile_path, encoding="utf-8") as f:
            profiles = json.load(f).get("profiles", {})
    except (OSError, ValueError):
        return None, None
    mine = {key: entry for key, entry in profiles.items()
            if isinstance(entry, dict) and key.startswith(family)}
    for key, entry in sorted(mine.items(),
                             key=lambda kv: kv[1].get("recorded_at") or "",
                             reverse=True):
        label = (entry.get("mesh") or {}).get("label")
        if mesh_label is not None and label != mesh_label:
            continue
        strategy = (entry.get("config") or {}).get("merge_strategy")
        if not isinstance(strategy, str) or strategy == "auto":
            continue
        if allowed is not None and strategy not in allowed:
            continue
        return strategy, key
    return None, None


def resolve_prior(*, records: Optional[Iterable[dict]] = None,
                  run_id: Optional[str] = None,
                  profile_path: Optional[str] = None,
                  family: str = "wordcount",
                  presets=None, geometry_ok=None,
                  mesh_label: Optional[str] = None,
                  merge_allowed=None,
                  index_dir: Optional[str] = None,
                  config_key: Optional[str] = None,
                  group: Optional[str] = None) -> dict:
    """What did runs like this one do before — the ONE prior-run read
    (ISSUE 14).  Three sources, any subset:

    * ``records`` (an append-mode ledger's records): the latest ``data``
      record and the combiner mode it resolves (exactly the old
      ``datahealth.resolve_combiner`` semantics: skew-hot -> hot-cache,
      anything else -> off), plus the single-run view
      (:func:`run_view`) ``derive_signals`` consumes;
    * ``profile_path`` (a searched ``tuned.json``): the geometry it
      warm-starts (exactly the old ``analysis.geometry.resolve_auto``
      semantics — pass ``presets``/``geometry_ok`` for validation),
      plus the merge strategy the static reduction planner's freshest
      profile warm-starts (ISSUE 20: ``mesh_label`` pins the planned
      mesh geometry, ``merge_allowed`` restricts to strategies the
      runtime mesh can execute; no match resolves to ``'tree'`` with
      ``merge_strategy_profile=None``, so callers can announce the
      fallback loudly);
    * ``index_dir`` (+ ``config_key`` or ``group``): the warehouse
      prior — the latest matching index row and the group's drift
      verdict (the serving layer's warm-start / billing read).

    Returns ``{combiner, geometry, run_id, run_records, fleet,
    data_record, data_health, history}`` with every unrequested source's
    keys at their neutral value — absence of a prior is itself
    information, never an error."""
    out: dict = {"combiner": "off", "geometry": "default",
                 "merge_strategy": "tree", "merge_strategy_profile": None,
                 "run_id": run_id, "run_records": [], "fleet": None,
                 "data_record": None, "data_health": None, "history": None}
    if records is not None:
        records = [r for r in records if isinstance(r, dict)]
        out.update(run_view(records, run_id))
        rec = datahealth.latest_data_record(records)
        out["data_record"] = rec
        if rec is not None:
            out["data_health"] = datahealth.classify(rec)
            if out["data_health"]["verdict"] == "skew-hot":
                out["combiner"] = "hot-cache"
    if profile_path is not None:
        out["geometry"] = freshest_profile_geometry(
            profile_path, family, presets=presets, geometry_ok=geometry_ok)
        strategy, key = freshest_profile_merge_strategy(
            profile_path, mesh_label=mesh_label, allowed=merge_allowed)
        if strategy is not None:
            out["merge_strategy"] = strategy
            out["merge_strategy_profile"] = key
    if index_dir is not None:
        index = read_index(index_dir)
        if index is not None:
            rows = rows_for(index, key=config_key, group=group)
            out["history"] = {
                "rows": len(rows),
                "latest": rows[-1] if rows else None,
                "drift": classify_drift(
                    group_rows(index, rows[-1]["group"]) if rows
                    else []),
            }
    return out


# -- per-run digests ----------------------------------------------------------

def corpus_bucket(n_bytes, chunk_bytes=None) -> str:
    """The corpus-shape key component: a power-of-two size bucket
    (``b<k>``: 2^(k-1) < bytes <= 2^k) + the chunk geometry.  Runs "of
    the same shape" must share a bucket for their series to be
    comparable; exact byte counts would shatter every series."""
    n = _num(n_bytes)
    size = f"b{int(n - 1).bit_length()}" if n and n > 0 else "b0"
    c = _num(chunk_bytes)
    return f"{size}-c{int(c)}" if c else f"{size}-c?"


def _geometry_label(geom) -> str:
    """The compact geometry stamp for keying: a label string as-is, a
    spec dict as 'custom', absence as 'default' (pre-v6 ledgers)."""
    if isinstance(geom, str) and geom:
        return geom
    if isinstance(geom, dict):
        return "custom"
    return "default"


def digest_run(recs: List[dict], *, source: str, run_id,
               instance: int, fleet_view: Optional[dict] = None) -> dict:
    """One run instance's records -> the full digest the warehouse
    stores: identity + config stamps, outcome, throughput, phase shares,
    the timeline ``bottleneck``, the data-health classification, window
    stats, the last heartbeat (crashed/in-flight runs keep their cursor,
    ledger v8), and fleet verdicts on sharded runs."""
    view = run_view(recs, run_id)
    recs = view["run_records"]
    start = next((r for r in recs if r.get("kind") == "run_start"), None)
    end = next((r for r in recs if r.get("kind") == "run_end"), None)
    failures = [r for r in recs if r.get("kind") == "failure"]
    # The one completed/crashed/in-flight rule (fleet.run_status),
    # stored as the two booleans the index rows filter on.
    status = fleet_mod.run_status(end is not None, len(failures))
    steps = [r for r in recs if r.get("kind") == "step"]
    progress = [r for r in recs if r.get("kind") == "progress"]
    ts = _num((start or {}).get("ts"))
    if ts is None:
        ts = next((_num(r.get("ts")) for r in recs
                   if _num(r.get("ts")) is not None), 0.0)

    phases: dict = {}
    if end and isinstance(end.get("phases"), dict):
        phases = {k: v for k, v in end["phases"].items()
                  if _num(v) is not None}
    else:  # crashed run: fold the step deltas that DID land
        for r in steps:
            for k, v in (r.get("phases") or {}).items():
                if _num(v) is not None:
                    phases[k] = phases.get(k, 0.0) + float(v)
    stream_total = sum(phases.get(k, 0.0) for k in _STREAMING_PHASES)
    shares = {k: round(phases[k] / stream_total, 4)
              for k in _STREAMING_PHASES
              if phases.get(k) and stream_total > 0}

    bytes_done = _num((end or {}).get("bytes"))
    if bytes_done is None:
        cursors = [_num(r.get("cursor_bytes")) for r in steps + progress]
        cursors = [c for c in cursors if c is not None]
        bytes_done = max(cursors) if cursors else None
    # `or None`: run_end rounds gb_per_s coarsely enough that a slow CPU
    # smoke run reads 0.0 — recompute from bytes/elapsed rather than let
    # a rounded zero pollute the drift baselines.
    gb_per_s = _num((end or {}).get("gb_per_s")) or None
    if gb_per_s is None:
        el = _num((end or {}).get("elapsed_s"))
        if bytes_done and el:
            gb_per_s = round(bytes_done / 1e9 / el, 9)

    art = timeline.reconstruct(recs, run_id=view["run_id"])
    bottleneck = None
    if art is not None:
        bn = art["bottleneck"]
        span = _num(bn.get("span_s"))
        saving = _num(bn.get("projected_saving_s"))
        bottleneck = {"resource": bn.get("resource"),
                      "projected_saving_s": saving,
                      "saving_frac": round(saving / span, 4)
                      if span and saving is not None else None}
    health = datahealth.classify_run(recs, run_id=view["run_id"])

    pipeline = (end or {}).get("pipeline") \
        if isinstance((end or {}).get("pipeline"), dict) else None
    tune = next((r for r in recs if r.get("kind") == "tune"), None)
    fleet_rec = view["fleet"]
    fleet_bn = None
    if fleet_view is not None:
        fleet_bn = (fleet_view.get("fleet_bottleneck") or {}).get("verdict")
    elif fleet_rec is not None:
        fleet_bn = (fleet_rec.get("fleet_bottleneck") or {}).get("verdict")

    last_progress = None
    if progress:
        p = progress[-1]
        last_progress = {k: p.get(k) for k in
                         ("cursor_bytes", "total_bytes", "frac",
                          "gb_per_s", "eta_s", "inflight_depth",
                          "groups_retired")
                         if p.get(k) is not None}

    digest = {
        "history_version": HISTORY_VERSION,
        "source": os.path.basename(source),
        "run_id": run_id,
        "instance": int(instance),
        "ts": round(ts, 6),
        "family": (start or {}).get("job"),
        "driver": (start or {}).get("driver"),
        "backend": (start or {}).get("backend"),
        "devices": (start or {}).get("devices"),
        "chunk_bytes": (start or {}).get("chunk_bytes"),
        "superstep": (start or {}).get("superstep"),
        "map_impl": (start or {}).get("map_impl") or "split",
        "combiner": (start or {}).get("combiner") or "off",
        "geometry": _geometry_label((start or {}).get("geometry")),
        "ledger_version": (start or {}).get("ledger_version"),
        "processes": (start or {}).get("processes"),
        "completed": status == "completed",
        "crashed": status == "crashed",
        "failures": len(failures),
        "steps": sum(int(_num(r.get("steps")) or 1) for r in steps),
        "bytes": int(bytes_done) if bytes_done is not None else None,
        "wall_s": _num((end or {}).get("elapsed_s")),
        "gb_per_s": gb_per_s,
        "phases": {k: round(v, 4) for k, v in sorted(phases.items())},
        "phase_shares": shares,
        "bottleneck": bottleneck,
        "data_verdict": (health or {}).get("verdict"),
        "data_signals": (health or {}).get("signals"),
        "pipeline": {k: pipeline.get(k) for k in
                     ("inflight_groups", "prefetch_depth", "depth_max",
                      "full_frac", "overlap_fraction")} if pipeline else None,
        "tune_rule": (tune or {}).get("rule"),
        "fleet_bottleneck": fleet_bn,
        "progress": last_progress,
    }
    digest["id"] = _digest_id(digest)
    digest["key"] = config_key(digest)
    digest["group"] = group_key(digest)
    return digest


def _digest_id(digest: dict) -> str:
    """Deterministic identity of one ingested run instance: same source
    file + run instance -> same id on every re-ingest (the byte-stable
    dedupe anchor)."""
    ident = [digest.get("source"), digest.get("run_id"),
             digest.get("instance"), digest.get("ts")]
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()[:16]


def config_key(digest: dict) -> str:
    """``family/backend/corpus/geometry/combiner/map_impl`` — the "runs
    like this one" key longitudinal series live under."""
    return "/".join([
        str(digest.get("family") or "?"),
        str(digest.get("backend") or "?"),
        corpus_bucket(digest.get("bytes"), digest.get("chunk_bytes")),
        str(digest.get("geometry") or "default"),
        str(digest.get("combiner") or "off"),
        str(digest.get("map_impl") or "split"),
    ])


def group_key(digest: dict) -> str:
    """``family/backend/corpus`` — the drift-comparison group (stamp
    changes inside it read as config-drift, not as a trend)."""
    return "/".join(config_key(digest).split("/")[:3])


# -- ingest + the on-disk index ----------------------------------------------

def expand_sources(sources: Iterable[str]) -> List[str]:
    """Files, directories and globs -> main ledger paths, sorted and
    deduplicated.  Shard files (``*.h<p>.jsonl``) are folded under their
    main ledger (which need not exist — shard-only fleets still ingest);
    non-jsonl files are skipped."""
    out = set()
    for src in sources:
        if os.path.isdir(src):
            paths = glob_mod.glob(os.path.join(glob_mod.escape(src),
                                               "*.jsonl"))
        else:
            paths = glob_mod.glob(src) or [src]
        for p in paths:
            m = fleet_mod._SHARD_RE.search(p)
            out.add(p[:m.start()] if m else p)
    return sorted(out)


def ledger_runs(path: str):
    """One main ledger path -> ``([(run_id, instance, records)], by_host)``.
    Shards next to the path merge through the existing ``obs/fleet.py``
    machinery; a shard-only fleet (no main file) ingests its merged
    stream instead."""
    records = read_jsonl(path) if os.path.exists(path) else []
    shard = fleet_mod.shard_paths(path)
    by_host = {h: read_jsonl(p) for h, p in shard.items()} if shard else {}
    runs = split_instances(records)
    if not runs and by_host:
        runs = split_instances(fleet_mod.merged_records(by_host))
    return runs, by_host


def index_row(digest: dict) -> dict:
    """The compact per-run row ``history.json`` keeps (the full digest
    lives in ``runs/<id>.json``)."""
    row = {k: digest.get(k) for k in
           ("id", "source", "run_id", "instance", "ts", "key", "group",
            "family", "backend", "chunk_bytes", "geometry", "combiner",
            "map_impl", "completed", "crashed", "bytes", "gb_per_s",
            "data_verdict", "fleet_bottleneck")}
    row["bottleneck"] = (digest.get("bottleneck") or {}).get("resource")
    return row


def ingest(sources: Iterable[str], index_dir: str) -> dict:
    """Ingest ledgers into the warehouse at ``index_dir`` and return the
    updated index.  Idempotent and byte-stable: the digest id is a pure
    function of (source basename, run_id, instance, start ts), rows
    merge by id, and both files serialize with sorted keys — re-ingesting
    the same ledgers rewrites identical bytes."""
    index = read_index(index_dir) or {"history_version": HISTORY_VERSION,
                                      "runs": {}, "keys": {}}
    runs_dir = os.path.join(index_dir, "runs")
    os.makedirs(runs_dir, exist_ok=True)
    for path in expand_sources(sources):
        runs, by_host = ledger_runs(path)
        for rid, instance, recs in runs:
            fview = None
            if by_host:
                try:
                    fview = fleet_mod.fleet_view(by_host, rid)
                except Exception:
                    fview = None  # a broken shard must not block ingest
            digest = digest_run(recs, source=path, run_id=rid,
                                instance=instance, fleet_view=fview)
            dpath = os.path.join(runs_dir, digest["id"] + ".json")
            body = json.dumps(digest, sort_keys=True, indent=1) + "\n"
            if not os.path.exists(dpath) \
                    or open(dpath, encoding="utf-8").read() != body:
                with open(dpath, "w", encoding="utf-8") as f:
                    f.write(body)
            index["runs"][digest["id"]] = index_row(digest)
    index["keys"] = _rebuild_keys(index["runs"])
    write_index(index_dir, index)
    return index


def _row_order(row: dict):
    return (row.get("ts") or 0.0, str(row.get("run_id")),
            row.get("instance") or 0, row.get("id"))


def _rebuild_keys(rows: dict) -> dict:
    keys: Dict[str, List[str]] = {}
    for rid in sorted(rows, key=lambda i: _row_order(rows[i])):
        keys.setdefault(rows[rid]["key"], []).append(rid)
    return keys


def index_path(index_dir: str) -> str:
    return os.path.join(index_dir, "history.json")


def read_index(index_dir: str) -> Optional[dict]:
    try:
        with open(index_path(index_dir), encoding="utf-8") as f:
            index = json.load(f)
    except (OSError, ValueError):
        return None
    return index if isinstance(index, dict) else None


def write_index(index_dir: str, index: dict) -> str:
    os.makedirs(index_dir, exist_ok=True)
    p = index_path(index_dir)
    with open(p, "w", encoding="utf-8") as f:
        f.write(json.dumps(index, sort_keys=True, indent=1) + "\n")
    return p


def read_digest(index_dir: str, digest_id: str) -> Optional[dict]:
    try:
        with open(os.path.join(index_dir, "runs", digest_id + ".json"),
                  encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# -- longitudinal queries -----------------------------------------------------

def rows_for(index: dict, key: Optional[str] = None,
             group: Optional[str] = None) -> List[dict]:
    """The compact rows under one config key (exact) or one drift group
    (prefix), in time order."""
    rows = index.get("runs", {})
    if key is not None:
        ids = index.get("keys", {}).get(key, [])
        return [rows[i] for i in ids if i in rows]
    out = [r for r in rows.values()
           if group is None or r.get("group") == group]
    return sorted(out, key=_row_order)


def group_rows(index: dict, group: str) -> List[dict]:
    return rows_for(index, group=group)


def series(index: dict, key: str, metric: str = "gb_per_s") -> List[list]:
    """``[(ts, value)]`` for one metric under one config key — the
    longitudinal throughput/size series.  None values skip (a crashed
    run has no GB/s; its absence is visible in the row count)."""
    return [[row.get("ts"), row.get(metric)]
            for row in rows_for(index, key=key)
            if row.get(metric) is not None]


def phase_share_series(index_dir: str, index: dict, key: str,
                       phase: str) -> List[list]:
    """``[(ts, share)]`` of one streaming phase under one config key —
    read from the full digests (shares are not in the compact rows)."""
    out = []
    for row in rows_for(index, key=key):
        d = read_digest(index_dir, row["id"]) or {}
        v = (d.get("phase_shares") or {}).get(phase)
        if v is not None:
            out.append([row.get("ts"), v])
    return out


def verdict_streak(index: dict, key: str,
                   field: str = "data_verdict") -> dict:
    """The trailing run of identical verdicts under one config key —
    ``{value, length, runs}`` (a skew-hot streak of 4 is a corpus fact;
    a streak of 1 after 3 cleans is weather)."""
    rows = rows_for(index, key=key)
    vals = [r.get(field) for r in rows]
    streak = 0
    for v in reversed(vals):
        if not vals or v != vals[-1]:
            break
        streak += 1
    return {"value": vals[-1] if vals else None, "length": streak,
            "runs": len(vals)}


# -- the drift classifier -----------------------------------------------------

def classify_drift(rows: List[dict]) -> dict:
    """Time-ordered rows of ONE drift group -> ``{verdict, flags,
    signals}`` (the ``datahealth`` rule-table discipline):

    ==============  ========================================================
    verdict         rule (first match wins)
    ==============  ========================================================
    no-history      fewer than 2 runs in the group — nothing to compare
    config-drift    the latest run's config key differs from the previous
                    run's (geometry/combiner/map_impl/chunk stamp moved):
                    the series is not comparable across the boundary
    regressing      latest GB/s < (1 - DRIFT_FRAC) x the median of up to
                    DRIFT_WINDOW prior same-key runs
    improving       latest GB/s > (1 + DRIFT_FRAC) x that baseline median
    steady          neither side clears DRIFT_FRAC (or throughput is
                    missing on either side — absence is not a trend)
    ==============  ========================================================

    Every flag carries the measured numbers, so downstream readers
    (benchwatch rows, the serving layer) read arithmetic, not
    adjectives."""
    rows = sorted(rows, key=_row_order)
    flags: List[dict] = []
    signals: dict = {"runs": len(rows)}

    def done(verdict):
        return {"verdict": verdict, "flags": flags, "signals": signals}

    if len(rows) < 2:
        return done("no-history")
    latest, prev = rows[-1], rows[-2]
    signals["latest_run_id"] = latest.get("run_id")
    signals["latest_key"] = latest.get("key")
    if latest.get("key") != prev.get("key"):
        # Rows come from ONE group (family/backend/corpus pinned by the
        # group key, chunk geometry included in the corpus bucket), so a
        # key change can only be one of the _KEY_STAMPS moving.
        moved = [s for s in _KEY_STAMPS
                 if latest.get(s) != prev.get(s)]
        signals["previous_key"] = prev.get("key")
        flags.append({
            "flag": "config-drift",
            "detail": (f"config moved between the last two runs "
                       f"({', '.join(moved)}): "
                       f"{prev.get('key')} -> {latest.get('key')} — "
                       "the throughput series is not comparable across "
                       "this boundary; judge drift after the new key "
                       "accumulates runs")})
        return done("config-drift")
    base_rows = [r for r in rows[:-1]
                 if r.get("key") == latest.get("key")][-DRIFT_WINDOW:]
    baseline = _median([r.get("gb_per_s") for r in base_rows
                        if _num(r.get("gb_per_s")) is not None])
    latest_gbps = _num(latest.get("gb_per_s"))
    signals["baseline_gbps"] = baseline
    signals["latest_gbps"] = latest_gbps
    signals["window"] = len(base_rows)
    if baseline is None or latest_gbps is None or baseline <= 0:
        return done("steady")
    delta = (latest_gbps - baseline) / baseline
    signals["delta_frac"] = round(delta, 4)
    if delta < -DRIFT_FRAC:
        flags.append({
            "flag": "regressing",
            "detail": (f"latest run {latest.get('run_id')} measured "
                       f"{latest_gbps:.4f} GB/s, {abs(delta):.0%} below "
                       f"the {len(base_rows)}-run baseline median "
                       f"{baseline:.4f} GB/s (gate {DRIFT_FRAC:.0%})")})
        return done("regressing")
    if delta > DRIFT_FRAC:
        flags.append({
            "flag": "improving",
            "detail": (f"latest run {latest.get('run_id')} measured "
                       f"{latest_gbps:.4f} GB/s, {delta:.0%} above the "
                       f"{len(base_rows)}-run baseline median "
                       f"{baseline:.4f} GB/s (gate {DRIFT_FRAC:.0%})")})
        return done("improving")
    return done("steady")


def _median(xs: List) -> Optional[float]:
    xs = sorted(float(x) for x in xs)
    n = len(xs)
    if not n:
        return None
    return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2


def drift_report(index: dict) -> dict:
    """Every drift group's verdict — the benchwatch ``history-report``
    payload."""
    groups = sorted({r.get("group") for r in index.get("runs", {}).values()
                     if r.get("group")})
    return {g: classify_drift(group_rows(index, g)) for g in groups}


# -- rendering ----------------------------------------------------------------

def render(index: dict, out, index_dir: Optional[str] = None,
           drift: bool = False) -> None:
    rows = index.get("runs", {})
    keys = index.get("keys", {})
    out.write(f"history: {len(rows)} runs under {len(keys)} config keys"
              + (f" ({index_path(index_dir)})" if index_dir else "") + "\n")
    for key in sorted(keys):
        krows = rows_for(index, key=key)
        gbps = [r.get("gb_per_s") for r in krows
                if r.get("gb_per_s") is not None]
        # %.4g, not %.4f: a CPU smoke run's 3e-06 GB/s must not render
        # as an alarming 0.0000.
        tail = f", latest {gbps[-1]:.4g} GB/s" if gbps else ""
        done = sum(1 for r in krows if r.get("completed"))
        out.write(f"  {key}: {len(krows)} runs ({done} completed){tail}\n")
    if drift:
        for g, verdict in sorted(drift_report(index).items()):
            out.write(f"  drift {g}: {verdict['verdict']}\n")
            for f in verdict["flags"]:
                out.write(f"    {f['flag']}: {f['detail']}\n")


# -- selftest ----------------------------------------------------------------

def _fixture_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, os.pardir, "tools", "fixtures")


def selftest() -> int:
    """Ingest the checked-in fixtures into a temp warehouse and assert
    the hand arithmetic: instance counts, config keys, the drift rule
    table, byte-stable re-ingest, fleet merge, forward compat, and the
    resolve_prior parity contracts."""
    import io
    import shutil
    import tempfile

    fdir = _fixture_dir()
    d = tempfile.mkdtemp(prefix="history_selftest_")
    try:
        # --- ingest the drift fixture: 4 same-key runs + a geometry flip.
        idx = ingest([os.path.join(fdir, "history_ledger.jsonl")], d)
        rows = idx["runs"]
        assert len(rows) == 6, f"6 run instances expected, got {len(rows)}"
        # The pallas series: 4 runs under ONE key (family wordcount,
        # backend pallas, 256 MiB corpus bucket b28 at 4 MiB chunks).
        pkey = "wordcount/pallas/b28-c4194304/default/off/split"
        prows = rows_for(idx, key=pkey)
        assert [r["run_id"] for r in prows] == ["h1", "h2", "h3", "h4"], prows
        s = series(idx, pkey)
        assert [v for _, v in s] == [0.1, 0.098, 0.101, 0.085], s
        # Drift: baseline median of (0.100, 0.098, 0.101) = 0.100;
        # latest 0.085 is 15% below — regressing at the 10% gate.
        dv = classify_drift(group_rows(idx, "wordcount/pallas/b28-c4194304"))
        assert dv["verdict"] == "regressing", dv
        assert dv["signals"]["baseline_gbps"] == 0.1, dv["signals"]
        assert dv["signals"]["delta_frac"] == round(-0.015 / 0.1, 4), dv
        assert "15% below" in dv["flags"][0]["detail"], dv["flags"]
        # The xla pair: g2 flipped geometry default -> tall512, so the
        # group verdict is config-drift and the two runs hold two keys.
        gv = classify_drift(group_rows(idx, "wordcount/xla/b28-c4194304"))
        assert gv["verdict"] == "config-drift", gv
        assert "geometry" in gv["flags"][0]["detail"], gv["flags"]
        assert len(rows_for(idx, group="wordcount/xla/b28-c4194304")) == 2
        # Verdict streak on the pallas key: all four runs classified
        # skew-hot -> a streak of 4.
        st = verdict_streak(idx, pkey)
        assert st == {"value": "skew-hot", "length": 4, "runs": 4}, st

        # --- synthesized rule-table walks (improving / steady /
        # no-history), datahealth-fixture style.
        def row(i, gbps, key="f/b/c/g/o/m"):
            return {"id": f"r{i}", "ts": float(i), "run_id": f"r{i}",
                    "instance": 0, "key": key, "group": "f/b/c",
                    "gb_per_s": gbps}

        up = [row(i, g) for i, g in enumerate([0.10, 0.10, 0.12])]
        assert classify_drift(up)["verdict"] == "improving"
        flat = [row(i, g) for i, g in enumerate([0.10, 0.10, 0.105])]
        assert classify_drift(flat)["verdict"] == "steady"
        assert classify_drift([row(0, 0.1)])["verdict"] == "no-history"
        assert classify_drift([])["verdict"] == "no-history"
        nog = [row(0, 0.1), row(1, None)]
        assert classify_drift(nog)["verdict"] == "steady", \
            "missing throughput is not a trend"

        # --- byte-stable re-ingest: same files in -> identical bytes out.
        before = open(index_path(d), encoding="utf-8").read()
        idx2 = ingest([os.path.join(fdir, "history_ledger.jsonl")], d)
        after = open(index_path(d), encoding="utf-8").read()
        assert before == after, "re-ingest must rewrite identical bytes"
        assert len(idx2["runs"]) == 6
        did = prows[-1]["id"]
        dig = read_digest(d, did)
        assert dig is not None and dig["gb_per_s"] == 0.085, dig
        assert dig["data_verdict"] == "skew-hot", dig
        assert dig["phase_shares"], dig

        # --- the whole fixture zoo ingests: mini (10 instances incl. the
        # in-flight v8 fixture10 and the v9 chaotic fixture11), the clean
        # counterpart, the two-host fleet shards (fleet verdict
        # attached), the future ledger (unknown kinds/fields
        # skip-or-consume, never an error).
        z = tempfile.mkdtemp(prefix="history_zoo_")
        try:
            zidx = ingest([os.path.join(fdir, "mini_ledger.jsonl"),
                           os.path.join(fdir, "mini_ledger_b.jsonl"),
                           os.path.join(fdir, "fleet_ledger.jsonl"),
                           os.path.join(fdir, "future_ledger.jsonl")], z)
            zrows = sorted(zidx["runs"].values(), key=_row_order)
            by_run = {r["run_id"]: r for r in zrows}
            assert len([r for r in zrows
                        if r["source"] == "mini_ledger.jsonl"]) == 10
            assert by_run["fixture10"]["completed"] is False
            # The v9 chaotic run (ISSUE 15): fault/degrade records skip-
            # or-consume through ingest; the run digests as completed.
            assert by_run["fixture11"]["completed"] is True
            zdig = read_digest(z, by_run["fixture10"]["id"])
            assert zdig["progress"]["frac"] == 0.5, zdig["progress"]
            assert by_run["fleet01"]["fleet_bottleneck"] \
                == "straggler-bound", by_run["fleet01"]
            assert by_run["future01"]["completed"] is True
            assert by_run["fixture05"]["data_verdict"] == "spill-bound"
            # Directory ingest expands the same main ledgers (shards fold
            # under fleet_ledger.jsonl instead of ingesting separately).
            srcs = expand_sources([fdir])
            assert os.path.join(fdir, "fleet_ledger.jsonl") in srcs
            assert not any(".h0." in s or ".h1." in s for s in srcs), srcs
        finally:
            shutil.rmtree(z, ignore_errors=True)

        # --- resolve_prior parity: the three reads it replaced.
        # (1) combiner: latest data record's verdict decides, exactly
        # datahealth.resolve_combiner.
        skew = {"kind": "data", "run_id": "a", "tokens": 1000,
                "top_count": 200, "chunks": 1}
        clean = {"kind": "data", "run_id": "b", "tokens": 1000,
                 "top_count": 10, "chunks": 1}
        for recs in ([skew], [clean], [], [clean, skew], [skew, clean]):
            assert resolve_prior(records=recs)["combiner"] \
                == datahealth.resolve_combiner(recs), recs
        # (2) geometry: freshest non-default profile entry decides.
        prof = os.path.join(d, "tuned.json")
        with open(prof, "w", encoding="utf-8") as f:
            json.dump({"profiles": {
                "wordcount-geometry/zipf": {
                    "recorded_at": "2026-01-01T00:00:00",
                    "config": {"geometry": "tall512"}},
                "wordcount/zipf": {
                    "recorded_at": "2026-02-01T00:00:00",
                    "config": {"geometry": "default"}}}}, f)
        p = resolve_prior(profile_path=prof, presets={"tall512"})
        assert p["geometry"] == "tall512", p
        assert resolve_prior(profile_path=os.path.join(d, "nope.json"))[
            "geometry"] == "default"
        # (2b) merge strategy (ISSUE 20): freshest redplan profile whose
        # planned mesh matches; mesh-label/allowed misses fall back to
        # 'tree' with a None profile key (the caller's loud-fallback cue).
        with open(prof, "w", encoding="utf-8") as f:
            json.dump({"profiles": {
                "wordcount-redplan/static/2dx4i-cap262144": {
                    "recorded_at": "2026-03-01T00:00:00",
                    "mesh": {"label": "2dx4i"},
                    "config": {"merge_strategy": "hier-kr-tree"}},
                "wordcount-redplan/static/8i-cap262144": {
                    "recorded_at": "2026-02-01T00:00:00",
                    "mesh": {"label": "8i"},
                    "config": {"merge_strategy": "keyrange"}}}}, f)
        mp = resolve_prior(profile_path=prof)
        assert mp["merge_strategy"] == "hier-kr-tree" \
            and mp["merge_strategy_profile"] \
            == "wordcount-redplan/static/2dx4i-cap262144", mp
        mp = resolve_prior(profile_path=prof, mesh_label="8i")
        assert mp["merge_strategy"] == "keyrange", mp
        mp = resolve_prior(profile_path=prof,
                           merge_allowed=("tree", "gather", "keyrange"))
        assert mp["merge_strategy"] == "keyrange", mp  # hier-* filtered
        mp = resolve_prior(profile_path=prof, mesh_label="16i")
        assert mp["merge_strategy"] == "tree" \
            and mp["merge_strategy_profile"] is None, mp
        # (3) the derive_signals run view: first stamped run chosen, and
        # a merged fleet stream anchors on host 0 (never the chimera).
        merged = [
            {"run_id": "m", "kind": "run_start", "host": 0},
            {"run_id": "m", "kind": "run_start", "host": 1},
            {"run_id": "m", "kind": "group", "host": 1, "staged_at": 1.0,
             "dispatched_at": 1.1, "token_ready_at": 2.0,
             "retired_at": 2.1, "step_first": 0},
            {"run_id": "m", "kind": "fleet",
             "fleet_bottleneck": {"verdict": "straggler-bound"}},
        ]
        v = resolve_prior(records=merged)
        assert v["run_id"] == "m" and v["fleet"] is not None
        assert all(r.get("host") in (0, None) for r in v["run_records"]), \
            v["run_records"]
        # (4) the warehouse prior: latest row + group drift for a key.
        wp = resolve_prior(index_dir=d, config_key=pkey)
        assert wp["history"]["rows"] == 4
        assert wp["history"]["latest"]["run_id"] == "h4"
        assert wp["history"]["drift"]["verdict"] == "regressing"

        # --- render path runs clean.
        buf = io.StringIO()
        render(idx, buf, index_dir=d, drift=True)
        body = buf.getvalue()
        assert "6 runs" in body and "drift wordcount/pallas" in body, body
        assert "regressing" in body and "config-drift" in body, body
    finally:
        shutil.rmtree(d, ignore_errors=True)
    print("history selftest ok (6 fixture runs, regressing/config-drift/"
          "improving/steady/no-history verdicts, streak 4, byte-stable "
          "re-ingest, 10-instance mini zoo + fleet + future flow-through, "
          "resolve_prior parity x4 + redplan merge-strategy warm-start)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="ingest mapreduce_tpu run ledgers into the run-history "
                    "warehouse and query it")
    ap.add_argument("sources", nargs="*",
                    help="ledger files, directories, or globs to ingest "
                         "(omit to just report on an existing index)")
    ap.add_argument("--index", default=None, metavar="DIR",
                    help="warehouse directory (history.json + runs/)")
    ap.add_argument("--key", default=None,
                    help="config key for --series / resolve-prior queries")
    ap.add_argument("--series", default=None, metavar="METRIC",
                    help="print the [ts, value] series of a row metric "
                         "(e.g. gb_per_s) under --key")
    ap.add_argument("--drift", action="store_true",
                    help="print per-group drift verdicts")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable index/report")
    ap.add_argument("--selftest", action="store_true",
                    help="run against the checked-in fixtures and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.index:
        ap.error("--index DIR is required (or --selftest)")
    if args.sources:
        index = ingest(args.sources, args.index)
    else:
        index = read_index(args.index)
        if index is None:
            print(f"no history index at {index_path(args.index)}",
                  file=sys.stderr)
            return 1
    if args.series:
        if not args.key:
            ap.error("--series requires --key")
        print(json.dumps(series(index, args.key, args.series)))
        return 0
    if args.json:
        payload = {"index": index}
        if args.drift:
            payload["drift"] = drift_report(index)
        print(json.dumps(payload, sort_keys=True))
        return 0
    render(index, sys.stdout, index_dir=args.index, drift=args.drift)
    return 0


if __name__ == "__main__":
    sys.exit(main())
