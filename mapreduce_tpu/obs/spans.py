"""Span-based tracing: one context manager = profiler region + phase time.

ISSUE 2 tentpole (4): the executor's phase decomposition (``read_wait`` /
``stage`` / ``dispatch``) and the XProf timeline were previously separate
worlds — the ledger said "dispatch took 8 s" and the profiler trace had no
marker saying which 8 s that was.  A :func:`span` nests a
``jax.profiler.TraceAnnotation`` (the same primitive as
``runtime.profiling.region``) around the timed section AND accumulates the
wall-clock into a :class:`...runtime.metrics.PhaseTimer` and/or a registry
histogram, so ledger records and profiler timelines line up by
construction.

Host-only: a TraceAnnotation is a nanosecond-scale TraceMe when no trace is
active, and nothing here runs inside a jitted program.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def span(name: str, timer=None, registry=None, metric: Optional[str] = None,
         annotate: bool = True) -> Iterator[None]:
    """Time a section as ``name``.

    Args:
      name: phase key in ``timer`` and the profiler-timeline label.
      timer: a ``PhaseTimer`` to accumulate into (optional).
      registry: a ``MetricsRegistry`` for a histogram observation (optional).
      metric: histogram name; defaults to ``"span." + name``.
      annotate: emit the profiler TraceAnnotation (on by default; off when
        a caller spans inside a tight host loop it never profiles).
    """
    ann = None
    if annotate:
        import jax

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        if timer is not None:
            timer.phases[name] = timer.phases.get(name, 0.0) + dt
        if registry is not None:
            registry.observe(metric or f"span.{name}", dt)
