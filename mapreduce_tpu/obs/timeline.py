"""Measured pipeline timeline: reconstruct per-resource lanes from a run
ledger's ``group`` records (ISSUE 7 tentpole).

The dispatch window (ISSUE 5) made streamed ingest an overlapped pipeline,
but the ledger recorded only per-step aggregated phase *deltas* — which
resource (reader, host staging, H2D, device compute, retire) actually
bounded a run, and where the device sat idle between groups, was
unobservable.  The executor now stamps every superstep group's lifecycle
with monotonic-clock timestamps and emits one ``group`` record per retired
group; this module turns those records back into:

* a per-resource **interval timeline** (``lanes``): merged busy intervals
  per lane, normalized to the run's first observation;
* a **measured overlap matrix** (``overlap_s``): pairwise concurrency
  seconds between lanes — the measured counterpart of the run-end
  ``overlap_fraction`` scalar;
* **device-idle gap analysis** (``device_idle``): every gap between device
  busy intervals, attributed to the lane that was blocking (covering the
  most of the gap) when it opened;
* a **critical-path verdict** (``bottleneck``): the bounding resource and
  the projected wall-clock saving if it were infinitely fast — the
  machine-readable dict the window autotuner (ROADMAP item 1) consumes.

Lane semantics (host-observed; nothing here adds a device sync):

==========  ===============================================================
lane        interval per group
==========  ===============================================================
reader      ``read_at -> staged_at``: the group's batches leaving the
            prefetching reader and accumulating into a superstep group
staging     ``staged_at -> dispatched_at``: host assembly + H2D placement
            enqueue + program enqueue (the ``stage``/``dispatch`` phases)
h2d         ``staged_at -> h2d_done_at``: present only where the executor
            explicitly observed the transfer complete (the end-of-stream
            ``h2d_tail`` wait); per-group H2D completion is not
            host-observable without the very sync the window exists to
            avoid — finer splits are XProf's job
device      ``dispatched_at -> token_ready_at``: enqueue to the observed
            readiness of the group's completion token (an upper bound:
            the token may have been ready before the loop looked;
            ``retire_wait_s`` says how long the look actually blocked)
retire      ``token_ready_at -> retired_at``: retire bookkeeping (window
            pop, staging-buffer recycling)
==========  ===============================================================

Fleet extensions (ISSUE 13): ``reconstruct(..., host=h)`` keeps one
process's records (multi-host shards stamp every record with ``host``),
and ``with_collective=True`` adds a ``collective`` lane from the per-run
``collective`` records (the observed collective-finish interval) — in
the lanes/overlap output but never in the single-run ``bottleneck``
election, which stays the STREAM verdict; cross-host straggler/collective
attribution is ``obs/fleet.py``'s ``fleet_bottleneck``.

The critical-path model: a lane's **exclusive seconds** (active while no
other lane is) are the only seconds an infinitely fast version of it could
remove from the measured span — overlapped seconds are covered by other
work by construction.  The bounding resource is the lane with the most
exclusive time.

Deliberately jax-free and import-free of the rest of the package, so
``tools/obs_report.py`` / ``tools/trace_export.py`` can load this module
by file path on a box that has neither jax nor the package installed.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional, Tuple

#: Resource lanes, in display/tie-break order.
LANES: Tuple[str, ...] = ("reader", "staging", "h2d", "device", "retire")

#: The fleet lane set (ISSUE 13): LANES plus the ``collective`` lane fed
#: by the per-run ``collective`` ledger records (the observed finish
#: interval).  The collective lane is opt-in (``with_collective=True``)
#: and deliberately excluded from the single-run ``bottleneck`` election:
#: that verdict names the STREAM's bounding resource — cross-host
#: collective attribution is ``obs/fleet.py``'s ``fleet_bottleneck``.
FLEET_LANES: Tuple[str, ...] = LANES + ("collective",)

#: Phase-delta fallback when a run carries no ``group`` records (batch
#: ledgers, pre-v2 ledgers, a live run before any group retired): which
#: resource lane each streaming phase blames.  ``dispatch`` maps to
#: device — a large dispatch share means the enqueue blocked on a full
#: device queue — and so do ``retire_wait``, ``compute_tail`` and the
#: legacy ``drain`` they decomposed from.  The ONE copy of this rule
#: table: ``tuning/engine.py`` and ``tools/obswatch.py`` both read it.
PHASE_LANE = {"read_wait": "reader", "stage": "staging",
              "dispatch": "device", "retire_wait": "device",
              "compute_tail": "device", "drain": "device",
              "h2d_tail": "h2d"}

_Interval = Tuple[float, float]


# -- interval arithmetic ----------------------------------------------------

def _merge(intervals: Iterable[_Interval]) -> List[_Interval]:
    """Sorted, coalesced intervals (touching intervals merge)."""
    out: List[List[float]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _total(intervals: Iterable[_Interval]) -> float:
    return sum(e - s for s, e in intervals)


def _intersection_s(a: List[_Interval], b: List[_Interval]) -> float:
    """Total intersection seconds of two MERGED interval lists."""
    i = j = 0
    tot = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            tot += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return tot


def _cover_s(intervals: List[_Interval], lo: float, hi: float) -> float:
    """Seconds of ``intervals`` falling inside ``[lo, hi]``."""
    tot = 0.0
    for s, e in intervals:
        s2, e2 = max(s, lo), min(e, hi)
        if e2 > s2:
            tot += e2 - s2
    return tot


def _exclusive_s(lanes: dict) -> dict:
    """Per-lane seconds active while NO other lane is (sweep over the
    merged intervals) — the measured critical-path attribution."""
    events = []
    for lane, intervals in lanes.items():
        for s, e in intervals:
            events.append((s, 0, lane))
            events.append((e, 1, lane))
    events.sort(key=lambda ev: (ev[0], ev[1]))
    active = {lane: 0 for lane in lanes}
    excl = {lane: 0.0 for lane in lanes}
    prev: Optional[float] = None
    for t, kind, lane in events:
        if prev is not None and t > prev:
            on = [ln for ln, n in active.items() if n > 0]
            if len(on) == 1:
                excl[on[0]] += t - prev
        active[lane] += 1 if kind == 0 else -1
        prev = t
    return excl


# -- group records -> intervals ---------------------------------------------

def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


def group_intervals(rec: dict) -> Optional[dict]:
    """One ``group`` record's lane intervals (absolute monotonic seconds).
    Returns None for records missing the core lifecycle (forward compat:
    a future record shape is skipped, never an error); zero-length
    intervals are dropped."""
    s = _num(rec.get("staged_at"))
    d = _num(rec.get("dispatched_at"))
    t = _num(rec.get("token_ready_at"))
    e = _num(rec.get("retired_at"))
    if None in (s, d, t, e):
        return None
    out = {}
    r = _num(rec.get("read_at"))
    if r is not None and s > r:
        out["reader"] = (r, s)
    if d > s:
        out["staging"] = (s, d)
    if t > d:
        out["device"] = (d, t)
    if e > t:
        out["retire"] = (t, e)
    h = _num(rec.get("h2d_done_at"))
    if h is not None and h > s:
        out["h2d"] = (s, min(h, e))
    return out or None


def iter_groups(records: Iterable[dict],
                run_id: Optional[str] = None,
                host: Optional[int] = None) -> Iterator[dict]:
    """The ``group`` records of one run (the first run carrying any, when
    ``run_id`` is not given).  ``host`` (ISSUE 13) keeps only records
    stamped with that process index — the per-host lane filter fleet
    merges reconstruct through.  Unknown kinds and malformed rows skip."""
    chosen = run_id
    for rec in records:
        if not isinstance(rec, dict) or rec.get("kind") != "group":
            continue
        if host is not None and rec.get("host") != host:
            continue
        if chosen is None:
            chosen = rec.get("run_id")
        if rec.get("run_id") == chosen:
            yield rec


def iter_collectives(records: Iterable[dict],
                     run_id: Optional[str] = None,
                     host: Optional[int] = None) -> Iterator[dict]:
    """The ``collective`` records of one run (ISSUE 13), same selection
    rules as :func:`iter_groups`."""
    chosen = run_id
    for rec in records:
        if not isinstance(rec, dict) or rec.get("kind") != "collective":
            continue
        if host is not None and rec.get("host") != host:
            continue
        if chosen is None:
            chosen = rec.get("run_id")
        if rec.get("run_id") == chosen:
            yield rec


def collective_interval(rec: dict) -> Optional[_Interval]:
    """One ``collective`` record's (started_at, ended_at) interval, or
    None when malformed/zero-length (forward compat: skip, never error)."""
    s, e = _num(rec.get("started_at")), _num(rec.get("ended_at"))
    if s is None or e is None or e <= s:
        return None
    return (s, e)


# -- the reconstruction -----------------------------------------------------

def reconstruct(records: Iterable[dict],
                run_id: Optional[str] = None,
                host: Optional[int] = None,
                with_collective: bool = False) -> Optional[dict]:
    """Ledger records -> the timeline artifact (see module docstring), or
    None when the run carries no usable ``group`` records (pre-ISSUE-7
    ledgers degrade to "no timeline", never to an error).

    ``host`` (ISSUE 13) restricts the reconstruction to one process's
    records (fleet merges call this per host over clock-aligned shards);
    ``with_collective=True`` adds the ``collective`` lane from the run's
    ``collective`` records — visible in lanes/busy/overlap but excluded
    from the ``bottleneck`` election (see :data:`FLEET_LANES`).

    All times in the artifact are seconds relative to the run's first
    observed lifecycle timestamp (``t0``), rounded to microseconds.
    """
    if with_collective:
        records = list(records)  # a second pass reads the collectives
    groups = []
    for rec in iter_groups(records, run_id, host=host):
        iv = group_intervals(rec)
        if iv is not None:
            groups.append((rec, iv))
    if not groups:
        return None
    raw: dict = {lane: [] for lane in LANES}
    for _, iv in groups:
        for lane, span in iv.items():
            raw[lane].append(span)
    if with_collective:
        run = groups[0][0].get("run_id")
        coll = [collective_interval(rec)
                for rec in iter_collectives(records, run, host=host)]
        coll = [iv for iv in coll if iv is not None]
        if coll:
            raw["collective"] = coll
    t0 = min(s for spans in raw.values() for s, _ in spans)
    lanes = {lane: _merge([(s - t0, e - t0) for s, e in spans])
             for lane, spans in raw.items()}
    t_end = max(e for spans in lanes.values() for _, e in spans)

    busy = {lane: round(_total(spans), 6) for lane, spans in lanes.items()}
    overlap = {}
    present = [ln for ln in FLEET_LANES if lanes.get(ln)]
    for i, a in enumerate(present):
        for b in present[i + 1:]:
            overlap[f"{a}+{b}"] = round(
                _intersection_s(lanes[a], lanes[b]), 6)

    # Device-idle gaps, each attributed to the lane covering most of it.
    gaps = []
    blocked_on: dict = {}
    dev = lanes["device"]
    for (_, e0), (s1, _) in zip(dev, dev[1:]):
        best, best_cov = "idle", 0.0
        for lane in LANES:
            if lane == "device" or not lanes[lane]:
                continue
            cov = _cover_s(lanes[lane], e0, s1)
            if cov > best_cov + 1e-12:
                best, best_cov = lane, cov
        gaps.append({"start": round(e0, 6), "end": round(s1, 6),
                     "s": round(s1 - e0, 6), "blocking": best,
                     "blocking_s": round(best_cov, 6)})
        blocked_on[best] = round(blocked_on.get(best, 0.0) + (s1 - e0), 6)
    idle_total = round(sum(g["s"] for g in gaps), 6)

    excl = _exclusive_s(lanes)
    populated = [lane for lane in LANES if lanes[lane]]
    resource = max(populated, key=lambda ln: (excl[ln], busy[ln]))
    saving = excl[resource]
    span = t_end
    bottleneck = {
        "resource": resource,
        "busy_s": busy[resource],
        "exclusive_s": round(saving, 6),
        "projected_saving_s": round(saving, 6),
        "projected_span_s": round(span - saving, 6),
        "span_s": round(span, 6),
        "device_busy_s": busy.get("device", 0.0),
        "device_idle_s": idle_total,
        "detail": (f"{resource} is the measured critical path: "
                   f"{saving:.3f}s of the {span:.3f}s span is "
                   f"{resource}-exclusive — an infinitely fast {resource} "
                   f"saves ~{saving:.3f}s "
                   f"({100 * saving / span:.0f}% of span)" if span > 0
                   else f"{resource} (degenerate zero-length span)"),
    }
    return {
        "run_id": groups[0][0].get("run_id"),
        "groups": len(groups),
        "t0": round(t0, 6),
        "span_s": round(span, 6),
        "lanes": {lane: [[round(s, 6), round(e, 6)] for s, e in spans]
                  for lane, spans in lanes.items()},
        "lane_busy_s": busy,
        "exclusive_s": {lane: round(v, 6) for lane, v in excl.items()},
        "overlap_s": overlap,
        "device_idle": {"total_s": idle_total, "gaps": gaps,
                        "blocked_on": blocked_on},
        "bottleneck": bottleneck,
    }


# -- Chrome trace-event rendering -------------------------------------------

# Slice names per lane (what a Perfetto track shows on each group's slice).
_SLICE = {"reader": "read", "staging": "stage", "h2d": "h2d",
          "device": "compute", "retire": "retire",
          "collective": "collective"}


def to_chrome_trace(records: Iterable[dict],
                    run_id: Optional[str] = None) -> Optional[dict]:
    """Ledger records -> Chrome trace-event JSON (the ``tools/
    trace_export.py`` payload): one **pid per resource lane**, one **tid
    per group**, complete (``ph="X"``) slices for every lifecycle
    interval, flow arrows dispatch -> token_ready, and instant markers on
    the device lane for every attributed idle gap.  Open the written file
    in Perfetto (ui.perfetto.dev) or ``chrome://tracing``.

    Returns None when the run has no usable ``group`` records.
    """
    records = list(records)
    art = reconstruct(records, run_id)
    if art is None:
        return None
    pid = {lane: i + 1 for i, lane in enumerate(LANES)}
    events = []
    for lane in LANES:
        events.append({"ph": "M", "name": "process_name", "pid": pid[lane],
                       "args": {"name": lane}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid[lane], "args": {"sort_index": pid[lane]}})
    t0 = art["t0"]

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    named_threads = set()
    for rec in iter_groups(records, art["run_id"]):
        iv = group_intervals(rec)
        if iv is None:
            continue
        gid = int(rec.get("step_first", 0))
        label = f"g{rec.get('step_first', '?')}-{rec.get('step_last', '?')}"
        args = {k: rec.get(k) for k in
                ("step_first", "step_last", "steps", "group_bytes",
                 "retries", "retire_wait_s") if rec.get(k) is not None}
        # Data-plane annotations (ISSUE 8): the group's spill/rescue/
        # occupancy counters ride every slice's args (click a slice in
        # Perfetto to see what the data did), and groups that took the
        # spill-fallback or rescue-escalation cond get an instant marker
        # on the device lane — the 2x-map-cost chunks are visible as
        # events, not just numbers.
        data = rec.get("data")
        if isinstance(data, dict):
            args["data"] = data
        for lane, (s, e) in iv.items():
            if (pid[lane], gid) not in named_threads:
                named_threads.add((pid[lane], gid))
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid[lane], "tid": gid,
                               "args": {"name": f"group {label}"}})
            events.append({"ph": "X", "cat": "lane",
                           "name": f"{_SLICE[lane]} {label}",
                           "pid": pid[lane], "tid": gid, "ts": us(s),
                           "dur": round((e - s) * 1e6, 3), "args": args})
        if isinstance(data, dict) and "device" in iv:
            marks = []
            if data.get("fallback_chunks"):
                marks.append(f"{data['fallback_chunks']} spill fallback(s)")
            if data.get("rescue_escalations"):
                marks.append(f"{data['rescue_escalations']} rescue "
                             "escalation(s)")
            if marks:
                events.append({"ph": "i", "s": "t", "cat": "data",
                               "name": f"data: {', '.join(marks)} {label}",
                               "pid": pid["device"], "tid": gid,
                               "ts": us(iv["device"][0]),
                               "args": dict(data)})
        # Flow arrow: the dispatch hand-off from the staging lane into the
        # device lane (binds to the enclosing slices at each end).
        if "staging" in iv and "device" in iv:
            events.append({"ph": "s", "cat": "dispatch", "name": "dispatch",
                           "id": gid, "pid": pid["staging"], "tid": gid,
                           "ts": us(iv["staging"][1])})
            events.append({"ph": "f", "bp": "e", "cat": "dispatch",
                           "name": "dispatch", "id": gid,
                           "pid": pid["device"], "tid": gid,
                           "ts": us(iv["device"][1])})
    for gap in art["device_idle"]["gaps"]:
        events.append({"ph": "i", "s": "p", "cat": "idle",
                       "name": f"device idle {gap['s']:.3f}s: "
                               f"blocked on {gap['blocking']}",
                       "pid": pid["device"], "tid": 0,
                       "ts": round(gap["start"] * 1e6, 3),
                       "args": dict(gap)})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"run_id": art["run_id"], "groups": art["groups"],
                          "bottleneck": art["bottleneck"]}}
